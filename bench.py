"""Benchmark: Llama-3-8B decode throughput + prefill TTFT on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} — on
success AND on failure (failure lines carry value 0.0 and an "error" field,
so the driver always gets parseable output).

The reference's engine (llama.cpp cuBLAS, reference docker/Dockerfile.base:30)
publishes no numbers; the driver-provided target (BASELINE.md) is A10G-parity
decode throughput for Llama-3-8B Q4_K_M — llama.cpp-class engines decode
Q4_K_M 8B on an A10G at roughly 30-60 tok/s; vs_baseline is computed against
the 45 tok/s midpoint.

Resilience (round-1 postmortem): the device tunnel is SINGLE-SESSION — a
stale process holding it makes ``jax.devices()`` fail fast (UNAVAILABLE) or
hang forever.  The parent process therefore never touches jax itself: it
spawns the real bench as a child, enforces a backend-init deadline (the
child reports init on stderr) and a total deadline, kills hung children,
and retries with backoff.  Tune via LFKT_BENCH_ATTEMPTS (default 3),
LFKT_BENCH_INIT_TIMEOUT (s, default 420), LFKT_BENCH_TOTAL_TIMEOUT
(s, default 1500), LFKT_BENCH_BACKOFF (s, first gap, default 10, doubles).

The model is the real 8B architecture (models/config.py LLAMA3_8B) with
synthesized weights (zero-egress environment: weights cannot be downloaded,
and decode speed is value-independent — it is bound by HBM bytes/token,
which synthetic weights reproduce exactly).

Run standalone and ALONE (the device tunnel is single-session):
    python bench.py            # real chip, 8B
    LFKT_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python bench.py   # smoke

Timing note: on the tunneled device platform ``jax.block_until_ready`` can
return before execution finishes, so every measured section ends with a
small host fetch (``int(scalar)`` / ``np.asarray`` of a few tokens), which
is the only reliable sync.  All decode chunks are data-dependent (donated
state chain), so one final fetch syncs the whole chain.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

A10G_Q4KM_8B_TOK_S = 45.0  # midpoint of the 30-60 tok/s llama.cpp A10G range


def emit_result(d: dict) -> None:
    """Print one bench JSON line, stamped with provenance: git commit,
    device kind, and the LFKT_* knob fingerprint (utils/provenance.py).
    tools/check_manifest.py validates the stamp schema over the banked
    corpus, and tools/perf_gate.py refuses cross-knob-set comparisons.
    The stamp import is guarded: the parent's guaranteed failure JSON
    must print even from a checkout whose package does not import — the
    exact deterministic-ImportError class that fails every child attempt.
    Shared with bench_server.py (which delegates here; one copy only)."""
    try:
        from llama_fastapi_k8s_gpu_tpu.utils.provenance import stamp

        d = {**d, "provenance": stamp()}
    except Exception:
        pass  # metadata must never eat the result line
    print(json.dumps(d), flush=True)


_INIT_MARK = "LFKT_INIT_OK"

#: leaf key that marks a fused-layout weight dict per bench format — the
#: label-honesty check (report the fused format only if any tensor actually
#: got the layout).  Shared with bench_server.py.
#: any ONE of the listed leaf keys marks the format's fused layout
#: (q5km has two because `pre` is a LAYOUT variant: q5s split / q5p plane)
FUSED_KEYS = {"q4k": ("qs",), "q8": ("q8",), "q4km": ("qs",),
              "q5km": ("q5s", "q5p")}


def probe_fused_or_degrade(wfmt: str, tag: str):
    """Compile-probe the fused kernels ``wfmt`` relies on; on a Mosaic
    failure return ("int8", reason) so the caller serves/benches the
    fallback with correct attribution.  Shared by bench.py/bench_server.py
    so the two benches can't diverge in what they degrade."""
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.probe import (
        probe_fused_q4k,
        probe_fused_q5k,
        probe_fused_q6k,
        probe_fused_q8,
    )

    probes = {"q4k": [probe_fused_q4k], "q8": [probe_fused_q8],
              "q4km": [probe_fused_q4k, probe_fused_q6k],
              "q5km": [probe_fused_q5k, probe_fused_q6k]}
    for pr in probes.get(wfmt, []):
        err = pr()
        if err is not None:
            reason = f"fused {wfmt.upper()} kernel ({pr.__name__}): {err}"[:300]
            print(f"{tag}: {reason}; using int8", file=sys.stderr, flush=True)
            return "int8", reason
    return wfmt, None


def maybe_seed_compile_cache(repo: str, cache_dir: str) -> bool:
    """Restore the committed compile-cache seed when the cache dir is gone.

    Container restarts can reset the repo to its git state, deleting the
    (ignored) warm cache dir.  Entries restored IN PLACE at the same path
    still hit (measured: compile_s 4.8 after rm -rf + tar-restore;
    cross-dir copies miss — the key is path-scoped), so a committed seed
    tarball keeps a bare post-restart ``python bench.py`` warm.  Never
    clobbers a live cache; only the default repo-local location is
    seeded; extraction is restricted to ``.lfkt_xla_cache/`` members
    (``./``-prefix-normalized) with ``filter="data"``; a bad or stale
    seed degrades to a cold run, never to a failure.  Returns True when
    the seed was extracted.
    """
    seed = os.path.join(repo, "tools", "xla_cache_seed.tgz")
    if (os.path.realpath(cache_dir)
            != os.path.realpath(os.path.join(repo, ".lfkt_xla_cache"))
            or os.path.isdir(cache_dir) or not os.path.exists(seed)):
        return False
    import tarfile

    def _norm(n):
        return n[2:] if n.startswith("./") else n

    try:
        with tarfile.open(seed) as tf:
            members = [m for m in tf.getmembers()
                       if _norm(m.name) == ".lfkt_xla_cache"
                       or _norm(m.name).startswith(".lfkt_xla_cache/")]
            if not members:
                raise ValueError("no .lfkt_xla_cache/ members")
            tf.extractall(repo, members=members, filter="data")
        print(f"bench: seeded compile cache from {seed}",
              file=sys.stderr, flush=True)
        return True
    except Exception as e:  # seed is insurance, never a hard dep
        print(f"bench: cache seed extract failed: {e}",
              file=sys.stderr, flush=True)
        return False


# ---------------------------------------------------------------------------
# child: the actual benchmark (runs with LFKT_BENCH_CHILD=1)
# ---------------------------------------------------------------------------

def synth_params_device(cfg, seed: int = 0, fmt: str = "int8") -> dict:
    """Device-side random params (no multi-GB host RNG / transfer).

    ``fmt="int8"``: per-channel int8 (ops/linear.py).  ``fmt="q4k"``: the
    fused Q4_K kernel layout (ops/pallas/qmatmul.py) — random packed nibbles
    + small scales.  ``fmt="q8"``: the fused Q8_0 layout
    (ops/pallas/q8matmul.py) — the BASELINE's named Q8_0 config at ~1.13
    B/weight.  ``fmt="q4km"``: the Q4_K_M tensor-type mix — fused Q6_K for
    ``attn_v``/``ffn_down``/``output`` (~0.88 B/w), fused Q4_K for the rest
    (~0.63 B/w) — mirroring coldstart_main's file writer (the repo's
    file-fidelity definition).  ``fmt="q5km"``: the Q5_K_M analogue —
    the same Q6_K tensors plus fused Q5_K for the rest (~0.75 B/w split /
    ~1.125 B/w under the default ``pre`` layout).  Slightly conservative
    vs a genuine llama.cpp artifact, whose ``use_more_bits`` recipe puts
    only about half the ffn_down layers on Q6_K (~5% fewer HBM
    bytes/token than this grid); a real Q4_K_M file (reference
    api.py:14) serves at or above
    the number this grid reports.  Decode bandwidth is value-independent,
    so these measure exactly what real quantized weights would.
    """
    import jax
    import jax.numpy as jnp

    from llama_fastapi_k8s_gpu_tpu.ops.pallas.qmatmul import TK, q4k_compatible

    kv_dim = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    key = jax.random.PRNGKey(seed)

    def lin(k, out_dim, in_dim, want=None):
        want = want or fmt
        if want == "q4km":
            want = "q4k"
        if want == "q5km":
            want = "q5k"
        if want == "q5k" and q4k_compatible(out_dim, in_dim, for_tpu=True):
            # fused Q5_K layout (ops/pallas/q5matmul.py): combined-nibble
            # plane + high-bit plane + lane-tiled scales, ~0.75 B/w split /
            # ~1.125 B/w under the default `pre` layout.
            # LAYOUT variants must be honored here too — the kernels
            # dispatch on plane presence, so a synthetic split grid under
            # LFKT_Q5K_KERNEL=pre would silently A/B the split path
            # against itself (the hollow-A/B trap).
            from llama_fastapi_k8s_gpu_tpu.ops.pallas.q5matmul import (
                Q5K_VARIANTS,
                _env_variant,
            )

            sm5 = jnp.full((L, in_dim // TK, out_dim, 128),
                           (in_dim ** -0.5) / 16.0, jnp.bfloat16)
            if _env_variant("LFKT_Q5K_KERNEL", Q5K_VARIANTS) == "pre":
                q5p = jax.random.randint(k, (L, out_dim, in_dim),
                                         0, 32, jnp.int8)
                return {"q5p": q5p, "sm5": sm5}
            k1, k2 = jax.random.split(k)
            q5s = jax.random.randint(k1, (L, out_dim, in_dim // 2),
                                     -128, 128, jnp.int8)
            q5h = jax.random.randint(k2, (L, out_dim, in_dim // 8),
                                     -128, 128, jnp.int8)
            return {"q5s": q5s, "q5h": q5h, "sm5": sm5}
        if want == "q4k" and q4k_compatible(out_dim, in_dim, for_tpu=True):
            qs = jax.random.randint(k, (L, out_dim, in_dim // 2),
                                    -128, 128, jnp.int8)
            sm = jnp.full((L, in_dim // TK, out_dim, 128),
                          (in_dim ** -0.5) / 8.0, jnp.bfloat16)
            return {"qs": qs, "sm": sm}
        if want == "q6k" and q4k_compatible(out_dim, in_dim, for_tpu=True):
            k1, k2 = jax.random.split(k)
            q4 = jax.random.randint(k1, (L, out_dim, in_dim // 2),
                                    -128, 128, jnp.int8)
            q2 = jax.random.randint(k2, (L, out_dim, in_dim // 4),
                                    -128, 128, jnp.int8)
            sm6 = jnp.full((L, in_dim // TK, out_dim, 128),
                           (in_dim ** -0.5) / 32.0, jnp.bfloat16)
            return {"q4": q4, "q2": q2, "sm6": sm6}
        if want == "q8" and q4k_compatible(out_dim, in_dim, for_tpu=True):
            q8 = jax.random.randint(k, (L, out_dim, in_dim),
                                    -127, 128, jnp.int8)
            sm8 = jnp.full((L, in_dim // TK, out_dim, 128),
                           (in_dim ** -0.5) / 127.0, jnp.bfloat16)
            return {"q8": q8, "sm8": sm8}
        q = jax.random.randint(k, (L, out_dim, in_dim), -127, 128, jnp.int8)
        s = jnp.full((L, out_dim), (in_dim ** -0.5) / 127.0, jnp.float32)
        return {"q": q, "s": s}

    # Q4_K_M / Q5_K_M per-name type map: attn_v, ffn_down and the output
    # head ride Q6_K, everything else Q4_K resp. Q5_K (llama.cpp's
    # use_more_bits recipe; mirrors coldstart_main's file writer)
    q6 = "q6k" if fmt in ("q4km", "q5km") else None

    ks = jax.random.split(key, 8)
    emb = (jax.random.normal(ks[0], (cfg.vocab_size, cfg.dim), jnp.bfloat16)
           * (cfg.dim ** -0.5))
    return {
        "tok_emb": emb,
        "layers": {
            "attn_norm": jnp.ones((L, cfg.dim), jnp.float32),
            "wq": lin(ks[1], cfg.dim, cfg.dim),
            "wk": lin(ks[2], kv_dim, cfg.dim),
            "wv": lin(ks[3], kv_dim, cfg.dim, q6),
            "wo": lin(ks[4], cfg.dim, cfg.dim),
            "ffn_norm": jnp.ones((L, cfg.dim), jnp.float32),
            "w_gate": lin(ks[5], cfg.ffn_dim, cfg.dim),
            "w_up": lin(ks[6], cfg.ffn_dim, cfg.dim),
            "w_down": lin(ks[7], cfg.dim, cfg.ffn_dim, q6),
        },
        "out_norm": jnp.ones(cfg.dim, jnp.float32),
        "output": _synth_output_head(cfg, fmt, ks[0]),
    }


def _synth_output_head(cfg, fmt: str, key):
    """Output-head weights in the bench format (unstacked — the head is not
    part of the per-layer scan)."""
    import jax
    import jax.numpy as jnp

    from llama_fastapi_k8s_gpu_tpu.ops.pallas.qmatmul import TK, q4k_compatible

    if fmt == "q4k" and q4k_compatible(cfg.vocab_size, cfg.dim, for_tpu=True):
        return {
            "qs": jax.random.randint(key, (cfg.vocab_size, cfg.dim // 2),
                                     -128, 128, jnp.int8),
            "sm": jnp.full((cfg.dim // TK, cfg.vocab_size, 128),
                           (cfg.dim ** -0.5) / 8.0, jnp.bfloat16),
        }
    if (fmt in ("q4km", "q5km")
            and q4k_compatible(cfg.vocab_size, cfg.dim, for_tpu=True)):
        # Q4_K_M / Q5_K_M files store output.weight as Q6_K
        k1, k2 = jax.random.split(key)
        return {
            "q4": jax.random.randint(k1, (cfg.vocab_size, cfg.dim // 2),
                                     -128, 128, jnp.int8),
            "q2": jax.random.randint(k2, (cfg.vocab_size, cfg.dim // 4),
                                     -128, 128, jnp.int8),
            "sm6": jnp.full((cfg.dim // TK, cfg.vocab_size, 128),
                            (cfg.dim ** -0.5) / 32.0, jnp.bfloat16),
        }
    if fmt == "q8" and q4k_compatible(cfg.vocab_size, cfg.dim, for_tpu=True):
        return {
            "q8": jax.random.randint(key, (cfg.vocab_size, cfg.dim),
                                     -127, 128, jnp.int8),
            "sm8": jnp.full((cfg.dim // TK, cfg.vocab_size, 128),
                            (cfg.dim ** -0.5) / 127.0, jnp.bfloat16),
        }
    return {
        "q": jax.random.randint(key, (cfg.vocab_size, cfg.dim),
                                -127, 128, jnp.int8),
        "s": jnp.full((cfg.vocab_size,), (cfg.dim ** -0.5) / 127.0,
                      jnp.float32),
    }


def _rand_q4k_blocks(rng, n_elem: int) -> "np.ndarray":
    """Valid random Q4_K block bytes (layout per gguf/quants.py: f16 d |
    f16 dmin | 12B packed scale/min | 128B nibbles).  Load speed is
    value-independent, so random payloads measure the real cold start."""
    import numpy as np

    nb = n_elem // 256
    blk = np.empty((nb, 144), dtype=np.uint8)
    d = np.full(nb, 0.002, np.float16)
    dmin = np.full(nb, 0.001, np.float16)
    blk[:, 0:2] = d.view(np.uint8).reshape(nb, 2)
    blk[:, 2:4] = dmin.view(np.uint8).reshape(nb, 2)
    blk[:, 4:16] = rng.integers(0, 64, (nb, 12), dtype=np.uint8)  # 6-bit fields
    blk[:, 16:144] = rng.integers(0, 256, (nb, 128), dtype=np.uint8)
    return blk.reshape(-1)


def _rand_q6k_blocks(rng, n_elem: int) -> "np.ndarray":
    """Valid random Q6_K block bytes (128B ql | 64B qh | 16×i8 scales | f16 d)."""
    import numpy as np

    nb = n_elem // 256
    blk = np.empty((nb, 210), dtype=np.uint8)
    blk[:, 0:192] = rng.integers(0, 256, (nb, 192), dtype=np.uint8)
    blk[:, 192:208] = rng.integers(1, 4, (nb, 16), dtype=np.uint8)  # small +scales
    d = np.full(nb, 0.002, np.float16)
    blk[:, 208:210] = d.view(np.uint8).reshape(nb, 2)
    return blk.reshape(-1)


def coldstart_main() -> None:
    """LFKT_BENCH_COLDSTART=1: measure the REAL load path (VERDICT r2 #6) —
    write a full-size 8B Q4_K_M-style GGUF (Q4_K attn/ffn, Q6_K attn_v +
    ffn_down + output — the mixed-type layout llama.cpp's Q4_K_M files have),
    then load it through GGUF mmap → native C++/Pallas dequant → HBM and
    serve one completion.  Reports write_s / load_s / compile+first_ttft_s,
    which gate the Helm startup-probe budget (helm/values.yaml)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logging

    # surface the engine's load-phase INFO logs on stderr (the suite keeps
    # per-step .err files; without this the phase attribution is silent)
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    import jax

    dev = jax.devices()[0]
    print(f"{_INIT_MARK} {dev}", file=sys.stderr, flush=True)

    path = os.environ.get("LFKT_COLDSTART_PATH", "/tmp/lfkt_coldstart_8b.gguf")
    t0 = time.time()
    if not (os.path.exists(path)
            and os.environ.get("LFKT_COLDSTART_REUSE") == "1"):
        write_coldstart_file(path)
    write_s = time.time() - t0
    size_gb = os.path.getsize(path) / 1e9

    from llama_fastapi_k8s_gpu_tpu.engine import Engine

    t1 = time.time()
    eng = Engine(path, n_ctx=1024, weight_format="q4k",
                 prefill_buckets=(128, 256, 512, 1024))
    load_s = time.time() - t1
    t2 = time.time()
    out = eng.create_chat_completion(
        messages=[{"role": "user", "content": "benchmark cold start"}],
        max_tokens=32)
    first_req_s = time.time() - t2
    # the first request's timings are compile-laden; steady state needs
    # warm programs AND a decode run long enough to wash out the prefill
    # and chunk-boundary edges (VERDICT r3 #1: the cold-start probe's
    # 32-token runs under-measured the real file's steady throughput)
    out = eng.create_chat_completion(
        messages=[{"role": "user", "content": "benchmark steady state"}],
        max_tokens=256)
    timings = out.get("lfkt_timings", {})
    result = {
        "metric": "coldstart_load_s[llama3-8b,q4km-file]",
        "value": round(load_s, 1),
        "unit": "seconds",
        "vs_baseline": 0.0,   # no reference number exists; informational
        "file_gb": round(size_gb, 2),
        "write_s": round(write_s, 1),
        "first_request_s": round(first_req_s, 1),   # jit compile + generate
        "ttft_s_steady": timings.get("ttft_s"),
        "tokens_per_sec": timings.get("tokens_per_sec"),
        "load_phases": getattr(eng, "load_phases", None),
        "device": str(dev),
    }
    emit_result(result)


def write_coldstart_file(path: str) -> None:
    """Write the full-size 8B Q4_K_M-style GGUF coldstart_main loads.

    Pure numpy — safe to run in a process that never touches the device
    (tools/write_coldstart_gguf.py pre-writes the file so the chip-holding
    bench only pays the LOAD, not the ~8 min write, under its watchdog)."""
    import dataclasses

    import numpy as np

    from llama_fastapi_k8s_gpu_tpu.gguf import GGMLType, GGUFWriter
    from llama_fastapi_k8s_gpu_tpu.models.config import LLAMA3_8B
    from llama_fastapi_k8s_gpu_tpu.testing import (
        synth_bpe_vocab,
        write_llama_gguf_meta,
    )

    cfg = LLAMA3_8B
    rng = np.random.default_rng(0)
    tokens, merges, types = synth_bpe_vocab(n_merges=280_000)
    # pad/trim to the exact 8B vocab so tensor shapes are authentic
    specials = tokens[-7:]
    body = tokens[:-7]
    need = cfg.vocab_size - len(specials)
    body = (body + [f"<pad{i}>" for i in range(need - len(body))])[:need]
    tokens = body + specials
    types = [1] * need + [3] * len(specials)
    w = GGUFWriter(path)
    write_llama_gguf_meta(w, dataclasses.replace(cfg, vocab_size=len(tokens)),
                          tokens, types, merges=merges,
                          name="llama3-8b-synthetic-q4km", n_ctx=8192)
    kv_dim = cfg.n_kv_heads * cfg.head_dim

    def raw(name, shape, kind):
        # `shape` is numpy order (out, in); GGUF tensor shapes are
        # innermost-first, which is what add_raw_tensor stores verbatim
        n = int(np.prod(shape))
        if kind == GGMLType.Q4_K:
            data = _rand_q4k_blocks(rng, n)
        elif kind == GGMLType.Q6_K:
            data = _rand_q6k_blocks(rng, n)
        else:  # F16
            data = (rng.standard_normal(n).astype(np.float16)
                    * cfg.dim ** -0.5).view(np.uint8)
        w.add_raw_tensor(name, tuple(reversed(shape)), kind, data)

    def f32(name, shape):
        w.add_tensor(name, np.ones(shape, np.float32), GGMLType.F32)

    raw("token_embd.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    for i in range(cfg.n_layers):
        p = f"blk.{i}."
        f32(p + "attn_norm.weight", (cfg.dim,))
        raw(p + "attn_q.weight", (cfg.dim, cfg.dim), GGMLType.Q4_K)
        raw(p + "attn_k.weight", (kv_dim, cfg.dim), GGMLType.Q4_K)
        raw(p + "attn_v.weight", (kv_dim, cfg.dim), GGMLType.Q6_K)
        raw(p + "attn_output.weight", (cfg.dim, cfg.dim), GGMLType.Q4_K)
        f32(p + "ffn_norm.weight", (cfg.dim,))
        raw(p + "ffn_gate.weight", (cfg.ffn_dim, cfg.dim), GGMLType.Q4_K)
        raw(p + "ffn_up.weight", (cfg.ffn_dim, cfg.dim), GGMLType.Q4_K)
        raw(p + "ffn_down.weight", (cfg.dim, cfg.ffn_dim), GGMLType.Q6_K)
    f32("output_norm.weight", (cfg.dim,))
    raw("output.weight", (cfg.vocab_size, cfg.dim), GGMLType.Q6_K)
    w.write()


def ttft_sweep_main() -> None:
    """``python bench.py --ttft-sweep`` (env: LFKT_BENCH_TTFT_SWEEP=1):
    the long-context TTFT grid — context ladder × prefill-chunk sweep —
    emitting ONE JSON line per point so a round can bank the whole
    TTFT-vs-context curve as an artifact (round-6 targets: 8k < 500 ms,
    32k < 2.5 s).

    Axes (env-tunable): LFKT_BENCH_TTFT_CTXS (default
    ``2048,8192,16384,32768``) × LFKT_BENCH_TTFT_CHUNKS (default
    ``0,512,1024,2048``; 0 = monolithic bucket prefill).  Each chunked
    point runs the engine's double-buffered slice walk — the same
    prefill_chunk_jit programs and overlap bound Engine._prefill_padded
    serves with (LFKT_PREFILL_OVERLAP), so a point IS the serving
    configuration, not a proxy.  The flash kernel's fused-KV-block size
    rides LFKT_FLASH_KV_UNROLL (one value per process: it is baked into
    the compiled programs) and is stamped on every line.
    """
    import dataclasses
    from collections import deque

    import jax
    import jax.numpy as jnp

    from llama_fastapi_k8s_gpu_tpu.utils.config import (
        force_cpu_if_requested,
        knob,
    )

    force_cpu_if_requested()

    from llama_fastapi_k8s_gpu_tpu.utils.jaxcache import setup_compile_cache

    if jax.default_backend() != "cpu":
        repo = os.path.dirname(os.path.abspath(__file__))
        cache_dir = os.environ.setdefault(
            "LFKT_COMPILE_CACHE_DIR", os.path.join(repo, ".lfkt_xla_cache"))
        maybe_seed_compile_cache(repo, cache_dir)
    setup_compile_cache()

    from llama_fastapi_k8s_gpu_tpu.models.config import LLAMA3_8B, ModelConfig
    from llama_fastapi_k8s_gpu_tpu.models.generate import (
        prefill_chunk_jit,
        prefill_jit,
        sample_jit,
    )
    from llama_fastapi_k8s_gpu_tpu.models.llama import init_cache
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.probe import (
        probe_flash_attention,
    )
    from llama_fastapi_k8s_gpu_tpu.sampling.sample import (
        SamplingParams,
        sampling_tensors,
        seed_window,
    )

    preset = os.environ.get("LFKT_BENCH_PRESET", "llama3-8b")
    wfmt = os.environ.get("LFKT_BENCH_FMT", "q4km")
    tiny = preset == "tiny"
    if tiny:
        cfg0 = ModelConfig(vocab_size=512, dim=128, n_layers=2, n_heads=8,
                           n_kv_heads=4, ffn_dim=256, n_ctx=256)
        ctxs_def, chunks_def, attn_def = "64,128", "0,16", "xla"
    else:
        cfg0 = LLAMA3_8B
        ctxs_def, chunks_def, attn_def = \
            "2048,8192,16384,32768", "0,512,1024,2048", "pallas"
    ctxs = [int(c) for c in os.environ.get(
        "LFKT_BENCH_TTFT_CTXS", ctxs_def).split(",") if c]
    chunks = [int(c) for c in os.environ.get(
        "LFKT_BENCH_TTFT_CHUNKS", chunks_def).split(",") if c != ""]
    attn = os.environ.get("LFKT_BENCH_ATTN", attn_def)
    kv_dtype = os.environ.get("LFKT_KV_DTYPE", "bf16")
    overlap = int(knob("LFKT_PREFILL_OVERLAP"))
    kv_unroll = int(knob("LFKT_FLASH_KV_UNROLL"))

    dev = jax.devices()[0]
    print(f"{_INIT_MARK} {dev}", file=sys.stderr, flush=True)

    fallbacks = {}
    wfmt, reason = probe_fused_or_degrade(wfmt, "ttft-sweep")
    if reason is not None:
        fallbacks["fmt_fallback"] = reason
    if attn == "pallas":
        err = probe_flash_attention(quantized=kv_dtype == "int8")
        if err is not None:
            fallbacks["attn_fallback"] = f"flash attention: {err}"[:300]
            attn = "xla"

    params = synth_params_device(dataclasses.replace(cfg0, n_ctx=ctxs[0]),
                                 fmt=wfmt)
    fused_key = FUSED_KEYS.get(wfmt)
    if fused_key is not None and not any(
            isinstance(v, dict) and any(fk in v for fk in fused_key)
            for v in [*params["layers"].values(), params["output"]]):
        wfmt = "int8"
    sp = SamplingParams()
    st = sampling_tensors(sp)

    def one_ttft(cfg, prompt_len: int, chunk: int) -> float:
        """One prompt → first sampled token, seconds.  chunk=0: monolithic
        prefill_jit at the bucket; chunk>0: the engine's overlapped slice
        walk (zero-copy host views, async dispatch, depth-bounded)."""
        import numpy as np

        prompt = np.arange(1, prompt_len + 1, dtype=np.int32)
        cache = init_cache(cfg)
        t0 = time.time()
        if chunk <= 0:
            logits, cache = prefill_jit(
                params, cfg, jnp.asarray(prompt), jnp.int32(prompt_len),
                cache)
        else:
            logits = None
            inflight = deque()
            off = 0
            while off < prompt_len:
                n = min(chunk, prompt_len - off)
                lg, cache = prefill_chunk_jit(
                    params, cfg, jnp.asarray(prompt[off:off + n]),
                    jnp.int32(off), jnp.int32(n - 1), cache)
                logits = lg
                inflight.append(lg)
                if len(inflight) > overlap:
                    jax.block_until_ready(inflight.popleft())
                off += n
        window, wpos = seed_window(prompt.tolist())
        tok, *_ = sample_jit(logits, window, wpos, jax.random.PRNGKey(0),
                             st, cfg)
        int(tok)  # host fetch: the only reliable sync on the tunneled device
        return time.time() - t0

    for n_ctx in ctxs:
        cfg = dataclasses.replace(cfg0, n_ctx=n_ctx, attn_impl=attn,
                                  kv_dtype=kv_dtype)
        # half-context prompts, the convention of the existing 8k/16k/32k
        # PERF ladder (bench_8k/16k/32k_2026-08-01 artifacts)
        prompt_len = n_ctx // 2
        for chunk in chunks:
            if chunk > prompt_len:
                continue                  # one slice == monolithic: skip dup
            one_ttft(cfg, prompt_len, chunk)   # compile
            samples = sorted(one_ttft(cfg, prompt_len, chunk)
                             for _ in range(5))
            ms = samples[len(samples) // 2] * 1000.0
            kv_tag = "" if kv_dtype == "bf16" else f",kv-{kv_dtype}"
            line = {
                "metric": (f"ttft_ms_p50[ttft-sweep,{preset},{wfmt}{kv_tag}"
                           f",ctx{n_ctx},"
                           f"{'mono' if chunk <= 0 else f'chunk{chunk}'}]"),
                "value": round(ms, 1),
                "unit": "ms",
                "vs_baseline": 0.0,   # informational grid; no A10G analogue
                "n_ctx": n_ctx,
                "prompt_tokens": prompt_len,
                "prefill_chunk": chunk,
                "prefill_overlap": overlap,
                "attn_impl": attn,
                "kv_unroll": kv_unroll,
                "samples_ms": [round(s * 1000.0, 1) for s in samples],
                "device": str(dev),
            }
            line.update(fallbacks)
            emit_result(line)


def decode_unroll_sweep_main() -> None:
    """``python bench.py --decode-unroll-sweep`` (env:
    LFKT_BENCH_UNROLL_SWEEP=1): the layer-looped decode A/B grid
    (ISSUE 12 / ROADMAP item 2) — ``LFKT_BENCH_UNROLLS`` (default
    ``0,4,8,-1``) values of ``decode_layer_unroll``, one JSON line per
    point: steady-state decode step time (the HBM-roofline adjudication
    number), tok/s, and the deterministic per-step launch audit
    (obs/launches.py) so every banked line carries its own proof of the
    launch-count collapse.

    Weight format defaults to ``int8`` (env LFKT_BENCH_FMT): the fused
    K-quant layouts gate off the looped kernel (their block planes need a
    per-layer restack — docs/PERF.md round 8), so the sweep adjudicates
    launch overhead on the int8 path the kernel actually serves.  Each
    point is a ``dataclasses.replace`` of the same config — the knob is a
    ModelConfig field precisely so this sweep can retrace in-process
    instead of spawning one child per K."""
    import dataclasses
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from llama_fastapi_k8s_gpu_tpu.utils.config import force_cpu_if_requested

    force_cpu_if_requested()

    from llama_fastapi_k8s_gpu_tpu.utils.jaxcache import setup_compile_cache

    if jax.default_backend() != "cpu":
        repo = os.path.dirname(os.path.abspath(__file__))
        cache_dir = os.environ.setdefault(
            "LFKT_COMPILE_CACHE_DIR", os.path.join(repo, ".lfkt_xla_cache"))
        maybe_seed_compile_cache(repo, cache_dir)
    setup_compile_cache()

    from llama_fastapi_k8s_gpu_tpu.models.config import LLAMA3_8B, ModelConfig
    from llama_fastapi_k8s_gpu_tpu.models.generate import (
        generate_chunk_jit,
        init_state,
        prefill_jit,
        sample_jit,
    )
    from llama_fastapi_k8s_gpu_tpu.models.params import synth_params
    from llama_fastapi_k8s_gpu_tpu.obs.launches import decode_step_launches
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.decode_loop import (
        effective_unroll,
    )
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.probe import probe_decode_loop
    from llama_fastapi_k8s_gpu_tpu.sampling.sample import (
        SamplingParams,
        sampling_tensors,
        seed_window,
    )

    preset = os.environ.get("LFKT_BENCH_PRESET", "llama3-8b")
    wfmt = os.environ.get("LFKT_BENCH_FMT", "int8")
    tiny = preset == "tiny"
    if tiny:
        cfg0 = ModelConfig(vocab_size=512, dim=128, n_layers=2, n_heads=8,
                           n_kv_heads=4, ffn_dim=256, n_ctx=256)
        n_decode, unrolls_def = 32, "0,2,-1"
    else:
        cfg0 = LLAMA3_8B
        n_decode, unrolls_def = 256, "0,4,8,-1"
    cfg0 = dataclasses.replace(cfg0, kv_dtype=os.environ.get(
        "LFKT_KV_DTYPE", "bf16"))
    unrolls = [int(u) for u in os.environ.get(
        "LFKT_BENCH_UNROLLS", unrolls_def).split(",") if u.strip()]
    chunk = 8

    dev = jax.devices()[0]
    print(f"{_INIT_MARK} {dev}", file=sys.stderr, flush=True)

    fallbacks = {}
    if wfmt not in ("bf16", "int8"):
        # the fused layouts cannot serve the looped kernel — an explicit
        # LFKT_BENCH_FMT=q4km run degrades loudly rather than silently
        # measuring the per-layer path at every K
        fallbacks["fmt_fallback"] = (
            f"{wfmt} gates off the looped kernel; sweeping int8 instead")
        wfmt = "int8"
    params = synth_params(cfg0, fmt=wfmt)
    sp = SamplingParams()
    st = sampling_tensors(sp)

    err = probe_decode_loop(quantized=cfg0.kv_dtype == "int8",
                            int8_weights=wfmt == "int8",
                            n_kv=cfg0.n_kv_heads, head_dim=cfg0.head_dim,
                            n_ctx=cfg0.n_ctx, n_heads=cfg0.n_heads,
                            ffn_dim=cfg0.ffn_dim)
    if err is not None:
        fallbacks["loop_fallback"] = f"decode-loop probe: {err}"[:300]
        unrolls = [0]

    prompt = list(range(1, 17))

    def one_rate(cfg) -> float:
        """tokens/sec over ``n_decode`` steady-state decode tokens."""
        state = init_state(cfg)
        logits, state["cache"] = prefill_jit(
            params, cfg, jnp.asarray(prompt, jnp.int32),
            jnp.int32(len(prompt)), state["cache"])
        window, wpos = seed_window(prompt)
        tok, window, wpos, key = sample_jit(
            logits, window, wpos, state["key"], st, cfg)
        state.update(pos=jnp.int32(len(prompt)), token=tok,
                     window=window, wpos=wpos, key=key)
        state, toks = generate_chunk_jit(params, cfg, state, st,
                                         n_steps=chunk)   # warm / compile
        int(np.asarray(toks)[-1])
        t0 = time.time()
        for _ in range(n_decode // chunk):
            state, toks = generate_chunk_jit(params, cfg, state, st,
                                             n_steps=chunk)
        int(np.asarray(toks)[-1])   # host fetch: the only reliable sync
        return (n_decode // chunk) * chunk / (time.time() - t0)

    for K in unrolls:
        cfg = dataclasses.replace(cfg0, decode_layer_unroll=K)
        eff = effective_unroll(cfg)
        audit = decode_step_launches(params, cfg)
        rates = sorted(one_rate(cfg) for _ in range(3))
        rate = rates[1]
        ktag = "kall" if K == -1 else f"k{K}"
        line = {
            "metric": (f"decode_step_ms[decode-unroll,{preset},{wfmt},"
                       f"kv-{cfg.kv_dtype},{ktag}]"),
            "value": round(1000.0 / rate, 3),
            "unit": "ms",
            "vs_baseline": 0.0,   # informational grid; no A10G analogue
            "tokens_per_sec": round(rate, 2),
            "decode_layer_unroll": K,
            "effective_unroll": eff,
            "launches_per_step": audit["total"],
            "launches_in_loop": audit["in_loop"],
            "decode_chunk": chunk,
            "n_decode_tokens": n_decode,
            "samples_tok_s": [round(r, 2) for r in rates],
            "device": str(dev),
        }
        line.update(fallbacks)
        emit_result(line)


def replay_main() -> None:
    """``python bench.py --multiturn-replay`` (env: LFKT_BENCH_REPLAY=1):
    the block-paged radix prefix cache's payoff measurement —
    ``LFKT_BENCH_CONVS`` conversations sharing one system prompt, each
    replayed for ``LFKT_BENCH_TURNS`` turns through a serial engine with
    ``LFKT_KV_PAGED=1`` (parallel/kvpool.py).  Emits ONE JSON line:
    warm-turn TTFT p50 (prefix hit) vs cold p50 (full prefill), the
    prefix hit ratio, and the pool's event counters/occupancy — the
    artifact that shows warm-turn prefill work reduced by the matched
    prefix length.

    Runs against a synthesized tiny GGUF by default (CPU smoke,
    ``tests/test_bench_entrypoints.py``); point ``LFKT_BENCH_REPLAY_GGUF``
    at a real model file for chip sessions.
    """
    import statistics
    import tempfile

    import jax

    from llama_fastapi_k8s_gpu_tpu.utils.config import force_cpu_if_requested

    force_cpu_if_requested()

    from llama_fastapi_k8s_gpu_tpu.utils.jaxcache import setup_compile_cache

    setup_compile_cache()

    from llama_fastapi_k8s_gpu_tpu.engine import Engine
    from llama_fastapi_k8s_gpu_tpu.models.config import ModelConfig
    from llama_fastapi_k8s_gpu_tpu.testing import (
        TINY_CFG,
        write_tiny_llama_gguf,
    )

    preset = os.environ.get("LFKT_BENCH_PRESET", "tiny")
    n_convs = int(os.environ.get("LFKT_BENCH_CONVS", "3"))
    n_turns = int(os.environ.get("LFKT_BENCH_TURNS", "4"))
    max_tokens = int(os.environ.get("LFKT_BENCH_MAX_TOKENS", "12"))
    n_ctx = int(os.environ.get("LFKT_BENCH_NCTX", "512"))
    page_tokens = int(os.environ.get("LFKT_BENCH_PAGE_TOKENS", "16"))
    pool_pages = int(os.environ.get("LFKT_BENCH_POOL_PAGES", "0"))
    spill_pages = int(os.environ.get("LFKT_BENCH_SPILL_PAGES", "32"))
    gguf = os.environ.get("LFKT_BENCH_REPLAY_GGUF", "")
    if not gguf:
        gguf = os.path.join(tempfile.mkdtemp(prefix="lfkt-replay-"),
                            "tiny.gguf")
        write_tiny_llama_gguf(gguf, cfg=ModelConfig(
            **{**TINY_CFG.__dict__, "n_ctx": n_ctx}))

    dev = jax.devices()[0]
    print(f"{_INIT_MARK} {dev}", file=sys.stderr, flush=True)

    eng = Engine(gguf, n_ctx=n_ctx, decode_chunk=8,
                 max_gen_tokens=max_tokens,
                 prefill_buckets=(64, 128, 256, 512),
                 prefill_chunk=max(16, page_tokens),
                 kv_paged=True, kv_page_tokens=page_tokens,
                 kv_pool_pages=pool_pages, kv_spill_pages=spill_pages,
                 prefix_min=page_tokens)
    eng.warmup()
    stats0 = eng._kvpool.stats()     # warmup's own commits/misses excluded

    system = {"role": "system",
              "content": "You are a helpful, careful assistant who answers "
                         "briefly and precisely. " * 2}
    calls = []                       # (conv, turn, ttft_s, reused_tokens)
    for c in range(n_convs):
        msgs = [system,
                {"role": "user", "content": f"Conversation {c}: first ask."}]
        for t in range(n_turns):
            r = eng.create_chat_completion(msgs, temperature=0.0,
                                           max_tokens=max_tokens)
            tm = r["lfkt_timings"]
            calls.append((c, t, tm["ttft_s"], tm["prefix_reused_tokens"]))
            msgs = msgs + [
                {"role": "assistant",
                 "content": r["choices"][0]["message"]["content"]},
                {"role": "user", "content": f"Follow-up {t} of chat {c}."}]

    stats1 = eng._kvpool.stats()
    delta = {k: stats1[k] - stats0.get(k, 0) for k in stats1}
    consulted = delta["hits"] + delta["misses"]
    warm = sorted(ttft for _c, _t, ttft, reused in calls if reused > 0)
    cold = sorted(ttft for _c, _t, ttft, reused in calls if reused == 0)
    p50 = (lambda xs: statistics.median(xs) * 1000.0 if xs else 0.0)
    line = {
        # warm-turn TTFT is THE number multi-turn traffic feels; hit
        # ratio/reused tokens attribute it to the radix cache
        "metric": f"warm_ttft_ms_p50[kv-paged-replay,{preset}]",
        "value": round(p50(warm), 1),
        "unit": "ms",
        "vs_baseline": 0.0,          # informational; no A10G analogue
        "cold_ttft_ms_p50": round(p50(cold), 1),
        "warm_turns": len(warm),
        "cold_turns": len(cold),
        "prefix_hit_ratio": round(delta["hits"] / consulted, 3)
        if consulted else 0.0,
        "reused_tokens_total": delta["reused_tokens"],
        "conversations": n_convs,
        "turns_per_conversation": n_turns,
        "page_tokens": page_tokens,
        "pool": eng.kv_pool_occupancy(),
        "pool_events": delta,
        "per_turn": [
            {"conv": c, "turn": t, "ttft_ms": round(ttft * 1000.0, 1),
             "reused_tokens": reused}
            for c, t, ttft, reused in calls],
        "device": str(dev),
    }
    emit_result(line)


def child_main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    if os.environ.get("LFKT_BENCH_COLDSTART") == "1":
        coldstart_main()
        return
    if os.environ.get("LFKT_BENCH_TTFT_SWEEP") == "1":
        ttft_sweep_main()
        return
    if os.environ.get("LFKT_BENCH_UNROLL_SWEEP") == "1":
        decode_unroll_sweep_main()
        return
    if os.environ.get("LFKT_BENCH_REPLAY") == "1":
        replay_main()
        return

    import jax
    import numpy as np
    import jax.numpy as jnp

    from llama_fastapi_k8s_gpu_tpu.utils.config import force_cpu_if_requested

    force_cpu_if_requested()   # site-hook defense (one copy: utils/config)

    from llama_fastapi_k8s_gpu_tpu.utils.jaxcache import setup_compile_cache

    # Default the persistent-cache location on the accelerator: the driver
    # invokes `python bench.py` with a bare env, and without this it pays
    # ~60 s of remote compiles inside its own watchdog budget even when a
    # prior chip-suite run has already warmed the cache.  Repo-local (not
    # /tmp) so the warm state survives container restarts, which clear /tmp
    # — a restart mid-round previously cost the next bare run ~66 s of
    # recompiles plus a ~250 s cold synth-load path.
    if jax.default_backend() != "cpu":
        repo = os.path.dirname(os.path.abspath(__file__))
        cache_dir = os.environ.setdefault(
            "LFKT_COMPILE_CACHE_DIR", os.path.join(repo, ".lfkt_xla_cache"))
        maybe_seed_compile_cache(repo, cache_dir)
    setup_compile_cache()

    from llama_fastapi_k8s_gpu_tpu.models.config import LLAMA3_8B, ModelConfig
    from llama_fastapi_k8s_gpu_tpu.models.generate import (
        generate_chunk_jit,
        init_state,
        prefill_jit,
        sample_jit,
    )
    from llama_fastapi_k8s_gpu_tpu.sampling.sample import (
        SamplingParams,
        sampling_tensors,
        seed_window,
    )

    import dataclasses

    tiny = ModelConfig(vocab_size=512, dim=128, n_layers=2, n_heads=8,
                       n_kv_heads=4, ffn_dim=256, n_ctx=256)

    # Presets: tiny (CPU smoke) | llama3-8b (headline decode/TTFT) |
    # llama3-8b-8k (long-context: 4k prompt into an 8k ring via the Pallas
    # flash prefill kernel — the reference caps n_ctx at 1024, api.py:27).
    #
    # Headline defaults are the SERVING defaults (VERDICT r2 #1/#2): the
    # fused-Q4_K weight format (the baseline's named Q4_K_M config,
    # reference api.py:14) and the Pallas flash prefill that
    # engine.Engine(attn_impl="auto") resolves to on TPU with head_dim 128.
    preset = os.environ.get("LFKT_BENCH_PRESET", "llama3-8b")
    # q4km (file-fidelity Q4_K_M mix, the headline) | q5km (Q5_K_M mix)
    # | q4k | q8 | int8 | f16
    wfmt = os.environ.get("LFKT_BENCH_FMT", "q4km")
    fmt_label = wfmt
    if wfmt == "f16":
        # BASELINE config #3's F16 GGUF variant: an F16 file serves int8
        # (engine.py _probe_fused_format — bf16 8B can't share 16 GB HBM
        # with the KV cache).  The bench measures that serving grid under
        # its honest label.
        wfmt = "int8"
        fmt_label = "f16file-int8"
    if preset == "tiny":
        cfg, p_def, ctx_def, attn_def = tiny, 128, tiny.n_ctx, "xla"
    elif preset == "llama3-8b-8k":
        cfg, p_def, ctx_def, attn_def = LLAMA3_8B, 4096, 8192, "pallas"
    elif preset == "mistral-7b":
        # BASELINE config #4: Mistral-7B, sliding-window attention path
        # (v0.1's window=4096).  At the reference's n_ctx=1024 the window
        # exceeds the ring and masks nothing; run with LFKT_BENCH_NCTX=8192
        # LFKT_BENCH_PROMPT=4096 to see the flash kernel's window
        # block-skip actually truncate attention.
        from llama_fastapi_k8s_gpu_tpu.models.config import MISTRAL_7B

        mcfg = dataclasses.replace(MISTRAL_7B, sliding_window=4096)
        cfg, p_def, ctx_def, attn_def = mcfg, 128, MISTRAL_7B.n_ctx, "pallas"
    else:
        cfg, p_def, ctx_def, attn_def = LLAMA3_8B, 128, LLAMA3_8B.n_ctx, "pallas"
    # kv_dtype axis (same knob as the server, utils/config.py): int8 halves
    # the ring's HBM reads — the next BENCH round compares bf16 vs int8
    # decode throughput and max-lane headroom on one grid
    kv_dtype = os.environ.get("LFKT_KV_DTYPE", "bf16")
    cfg = dataclasses.replace(
        cfg,
        n_ctx=int(os.environ.get("LFKT_BENCH_NCTX", ctx_def)),
        attn_impl=os.environ.get("LFKT_BENCH_ATTN", attn_def),
        kv_dtype=kv_dtype,
    )
    prompt_len = int(os.environ.get("LFKT_BENCH_PROMPT", p_def))
    gen_tokens = int(os.environ.get(
        "LFKT_BENCH_TOKENS", "256" if preset != "tiny" else "32"))
    chunk = int(os.environ.get("LFKT_BENCH_CHUNK", "16"))
    # decode-chunk sweep (VERDICT r2 #8): measure several chunk sizes, take
    # the best as the headline and report the sweep so the engine default
    # (utils/config.py LFKT_DECODE_CHUNK) is chosen by data, not habit.
    sweep_env = os.environ.get(
        "LFKT_BENCH_SWEEP", "" if preset == "tiny" else "8,16,32")
    sweep = [int(c) for c in sweep_env.split(",") if c] or [chunk]
    if chunk not in sweep:
        sweep.insert(0, chunk)

    dev = jax.devices()[0]
    # tell the watchdog parent that backend init survived (the single-session
    # tunnel hangs or faults here when another process holds the device)
    print(f"{_INIT_MARK} {dev}", file=sys.stderr, flush=True)

    # compile-probe the risky Pallas kernels up front (ops/pallas/probe.py)
    # so a Mosaic failure degrades the config — with correct attribution in
    # the result JSON — instead of zeroing the whole headline
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.probe import (
        probe_flash_attention,
    )

    fallbacks = {}
    wfmt, reason = probe_fused_or_degrade(wfmt, "bench")
    if reason is not None:
        fallbacks["fmt_fallback"] = reason
        fmt_label = "int8"
    if cfg.attn_impl == "pallas":
        err = probe_flash_attention(quantized=cfg.kv_dtype == "int8")
        if err is not None:
            fallbacks["attn_fallback"] = f"flash attention: {err}"[:300]
            print(f"bench: {fallbacks['attn_fallback']}; using attn_impl=xla",
                  file=sys.stderr, flush=True)
            cfg = dataclasses.replace(cfg, attn_impl="xla")
    if cfg.kv_dtype == "int8":
        # mirror the engine's degrade path (engine.py): a failed quantize-
        # kernel probe pins the identical XLA write formulation
        from llama_fastapi_k8s_gpu_tpu.ops.pallas.kvquant import (
            force_xla_quant,
        )
        from llama_fastapi_k8s_gpu_tpu.ops.pallas.probe import probe_kv_quant

        err = probe_kv_quant()
        if err is not None:
            fallbacks["kv_quant_fallback"] = f"kv quantize: {err}"[:300]
            print(f"bench: {fallbacks['kv_quant_fallback']}; quantizing "
                  f"cache writes via XLA", file=sys.stderr, flush=True)
            force_xla_quant(True)

    t0 = time.time()
    params = synth_params_device(cfg, fmt=wfmt)
    # label honesty: report the fused format only if any tensor actually
    # got the layout (tiny shapes fall back to int8)
    fused_key = FUSED_KEYS.get(wfmt)
    if fused_key is not None and not any(
            isinstance(v, dict) and any(fk in v for fk in fused_key)
            for v in [*params["layers"].values(), params["output"]]):
        wfmt = fmt_label = "int8"
    # sync: reduce EVERY leaf to a scalar and fetch it (block_until_ready is
    # unreliable on the tunneled platform; partial fetches leak into compile_s)
    float(sum(x.sum().astype(jnp.float32)
              for x in jax.tree_util.tree_leaves(params)))
    load_s = time.time() - t0

    sp = SamplingParams()
    st = sampling_tensors(sp)
    prompt = list(range(1, prompt_len + 1))
    tokens = jnp.asarray(prompt, jnp.int32)

    def one_request(state):
        logits, cache = prefill_jit(params, cfg, tokens, jnp.int32(prompt_len),
                                    state["cache"])
        window, wpos = seed_window(prompt)
        tok, window, wpos, key = sample_jit(logits, window, wpos,
                                            jax.random.PRNGKey(0), st, cfg)
        int(tok)  # host fetch: the only reliable sync on the tunneled device
        return {
            "cache": cache, "pos": jnp.int32(prompt_len), "token": tok,
            "window": window, "wpos": wpos, "key": key,
        }

    # warmup: compile prefill + every swept decode-chunk program
    state = one_request(init_state(cfg))
    for c in sweep:
        state, _ = generate_chunk_jit(params, cfg, state, st, n_steps=c)
    int(state["pos"])
    compile_s = time.time() - t0 - load_s

    # TTFT: prompt → first sampled token (steady-state, median of 5)
    ttfts = []
    for _ in range(5):
        t1 = time.time()
        state = one_request(state)
        ttfts.append(time.time() - t1)
    ttft_ms = sorted(ttfts)[len(ttfts) // 2] * 1000

    # decode throughput per chunk size: gen_tokens steady-state tokens each
    state = one_request(state)
    chunk_sweep = {}
    for c in sweep:
        n_chunks = max(1, gen_tokens // c)
        t2 = time.time()
        for _ in range(n_chunks):
            state, toks = generate_chunk_jit(params, cfg, state, st, n_steps=c)
        np.asarray(toks)  # chunks chain through donated state: one fetch syncs
        decode_s = time.time() - t2
        chunk_sweep[str(c)] = round((n_chunks * c) / decode_s, 2)
    chunk = max(sweep, key=lambda c: chunk_sweep[str(c)])
    tok_s = chunk_sweep[str(chunk)]

    from llama_fastapi_k8s_gpu_tpu.models.llama import cache_nbytes

    # label honesty: a non-default KV dtype gets its own metric key so a
    # BENCH round can carry bf16 and int8 rows side by side
    kv_tag = "" if cfg.kv_dtype == "bf16" else f",kv-{cfg.kv_dtype}"
    result = {
        "metric": (f"decode_tokens_per_sec_per_chip"
                   f"[{preset},{fmt_label}{kv_tag},synthetic]"),
        "value": round(tok_s, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_s / A10G_Q4KM_8B_TOK_S, 3),
        "ttft_ms_p50": round(ttft_ms, 1),
        "prompt_tokens": prompt_len,
        "n_ctx": cfg.n_ctx,
        "attn_impl": cfg.attn_impl,
        "kv_dtype": cfg.kv_dtype,
        "kv_cache_bytes": cache_nbytes(cfg),
        "gen_tokens": max(1, gen_tokens // chunk) * chunk,
        "decode_chunk": chunk,
        "chunk_sweep": chunk_sweep,
        "device": str(dev),
        "load_s": round(load_s, 1),
        "compile_s": round(compile_s, 1),
    }
    result.update(fallbacks)
    emit_result(result)


# ---------------------------------------------------------------------------
# parent: watchdog orchestrator (no jax import — must stay hang-proof)
# ---------------------------------------------------------------------------

def _preflight_warn() -> None:
    """Best-effort stderr warning if another python process might hold the
    single-session device tunnel (round-1 failure cause: a stale server)."""
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,args"], capture_output=True, text=True,
            timeout=5).stdout
    except Exception:
        return
    me = os.getpid()
    for line in out.splitlines():
        parts = line.strip().split(None, 2)
        if len(parts) < 3 or not parts[0].isdigit():
            continue
        pid, exe, rest = int(parts[0]), parts[1], parts[2]
        if pid in (me, os.getppid()) or "python" not in os.path.basename(exe):
            continue
        if "-m llama_fastapi_k8s_gpu_tpu" in rest or "bench.py" in rest:
            print(f"bench.py preflight: possible device-holding process: "
                  f"{line.strip()[:160]}", file=sys.stderr, flush=True)


def _kill(proc: subprocess.Popen) -> bool:
    """Terminate the child; returns False if it survived SIGKILL (stuck in
    uninterruptible I/O on the hung tunnel) — the caller must NOT spawn
    another child against the single-session device in that case."""
    for sig in (signal.SIGTERM, signal.SIGKILL):
        if proc.poll() is not None:
            return True
        try:
            proc.send_signal(sig)
        except ProcessLookupError:
            return True
        try:
            proc.wait(timeout=5)
            return True
        except subprocess.TimeoutExpired:
            continue
    return proc.poll() is not None


def _run_attempt(init_timeout: float, total_timeout: float):
    """One child run. Returns (json_line | None, error_str | None, retriable).

    ``retriable=False`` means another attempt cannot help: either the child
    failed deterministically (e.g. ImportError — fast exit with no backend
    error in stderr) or it could not be killed and still holds the
    single-session device tunnel."""
    env = dict(os.environ, LFKT_BENCH_CHILD="1")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)

    init_seen = threading.Event()
    stdout_lines: list[str] = []
    stderr_tail: list[str] = []

    def read_out():
        for line in proc.stdout:
            line = line.strip()
            if line:
                stdout_lines.append(line)

    def read_err():
        for line in proc.stderr:
            line = line.rstrip()
            if _INIT_MARK in line:
                init_seen.set()
            stderr_tail.append(line)
            del stderr_tail[:-40]

    th_o = threading.Thread(target=read_out, daemon=True)
    th_e = threading.Thread(target=read_err, daemon=True)
    th_o.start(); th_e.start()

    start = time.monotonic()
    err = None
    retriable = True
    while True:
        rc = proc.poll()
        if rc is not None:
            break
        waited = time.monotonic() - start
        if not init_seen.is_set() and waited > init_timeout:
            err = (f"backend init did not complete within {init_timeout:.0f}s "
                   f"(single-session device tunnel hung/held?)")
            if not _kill(proc):
                err += ("; child UNKILLABLE and still holds the device "
                        "tunnel — not retrying")
                retriable = False
            break
        if waited > total_timeout:
            err = f"bench did not finish within {total_timeout:.0f}s"
            if not _kill(proc):
                err += ("; child UNKILLABLE and still holds the device "
                        "tunnel — not retrying")
                retriable = False
            break
        time.sleep(0.5)
    th_o.join(timeout=5); th_e.join(timeout=5)

    metric_lines = []
    for line in stdout_lines:
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                metric_lines.append(line)
        except ValueError:
            continue
    if metric_lines and err is None and proc.poll() == 0:
        # multi-point modes (--ttft-sweep) emit one line per grid point;
        # the single-metric modes emit exactly one — forward them all.
        # Success requires a CLEAN exit: a sweep child killed mid-grid
        # (timeout, OOM at the 32k point) has printed a silently partial
        # grid, and banking it as complete would drop exactly the rows
        # the round targets — retry/fail instead.
        return metric_lines, None, True
    if metric_lines:
        cause = err or f"rc={proc.poll()}"
        err = (f"child emitted {len(metric_lines)} metric line(s) but did "
               f"not finish cleanly ({cause}); discarding the partial grid")
    if err is None:
        tail = " | ".join(stderr_tail[-6:])[-600:]
        err = f"child exited rc={proc.poll()} without a result: {tail}"
        # Deterministic Python failures (bad env var, ImportError, div-by-0)
        # cannot be fixed by retrying; transient device faults (UNAVAILABLE —
        # the round-1 failure mode — and friends) can.  Classify by stderr;
        # an empty tail is ambiguous, so retry it.
        transient = not tail or any(m in tail for m in (
            "UNAVAILABLE", "Unavailable", "RESOURCE_EXHAUSTED", "DEADLINE",
            "INTERNAL", "ABORTED", "initialize backend", "tunnel"))
        retriable = transient
    return None, err, retriable


def main() -> None:
    if "--ttft-sweep" in sys.argv[1:]:
        # flag → env so the watchdog-spawned child (argument-less) sees it
        os.environ["LFKT_BENCH_TTFT_SWEEP"] = "1"
    if "--decode-unroll-sweep" in sys.argv[1:]:
        os.environ["LFKT_BENCH_UNROLL_SWEEP"] = "1"
    if "--multiturn-replay" in sys.argv[1:]:
        os.environ["LFKT_BENCH_REPLAY"] = "1"
    if os.environ.get("LFKT_BENCH_CHILD") == "1":
        child_main()
        return

    def env_num(name: str, default: float) -> float:
        # the parent must never die before printing its JSON line, so a
        # malformed knob falls back to the default instead of raising
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            print(f"bench.py: ignoring malformed {name}", file=sys.stderr)
            return default

    _preflight_warn()
    # Fewer, longer attempts (round-4 lesson): the device grant can queue
    # for many minutes behind a stale session, and every child killed at
    # its init deadline becomes ANOTHER stale claimant that pushes the
    # grant further out.  3 x 420 s covers the same wall clock as the old
    # 5 x 180 s with two fewer kills.
    attempts = max(1, int(env_num("LFKT_BENCH_ATTEMPTS", 3)))
    init_timeout = env_num("LFKT_BENCH_INIT_TIMEOUT", 420)
    total_timeout = env_num("LFKT_BENCH_TOTAL_TIMEOUT", 1500)
    backoff = env_num("LFKT_BENCH_BACKOFF", 10)
    # hard cap across ALL attempts+backoffs, so an external harness timeout
    # can't kill the parent before the guaranteed JSON line is printed
    deadline = time.monotonic() + env_num("LFKT_BENCH_DEADLINE", 3000)

    errors: list[str] = []
    for i in range(attempts):
        if i:
            gap = min(backoff * (2 ** (i - 1)),
                      max(0.0, deadline - time.monotonic() - 60))
            print(f"bench.py: attempt {i} failed ({errors[-1][:200]}); "
                  f"retrying in {gap:.0f}s", file=sys.stderr, flush=True)
            time.sleep(gap)
        remaining = deadline - time.monotonic()
        if remaining < 60:
            errors.append(f"overall deadline reached after {i} attempt(s)")
            break
        lines, err, retriable = _run_attempt(
            min(init_timeout, remaining), min(total_timeout, remaining))
        if lines is not None:
            for line in lines:
                print(line, flush=True)
            return
        errors.append(err or "unknown error")
        if not retriable:
            break

    sweep = os.environ.get("LFKT_BENCH_TTFT_SWEEP") == "1"
    unroll_sweep = os.environ.get("LFKT_BENCH_UNROLL_SWEEP") == "1"
    replay = os.environ.get("LFKT_BENCH_REPLAY") == "1"
    # replay's child defaults to the tiny synthetic preset; the failure
    # line must carry the SAME metric name a success would
    preset = os.environ.get("LFKT_BENCH_PRESET",
                            "tiny" if replay else "llama3-8b")
    wfmt = os.environ.get("LFKT_BENCH_FMT",
                          "int8" if unroll_sweep else "q4km")
    if replay:
        metric = f"warm_ttft_ms_p50[kv-paged-replay,{preset}]"
    elif unroll_sweep:
        metric = f"decode_step_ms[decode-unroll,{preset},{wfmt}]"
    elif sweep:
        metric = f"ttft_ms_p50[ttft-sweep,{preset},{wfmt}]"
    else:
        metric = f"decode_tokens_per_sec_per_chip[{preset},{wfmt},synthetic]"
    emit_result({
        "metric": metric,
        "value": 0.0,
        "unit": "ms" if sweep or unroll_sweep or replay
                else "tokens/sec/chip",
        "vs_baseline": 0.0,
        "error": f"{len(errors)} attempt(s) failed; last: {errors[-1][:500]}",
        "attempts": len(errors),
    })
    sys.exit(1)  # failure JSON is on stdout either way; CI must see rc!=0


if __name__ == "__main__":
    main()

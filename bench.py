"""Benchmark: Llama-3-8B decode throughput + prefill TTFT on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The reference's engine (llama.cpp cuBLAS, reference docker/Dockerfile.base:30)
publishes no numbers; the driver-provided target (BASELINE.md) is A10G-parity
decode throughput for Llama-3-8B Q4_K_M — llama.cpp-class engines decode
Q4_K_M 8B on an A10G at roughly 30-60 tok/s; vs_baseline is computed against
the 45 tok/s midpoint.

The model is the real 8B architecture (models/config.py LLAMA3_8B) with
synthesized int8 weights (zero-egress environment: weights cannot be
downloaded, and decode speed is value-independent — it is bound by HBM
bytes/token, which synthetic weights reproduce exactly).

Run standalone and ALONE (the device tunnel is single-session):
    python bench.py            # real chip, 8B
    LFKT_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python bench.py   # smoke

Timing note: on the tunneled device platform ``jax.block_until_ready`` can
return before execution finishes, so every measured section ends with a
small host fetch (``int(scalar)`` / ``np.asarray`` of a few tokens), which
is the only reliable sync.  All decode chunks are data-dependent (donated
state chain), so one final fetch syncs the whole chain.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np
import jax.numpy as jnp

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # a site hook may pre-register the tunneled device platform and override
    # the env var at startup; the post-import config update wins if no
    # backend is initialized yet (same defense as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from llama_fastapi_k8s_gpu_tpu.models.config import LLAMA3_8B, ModelConfig  # noqa: E402
from llama_fastapi_k8s_gpu_tpu.models.generate import (  # noqa: E402
    generate_chunk_jit,
    init_state,
    prefill_jit,
    sample_jit,
)
from llama_fastapi_k8s_gpu_tpu.sampling.sample import (  # noqa: E402
    SamplingParams,
    sampling_tensors,
    seed_window,
)

A10G_Q4KM_8B_TOK_S = 45.0  # midpoint of the 30-60 tok/s llama.cpp A10G range

TINY = ModelConfig(vocab_size=512, dim=128, n_layers=2, n_heads=8,
                   n_kv_heads=4, ffn_dim=256, n_ctx=256)


def synth_int8_device(cfg: ModelConfig, seed: int = 0, fmt: str = "int8") -> dict:
    """Device-side random params (no multi-GB host RNG / transfer).

    ``fmt="int8"``: per-channel int8 (ops/linear.py).  ``fmt="q4k"``: the
    fused Q4_K kernel layout (ops/pallas/qmatmul.py) — random packed nibbles
    + small scales; decode bandwidth is value-independent, so this measures
    exactly what real Q4_K weights would.
    """
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.qmatmul import TK, q4k_compatible

    kv_dim = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    key = jax.random.PRNGKey(seed)

    def lin(k, out_dim, in_dim):
        if fmt == "q4k" and q4k_compatible(out_dim, in_dim, for_tpu=True):
            qs = jax.random.randint(k, (L, out_dim, in_dim // 2),
                                    -128, 128, jnp.int8)
            sm = jnp.full((L, in_dim // TK, out_dim, 128),
                          (in_dim ** -0.5) / 8.0, jnp.bfloat16)
            return {"qs": qs, "sm": sm}
        q = jax.random.randint(k, (L, out_dim, in_dim), -127, 128, jnp.int8)
        s = jnp.full((L, out_dim), (in_dim ** -0.5) / 127.0, jnp.float32)
        return {"q": q, "s": s}

    ks = jax.random.split(key, 8)
    emb = (jax.random.normal(ks[0], (cfg.vocab_size, cfg.dim), jnp.bfloat16)
           * (cfg.dim ** -0.5))
    return {
        "tok_emb": emb,
        "layers": {
            "attn_norm": jnp.ones((L, cfg.dim), jnp.float32),
            "wq": lin(ks[1], cfg.dim, cfg.dim),
            "wk": lin(ks[2], kv_dim, cfg.dim),
            "wv": lin(ks[3], kv_dim, cfg.dim),
            "wo": lin(ks[4], cfg.dim, cfg.dim),
            "ffn_norm": jnp.ones((L, cfg.dim), jnp.float32),
            "w_gate": lin(ks[5], cfg.ffn_dim, cfg.dim),
            "w_up": lin(ks[6], cfg.ffn_dim, cfg.dim),
            "w_down": lin(ks[7], cfg.dim, cfg.ffn_dim),
        },
        "out_norm": jnp.ones(cfg.dim, jnp.float32),
        "output": (
            {
                "qs": jax.random.randint(ks[0], (cfg.vocab_size, cfg.dim // 2),
                                         -128, 128, jnp.int8),
                "sm": jnp.full((cfg.dim // TK, cfg.vocab_size, 128),
                               (cfg.dim ** -0.5) / 8.0, jnp.bfloat16),
            }
            if fmt == "q4k" and q4k_compatible(cfg.vocab_size, cfg.dim, for_tpu=True)
            else {
                "q": jax.random.randint(ks[0], (cfg.vocab_size, cfg.dim),
                                        -127, 128, jnp.int8),
                "s": jnp.full((cfg.vocab_size,), (cfg.dim ** -0.5) / 127.0,
                              jnp.float32),
            }
        ),
    }


def main():
    preset = os.environ.get("LFKT_BENCH_PRESET", "llama3-8b")
    wfmt = os.environ.get("LFKT_BENCH_FMT", "int8")  # int8 | q4k
    cfg = TINY if preset == "tiny" else LLAMA3_8B
    prompt_len = 128
    gen_tokens = int(os.environ.get("LFKT_BENCH_TOKENS", "256" if preset != "tiny" else "32"))
    chunk = int(os.environ.get("LFKT_BENCH_CHUNK", "16"))

    dev = jax.devices()[0]
    t0 = time.time()
    params = synth_int8_device(cfg, fmt=wfmt)
    # label honesty: report q4k only if any tensor actually got the layout
    if wfmt == "q4k" and not any(
            isinstance(v, dict) and "qs" in v
            for v in [*params["layers"].values(), params["output"]]):
        wfmt = "int8"
    # sync: reduce EVERY leaf to a scalar and fetch it (block_until_ready is
    # unreliable on the tunneled platform; partial fetches leak into compile_s)
    float(sum(x.sum().astype(jnp.float32)
              for x in jax.tree_util.tree_leaves(params)))
    load_s = time.time() - t0

    sp = SamplingParams()
    st = sampling_tensors(sp)
    prompt = list(range(1, prompt_len + 1))
    tokens = jnp.asarray(prompt, jnp.int32)

    def one_request(state):
        logits, cache = prefill_jit(params, cfg, tokens, jnp.int32(prompt_len),
                                    state["cache"])
        window, wpos = seed_window(prompt)
        tok, window, wpos, key = sample_jit(logits, window, wpos,
                                            jax.random.PRNGKey(0), st, cfg)
        int(tok)  # host fetch: the only reliable sync on the tunneled device
        return {
            "cache": cache, "pos": jnp.int32(prompt_len), "token": tok,
            "window": window, "wpos": wpos, "key": key,
        }

    # warmup: compile prefill + decode-chunk
    state = one_request(init_state(cfg))
    state, _ = generate_chunk_jit(params, cfg, state, st, n_steps=chunk)
    int(state["pos"])
    compile_s = time.time() - t0 - load_s

    # TTFT: prompt → first sampled token (steady-state, median of 5)
    ttfts = []
    for _ in range(5):
        t1 = time.time()
        state = one_request(state)
        ttfts.append(time.time() - t1)
    ttft_ms = sorted(ttfts)[len(ttfts) // 2] * 1000

    # decode throughput: gen_tokens steady-state tokens
    state = one_request(state)
    n_chunks = max(1, gen_tokens // chunk)
    t2 = time.time()
    for _ in range(n_chunks):
        state, toks = generate_chunk_jit(params, cfg, state, st, n_steps=chunk)
    np.asarray(toks)  # chunks chain through donated state: one fetch syncs all
    decode_s = time.time() - t2
    tok_s = (n_chunks * chunk) / decode_s

    result = {
        "metric": f"decode_tokens_per_sec_per_chip[{preset},{wfmt},synthetic]",
        "value": round(tok_s, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tok_s / A10G_Q4KM_8B_TOK_S, 3),
        "ttft_ms_p50": round(ttft_ms, 1),
        "prompt_tokens": prompt_len,
        "gen_tokens": n_chunks * chunk,
        "decode_chunk": chunk,
        "device": str(dev),
        "load_s": round(load_s, 1),
        "compile_s": round(compile_s, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Server-level latency bench: p50 TTFT on the real ``/response`` path.

BASELINE.json's TTFT metric is defined at the **server boundary** — the
reference's hot path runs FastAPI → queue → semaphore → llama.cpp
(reference api.py:118-173).  The engine-level TTFT in ``bench.py`` omits the
tokenizer, chat template, HTTP framing, and queue hop; this bench closes that
gap (VERDICT r2 #3): it starts the in-tree httpd serving the real ASGI app
with a real Engine (synthetic 8B weights on the chip, full-scale synthetic
BPE vocab so tokenize cost is honest), fires loopback POSTs shaped like the
reference's ``BotMessageRequest``, and reports:

- ``ttft_ms_p50_server``  — time to the first *content* SSE chunk on
  ``/response/stream`` (true first-token latency through the whole stack);
- ``latency_ms_p50``      — full ``/response`` round trip (the non-streaming
  endpoint returns only the complete generation, so its latency is
  TTFT + decode of ``max_tokens``).

Prints ONE JSON line.  Run ALONE (single-session device tunnel):
    python bench_server.py                      # real chip, 8B q4k
    LFKT_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python bench_server.py   # smoke
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request


def emit_result(d: dict) -> None:
    """One provenance-stamped bench JSON line — single implementation in
    bench.py (shared like probe_fused_or_degrade, so the benches can't
    drift in what they stamp or how failure lines are guaranteed)."""
    from bench import emit_result as _emit

    _emit(d)


A10G_TTFT_MS = 300.0  # BASELINE.md: p50 TTFT < 300 ms on /response


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    t_start = time.time()

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import dataclasses

    from bench import synth_params_device
    from llama_fastapi_k8s_gpu_tpu.engine import Engine
    from llama_fastapi_k8s_gpu_tpu.models.config import LLAMA3_8B, ModelConfig
    from llama_fastapi_k8s_gpu_tpu.server import httpd
    from llama_fastapi_k8s_gpu_tpu.server.app import create_app
    from llama_fastapi_k8s_gpu_tpu.testing import synth_bpe_vocab
    from llama_fastapi_k8s_gpu_tpu.tokenizer import BPETokenizer

    preset = os.environ.get("LFKT_BENCH_PRESET", "llama3-8b")
    wfmt = os.environ.get("LFKT_BENCH_FMT", "q4km")
    n_req = int(os.environ.get("LFKT_BENCH_N_REQ", "12"))
    max_tokens = int(os.environ.get("LFKT_BENCH_MAX_TOKENS", "48"))
    port = int(os.environ.get("LFKT_BENCH_PORT", "8017"))
    spec_decode = os.environ.get("LFKT_SPEC_DECODE", "off")
    spec_draft = int(os.environ.get("LFKT_SPEC_DRAFT", "8"))
    fullctx = os.environ.get("LFKT_BENCH_FULLCTX") == "1"
    multiturn = os.environ.get("LFKT_BENCH_MULTITURN") == "1"
    # mixed-model arm (docs/MULTIMODEL.md): serve TWO models from one
    # process through the continuous scheduler and alternate model=
    # across lanes via /v1/chat/completions — per-model agg tok/s says
    # what co-residency costs vs a single-model pod
    mixed_models = os.environ.get("LFKT_BENCH_MIXED_MODELS") == "1"
    # disagg arm (serving/disagg/): the two-role LOOPBACK split —
    # role=both on one serial paged engine, so every cold prompt's
    # prefill crosses the full page wire (serialize → TCP → deserialize
    # → import → restore) — reported against a role-off control run of
    # the same fresh-prompt workload.  On one host this measures the
    # transfer OVERHEAD the split pays; across hosts the same wire buys
    # the prefill/decode interference removal (docs/RUNBOOK.md
    # "Operating a split prefill/decode fleet").
    disagg_arm = os.environ.get("LFKT_BENCH_DISAGG") == "1"
    from llama_fastapi_k8s_gpu_tpu.utils.config import env_bool

    lane_prefix = env_bool("LFKT_LANE_PREFIX_CACHE")
    if multiturn:
        # turn 1 is the no-reuse baseline and follow-ups are the sample;
        # fewer than 2 turns leaves nothing to report
        n_req = max(2, n_req)

    if preset == "tiny":
        cfg = ModelConfig(vocab_size=0, dim=128, n_layers=2, n_heads=8,
                          n_kv_heads=4, ffn_dim=256, n_ctx=256)
        n_merges = 2_000
    else:
        cfg = dataclasses.replace(LLAMA3_8B, attn_impl=os.environ.get(
            "LFKT_BENCH_ATTN", "pallas" if jax.default_backend() == "tpu"
            else "xla"))
        n_merges = 280_000

    dev = jax.devices()[0]
    from bench import FUSED_KEYS, probe_fused_or_degrade

    wfmt, _ = probe_fused_or_degrade(wfmt, "bench_server")
    tokens, merges, types = synth_bpe_vocab(n_merges=n_merges)
    cfg = dataclasses.replace(cfg, vocab_size=len(tokens))
    tok = BPETokenizer(tokens, merges, types,
                       bos_id=tokens.index("<|begin_of_text|>"),
                       eos_id=tokens.index("<|eot_id|>"))
    params = synth_params_device(cfg, fmt=wfmt)
    fused_key = FUSED_KEYS.get(wfmt)
    if fused_key is not None and not any(
            isinstance(v, dict) and any(fk in v for fk in fused_key)
            for v in [*params["layers"].values(), params["output"]]):
        wfmt = "int8"  # label honesty: tiny shapes fall back
    # kv_dtype axis (docs/KV_CACHE.md): the engines read it off cfg, and a
    # non-default dtype rides the wfmt label so every result metric keys
    # its arm (same convention as bench.py's kv-int8 tag)
    kv_dtype = os.environ.get("LFKT_KV_DTYPE", "bf16")
    cfg = dataclasses.replace(cfg, kv_dtype=kv_dtype)
    if kv_dtype != "bf16":
        wfmt = f"{wfmt},kv-{kv_dtype}"
    batch = int(os.environ.get("LFKT_BENCH_BATCH", "1"))
    if disagg_arm and batch > 1:
        raise SystemExit(
            "LFKT_BENCH_DISAGG=1 measures the serial two-role loopback; "
            "set LFKT_BENCH_BATCH=1 (the continuous-scheduler split rides "
            "the same client — bench it via LFKT_DISAGG_ROLE on a real "
            "two-process fleet)")
    if mixed_models and batch <= 1:
        raise SystemExit(
            "LFKT_BENCH_MIXED_MODELS=1 needs LFKT_BENCH_BATCH>1: the arm "
            "measures models interleaving across scheduler lanes")
    # fleet arm (serving/fleet/): TWO in-process serial paged replicas
    # behind the prefix-affinity router, multi-turn replay affinity-on vs
    # the round-robin control — the hit-ratio/warm-TTFT answer to "does
    # the router actually keep conversations on their warm replica"
    fleet_arm = os.environ.get("LFKT_BENCH_FLEET") == "1"
    if fleet_arm and (batch > 1 or mixed_models or disagg_arm or multiturn):
        raise SystemExit(
            "LFKT_BENCH_FLEET=1 is its own arm (two serial paged replicas "
            "behind the router): drop LFKT_BENCH_BATCH/MULTITURN/"
            "MIXED_MODELS/DISAGG")
    # the app sizes its in-flight permit pool from settings.batch_size
    # (server/app.py: Semaphore(max(1, settings.batch_size))) — without
    # this the server serializes requests at inflight=1 and a B-lane
    # engine decodes one lane at a time (measured: batch=4 aggregate
    # throughput equal to a single lane's).  The mixed arm serves TWO
    # B-lane engines, so its permit pool must cover both fleets.
    os.environ["LFKT_BATCH_SIZE"] = str(2 * batch if mixed_models else batch)
    from llama_fastapi_k8s_gpu_tpu.utils.config import Settings, get_settings

    settings = get_settings()

    if fleet_arm:
        # LFKT_BENCH_FLEET=1: two replicas (serial paged engines, same
        # synthetic weights — bit-identical greedy twins) each behind a
        # real httpd, fronted by a real FleetRouter; C conversations x T
        # turns replayed round-robin ACROSS conversations, so
        # consecutive requests belong to different conversations (the
        # k8s traffic shape).  Phase A routes policy=affinity, phase B
        # (fresh replicas: counters and radix trees start cold) routes
        # the identical replay policy=roundrobin.  Reported per phase:
        # the aggregate token-weighted prefix hit ratio
        # (prefix_cache_reused_tokens_total / tokens_prompt_total across
        # both replicas — the fraction of prompt tokens served from
        # cached KV pages) and warm (turn>=2) streamed TTFT p50.  C is
        # ODD on purpose: with 2 replicas an even C makes round-robin
        # accidentally affine ((t*C+c) mod 2 == c mod 2), flattering the
        # control.
        from llama_fastapi_k8s_gpu_tpu.serving.fleet.peers import PeerTable
        from llama_fastapi_k8s_gpu_tpu.serving.fleet.router import (
            FleetRouter,
        )

        convs = int(os.environ.get("LFKT_BENCH_CONVS", "3"))
        if convs % 2 == 0:
            convs += 1
        turns = max(2, int(os.environ.get("LFKT_BENCH_TURNS", "3")))
        page_tokens = (16 if preset == "tiny"
                       else settings.kv_page_tokens)
        pq = lambda v, q: v[min(len(v) - 1, int(q * len(v)))]  # noqa: E731

        def wait_http(url: str, deadline_s: float = 120.0) -> None:
            deadline = time.time() + deadline_s
            while True:
                try:
                    urllib.request.urlopen(url, timeout=5)
                    return
                except Exception:  # noqa: BLE001 — booting
                    if time.time() > deadline:
                        raise
                    time.sleep(0.2)

        def start_replica(rport: int):
            reng = Engine.from_parts(
                params, cfg, tok, template_kind="llama3",
                max_gen_tokens=max_tokens, attn_impl=cfg.attn_impl,
                decode_chunk=settings.decode_chunk,
                prefill_chunk=settings.prefill_chunk,
                kv_paged=True, kv_page_tokens=page_tokens)
            reng.warmup()
            rapp = create_app(engine=reng)
            threading.Thread(
                target=lambda: asyncio.run(
                    httpd.serve(rapp, host="127.0.0.1", port=rport)),
                daemon=True).start()
            wait_http(f"http://127.0.0.1:{rport}/health")
            return reng

        def start_router(rport: int, peer_ports: list, policy: str):
            table = PeerTable(
                peers=[f"127.0.0.1:{p}" for p in peer_ports],
                probe_seconds=1.0).start()
            router = FleetRouter(table, policy=policy)
            threading.Thread(
                target=lambda: asyncio.run(
                    router.serve("127.0.0.1", rport)),
                daemon=True).start()
            wait_http(f"http://127.0.0.1:{rport}/health/ready")
            return router

        def fleet_stream_ttft(rport: int, body: bytes):
            req = urllib.request.Request(
                f"http://127.0.0.1:{rport}/response/stream", data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            first, err, parts = None, None, []
            with urllib.request.urlopen(req, timeout=600) as r:
                for raw in r:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data:"):
                        continue
                    body_ln = line[5:].strip()
                    if body_ln == "[DONE]":
                        break
                    evt = json.loads(body_ln)
                    if "error" in evt:
                        err = str(evt["error"])
                        break
                    c = evt["choices"][0]["delta"].get("content")
                    if c:
                        if first is None:
                            first = (time.perf_counter() - t0) * 1e3
                        parts.append(c)
            if first is None:
                first = (time.perf_counter() - t0) * 1e3
            return first, "".join(parts), err

        def replica_metric(rport: int, name: str) -> float:
            """Sum of one family's series (labeled or not) on a replica."""
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}/metrics", timeout=30) as r:
                text = r.read().decode()
            total = 0.0
            for ln in text.splitlines():
                head, _, val = ln.rpartition(" ")
                if head == name or head.startswith(name + "{"):
                    total += float(val)
            return total

        def fleet_payload(c: int, history: list) -> bytes:
            # distinct persona + opener per conversation: affinity keys
            # differ AND the radix shares nothing across conversations,
            # so reuse measured here is conversation affinity, not the
            # shared-system-prompt effect PR 6 already banked
            return json.dumps({
                "bot_profile": {
                    "name": f"Bot{c}",
                    "appearance": "tall, green eyes, red hair, calm voice",
                    "system_prompt": f"You are concise assistant #{c} "
                                     "who answers briefly.",
                },
                "user_profile": {"name": "Sam"},
                "context": history,
            }).encode()

        followups = [
            "Interesting, tell me more.", "Why is that?", "Go on.",
            "What happened next?", "Could you expand on that?",
        ]

        def fleet_phase(policy: str, base_port: int) -> dict:
            p1, p2 = base_port + 1, base_port + 2
            start_replica(p1)
            start_replica(p2)
            router = start_router(base_port, [p1, p2], policy)
            histories = {
                c: [{"turn": "user",
                     "message": f"Hello bot {c}! Please introduce "
                                "yourself briefly and tell me a story."}]
                for c in range(convs)
            }
            warm, turn1, errors = [], [], []
            t0p = time.perf_counter()
            for t in range(turns):
                for c in range(convs):
                    body = fleet_payload(c, histories[c])
                    try:
                        ms, text, err = fleet_stream_ttft(base_port, body)
                    except Exception as e:  # noqa: BLE001 — transport
                        errors.append(f"{type(e).__name__}: {e}")
                        continue
                    if err is not None:
                        errors.append(err)
                        continue
                    (turn1 if t == 0 else warm).append(ms)
                    histories[c].append(
                        {"turn": "bot", "message": (text or "...")[:400]})
                    histories[c].append(
                        {"turn": "user",
                         "message": followups[(c + t) % len(followups)]})
            wall = time.perf_counter() - t0p
            per_replica = []
            reused = prompt = hits = misses = 0.0
            for p in (p1, p2):
                row = {
                    "port": p,
                    "reused_tokens": replica_metric(
                        p, "prefix_cache_reused_tokens_total"),
                    "prompt_tokens": replica_metric(
                        p, "tokens_prompt_total"),
                    "hits": replica_metric(p, "prefix_cache_hits_total"),
                    "misses": replica_metric(
                        p, "prefix_cache_misses_total"),
                }
                per_replica.append(row)
                reused += row["reused_tokens"]
                prompt += row["prompt_tokens"]
                hits += row["hits"]
                misses += row["misses"]
            warm.sort()
            turn1.sort()
            return {
                "policy": policy,
                # THE headline: fraction of submitted prompt tokens
                # served from cached KV pages, fleet-wide
                "hit_ratio_tokens": (round(reused / prompt, 4)
                                     if prompt else 0.0),
                "hit_ratio_requests": (round(hits / (hits + misses), 4)
                                       if hits + misses else 0.0),
                "warm_ttft_ms_p50": (round(pq(warm, 0.5), 1)
                                     if warm else None),
                "turn1_ttft_ms_p50": (round(pq(turn1, 0.5), 1)
                                      if turn1 else None),
                "warm_samples": len(warm),
                "errors": errors[:8],
                "per_replica": per_replica,
                "router": dict(router.counters),
                "wall_s": round(wall, 1),
            }

        aff = fleet_phase("affinity", port)
        ctl = fleet_phase("roundrobin", port + 10)
        ratio = (aff["hit_ratio_tokens"] / ctl["hit_ratio_tokens"]
                 if ctl["hit_ratio_tokens"] else None)
        result = {
            "metric": (f"fleet_prefix_hit_ratio[/response,{preset},"
                       f"{wfmt},affinity]"),
            "value": aff["hit_ratio_tokens"],
            "unit": "ratio",
            "vs_roundrobin_control": (round(ratio, 2)
                                      if ratio is not None else None),
            "affinity": aff,
            "control": ctl,
            "conversations": convs,
            "turns": turns,
            "kv_page_tokens": page_tokens,
            "max_tokens": max_tokens,
            "decode_chunk": settings.decode_chunk,
            "device": str(dev),
        }
        emit_result(result)
        os._exit(0)  # daemon server threads: skip graceful teardown

    if batch > 1:
        # continuous batching on one chip: B slot-scheduled lanes amortize
        # every weight read over up to B decode tokens — the aggregate-
        # throughput mode the reference cannot express (Semaphore(1)
        # serializes its generations, reference api.py:114)
        from llama_fastapi_k8s_gpu_tpu.engine import ContinuousEngine

        eng = ContinuousEngine.from_parts(
            params, cfg, tok, template_kind="llama3",
            max_gen_tokens=max_tokens, attn_impl=cfg.attn_impl,
            dp=1, batch_size=batch,
            # honor the same LFKT_* scheduler knobs the production factory
            # does (server/app.py passes each from Settings) — a
            # directly-constructed engine otherwise pins constructor
            # defaults and an env A/B silently measures the same arm
            # twice (the round-4 lane-prefix lesson).
            decode_chunk=settings.decode_chunk,
            adm_budget=settings.adm_budget,
            # the round-6 prefill-pipeline A/B axes: EMA admission
            # controller vs static budget (LFKT_ADM_CONTROLLER) and the
            # overlapped-prefill depth — both labeled on the metric so an
            # env A/B can never measure the same arm twice
            adm_controller=settings.adm_controller,
            adm_ema_alpha=settings.adm_ema_alpha,
            prefill_overlap=settings.prefill_overlap,
            spec_decode=spec_decode, spec_draft=spec_draft,
            # the lane-prefix A/B knobs (VERDICT r4 #8).  The admission
            # slice size matters to the A/B too: reuse is chunk-aligned,
            # so a 256-token slice needs 256 shared tokens before the
            # first claim pays.
            lane_prefix_cache=lane_prefix,
            prefill_chunk=settings.prefill_chunk)
        # report the engine's REALIZED setting, not the env request: spec
        # decode silently excludes lane-prefix reuse (continuous.py), and a
        # ',laneprefix'-labeled artifact with reuse actually off would be a
        # mislabeled A/B arm in the evidence ledger
        lane_prefix = bool(getattr(eng, "_lane_prefix", False))
        if mixed_models:
            # second co-resident model: SAME synthetic weights (identity
            # matters to the scheduler, not the bytes — sharing the
            # params pytree keeps the HBM cost honest to a real
            # two-model pod only in the KV/lane dimension, which is what
            # this arm measures: interleaved multi-model scheduling)
            from llama_fastapi_k8s_gpu_tpu.serving import ModelRegistry

            eng_b = ContinuousEngine.from_parts(
                params, cfg, tok, template_kind="llama3",
                max_gen_tokens=max_tokens, attn_impl=cfg.attn_impl,
                dp=1, batch_size=batch,
                decode_chunk=settings.decode_chunk,
                adm_budget=settings.adm_budget,
                adm_controller=settings.adm_controller,
                adm_ema_alpha=settings.adm_ema_alpha,
                prefill_overlap=settings.prefill_overlap,
                spec_decode=spec_decode, spec_draft=spec_draft,
                lane_prefix_cache=lane_prefix,
                prefill_chunk=settings.prefill_chunk)
            eng = ModelRegistry({"alpha": eng, "beta": eng_b}, "alpha")
    else:
        # prefix reuse stays OFF for the standard phases: they re-POST a
        # byte-identical payload n_req times, so the serial engine's
        # prompt-prefix KV reuse would silently shrink every measured
        # prefill to one suffix bucket and the TTFT metric (same name as
        # prior rounds') would stop measuring full-stack prefill latency.
        # The multiturn mode measures the reuse path, explicitly labeled.
        paged_kw = {}
        if disagg_arm:
            # the page wire needs the paged pool; small pages at tiny
            # scale so the fresh-prompt grid actually crosses page
            # boundaries (serial reuse is page-aligned)
            paged_kw = dict(kv_paged=True,
                            kv_page_tokens=32 if preset == "tiny"
                            else settings.kv_page_tokens)
        eng = Engine.from_parts(params, cfg, tok, template_kind="llama3",
                                max_gen_tokens=max_tokens,
                                attn_impl=cfg.attn_impl,
                                decode_chunk=settings.decode_chunk,
                                spec_decode=spec_decode,
                                spec_draft=spec_draft,
                                prefix_cache=multiturn,
                                prefill_chunk=settings.prefill_chunk,
                                prefill_overlap=settings.prefill_overlap,
                                **paged_kw)
    # compile every shape BEFORE the server phase, exactly like the
    # production factory (server/app.py calls eng.warmup() at startup);
    # without it the first request compiles for ~60 s and the 25 s
    # admission timeout 408s it, killing the warmup POST below
    eng.warmup()
    app = create_app(engine=eng)

    th = threading.Thread(
        target=lambda: asyncio.run(httpd.serve(app, host="127.0.0.1",
                                               port=port)),
        daemon=True)
    th.start()
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 60
    while True:  # wait for the socket
        try:
            urllib.request.urlopen(base + "/health", timeout=5)
            break
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(0.5)

    # LFKT_BENCH_FULLCTX=1: a chat history that fills the reference's whole
    # context budget (api.py:17 MAX_CONTEXT_TOKENS=1024 at the chars/4
    # estimate, each message at the 400-char clip), so prefill runs the
    # full 1024-token bucket through the server stack — the TTFT shape the
    # short-prompt run doesn't exercise (VERDICT r3 #6).
    if fullctx:
        lines = ("The quick brown fox jumps over the lazy dog near the "
                 "riverbank while autumn leaves drift slowly down. ")
        # size the history with the REAL tokenizer (the reference's chars/4
        # estimate over-admits for low-merge synthetic vocabs): take a
        # token-budgeted slice of a long text, then split it into
        # clip-sized (400-char) turns
        budget = max(32, cfg.n_ctx - 200)   # headroom: template + system
        ids = tok.encode(lines * 40)
        text = tok.decode(ids[:budget])
        context = [
            {"turn": "user" if i % 2 == 0 else "bot",
             "message": text[j:j + 400]}
            for i, j in enumerate(range(0, len(text), 400))
        ] + [{"turn": "user", "message": "Tell me about the weather today."}]
    else:
        context = [
            {"turn": "user", "message": "Tell me about the weather today."},
        ]
    payload = json.dumps({  # the reference's wire shape (data/requests.py)
        "bot_profile": {
            "name": "Ada",
            "appearance": "tall, green eyes, red hair, calm voice",
            "system_prompt": "You are a concise assistant.",
        },
        "user_profile": {"name": "Sam"},
        "context": context,
    }).encode()

    def post(path):
        return urllib.request.Request(
            base + path, data=payload,
            headers={"Content-Type": "application/json"})

    # warmup: compile every shape through the server path.  The server's
    # reference-parity 25 s admission timeout (api.py:18) can 408 a slow
    # first generation (early-process executions run 20-40x slow on this
    # platform) — but that generation still runs to completion server-side
    # and warms the programs, so retry instead of crashing; the retry
    # queues behind it and completes fast once warm.
    warm_deadline = time.time() + 900   # outlasts a fully cold compile path
    while True:
        try:
            with urllib.request.urlopen(post("/response"), timeout=1800) as r:
                r.read()
            break
        except urllib.error.HTTPError as e:
            if e.code != 408 or time.time() > warm_deadline:
                raise
            print("bench_server: warmup got 408 (cold generation overran "
                  "the 25s admission timeout); retrying",
                  file=sys.stderr, flush=True)
            time.sleep(2)
    warm_s = time.time() - t_start

    def read_metrics_counters(names) -> dict | None:
        """Scrape named counters off the app's /metrics; None when the
        endpoint is unreadable (so callers report null, not fabricated
        zeros)."""
        try:
            with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
                text = r.read().decode()
        except Exception:  # noqa: BLE001 — measurement aid, not the result
            return None
        out = {n: 0.0 for n in names}
        for ln in text.splitlines():
            parts = ln.split()
            if len(parts) == 2 and parts[0] in out:
                out[parts[0]] = float(parts[1])
        return out

    def stream_ttft(body: bytes):
        """POST /response/stream; returns (ttft_ms, full_text, error).
        Drains the stream fully (an abandoned generation runs to completion
        and would queue under the next sample's TTFT).  ``error`` is the
        server's SSE error event text (context overflow, timeout) or None —
        callers must stop measuring a conversation once it errors, or every
        later "sample" is a fast error round trip mislabeled as TTFT."""
        req = urllib.request.Request(
            base + "/response/stream", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        first = None
        err = None
        parts: list[str] = []
        with urllib.request.urlopen(req, timeout=600) as r:
            for raw in r:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data:"):
                    continue
                body_ln = line[5:].strip()
                if body_ln == "[DONE]":
                    break
                evt = json.loads(body_ln)
                if "error" in evt:
                    err = str(evt["error"])
                    break
                delta = evt["choices"][0]["delta"]
                c = delta.get("content")
                if c:
                    if first is None:
                        first = (time.perf_counter() - t0) * 1e3
                    parts.append(c)
        if first is None:
            first = (time.perf_counter() - t0) * 1e3
        return first, "".join(parts), err

    if disagg_arm:
        # LFKT_BENCH_DISAGG=1: the same engine serves both halves over
        # loopback TCP — control phase first (role off: the engine's
        # _disagg gate is None), then the client is installed and the
        # identical fresh-prompt workload re-runs through the wire.
        from llama_fastapi_k8s_gpu_tpu.serving.disagg.decoder import (
            DisaggClient,
        )
        from llama_fastapi_k8s_gpu_tpu.serving.disagg.prefiller import (
            PrefillServer,
        )

        psrv = PrefillServer(eng, host="127.0.0.1", port=0,
                             metrics=app.state.metrics)
        pcli = DisaggClient(f"127.0.0.1:{psrv.port}", eng._kvpool,
                            timeout_s=60.0, metrics=app.state.metrics)

        # a prompt long enough that the serial paged-reuse constraints
        # grant page-aligned reuse (bucket > smallest bucket, suffix
        # fits a smaller one) — sized with the REAL tokenizer
        filler_ids = tok.encode(
            "The quick brown fox jumps over the lazy dog near the old "
            "riverbank while autumn leaves drift slowly down. " * 40)
        filler = tok.decode(filler_ids[:min(150, cfg.n_ctx // 2)])

        def disagg_payload(tag: str) -> bytes:
            # the tag leads, so every request's FIRST page differs —
            # each sample is a cold radix miss and the hop must fire
            return json.dumps({
                "bot_profile": {
                    "name": "Ada",
                    "appearance": "tall, green eyes, red hair, calm voice",
                    "system_prompt": "You are a concise assistant.",
                },
                "user_profile": {"name": "Sam"},
                "context": [{"turn": "user",
                             "message": (f"[{tag}] " + filler)[:400]}],
            }).encode()

        pq = lambda v, q: v[min(len(v) - 1, int(q * len(v)))]  # noqa: E731

        def read_metric_sum(name: str) -> float | None:
            # streamed responses meter into the LABELED per-model family
            # (tokens_generated_total{model=...}) — sum its series
            try:
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=30) as r:
                    text = r.read().decode()
            except Exception:  # noqa: BLE001 — measurement aid
                return None
            total, found = 0.0, False
            for ln in text.splitlines():
                head, _, val = ln.rpartition(" ")
                if head == name or head.startswith(name + "{"):
                    total += float(val)
                    found = True
            return total if found else None

        def disagg_phase(label: str) -> dict:
            before = read_metric_sum("tokens_generated_total")
            samples = []
            t0p = time.perf_counter()
            for i in range(n_req):
                ms, _text, err = stream_ttft(disagg_payload(f"{label}{i}"))
                if err is None:
                    samples.append(ms)
                else:
                    print(f"bench_server: disagg {label} stream error: "
                          f"{err}", file=sys.stderr, flush=True)
            wall = time.perf_counter() - t0p
            after = read_metric_sum("tokens_generated_total")
            gen = (after - (before or 0.0)
                   if after is not None else None)
            samples.sort()
            return {
                "ttft_ms_p50": (round(pq(samples, 0.5), 1)
                                if samples else None),
                "ttft_ms_p95": (round(pq(samples, 0.95), 1)
                                if samples else None),
                "samples": len(samples),
                "agg_tok_s": (round(gen / wall, 1)
                              if gen and wall > 0 else None),
                "gen_tokens": int(gen) if gen is not None else None,
                "wall_s": round(wall, 1),
            }

        control = disagg_phase("ctl")      # role off: one attribute read
        eng.install_disagg(pcli)
        split = disagg_phase("dis")
        result = {
            "metric": (f"server_ttft_ms_p50[/response,{preset},{wfmt}"
                       ",disagg-loopback]"),
            "value": split["ttft_ms_p50"] or 0.0,
            "unit": "ms",
            "control": control,
            "disagg": split,
            "disagg_client": pcli.status(),
            "disagg_service": psrv.status(),
            "kv_page_tokens": eng._kvpool.page_tokens,
            "max_tokens": max_tokens,
            "n_requests": n_req,
            "warmup_s": round(warm_s, 1),
            "decode_chunk": settings.decode_chunk,
            "device": str(dev),
        }
        emit_result(result)
        os._exit(0)  # daemon server thread: skip graceful asyncio teardown

    if mixed_models:
        # LFKT_BENCH_MIXED_MODELS=1 + LFKT_BENCH_BATCH=B: `conc` worker
        # threads split across the two models, each POSTing
        # /v1/chat/completions with its model= — lanes of both models
        # decode concurrently and the schedulers interleave their waves
        # on the one device queue.  Per-model aggregate tok/s comes from
        # the responses' usage counts (the facade returns them; /response
        # strips usage off the wire).
        conc = int(os.environ.get("LFKT_BENCH_CONCURRENCY", str(2 * batch)))
        per = max(2, n_req // 2)
        model_names = ("alpha", "beta")
        agg = {name: {"tokens": 0, "completed": 0, "lat_ms": [],
                      "errors": 0} for name in model_names}
        lk = threading.Lock()

        def mixed_worker(i: int):
            name = model_names[i % 2]        # alternating model= per lane
            body = json.dumps({
                "model": name,
                "max_tokens": max_tokens,
                "temperature": 0.7,
                "messages": [{"role": "user",
                              "content": "Tell me about the weather "
                                         f"today, worker {i}."}],
            }).encode()
            req = urllib.request.Request(
                base + "/v1/chat/completions", data=body,
                headers={"Content-Type": "application/json"})
            for _ in range(per):
                t0 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req, timeout=600) as r:
                        doc = json.loads(r.read())
                    ms = (time.perf_counter() - t0) * 1e3
                    with lk:
                        agg[name]["tokens"] += doc["usage"]["completion_tokens"]
                        agg[name]["completed"] += 1
                        agg[name]["lat_ms"].append(ms)
                except Exception:  # noqa: BLE001 — count, keep sampling
                    with lk:
                        agg[name]["errors"] += 1

        t_mx = time.perf_counter()
        ths = [threading.Thread(target=mixed_worker, args=(i,))
               for i in range(conc)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        mx_s = time.perf_counter() - t_mx
        pq = lambda v, q: v[min(len(v) - 1, int(q * len(v)))]  # noqa: E731
        per_model = {}
        for name, a in agg.items():
            a["lat_ms"].sort()
            per_model[name] = {
                "agg_tok_s": (round(a["tokens"] / mx_s, 1)
                              if mx_s > 0 else None),
                "gen_tokens": a["tokens"],
                "completed": a["completed"],
                "errors": a["errors"],
                "latency_ms_p50": (round(pq(a["lat_ms"], 0.5), 1)
                                   if a["lat_ms"] else None),
            }
        total_tokens = sum(a["tokens"] for a in agg.values())
        result = {
            "metric": (f"server_mixed_models_agg_tok_s[/v1,{preset},{wfmt}"
                       f",models2,batch{batch}]"),
            "value": round(total_tokens / mx_s, 1) if mx_s > 0 else 0.0,
            "unit": "tok/s",
            "per_model": per_model,
            "models": list(model_names),
            "threads": conc,
            "requests_per_thread": per,
            "max_tokens": max_tokens,
            "decode_chunk": settings.decode_chunk,
            "batch_size": batch,
            "warmup_s": round(warm_s, 1),
            "wall_s": round(mx_s, 1),
            "scheduler_stats": eng.scheduler_stats(),
            "device": str(dev),
        }
        emit_result(result)
        os._exit(0)  # daemon server thread: skip graceful asyncio teardown

    if multiturn and batch > 1:
        # LFKT_BENCH_MULTITURN=1 + LFKT_BENCH_BATCH=C: C concurrent growing
        # conversations through the lane scheduler — the workload the
        # lane-prefix cache exists for (VERDICT r4 #8's "multiturn client
        # mix").  Each follow-up re-sends persona + full history; with
        # LFKT_LANE_PREFIX_CACHE=1 admission finds the freed lane still
        # holding that conversation's KV and prefills only the suffix.
        # Distinct openers keep claims conversation-specific (the shared
        # persona tokens are legitimate cross-conversation reuse).
        followups = [
            "Interesting, tell me more.", "Why is that?", "Go on.",
            "What happened next?", "Could you expand on that?",
        ]
        turns = int(os.environ.get("LFKT_BENCH_TURNS", "4"))
        turn1, follow = [], []
        lk = threading.Lock()

        completed = []
        errors = []

        def convo_worker(cid: int):
            convo = [{"turn": "user",
                      "message": f"Hello bot {cid}! Introduce yourself "
                                 "briefly."}]
            done = 0
            for t in range(turns):
                body = json.dumps({
                    "bot_profile": {
                        "name": "Ada",
                        "appearance": "tall, green eyes, red hair, calm voice",
                        "system_prompt": "You are a concise assistant.",
                    },
                    "user_profile": {"name": "Sam"},
                    "context": convo,
                }).encode()
                try:
                    ms, text, err = stream_ttft(body)
                except Exception as e:  # noqa: BLE001 — transport failure
                    with lk:
                        errors.append(f"{type(e).__name__}: {e}")
                    break
                if err is not None:
                    # conversation outgrew the context (or timed out):
                    # stop HERE — the turns measured so far are valid
                    with lk:
                        errors.append(err)
                    break
                done += 1
                with lk:
                    (turn1 if t == 0 else follow).append(ms)
                convo.append({"turn": "bot", "message": (text or "...")[:400]})
                convo.append({"turn": "user",
                              "message": followups[(cid + t) % len(followups)]})
            with lk:
                completed.append(done)

        names = ("scheduler_lane_prefix_hits",
                 "scheduler_lane_prefix_reused_tokens")
        before = read_metrics_counters(names)
        t_mt = time.perf_counter()
        ths = [threading.Thread(target=convo_worker, args=(c,))
               for c in range(batch)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        mt_s = time.perf_counter() - t_mt
        after = read_metrics_counters(names)
        follow.sort()
        turn1.sort()
        pq = lambda v, q: v[min(len(v) - 1, int(q * len(v)))]  # noqa: E731
        result = {
            "metric": (f"server_ttft_ms_p50[/response,{preset},{wfmt}"
                       f",multiturn,batch{batch}"
                       + (",laneprefix]" if lane_prefix else "]")),
            "value": round(pq(follow, 0.5), 1) if follow else 0.0,
            "unit": "ms",
            "vs_baseline": (round(A10G_TTFT_MS / pq(follow, 0.5), 3)
                            if follow else 0.0),
            "ttft_ms_p95_server": (round(pq(follow, 0.95), 1)
                                   if follow else None),
            "turn1_ttft_ms_p50": round(pq(turn1, 0.5), 1) if turn1 else None,
            "follow_samples": len(follow),
            "decode_chunk": settings.decode_chunk,
            "conversations": batch,
            "turns": turns,
            "turns_completed": sorted(completed),
            "stream_errors": errors[:8],
            "max_tokens": max_tokens,
            "warmup_s": round(warm_s, 1),
            "lane_prefix_cache": lane_prefix,
            "lane_prefix": (
                {k: after[k] - before[k] for k in names}
                if before is not None and after is not None else None),
            "scheduler_stats": eng.scheduler_stats(),
            "wall_s": round(mt_s, 1),
            "device": str(dev),
        }
        emit_result(result)
        os._exit(0)  # daemon server thread: skip graceful asyncio teardown

    if multiturn:
        # LFKT_BENCH_MULTITURN=1: ONE growing conversation — each request
        # re-sends persona + full history + a new user turn, the reference's
        # actual workload shape (api.py:44-63).  Follow-up turns share their
        # whole history prefix with the previous request, so this measures
        # what the serial engine's prompt-prefix KV reuse is for: follow-up
        # TTFT scaling with the NEW turn, not the history.  Serial-engine
        # semantics (one conversation), so the concurrency phase is skipped.
        followups = [
            "Interesting, tell me more.", "Why is that?", "Go on.",
            "What happened next?", "Could you expand on that?",
            "How does that relate?", "Give me an example.",
        ]
        convo = [{"turn": "user",
                  "message": "Hello! Please introduce yourself briefly."}]

        def mt_payload() -> bytes:
            return json.dumps({
                "bot_profile": {
                    "name": "Ada",
                    "appearance": "tall, green eyes, red hair, calm voice",
                    "system_prompt": "You are a concise assistant.",
                },
                "user_profile": {"name": "Sam"},
                "context": convo,
            }).encode()

        first_ttft = None
        follow = []
        # Per-turn reused-token deltas: once the server's reference-parity
        # truncation starts popping the oldest history turn (api.py:54-65),
        # follow-ups stop sharing the resident prefix and silently measure
        # full prefill again.  Reporting reuse PER TURN makes those turns
        # distinguishable in the artifact instead of polluting an
        # aggregate labeled "multiturn reuse" (ADVICE r4 #3).
        per_turn = []

        def reused_total() -> float | None:
            got = read_metrics_counters(("prefix_cache_reused_tokens_total",))
            return None if got is None else got["prefix_cache_reused_tokens_total"]

        mt_errors = []
        for k in range(n_req):
            r_before = reused_total()
            ms, text, err = stream_ttft(mt_payload())
            if err is not None:
                # conversation outgrew the context: stop measuring (later
                # "samples" would be fast error round trips, not TTFT)
                mt_errors.append(err)
                break
            r_after = reused_total()
            per_turn.append({
                "turn": k + 1, "ttft_ms": round(ms, 1),
                "reused_tokens": (int(r_after - r_before)
                                  if r_after is not None and r_before is not None
                                  else None),
            })
            if k == 0:
                first_ttft = ms
            else:
                follow.append(ms)
            convo.append({"turn": "bot", "message": (text or "...")[:400]})
            convo.append({"turn": "user",
                          "message": followups[k % len(followups)]})
        counters = read_metrics_counters(
            ("prefix_cache_hits_total", "prefix_cache_reused_tokens_total"))
        follow.sort()
        pq = lambda v, q: v[min(len(v) - 1, int(q * len(v)))]  # noqa: E731
        result = {
            "metric": (f"server_ttft_ms_p50[/response,{preset},{wfmt}"
                       ",multiturn]"),
            "value": round(pq(follow, 0.5), 1) if follow else 0.0,
            "unit": "ms",
            "vs_baseline": (round(A10G_TTFT_MS / pq(follow, 0.5), 3)
                            if follow else 0.0),
            "ttft_ms_p95_server": (round(pq(follow, 0.95), 1)
                                   if follow else None),
            "turn1_ttft_ms": (round(first_ttft, 1)
                              if first_ttft is not None else None),
            "turns": n_req,
            "turns_measured": len(per_turn),
            "stream_errors": mt_errors,
            "decode_chunk": settings.decode_chunk,
            "max_tokens": max_tokens,
            "warmup_s": round(warm_s, 1),
            "prefix_cache": counters,
            "per_turn": per_turn,
            "device": str(dev),
        }
        emit_result(result)
        return

    lat = []
    for _ in range(n_req):
        t0 = time.perf_counter()
        with urllib.request.urlopen(post("/response"), timeout=600) as r:
            json.loads(r.read())
        lat.append((time.perf_counter() - t0) * 1e3)

    ttft = []
    for _ in range(n_req):
        ms, _text, err = stream_ttft(payload)
        if err is None:     # fixed warmed payload: errors are unexpected —
            ttft.append(ms)  # drop the sample rather than time the error path
        else:
            print(f"bench_server: stream error during TTFT phase: {err}",
                  file=sys.stderr, flush=True)

    # concurrent load (BASELINE config #5: "concurrent /response load ...
    # back-pressure"): fan out parallel POSTs; the server queues up to 5 and
    # 503s beyond (reference api.py:113,158-160 semantics preserved).
    # Service capacity = inflight(batch) + queue(5), so the default
    # concurrency must exceed batch + 5 for the 503 path to actually fire.
    conc = int(os.environ.get("LFKT_BENCH_CONCURRENCY",
                              str(max(8, batch + 8))))
    per = max(2, n_req // 2)
    oks, rejects, errors = [], [], []
    lock = threading.Lock()

    def read_generated_total() -> float | None:
        # server-side counter of usage.completion_tokens per completed
        # request (`/response` strips the usage dict off the wire, so the
        # client can't count; app.py:237-238 records it before stripping)
        got = read_metrics_counters(("generated_tokens_total",))
        return None if got is None else got["generated_tokens_total"]

    def worker(seed: int):
        # closed loop: each thread completes `per` requests, retrying 503s
        # with exponential backoff + jitter (what a real client does), so
        # the phase sustains the advertised concurrency and still counts
        # every 503.  A fixed short backoff instead synchronizes the
        # excess threads into a retry stampede that starves queued
        # requests into 408s at >1.3x overload (observed on-chip).
        import random

        rnd = random.Random(seed)
        done = 0
        attempts = 0
        backoff = 0.1
        while done < per and attempts < per * 200:
            attempts += 1
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(post("/response"), timeout=600) as r:
                    r.read()
                with lock:
                    oks.append((time.perf_counter() - t0) * 1e3)
                done += 1
                backoff = 0.1
            except urllib.error.HTTPError as e:
                with lock:
                    (rejects if e.code == 503 else errors).append(e.code)
                if e.code == 503:
                    time.sleep(backoff * (0.5 + rnd.random()))
                    backoff = min(backoff * 2, 1.6)
                else:
                    done += 1   # non-503 failure: don't retry forever
            except Exception as e:  # noqa: BLE001 — connection-level failure:
                with lock:          # count it, keep the sample sizes honest
                    errors.append(type(e).__name__)
                done += 1

    gen_before = read_generated_total()
    t_conc = time.perf_counter()
    ths = [threading.Thread(target=worker, args=(i,)) for i in range(conc)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    conc_s = time.perf_counter() - t_conc
    gen_after = read_generated_total()
    gen_total = (gen_after - gen_before
                 if gen_after is not None and gen_before is not None else None)

    lat.sort(); ttft.sort(); oks.sort()
    p = lambda v, q: v[min(len(v) - 1, int(q * len(v)))]  # noqa: E731
    result = {
        "metric": (f"server_ttft_ms_p50[/response,{preset},{wfmt}"
                   + (",fullctx" if fullctx else "")
                   + (",spec" if spec_decode == "lookup" else "")
                   + (",laneprefix" if lane_prefix and batch > 1 else "")
                   + (",admstatic" if batch > 1
                      and not settings.adm_controller else "")
                   + (f",chunk{settings.decode_chunk}"
                      if settings.decode_chunk != Settings.decode_chunk
                      else "")
                   + (f",batch{batch}]" if batch > 1 else "]")),
        "value": round(p(ttft, 0.5), 1),
        "unit": "ms",
        "vs_baseline": round(A10G_TTFT_MS / max(p(ttft, 0.5), 1e-9), 3),
        "ttft_ms_p95_server": round(p(ttft, 0.95), 1),
        "latency_ms_p50": round(p(lat, 0.5), 1),
        "latency_ms_p95": round(p(lat, 0.95), 1),
        "decode_chunk": settings.decode_chunk,
        "max_tokens": max_tokens,
        "n_requests": n_req,
        "warmup_s": round(warm_s, 1),
        "concurrent": {
            "threads": conc, "completed": len(oks), "rejected_503": len(rejects),
            "other_errors": len(errors),
            "latency_ms_p95": round(p(oks, 0.95), 1) if oks else None,
            "req_per_sec": round(len(oks) / conc_s, 2) if conc_s > 0 else None,
            # aggregate decode throughput under load, from the server's
            # generated_tokens_total counter delta (random logits CAN
            # sample a stop token early, so len(oks)*max_tokens would
            # overcount; the usage dict never crosses the /response wire)
            "agg_tok_s": (round(gen_total / conc_s, 1)
                          if conc_s > 0 and gen_total is not None else None),
            "gen_tokens_total": (int(gen_total)
                                 if gen_total is not None else None),
        },
        "batch_size": batch,
        "device": str(dev),
    }
    if batch > 1:
        # admission-controller telemetry for the prefill-heavy agg A/B:
        # live budget + EMAs say WHY an arm's agg_tok_s moved
        result["scheduler_stats"] = eng.scheduler_stats()
        result["adm_controller"] = settings.adm_controller
    if spec_decode == "lookup":
        # acceptance telemetry: accepted/drafted is THE pays-or-not number
        if batch > 1:
            result["spec"] = eng.scheduler_stats().get("spec")
        else:
            # serial engine: scrape the spec counters the app exports
            result["spec"] = read_metrics_counters(
                ("spec_verify_steps_total", "spec_drafted_tokens_total",
                 "spec_accepted_tokens_total", "spec_fallback_steps_total"))
    emit_result(result)
    os._exit(0)  # daemon server thread: skip graceful asyncio teardown


if __name__ == "__main__":
    main()

# App image: the serving framework on top of the TPU base.
# Mirrors the reference's two-stage split (docker/Dockerfile.app:1-12) with
# the registry base swapped for the TPU one.
FROM myregistry/lfkt-tpu-base:0.1.0

COPY docker/requirements.txt /app/requirements.txt
RUN pip install --no-cache-dir -r /app/requirements.txt

COPY llama_fastapi_k8s_gpu_tpu /app/llama_fastapi_k8s_gpu_tpu
RUN mkdir -p /app/models

# Persistent XLA compile cache: restarts of the same container (or a
# mounted volume — helm compileCache.*) skip jit warmup recompiles.
ENV LFKT_COMPILE_CACHE_DIR=/xla-cache
RUN mkdir -p /xla-cache

# Exactly one worker: the model is loaded once per process (reference
# Dockerfile.app:12 `gunicorn -w 1`); the module entrypoint enforces it.
CMD ["python", "-m", "llama_fastapi_k8s_gpu_tpu.server"]

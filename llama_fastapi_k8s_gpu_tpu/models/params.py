"""Parameter loading: GGUF tensors → stacked JAX pytrees.

Performs at load time what llama.cpp does lazily per-matmul on GPU: weights
are dequantized once (numpy reference codecs; Pallas dequant kernels take
over on TPU) and placed in HBM in the chosen compute format.  Stacking the
per-layer tensors (axis 0 = layer) is what lets the model scan over layers.

Formats (``ops.linear``):
- ``bf16`` — exact dequant, 2 B/weight.  16 GB for Llama-3-8B: does NOT fit
  one v5e chip; use for small models and CPU tests.
- ``int8`` — symmetric per-channel requant of the dequantized weights,
  1 B/weight (~8.5 GB for 8B incl. bf16 embeddings).
- ``q4k`` — fused serving: Q4_K / Q5_K / Q6_K / Q8_0 tensors stay in
  (nearly) their GGUF bit layouts in HBM (~5 / 6 / 7 / 9 bit/weight) and
  are dequantized in-VMEM by their fused Pallas matmuls (ops/pallas/
  q*matmul.py); anything else falls back to int8.  The v5e serving
  format: lowest decode HBM traffic at file fidelity.  Because per-layer
  tensors are stacked for ``lax.scan``, the choice is per tensor *name*:
  a name fuses only if every layer's tensor of that name shares one
  eligible type (Q4_K_M files mix in Q6_K for some layers).

GGUF tensor names follow llama.cpp's convention: ``token_embd.weight``,
``blk.{i}.attn_{q,k,v,output}.weight``, ``blk.{i}.ffn_{gate,up,down}.weight``,
``blk.{i}.{attn,ffn}_norm.weight``, ``output_norm.weight``, ``output.weight``
(absent when embeddings are tied).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..gguf import GGUFFile
from ..ops import make_linear_bf16, make_linear_int8, make_linear_int8_device
from .config import ModelConfig

logger = logging.getLogger(__name__)

_LINEAR_MAKERS = {"bf16": make_linear_bf16, "int8": make_linear_int8}


def _tensor_to_device(t, dtype=jnp.float32) -> jax.Array:
    """Raw GGUF bytes → dequantized device array via the Pallas kernels
    (ops/pallas/dequant.py): the host ships quantized bytes, the chip
    expands them."""
    from ..ops.pallas import device_dequant

    flat = device_dequant(t.raw(), t.ggml_type, t.n_elements, dtype)
    return flat.reshape(tuple(reversed(t.shape)))


def _stack(dicts: list[dict], free: bool = False) -> dict:
    """List of identically-keyed (possibly nested) dicts → dict of stacked
    arrays.  ``free=True`` drops each per-layer ref as soon as its stacked
    leaf exists (overlap mode: the inputs are device arrays, so holding all
    of them through the whole stack would double device-memory peak; with
    progressive freeing the peak is ~1× weights + the largest single
    name's stack)."""
    out = {}
    for key in dicts[0]:
        vals = [d[key] for d in dicts]
        if isinstance(vals[0], dict):
            out[key] = _stack(vals, free=free)
        else:
            out[key] = jnp.stack(vals)
            if free:
                del vals
                for d in dicts:
                    d[key] = None
    return out


def load_params(gf: GGUFFile, cfg: ModelConfig, fmt: str = "bf16",
                on_device: bool | None = None,
                fused_types: frozenset | None = None,
                phases_out: dict | None = None) -> dict:
    """Dequantize all tensors from ``gf`` into a stacked param pytree.

    ``on_device=True`` (default on TPU) routes quantized tensors through the
    Pallas dequant kernels and requantizes int8 on device; ``False`` uses
    the numpy reference codecs.  Both produce identical pytrees.

    ``fused_types`` restricts which GGML types may use their fused kernel
    under ``fmt="q4k"`` (default: Q4_K, Q5_K, Q6_K and Q8_0).  The engine passes
    the set of types whose compile probes passed, so a Mosaic regression
    in ONE kernel degrades only that format's tensors to int8.
    """
    if on_device is None:
        on_device = jax.default_backend() == "tpu"
    base_fmt = "int8" if fmt == "q4k" else fmt
    make = _LINEAR_MAKERS[base_fmt]

    def _fused_names() -> dict[str, object]:
        """Linear positions that can serve a fused kernel, mapped to the
        ONE GGML type the whole (L, ...) stack will use — stacked scan
        params need a single layout per name.

        Uniform names use their file type.  Names mixing the K-quants
        (Q4_K/Q5_K/Q6_K — llama.cpp's Q4_K_M ``use_more_bits`` recipe puts
        e.g. half the ffn_down layers on Q6_K and half on Q4_K) are
        PROMOTED to the highest K-quant present: the minority layers are
        requantized onto the finer grid (16-element sub-block scales —
        strictly finer than the int8 per-row fallback this replaces) and
        the whole name stays on the fused decode path at ≤0.88 B/weight."""
        from ..gguf.constants import GGMLType
        from ..ops.pallas.qmatmul import q4k_compatible

        fusable = tuple(fused_types) if fused_types is not None \
            else (GGMLType.Q4_K, GGMLType.Q5_K, GGMLType.Q6_K, GGMLType.Q8_0)
        k_rank = {GGMLType.Q4_K: 0, GGMLType.Q5_K: 1, GGMLType.Q6_K: 2}
        names = ["attn_q", "attn_k", "attn_v", "attn_output",
                 "ffn_gate", "ffn_up", "ffn_down"]
        ok: dict[str, object] = {}
        for n in names:
            ts = [gf[f"blk.{i}.{n}.weight"] for i in range(cfg.n_layers)]
            if not all(q4k_compatible(*reversed(t.shape)) for t in ts):
                continue
            types = {t.ggml_type for t in ts}
            if len(types) == 1:
                t0 = ts[0].ggml_type
                if t0 in fusable:
                    ok[n] = t0
            elif types <= set(k_rank):
                target = max(types, key=k_rank.get)
                if target in fusable:
                    ok[n] = target
        t = gf.tensors.get("output.weight")
        if t is not None and t.ggml_type in fusable \
                and q4k_compatible(*reversed(t.shape)):
            ok["output"] = t.ggml_type
        return ok

    fused_names = _fused_names() if fmt == "q4k" else {}

    import time as _time

    # coarse load-phase attribution, logged at the end: prep (host packers /
    # codecs incl. the raw() mmap page-ins they trigger) vs stack (jnp.stack
    # = host->device transfer of every packed plane)
    phase_s = {"prep": 0.0, "stack": 0.0}

    def lin(name: str) -> dict:
        short = name.split(".")[-2] if name.startswith("blk.") else name.split(".")[0]
        if short in fused_names:
            from ..gguf.constants import GGMLType
            from ..ops.pallas.q5matmul import prep_q5k
            from ..ops.pallas.q6matmul import prep_q6k
            from ..ops.pallas.q8matmul import prep_q8_0
            from ..ops.pallas.qmatmul import prep_q4k

            t = gf[name]
            target = fused_names[short]
            if t.ggml_type != target:
                # K-quant promotion (mixed-type name): dequantize and
                # requantize onto the name's chosen finer grid
                from ..ops.linear import make_linear_q5k, make_linear_q6k

                maker = {GGMLType.Q5_K: make_linear_q5k,
                         GGMLType.Q6_K: make_linear_q6k}[target]
                return maker(t.astype_f32())
            n_out, k_in = tuple(reversed(t.shape))
            prep = {GGMLType.Q4_K: prep_q4k, GGMLType.Q5_K: prep_q5k,
                    GGMLType.Q6_K: prep_q6k,
                    GGMLType.Q8_0: prep_q8_0}[target]
            return prep(np.asarray(t.raw()), n_out, k_in)
        if on_device:
            w = _tensor_to_device(gf[name])
            if base_fmt == "int8":
                return make_linear_int8_device(w)
            return {"w": w.astype(jnp.bfloat16)}
        return make(gf[name].astype_f32())

    def norm(name: str):
        return jnp.asarray(gf[name].astype_f32(), dtype=jnp.float32)

    # LFKT_LOAD_OVERLAP=1: enqueue each layer's host→device transfer the
    # moment its planes are packed, so the (async) transfers stream while
    # the C++ packers prep the NEXT layers, instead of serializing all
    # packing before all transfer (the default _stack(host arrays) order).
    # The final stack then concatenates resident device arrays.  Default ON
    # since the 2026-08-01 coldstart A/B: 226.5 s -> 180.8 s load (the
    # first request then absorbs ~19 s of still-draining transfers, net
    # 245.8 -> 218.9 s to first token, -11% — coldstart_2026-08-01.json vs
    # coldstart_overlap_2026-08-01.json).
    from ..utils.config import env_bool

    overlap = env_bool("LFKT_LOAD_OVERLAP", default=True)

    layers = []
    t0 = _time.time()
    for i in range(cfg.n_layers):
        p = f"blk.{i}."
        layer = {
            "attn_norm": norm(p + "attn_norm.weight"),
            "wq": lin(p + "attn_q.weight"),
            "wk": lin(p + "attn_k.weight"),
            "wv": lin(p + "attn_v.weight"),
            "wo": lin(p + "attn_output.weight"),
            "ffn_norm": norm(p + "ffn_norm.weight"),
            "w_gate": lin(p + "ffn_gate.weight"),
            "w_up": lin(p + "ffn_up.weight"),
            "w_down": lin(p + "ffn_down.weight"),
        }
        if overlap:
            layer = jax.tree.map(jax.device_put, layer)
        layers.append(layer)
        logger.debug("loaded layer %d/%d", i + 1, cfg.n_layers)
    phase_s["prep"] = _time.time() - t0

    if on_device:
        emb = _tensor_to_device(gf["token_embd.weight"], jnp.bfloat16)
    else:
        emb = jnp.asarray(gf["token_embd.weight"].astype_f32(), dtype=jnp.bfloat16)
    if cfg.tie_embeddings or "output.weight" not in gf.tensors:
        output = {"w": emb}
    else:
        output = lin("output.weight")
    t0 = _time.time()
    stacked = _stack(layers, free=overlap)
    jax.block_until_ready(stacked)   # best-effort on the tunneled platform;
    #                                  coldstart_main times load externally
    phase_s["stack"] = _time.time() - t0
    logger.info("load_params phases: per-layer prep+transfer %.1fs, "
                "stack %.1fs", phase_s["prep"], phase_s["stack"])
    if phases_out is not None:
        # caller-owned out-param (Engine.load_phases → coldstart bench JSON);
        # no shared module state, so concurrent loads can't cross-report
        phases_out.update(phase_s)
    return {
        "tok_emb": emb,
        "layers": stacked,
        "out_norm": norm("output_norm.weight"),
        "output": output,
    }


#: the stacked linear names a transformer layer serves, in the operand
#: order the layer-looped decode kernel consumes them
LOOP_LINEARS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def decode_loop_plan(params: dict, cfg: ModelConfig):
    """Layer-major weight plan for the looped decode kernel
    (ops/pallas/decode_loop.py): per-linear format tags, or a refusal.

    Returns ``(fmts, None)`` — ``fmts`` maps each :data:`LOOP_LINEARS`
    name to ``"bf16"`` (a ``{"w"}`` plane) or ``"int8"`` (``{"q","s"}``)
    — or ``(None, reason)`` when the loaded weights cannot serve the
    looped kernel.  This is the load-path side of the kernel-looping
    transform: the in-kernel per-layer BlockSpec indexing needs every
    plane stacked **layer-major** with one uniform layout per name.
    bf16/int8 loads already satisfy that (``_stack`` put the layer axis
    first at load time), so the transform is a structural validation +
    flattening rather than a byte-moving restack.  The fused K-quant
    layouts (Q4_K/Q5_K/Q6_K/Q8_0 multi-plane dicts) are exactly the
    formats that WOULD need a real per-layer restack of their block
    planes — they refuse here and the caller degrades to the per-layer
    path with attribution (the chip-session follow-up, docs/PERF.md
    round 8).

    Trace-time only: a dict-shape walk, no device work — callers run it
    while jit traces a decode step.
    """
    layers = params.get("layers")
    if not isinstance(layers, dict):
        return None, "params carry no stacked layer tree"
    fmts: dict[str, str] = {}
    for name in LOOP_LINEARS:
        w = layers.get(name)
        if not isinstance(w, dict):
            return None, f"stacked linear {name!r} missing from params"
        if "w" in w:
            fmts[name] = "bf16"
        elif "q" in w and "s" in w:
            fmts[name] = "int8"
        else:
            return None, (
                f"linear {name!r} is a fused quantized layout "
                f"(keys {sorted(w)}): the in-kernel fused K-quant matmul "
                "needs its block planes restacked per layer — serve "
                "per-layer decode (docs/RUNBOOK.md 'Tuning layer-looped "
                "decode')")
    for nm in ("attn_norm", "ffn_norm"):
        if nm not in layers:
            return None, f"stacked norm {nm!r} missing from params"
    return fmts, None


def synth_params(cfg: ModelConfig, fmt: str = "bf16", seed: int = 0,
                 scale: float | None = None) -> dict:
    """Random-weight params with the exact structure of :func:`load_params`.

    Used for tests and for benchmarking real-size models without network
    egress (BASELINE.md: bench models are synthesized, not downloaded).
    """
    rng = np.random.default_rng(seed)
    make = _LINEAR_MAKERS["int8" if fmt == "q4k" else fmt]
    if scale is None:
        scale = cfg.dim ** -0.5

    def lin(out_dim, in_dim):
        w = rng.standard_normal((out_dim, in_dim), dtype=np.float32) * scale
        if fmt == "q4k":
            from ..ops import make_linear_q4k
            from ..ops.pallas.qmatmul import q4k_compatible

            if q4k_compatible(out_dim, in_dim):
                return make_linear_q4k(w)
        return make(w)

    kv_dim = cfg.n_kv_heads * cfg.head_dim
    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "attn_norm": jnp.ones(cfg.dim, jnp.float32),
            "wq": lin(cfg.dim, cfg.dim),
            "wk": lin(kv_dim, cfg.dim),
            "wv": lin(kv_dim, cfg.dim),
            "wo": lin(cfg.dim, cfg.dim),
            "ffn_norm": jnp.ones(cfg.dim, jnp.float32),
            "w_gate": lin(cfg.ffn_dim, cfg.dim),
            "w_up": lin(cfg.ffn_dim, cfg.dim),
            "w_down": lin(cfg.dim, cfg.ffn_dim),
        })
    emb = jnp.asarray(
        rng.standard_normal((cfg.vocab_size, cfg.dim), dtype=np.float32) * scale,
        dtype=jnp.bfloat16,
    )
    output = {"w": emb} if cfg.tie_embeddings else lin(cfg.vocab_size, cfg.dim)
    return {
        "tok_emb": emb,
        "layers": _stack(layers),
        "out_norm": jnp.ones(cfg.dim, jnp.float32),
        "output": output,
    }

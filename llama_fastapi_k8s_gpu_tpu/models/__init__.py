from .config import ModelConfig  # noqa: F401
from .llama import init_cache, prefill, decode_step, forward  # noqa: F401
from .params import load_params, synth_params  # noqa: F401

"""Llama-family transformer as pure JAX functions.

This is the in-tree replacement for the model graph the reference runs inside
llama.cpp (``create_chat_completion``'s prefill/decode, reference
api.py:55-63): RMSNorm → GQA attention with interleaved RoPE → SwiGLU, over a
preallocated, donated KV cache.  Design choices are TPU-first:

- layers are *stacked* and iterated with ``lax.scan`` so XLA compiles one
  layer body regardless of depth (compile time ∝ 1, not n_layers);
- K/V are written with ``dynamic_update_slice`` and attention masks the full
  ``n_ctx`` ring, so prefill and decode share one code path with static
  shapes (prompt lengths are bucketed by the engine to bound recompiles);
- sliding-window masking (Mistral) is the same mask with one extra term;
- matmuls go through ``ops.linear`` so bf16 / int8 / (later) fused-Q4_K
  weights are interchangeable without touching the graph.

RoPE is the *interleaved* (ggml "NORM") variant: GGUF conversion permutes
Q/K weights to this convention, so parity with llama.cpp requires it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import linear
from ..ops.linear import linear_at
from .config import ModelConfig


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return ((x32 * inv) * w.astype(jnp.float32)).astype(x.dtype)


def rope_interleaved(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: (S, H, hd); rotate pairs (2i, 2i+1) by pos * theta^(-2i/hd)."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]  # (S, half)
    cos = jnp.cos(ang)[:, None, :]  # (S, 1, half)
    sin = jnp.sin(ang)[:, None, :]
    xf = x.astype(jnp.float32)
    x1 = xf[..., 0::2]
    x2 = xf[..., 1::2]
    o1 = x1 * cos - x2 * sin
    o2 = x1 * sin + x2 * cos
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)


def init_cache(cfg: ModelConfig, dtype=jnp.bfloat16) -> dict:
    """KV ring, HEAD-MAJOR: (L, n_kv, n_ctx, hd).  Head-major is the layout
    every attention consumer reads (XLA decode scores, the flash kernel's
    per-head blocks, ring chunks), so readers slice it directly; the
    sequence-major alternative forced a full-ring transpose per layer per
    decode step and per flash prefill call (VERDICT r3 #9, ≤ ~1 ms/token
    at 8k).  Writers pay instead: the S NEW tokens' (S, n_kv, hd) slab is
    transposed before its dynamic_update_slice — S ≤ bucket-size, not
    n_ctx.

    ``cfg.kv_dtype == "int8"`` swaps the two bf16 leaves for the quantized
    layout (docs/KV_CACHE.md): int8 value rings ``k_q``/``v_q`` of the same
    shape plus per-head, per-token symmetric f32 scales ``k_s``/``v_s``
    (L, n_kv, n_ctx) — HBM per token-head drops 2·hd → hd + 4 bytes, and
    attention reads stream int8."""
    shape = (cfg.n_layers, cfg.n_kv_heads, cfg.n_ctx, cfg.head_dim)
    if cfg.kv_dtype == "int8":
        sshape = shape[:-1]
        return {
            "k_q": jnp.zeros(shape, jnp.int8),
            "v_q": jnp.zeros(shape, jnp.int8),
            "k_s": jnp.zeros(sshape, jnp.float32),
            "v_s": jnp.zeros(sshape, jnp.float32),
        }
    if cfg.kv_dtype not in ("bf16", "bfloat16"):
        raise ValueError(f"kv_dtype must be bf16|int8, got {cfg.kv_dtype!r}")
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_nbytes(cfg: ModelConfig) -> int:
    """HBM bytes of ONE cache ring under ``cfg`` (batched engines hold one
    per lane) — the /health ``kv_cache_bytes`` figure and the lane-headroom
    math in docs/KV_CACHE.md, computed from shapes so callers never need a
    live cache."""
    per_tok_head = cfg.head_dim * (1 if cfg.kv_dtype == "int8" else 2) \
        + (4 if cfg.kv_dtype == "int8" else 0)
    return 2 * cfg.n_layers * cfg.n_kv_heads * cfg.n_ctx * per_tok_head


def xla_attention(q, kk, vv, cks, cvs, positions, cfg: ModelConfig,
                  out_dtype):
    """The XLA score-matrix attention over a full head-major ring — the
    decode path (S=1 always lands here) and the small-prompt prefill path.

    Extracted from :func:`_layer` so the layer-looped decode kernel
    (ops/pallas/decode_loop.py) runs the SAME code: bit-exactness of the
    looped path is then a property of shared source, not of two
    implementations agreeing.  ``cks``/``cvs`` are the int8 cache's
    per-head per-token scales (None for bf16): scores are linear in K and
    probs·V is linear in V, so both scale sets fold OUTSIDE the int8
    contractions and no dequantized ring is ever materialized."""
    S = q.shape[0]
    n_kv, group, hd = cfg.n_kv_heads, cfg.n_heads // cfg.n_kv_heads, cfg.head_dim
    quant = cks is not None
    # (S, n_kv, group, hd) → (n_kv, group, S, hd)
    qg = q.reshape(S, n_kv, group, hd).transpose(1, 2, 0, 3)
    if quant:
        # scores are linear in K, so the per-token scale factors out of
        # the contraction: einsum over the RAW int8 ring (the int8→bf16
        # convert fuses into the dot's operand read — HBM moves int8),
        # then scale each key column once.  No dequantized ring is ever
        # materialized.
        scores = jnp.einsum(
            "ngsh,nch->ngsc", qg, kk.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * (hd ** -0.5) * cks[:, None, None, :]
    else:
        scores = jnp.einsum(
            "ngsh,nch->ngsc", qg, kk, preferred_element_type=jnp.float32
        ) * (hd ** -0.5)  # (n_kv, group, S, n_ctx)

    key_pos = jnp.arange(cfg.n_ctx)
    q_pos = positions  # (S,)
    mask = key_pos[None, :] <= q_pos[:, None]  # causal over the whole ring
    if cfg.sliding_window:
        mask &= key_pos[None, :] > q_pos[:, None] - cfg.sliding_window
    scores = jnp.where(mask[None, None, :, :], scores, -jnp.inf)
    if quant:
        # same trick on V: probs·(q·s) == (probs·s)·q — fold the value
        # scales into the (tiny) probability matrix, contract int8
        probs = (jax.nn.softmax(scores, axis=-1)
                 * cvs[:, None, None, :]).astype(qg.dtype)
        ctx = jnp.einsum("ngsc,nch->ngsh", probs, vv.astype(qg.dtype))
    else:
        probs = jax.nn.softmax(scores, axis=-1).astype(vv.dtype)
        ctx = jnp.einsum("ngsc,nch->ngsh", probs, vv)  # (n_kv, group, S, hd)
    return ctx.transpose(2, 0, 1, 3).reshape(S, cfg.n_heads * hd).astype(out_dtype)


def _layer(h, layers, i, cache, positions, pos_offset,
           cfg: ModelConfig):
    """One transformer block over S tokens against layer ``i`` of the
    stacked weights. ``cache``: the FULL stacked cache pytree, head-major
    (L, n_kv, n_ctx, hd) value leaves (+ (L, n_kv, n_ctx) scale leaves
    under ``kv_dtype=int8``).

    The weights stay STACKED (L, ...) and are addressed per layer with
    :func:`ops.linear.linear_at` — scanning them as xs would materialize a
    per-layer copy of every fused quantized plane before its pallas_call
    (+6.3 ms/token measured on 8B v5e decode, tools/decode_breakdown.py).
    The cache is updated the same way: only the S new token slots of layer
    ``i`` are written (``dynamic_update_slice`` at (i, pos, 0, 0)); carrying
    per-layer caches through ``lax.scan`` xs/ys instead restacks the whole
    ring every step — ~256 MB/token at n_ctx 1024, ~2 GB at 8192."""
    S = h.shape[0]
    n_kv, group, hd = cfg.n_kv_heads, cfg.n_heads // cfg.n_kv_heads, cfg.head_dim
    quant = cfg.kv_dtype == "int8"

    def lin(x, name):
        return linear_at(x, layers[name], i)

    def at_layer(leaf):
        return jax.lax.dynamic_index_in_dim(leaf, i, axis=0, keepdims=False)

    hn = rms_norm(h, layers["attn_norm"][i], cfg.rms_eps)
    q = lin(hn, "wq").reshape(S, cfg.n_heads, hd)
    k = lin(hn, "wk").reshape(S, n_kv, hd)
    v = lin(hn, "wv").reshape(S, n_kv, hd)
    q = rope_interleaved(q, positions, cfg.rope_theta)
    k = rope_interleaved(k, positions, cfg.rope_theta)

    if quant:
        # quantize ONLY the S new tokens' head-major slab (kvquant.py: int8
        # values + per-head per-token f32 scales), then write both planes
        from ..ops.pallas.kvquant import quantize_kv

        kq, ks = quantize_kv(k.transpose(1, 0, 2))     # (n_kv, S, hd)
        vq, vs = quantize_kv(v.transpose(1, 0, 2))
        cache = {
            "k_q": jax.lax.dynamic_update_slice(
                cache["k_q"], kq[None], (i, 0, pos_offset, 0)),
            "v_q": jax.lax.dynamic_update_slice(
                cache["v_q"], vq[None], (i, 0, pos_offset, 0)),
            "k_s": jax.lax.dynamic_update_slice(
                cache["k_s"], ks[None], (i, 0, pos_offset)),
            "v_s": jax.lax.dynamic_update_slice(
                cache["v_s"], vs[None], (i, 0, pos_offset)),
        }
        ck, cv = at_layer(cache["k_q"]), at_layer(cache["v_q"])
        cks, cvs = at_layer(cache["k_s"]), at_layer(cache["v_s"])
    else:
        # head-major write: transpose only the S new tokens, not the ring
        kh = k.astype(cache["k"].dtype).transpose(1, 0, 2)   # (n_kv, S, hd)
        vh = v.astype(cache["v"].dtype).transpose(1, 0, 2)
        cache = {
            "k": jax.lax.dynamic_update_slice(
                cache["k"], kh[None], (i, 0, pos_offset, 0)),
            "v": jax.lax.dynamic_update_slice(
                cache["v"], vh[None], (i, 0, pos_offset, 0)),
        }
        ck, cv = at_layer(cache["k"]), at_layer(cache["v"])
        cks = cvs = None

    if cfg.attn_impl == "ring":
        # sequence-parallel: KV sharded over the sp mesh axis (parallel/ring.py)
        from ..parallel.ring import ring_attention, sharded_decode_attention

        if quant:
            # the ring collectives pass K/V chunks chip-to-chip, so this
            # path materializes the layer's ring in bf16 (elementwise →
            # stays sp-sharded); only XLA/flash get the fused-scale reads
            from ..ops.pallas.kvquant import dequantize_kv

            ck = dequantize_kv(ck, cks, h.dtype)
            cv = dequantize_kv(cv, cvs, h.dtype)
        attn = ring_attention if S > 1 else sharded_decode_attention
        ctx = attn(
            q, ck, cv, pos_offset,
            sm_scale=hd ** -0.5,
            sliding_window=cfg.sliding_window,
        ).reshape(S, cfg.n_heads * hd).astype(h.dtype)
    elif cfg.attn_impl == "pallas" and S > 1:
        # blockwise flash kernel: streams K/V, never materializes scores;
        # int8 caches ride the fused-dequant path (scales folded in-kernel)
        from ..ops.pallas import flash_attention, use_interpret

        ctx = flash_attention(
            q, ck, cv, pos_offset,
            sm_scale=hd ** -0.5,
            sliding_window=cfg.sliding_window,
            k_scale=cks,
            v_scale=cvs,
            interpret=use_interpret(),
        ).reshape(S, cfg.n_heads * hd).astype(h.dtype)
    else:
        ctx = xla_attention(q, ck, cv, cks, cvs, positions, cfg, h.dtype)
    h = h + lin(ctx, "wo")

    hn = rms_norm(h, layers["ffn_norm"][i], cfg.rms_eps)
    gated = jax.nn.silu(lin(hn, "w_gate").astype(jnp.float32)).astype(h.dtype)
    h = h + lin(gated * lin(hn, "w_up"), "w_down")
    return h, cache


def _loop_unroll(params: dict, cfg: ModelConfig, S: int):
    """(effective layers-per-launch, weight plan) for this trace — (0,
    None) selects the per-layer path.  All inputs are trace-time static;
    every ineligible armed configuration is attributed once (log + the
    /debug/compiles degrade ledger) via
    :func:`..ops.pallas.decode_loop.note_degrade` so a pod that silently
    serves per-layer decode can always explain why."""
    if not cfg.decode_layer_unroll or S != 1:
        return 0, None   # off, or a prefill/verify trace: not a decode step
    from ..ops.pallas.decode_loop import (
        decode_loop_disabled,
        effective_unroll,
        loop_geometry,
        note_degrade,
    )

    if cfg.attn_impl == "ring":
        # sp-sharded rings gate off: the ring collectives cross chips,
        # which a single fused kernel cannot (docs/RUNBOOK.md)
        note_degrade("decode_loop",
                     "attn_impl=ring (sequence-parallel) serves per-layer")
        return 0, None
    from .params import decode_loop_plan

    fmts, reason = decode_loop_plan(params, cfg)
    if reason is not None:
        note_degrade("decode_loop", reason)
        return 0, None
    reason = decode_loop_disabled(loop_geometry(cfg, fmts))
    if reason is not None:
        note_degrade("decode_loop", reason)
        return 0, None
    return effective_unroll(cfg), fmts


def forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,      # (S,) int32, padded to a static bucket
    pos_offset: jax.Array,  # scalar int32: cache position of tokens[0]
    cache: dict,
    last_idx: jax.Array | None = None,  # scalar int32: position of last real token
    return_all: bool = False,
):
    """Run S tokens through the stack. Returns (logits, new_cache):
    logits (vocab,) at ``last_idx`` (default S-1), or (S, vocab) if
    ``return_all``."""
    S = tokens.shape[0]
    h = jnp.take(params["tok_emb"], tokens, axis=0).astype(jnp.bfloat16)
    positions = pos_offset + jnp.arange(S, dtype=jnp.int32)

    # trace-time layer-count check over EVERY stacked leaf: looping over ids
    # (not weight xs) would otherwise let a config/checkpoint depth mismatch
    # silently clamp the per-layer gathers to the last real layer (scan over
    # xs used to enforce this shape agreement implicitly)
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            params["layers"])[0]:
        if leaf.shape[0] != cfg.n_layers:
            name = jax.tree_util.keystr(path)
            raise ValueError(
                f"stacked leaf {name} has {leaf.shape[0]} layers but "
                f"cfg.n_layers={cfg.n_layers}")

    # Layer-looped decode (ROADMAP item 2; "Kernel Looping", PAPERS.md):
    # with ``cfg.decode_layer_unroll`` armed, a single-token decode step
    # runs K layers per Pallas launch instead of the per-layer kernel
    # chain — O(L/K) launches per step instead of O(L × ops).  Trace-time
    # selection: S, the config knob, the weight-plan eligibility and the
    # probe-degrade flag are all static, so the per-layer path below
    # compiles exactly as before whenever the loop is off or ineligible.
    K, loop_fmts = _loop_unroll(params, cfg, S)
    if K:
        from ..ops.pallas.decode_loop import forward_layers_looped

        h, new_cache = forward_layers_looped(
            params["layers"], cfg, h, pos_offset, cache, K, loop_fmts)
    else:
        # fori_loop (not scan with cache xs/ys): the stacked cache rides the
        # carry and each layer writes only its S new token slots in place —
        # scan's ys-restack rewrites the entire ring every call (~256
        # MB/token at n_ctx 1024, ~2 GB at 8192 — measured as most of the
        # 8k decode gap)
        def body(i, carry):
            return _layer(carry[0], params["layers"], jnp.int32(i), carry[1],
                          positions, pos_offset, cfg)

        h, new_cache = jax.lax.fori_loop(0, cfg.n_layers, body, (h, cache))

    out_w = params["output"]
    if return_all:
        hn = rms_norm(h, params["out_norm"], cfg.rms_eps)
        logits = linear(hn, out_w).astype(jnp.float32)
        return logits, new_cache
    if last_idx is None:
        last_idx = jnp.int32(S - 1)
    h_last = jax.lax.dynamic_slice_in_dim(h, last_idx, 1, axis=0)
    hn = rms_norm(h_last, params["out_norm"], cfg.rms_eps)
    logits = linear(hn, out_w).astype(jnp.float32)[0]
    return logits, new_cache


def prefill(params, cfg: ModelConfig, tokens, length, cache):
    """Prompt pass: tokens padded to a bucket, ``length`` = real token count.
    Returns logits at the last real token."""
    return forward(params, cfg, tokens, jnp.int32(0), cache, last_idx=length - 1)


def decode_step(params, cfg: ModelConfig, token, pos, cache):
    """One autoregressive step: ``token`` at cache position ``pos``."""
    return forward(params, cfg, token[None], pos, cache)

"""On-device autoregressive generation.

The hot loop the reference runs inside llama.cpp's C++ decode (SURVEY.md §3.2
"THE hot loop") becomes a ``lax.scan`` over decode steps: embed → layers →
logits → sampling chain → next token, entirely on device.  The host only sees
a chunk of ``n_steps`` sampled tokens per dispatch (checks stop conditions,
streams text out), so per-token host↔device round-trips — the classic TPU
decode-latency killer — are amortized away.  The KV cache and generation
state are donated across chunks, so decode is allocation-free at steady
state.  The ``donate_argnames`` declarations below are the source of
truth for lfkt-lint's DON donor registry: a caller that reads the
donated cache/state after dispatch (or keeps a stale alias) fails
tier-1 statically (DON001-002, docs/LINT.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..obs.devtime import timed_jit
from ..sampling.sample import PENALTY_WINDOW, sample_chain
from .config import ModelConfig
from .llama import forward, init_cache, prefill


def init_state(cfg: ModelConfig, cache=None, seed: int = 0) -> dict:
    """Generation state pytree (cache + position + sampling state)."""
    return {
        "cache": cache if cache is not None else init_cache(cfg),
        "pos": jnp.int32(0),                # next cache slot to write
        "token": jnp.int32(0),              # token to feed next
        "window": jnp.full(PENALTY_WINDOW, -1, jnp.int32),
        "wpos": jnp.int32(0),
        "key": jax.random.PRNGKey(seed),
    }


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill_jit(params, cfg: ModelConfig, tokens, length, cache):
    """Bucketed prompt pass. tokens (S,) padded; length = real count.
    Returns (logits_at_last_real_token, cache)."""
    return prefill(params, cfg, tokens, length, cache)


prefill_jit = timed_jit("prefill", prefill_jit, site="models.generate")


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill_chunk_jit(params, cfg: ModelConfig, tokens, pos_offset, last_idx,
                      cache):
    """One slice of a chunked prompt pass: ``tokens`` (C,) enter the cache
    at ``pos_offset``; returns (logits at ``last_idx`` within the chunk,
    cache).  The continuous scheduler prefills admissions in these chunks
    so live lanes' decode interleaves instead of stalling for a whole
    bucket (engine/continuous.py); callers discard the logits of every
    chunk except the one containing the prompt's last real token."""
    return forward(params, cfg, tokens, pos_offset, cache, last_idx=last_idx)


prefill_chunk_jit = timed_jit("prefill_chunk", prefill_chunk_jit,
                              site="models.generate")


@functools.partial(jax.jit, static_argnames=("cfg", "top_k"))
def sample_jit(logits, window, wpos, key, st, cfg: ModelConfig, top_k: int = 40):
    """Sample the first token (from prefill logits) and update sampler state."""
    key, sub = jax.random.split(key)
    token = sample_chain(logits, window, sub, st, top_k=top_k)
    window = window.at[wpos % PENALTY_WINDOW].set(token)
    return token, window, wpos + 1, key


sample_jit = timed_jit("first_sample", sample_jit, site="models.generate")


def generate_chunk(params, cfg: ModelConfig, state: dict, st: dict,
                   n_steps: int, top_k: int = 40):
    """Pure ``n_steps`` decode+sample scan (the body of
    :func:`generate_chunk_jit`; parallel/ring.py re-jits it under a ring
    context for sequence-parallel decode)."""

    def step(carry, _):
        logits, cache = forward(
            params, cfg, carry["token"][None], carry["pos"], carry["cache"]
        )
        key, sub = jax.random.split(carry["key"])
        token = sample_chain(logits, carry["window"], sub, st, top_k=top_k)
        window = carry["window"].at[carry["wpos"] % PENALTY_WINDOW].set(token)
        new_carry = {
            "cache": cache,
            "pos": carry["pos"] + 1,
            "token": token,
            "window": window,
            "wpos": carry["wpos"] + 1,
            "key": key,
        }
        return new_carry, token

    return jax.lax.scan(step, state, None, length=n_steps)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "top_k"),
    donate_argnames=("state",),
)
def generate_chunk_jit(params, cfg: ModelConfig, state: dict, st: dict,
                       n_steps: int, top_k: int = 40):
    """Run ``n_steps`` decode+sample steps on device.

    state["token"] is the most recently sampled (not yet decoded) token.
    Returns (new_state, tokens (n_steps,)) — the tokens sampled this chunk.
    """
    return generate_chunk(params, cfg, state, st, n_steps, top_k)


generate_chunk_jit = timed_jit("decode_chunk", generate_chunk_jit,
                               site="models.generate")


def spec_verify(params, cfg: ModelConfig, state: dict, st: dict,
                draft, top_k: int = 40):
    """Speculative-decoding verify step (prompt-lookup drafts, engine.py).

    Feeds ``[state["token"], draft...]`` — D+1 tokens — through ONE forward
    at positions pos..pos+D (a short prefill continuation: the MXU sees a
    batched matmul instead of D+1 matvecs, and HBM weight traffic is paid
    once for up to D+1 tokens), then replays the sampling chain
    sequentially over the returned logits.  Position ``i``'s sample is
    *emitted* iff every earlier sample matched its draft token, so the
    emitted prefix — including the first mismatch, which IS the true
    sample — is distributed exactly as sequential decoding, for any
    sampler: each emitted token consumes the same PRNG fold, penalty
    window, and conditioning as the vanilla path.  (The batched forward's
    logits differ from the sequential ones only by float reduction order,
    so greedy outputs are identical — pinned by tests/test_spec_decode.py
    — and sampled outputs are equal in distribution up to those ULPs, the
    property every batched-verify speculative decoder shares, llama.cpp's
    included.)  Rejected positions leave stale K/V in
    cache slots beyond the new ``pos``; the attention mask is
    position-based (models/llama.py), so they are never read and get
    overwritten as decode advances.

    Returns (new_state, tokens (D+1,), count): ``tokens[:count]`` are the
    emitted tokens (1 ≤ count ≤ D+1); the llama.cpp analogue is the
    tree-less speculative loop of its lookup-decoding example.
    """
    import dataclasses

    D = draft.shape[0]
    seq = jnp.concatenate([state["token"][None], draft])
    if cfg.attn_impl == "pallas":
        # the flash prefill kernel is tuned (and startup-probed) for
        # bucket-sized S; a D+1-token block would hit it with unaligned
        # tiny tiles.  The XLA score-matrix path is cheap at S ≈ 9.
        cfg = dataclasses.replace(cfg, attn_impl="xla")
    logits, cache = forward(params, cfg, seq, state["pos"], state["cache"],
                            return_all=True)
    # pad the draft so position D (no guess to match) never extends `alive`
    dpad = jnp.concatenate([draft, jnp.int32(-1)[None]])

    def step(carry, xs):
        lg, d_i = xs
        nk, sub = jax.random.split(carry["key"])
        s = sample_chain(lg, carry["window"], sub, st, top_k=top_k)
        emit = carry["alive"]
        win2 = carry["window"].at[carry["wpos"] % PENALTY_WINDOW].set(s)
        new_carry = {
            "key": jnp.where(emit, nk, carry["key"]),
            "window": jnp.where(emit, win2, carry["window"]),
            "wpos": jnp.where(emit, carry["wpos"] + 1, carry["wpos"]),
            "alive": jnp.logical_and(carry["alive"], s == d_i),
            "last": jnp.where(emit, s, carry["last"]),
            "count": carry["count"] + emit.astype(jnp.int32),
        }
        return new_carry, s

    init = {
        "key": state["key"], "window": state["window"], "wpos": state["wpos"],
        "alive": jnp.bool_(True), "last": state["token"],
        "count": jnp.int32(0),
    }
    fin, toks = jax.lax.scan(step, init, (logits, dpad))
    new_state = {
        "cache": cache,
        "pos": state["pos"] + fin["count"],
        "token": fin["last"],
        "window": fin["window"],
        "wpos": fin["wpos"],
        "key": fin["key"],
    }
    return new_state, toks, fin["count"]


spec_verify_jit = timed_jit("spec_verify", functools.partial(
    jax.jit,
    static_argnames=("cfg", "top_k"),
    donate_argnames=("state",),
)(spec_verify), site="models.generate")

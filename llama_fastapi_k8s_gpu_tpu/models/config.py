"""Model architecture config, derived from GGUF metadata.

Mirrors the hparams llama.cpp reads when the reference loads a model
(``Llama(model_path=..., n_ctx=1024)``, reference api.py:24-28).  Covers the
Llama family (Llama-2/3) and Mistral (same graph + optional sliding-window
attention, BASELINE.json config "Mistral-7B ... sliding-window attention
path").
"""

from __future__ import annotations

import dataclasses

from ..gguf import GGUFFile


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    ffn_dim: int
    n_ctx: int
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    sliding_window: int = 0      # 0 = full causal attention
    tie_embeddings: bool = False
    dtype: str = "bfloat16"
    # "xla" materializes (S, n_ctx) scores; "pallas" streams K/V through the
    # blockwise flash kernel (ops/pallas/attention.py) on prefill paths;
    # "ring" shards the sequence over the sp mesh axis — only valid through
    # the parallel/ring.py entry points (sp_prefill / sp_decode_step), which
    # establish the mesh context the ring ops need.
    attn_impl: str = "xla"
    # KV-cache storage dtype: "bf16" (the default two-leaf {k, v} ring) or
    # "int8" (four-leaf {k_q, v_q, k_s, v_s}: int8 values + per-head
    # per-token symmetric f32 scales — ops/pallas/kvquant.py writes them,
    # the attention consumers dequantize in-register).  Static so the cache
    # pytree STRUCTURE is fixed at trace time (docs/KV_CACHE.md).
    kv_dtype: str = "bf16"
    # Layer-looped decode (ops/pallas/decode_loop.py; LFKT_DECODE_LAYER_
    # UNROLL): layers fused per Pallas launch on the single-token decode
    # step — 0 = off (the per-layer kernel chain), -1 = all layers in one
    # launch, K>0 = K layers per launch (clamped to a divisor of
    # n_layers).  A ModelConfig field rather than a process-lifetime env
    # read so a jit retrace (and therefore an in-process bench sweep /
    # A-B) is just ``dataclasses.replace`` — the knob is part of every
    # compiled program's static signature.
    decode_layer_unroll: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def from_gguf(cls, gf: GGUFFile, n_ctx: int | None = None) -> "ModelConfig":
        h = gf.hparam
        n_heads = int(h("attention.head_count"))
        vocab = h("vocab_size")
        if vocab is None:
            vocab = len(gf.metadata["tokenizer.ggml.tokens"])
        window = int(h("attention.sliding_window", 0) or 0)
        train_ctx = int(h("context_length", 4096))
        return cls(
            vocab_size=int(vocab),
            dim=int(h("embedding_length")),
            n_layers=int(h("block_count")),
            n_heads=n_heads,
            n_kv_heads=int(h("attention.head_count_kv", n_heads)),
            ffn_dim=int(h("feed_forward_length")),
            n_ctx=int(n_ctx if n_ctx is not None else min(train_ctx, 4096)),
            rope_theta=float(h("rope.freq_base", 10000.0)),
            rms_eps=float(h("attention.layer_norm_rms_epsilon", 1e-5)),
            sliding_window=window,
            tie_embeddings="output.weight" not in gf.tensors,
        )


# Canonical full-size configs (for synthesis / benches; no network egress, so
# bench models are built from these shapes with random weights).
LLAMA3_8B = ModelConfig(
    vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    ffn_dim=14336, n_ctx=1024, rope_theta=500000.0, rms_eps=1e-5,
)
MISTRAL_7B = ModelConfig(
    vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    ffn_dim=14336, n_ctx=1024, rope_theta=1000000.0, rms_eps=1e-5,
)

"""FastAPI serving layer.

Preserves the reference's externally observable behavior line by line
(reference api.py; SURVEY.md §2A #3-#8):

- ``POST /response`` with the same schema, the same system-prompt assembly
  quirks (insert at index 1, ``.f`` name-suffix gender clause,
  ``appearance.split(",")[3:]`` fact append — api.py:127-147), the same
  truncation (400-char clip, chars/4 estimate, pop-index-2 loop —
  api.py:30-46), and the same admission control: bounded queue(5) → 503,
  single consumer + semaphore(1) → strictly serial generation, 25 s future
  timeout → 408 with cancellation, engine errors → 500 (api.py:80-173).
- the vestigial ``GET /items/{item_id}`` echo route (api.py:175-177).
- the request-timing log middleware (api.py:179-194).

Additions the reference advertises but lacks (SURVEY.md §2C): ``GET /health``
(model/device/queue state, wired for k8s probes) and ``GET /metrics``
(Prometheus text).  All constants are env-overridable with identical defaults
(utils/config.py).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import math
import signal
import time
from datetime import datetime

import json

from .asgikit import (
    HTTPException,
    JSONResponse,
    MicroAPI,
    PlainTextResponse,
    Request,
    StreamingResponse,
)

import uuid

from ..obs import flightrec as _flightrec
from ..obs import memledger as _memledger
from ..obs.devtime import DEVTIME
from ..obs.logctx import access_logger, bind_request_id, sanitize_text
from ..obs.slo import SLOEngine
from ..obs.trace import TRACER, Tracer
from ..serving.fleet.affinity import AFFINITY_KEY_HEADER, PRIOR_OWNER_HEADER
from ..utils.config import Settings, get_settings
from ..utils.faults import FAULTS
from ..utils.health import (
    READY,
    STARTING,
    STATE_CODES,
    DeadlineExceeded,
    EngineUnavailable,
    HealthMonitor,
)
from ..utils.metrics import Metrics
from .schemas import BotMessageRequest, ChatCompletionRequest

logging.basicConfig(level=logging.INFO)
logger = logging.getLogger(__name__)

_STREAM_DONE = object()  # consumer→handler sentinel: stream finished cleanly


def _openai_error_body(status: int, message: str, code: str | None = None
                       ) -> dict:
    """The OpenAI-style error envelope served on every ``/v1/*`` failure
    (docs/MULTIMODEL.md facade mapping): 4xx are the caller's fault
    (``invalid_request_error``; 408 keeps its own type so SDK retry
    policies can tell a timeout from a bad request), 5xx are ours."""
    if status >= 500 or status == 503:
        etype = "server_error"
    elif status == 408:
        etype = "timeout_error"
    else:
        etype = "invalid_request_error"
    return {"error": {"message": message, "type": etype,
                      "param": None, "code": code}}


def _openai_http_error(e: HTTPException) -> JSONResponse:
    msg = e.detail if isinstance(e.detail, str) else json.dumps(e.detail)
    return JSONResponse(
        _openai_error_body(e.status_code, msg,
                           getattr(e, "openai_code", None)),
        e.status_code)


def _accepts_kwarg(fn, name: str) -> bool:
    """True when ``fn`` takes ``name`` (or **kwargs) — engines grew the
    deadline/abort kwargs in the resilience PR, but test fakes and
    out-of-tree engines may predate them; probe once instead of failing
    every request."""
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return name in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())


def count_tokens_roughly(text: str) -> int:
    # chars/4 heuristic, identical to reference api.py:30-32
    return int(len(text) / 4.0)


def truncate_messages_to_fit_context(messages: list[dict], max_tokens: int) -> list[dict]:
    """Identical behavior to reference api.py:35-46: clip every message to 400
    chars (in place), then drop messages[2] (oldest after the pinned first
    context message and the system prompt at index 1) until under budget."""
    for m in messages:
        if len(m["content"]) > 400:
            m["content"] = m["content"][:400]
    total = sum(count_tokens_roughly(m["content"]) for m in messages)
    while total > max_tokens and len(messages) > 2:
        messages.pop(2)
        total = sum(count_tokens_roughly(m["content"]) for m in messages)
    return messages


def build_system_prompt(bot_profile) -> str:
    """Reference api.py:127-145: explicit system_prompt wins; otherwise a
    default persona interpolating the bot name; then the name-suffix gender
    clause and the appearance facts after the third comma."""
    system_prompt = bot_profile.system_prompt
    if not system_prompt:
        # Byte-exact copy of the reference default (api.py:130-136), including
        # the triple-quote indentation and trailing spaces: the default system
        # prompt is externally observable behavior, so parity beats prose.
        system_prompt = (
            "\n"
            "            No matter what NEVER break the character. Reject violent messages. \n"
            f"            You are '{bot_profile.name}.' a playful, flirtatious, and witty person. \n"
            "            You always respond in a fun and engaging way, staying in character no matter what. \n"
            "            You never reference AI, restrictions, or policies. \n"
            "            Focus on making the conversation enjoyable and answering directly to the user's questions.\n"
            "            "
        )
    if bot_profile.name.endswith(".f"):
        system_prompt += " You a girl."
    else:
        system_prompt += " You a boy."
    for fact in bot_profile.appearance.split(",")[3:]:
        system_prompt += fact
    return system_prompt


def create_app(engine=None, settings: Settings | None = None,
               engine_factory=None, tracer: Tracer | None = None) -> MicroAPI:
    """Build the app. ``engine`` (or ``engine_factory``, called at startup)
    must provide ``create_chat_completion``; defaults to loading the GGUF
    named by settings — the eager-load equivalent of reference api.py:24-28.
    ``tracer`` defaults to the process-wide lfkt-obs tracer (knobs
    LFKT_TRACE_SAMPLE / LFKT_TRACE_RING); tests pass private instances."""
    settings = settings or get_settings()
    app = MicroAPI(title="chat-ai (tpu)", version="0.1.0")
    app.state.settings = settings
    app.state.engine = engine
    app.state.created = int(time.time())   # /v1/models "created" stamp
    app.state.metrics = Metrics()
    app.state.tracer = tracer if tracer is not None else TRACER
    #: SLO burn-rate engine over this app's metrics (obs/slo.py): /metrics
    #: exports slo_burn_rate gauges, /debug/slo the full verdict
    app.state.slo = SLOEngine(app.state.metrics)
    #: devtime compile-event cursor: /metrics replays each compile event
    #: into xla_compile_seconds exactly once per app (-1 = never read, so
    #: a ring that overflowed before this app existed charges no drop)
    app.state.devtime_cursor = -1
    app.state.ready = engine is not None
    #: pod health state machine (utils/health.py): STARTING until the
    #: engine is loaded; the watchdog moves it between READY/DEGRADED/DEAD
    app.state.health = HealthMonitor()
    app.state.watchdog = None
    #: disaggregated prefill/decode roles (serving/disagg/): armed at
    #: startup from LFKT_DISAGG_ROLE; None = the single-process path
    app.state.disagg = None
    #: fleet KV migration (serving/fleet/migrate.py): armed at startup
    #: from LFKT_MIGRATE; None = warm pages die with this pod
    app.state.migration = None
    #: live manifest reload (serving/registry.py reload_manifest): one
    #: reload at a time — POST /admin/models/reload and SIGHUP share it
    app.state.reload_busy = asyncio.Lock()
    app.state.engine_kw = {}   # which resilience kwargs the engine accepts
    # strong refs to fire-and-forget tasks: the loop holds only weak refs,
    # so an unreferenced task can be garbage-collected mid-flight (losing
    # its inflight permit and stranding its caller)
    app.state.bg_tasks = set()

    def _spawn(coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        app.state.bg_tasks.add(task)
        task.add_done_callback(app.state.bg_tasks.discard)
        return task

    def _queue_span(rd, now: float) -> None:
        """Record the admission-queue wait (enqueue → consumer pickup) on
        the request's trace; no-op for sampled-out requests."""
        tr = rd.get("trace")
        if tr is not None:
            tr.span("queue", t0=rd["enqueued_at"]).end(now)

    async def consumer():
        """Single drain task: strict FIFO, one generation *cycle* at a time
        (reference api.py:80-107).  With ``batch_size > 1`` and a
        batch-capable engine, a cycle coalesces up to batch_size queued
        requests into one mesh-batched generation (engine/batched.py);
        FIFO order is preserved."""
        queue = app.state.queue
        semaphore = app.state.semaphore
        while True:
            batch = [await queue.get()]
            continuous = hasattr(app.state.engine, "submit")
            if continuous:
                # slot scheduler: forward without a barrier — the engine
                # admits into free lanes at chunk boundaries.  In-flight
                # count is capped at batch_size so the bounded queue is
                # still the back-pressure surface (503 on overflow);
                # without the cap the engine's pending queue would absorb
                # unlimited work and 503 could never fire.
                rd = batch[0]
                now = time.time()
                app.state.metrics.observe(
                    "queue_wait_seconds", now - rd["enqueued_at"])
                _queue_span(rd, now)
                if rd["future"].cancelled():
                    logger.info("Future was cancelled before processing; skipping.")
                elif "stream_queue" in rd:
                    # streams ride scheduler lanes concurrently with batched
                    # requests; each holds an inflight permit so the bounded
                    # queue (503) stays the back-pressure surface for them too
                    await app.state.inflight.acquire()  # lfkt: transfers[inflight] -- permit released in _stream_task's finally
                    _spawn(_stream_task(rd))
                else:
                    await app.state.inflight.acquire()  # lfkt: transfers[inflight] -- permit released in _forward_to_scheduler's finally
                    _spawn(_forward_to_scheduler(rd))
                queue.task_done()
                continue
            can_batch = (settings.batch_size > 1
                         and hasattr(app.state.engine, "create_chat_completions"))
            while can_batch and len(batch) < settings.batch_size:
                try:
                    batch.append(queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            now = time.time()
            live, streams = [], []
            for rd in batch:
                app.state.metrics.observe(
                    "queue_wait_seconds", now - rd["enqueued_at"])
                _queue_span(rd, now)
                if rd["future"].cancelled():
                    logger.info("Future was cancelled before processing; skipping.")
                elif "stream_queue" in rd:
                    streams.append(rd)
                else:
                    live.append(rd)
            results: list[tuple] = []           # (request, response, error)
            if can_batch and live:
                # /v1 facade requests never coalesce into a mesh cycle:
                # the batched path applies the /response truncation quirks
                # and returns text, both wrong for the OpenAI contract —
                # they take the per-request path below instead
                batchable = [rd for rd in live if not rd.get("openai")]
                solo = [rd for rd in live if rd.get("openai")]
            else:
                batchable, solo = [], live
            if batchable:
                # batch-of-one included: MeshEngine.warmup compiles only the
                # batched shapes, so even solo requests must use them
                try:
                    responses = await _truncate_and_generate_batch(
                        batchable, semaphore)
                    results = [
                        (rd, None, r) if isinstance(r, Exception) else (rd, r, None)
                        for rd, r in zip(batchable, responses)
                    ]
                except Exception as e:  # noqa: BLE001 — one program, one failure
                    results = [(rd, None, e) for rd in batchable]
            for rd in solo:         # per-request isolation (reference semantics)
                try:
                    results.append((rd, await _truncate_and_generate(
                        rd, semaphore), None))
                except Exception as e:  # noqa: BLE001
                    results.append((rd, None, e))
            for rd, resp, err in results:
                if rd["future"].cancelled():
                    logger.info("Future cancelled during processing; "
                                "%s dropped.", "error" if err else "result")
                elif err is not None:
                    rd["future"].set_exception(err)
                else:
                    rd["future"].set_result(resp)
            for rd in streams:      # streaming requests, serial, in FIFO slot
                try:
                    await _truncate_and_stream(rd, semaphore)
                except Exception as e:  # noqa: BLE001 — never kill the consumer
                    logger.error("Error during streamed generation: %s", e)
                    try:
                        rd["stream_queue"].put_nowait(e)
                    except Exception:  # noqa: BLE001
                        pass
            for _ in batch:
                queue.task_done()

    def _model_label(obj=None) -> str:
        """Bounded-cardinality ``model`` label value: the per-request model
        from a response/timings dict when present, else the engine's (or
        the registry's default) name — one series per served model."""
        name = None
        if isinstance(obj, dict):
            name = obj.get("model")
        if not name:
            name = getattr(app.state.engine, "model_name", None)
        return str(name or "")

    def _observe_engine_timings(m, answer=None):
        """Record per-phase engine timings: prefer the per-request values
        attached to the response (no shared-state read-back); fall back to
        the engine's last_timings for paths that predate the attachment."""
        timings = answer.get("lfkt_timings") if isinstance(answer, dict) else None
        if timings is None:
            timings = getattr(app.state.engine, "last_timings", None)
        if timings:
            # per-prefill-bucket TTFT series, labeled per model: the SLO
            # engine evaluates each label series separately, so a
            # 32k-prompt (or one misbehaving co-resident model's)
            # violation cannot hide under the rest (docs/SLO.md —
            # worst_series now names the worst bucket AND model)
            model = _model_label(timings)
            m.observe("engine_ttft_seconds", timings["ttft_s"],
                      bucket=str(timings.get("bucket", 0)), model=model)
            if timings["tokens_per_sec"]:
                m.observe("engine_decode_tokens_per_sec",
                          timings["tokens_per_sec"], model=model)
            spec = timings.get("spec")
            if spec:   # speculative decode: acceptance is THE payoff number
                m.inc("spec_drafted_tokens_total", spec["drafted"])
                m.inc("spec_accepted_tokens_total", spec["accepted"])
                m.inc("spec_verify_steps_total", spec["verify_steps"])
                m.inc("spec_fallback_steps_total", spec["fallback_steps"])
            reused = timings.get("prefix_reused_tokens", 0)
            if reused:  # prompt-prefix KV reuse: prompt tokens NOT re-prefilled
                m.inc("prefix_cache_hits_total")
                m.inc("prefix_cache_reused_tokens_total", reused)

    def _meter_tokens(m, prompt: int, completion: int, model: str) -> None:
        """Per-model token metering (tokens_prompt_total /
        tokens_generated_total): multi-tenant billing from the engines'
        own usage counts, so nobody has to scrape /v1 response bodies."""
        if prompt:
            m.inc("tokens_prompt_total", prompt, model=model)
        if completion:
            m.inc("tokens_generated_total", completion, model=model)

    def _answer_to_text(answer, m) -> str:
        """OpenAI-shaped dict → concatenated choice text (reference
        api.py:65-74 semantics, incl. the dict typecheck → 500)."""
        if not isinstance(answer, dict):
            logger.error("Unexpected response type: %s. Response: %s",
                         type(answer), answer)
            raise HTTPException(status_code=500,
                                detail="Unexpected response from model")
        usage = answer.get("usage") or {}
        if usage.get("completion_tokens"):
            m.inc("generated_tokens_total", usage["completion_tokens"])
        _meter_tokens(m, usage.get("prompt_tokens", 0),
                      usage.get("completion_tokens", 0),
                      _model_label(answer))
        return "".join(c["message"]["content"]
                       for c in answer.get("choices", []) if "message" in c)

    def _answer_openai(answer, m) -> dict:
        """/v1 facade result: the engine's OpenAI-shaped completion dict
        verbatim (usage counts come straight from the engine's timings),
        minus the internal ``lfkt_timings`` rider."""
        if not isinstance(answer, dict):
            logger.error("Unexpected response type: %s. Response: %s",
                         type(answer), answer)
            raise HTTPException(status_code=500,
                                detail="Unexpected response from model")
        usage = answer.get("usage") or {}
        if usage.get("completion_tokens"):
            m.inc("generated_tokens_total", usage["completion_tokens"])
        _meter_tokens(m, usage.get("prompt_tokens", 0),
                      usage.get("completion_tokens", 0),
                      _model_label(answer))
        answer = dict(answer)
        answer.pop("lfkt_timings", None)
        return answer

    def _finish_answer(rd, answer, m):
        """Shape one engine answer for its caller: the /v1 facade gets the
        OpenAI dict, the /response path its concatenated text."""
        if rd.get("openai"):
            return _answer_openai(answer, m)
        return _answer_to_text(answer, m)

    def _gen_kwargs(rd) -> dict:
        """Sampling/budget kwargs for one request: the pod's serving
        defaults (reference api.py:59-62), overridden by the request's own
        OpenAI fields when the /v1 facade set them (rd["params"])."""
        kw = dict(
            temperature=settings.temperature,
            top_p=settings.top_p,
            frequency_penalty=settings.frequency_penalty,
            presence_penalty=settings.presence_penalty,
        )
        kw.update(rd.get("params") or {})
        return kw

    def _validate_model(model: str | None) -> str | None:
        """400 for a model alias this pod does not serve.  Routed through
        the registry's manifest when one is loaded; a single-model process
        serves only its own name (or no name at all)."""
        if model is None:
            return None
        eng = app.state.engine
        has = getattr(eng, "has_model", None)
        if callable(has):
            if not has(model):
                known = ", ".join(eng.model_names())
                e = HTTPException(
                    status_code=400,
                    detail=f"unknown model {model!r}; this pod serves: "
                           f"{known}")
                e.openai_code = "model_not_found"
                raise e
            return model
        name = getattr(eng, "model_name", None)
        if name is not None and model != name:
            e = HTTPException(
                status_code=400,
                detail=f"unknown model {model!r}; this pod serves: {name}")
            e.openai_code = "model_not_found"
            raise e
        return model

    def _resilience_kw(rd) -> dict:
        """Deadline/abort/trace propagation kwargs for engines that accept
        them: the request's admission deadline, a did-the-caller-give-up
        callback (so a timed-out or disconnected request frees the engine
        within one decode step — the reference decoded to budget), and the
        request's trace for the engine's span tree (lfkt-obs)."""
        kw = {}
        if app.state.engine_kw.get("deadline"):
            kw["deadline"] = rd.get("deadline")
        if app.state.engine_kw.get("abort"):
            kw["abort"] = rd["future"].cancelled
        if app.state.engine_kw.get("trace"):
            kw["trace"] = rd.get("trace")
        return kw

    async def _truncate_and_generate(rd, semaphore) -> str:
        m = app.state.metrics
        async with semaphore:  # one generation at a time (reference api.py:50)
            try:
                # /v1 requests ride "raw": OpenAI clients manage their own
                # history, so the reference's 400-char clip + index-2
                # eviction must not rewrite their messages
                if rd.get("raw"):
                    messages = rd["messages"]
                else:
                    messages = truncate_messages_to_fit_context(
                        rd["messages"], settings.max_context_tokens)
                ckw = _gen_kwargs(rd)
                if app.state.engine_kw.get("model"):
                    ckw["model"] = rd.get("model")
                t0 = time.time()
                answer = await asyncio.to_thread(
                    lambda: app.state.engine.create_chat_completion(
                        messages=messages,
                        stream=False,
                        **ckw,
                        **_resilience_kw(rd),
                    ))
                m.observe("generation_seconds", time.time() - t0,
                          model=_model_label(answer))
                _observe_engine_timings(m, answer)
                return _finish_answer(rd, answer, m)
            except HTTPException:
                raise
            except ValueError as e:
                if rd.get("openai"):
                    # client input error (oversized prompt, bad params):
                    # the facade's structured 400, not a 500
                    raise HTTPException(status_code=400,
                                        detail=str(e)) from e
                m.inc("engine_errors_total")
                logger.error("Error during message generation: %s", e)
                raise HTTPException(
                    status_code=500,
                    detail=f"Error during message generation: {str(e)}",
                ) from e
            except EngineUnavailable as e:
                # watchdog trip / recovery in progress: retryable 503, not
                # the "this request hit a bug" 500
                m.inc("engine_unavailable_total")
                logger.error("Engine unavailable: %s", e)
                raise HTTPException(
                    status_code=503, detail=f"Engine unavailable: {e}") from e
            except DeadlineExceeded as e:
                m.inc("requests_timed_out_total")
                raise HTTPException(
                    status_code=408, detail="Generation timed out") from e
            except Exception as e:  # noqa: BLE001 — 500 semantics, api.py:76-78
                m.inc("engine_errors_total")
                logger.error("Error during message generation: %s", e)
                raise HTTPException(
                    status_code=500,
                    detail=f"Error during message generation: {str(e)}",
                ) from e

    async def _truncate_and_generate_batch(rds, semaphore):
        """Batched analogue of ``_truncate_and_generate`` over MeshEngine.
        Returns one entry per request: the response text, or an exception for
        that request alone (per-entry engine errors don't fail neighbors)."""
        m = app.state.metrics
        async with semaphore:
            try:
                batch_messages = [
                    truncate_messages_to_fit_context(rd["messages"],
                                                     settings.max_context_tokens)
                    for rd in rds
                ]
                batch_kw = {}
                if app.state.engine_kw.get("batch_deadlines"):
                    # per-entry deadline/abort propagation: an entry whose
                    # caller timed out or disconnected stops accumulating
                    # within one decode chunk instead of pinning the cycle
                    batch_kw["deadlines"] = [rd.get("deadline") for rd in rds]
                    batch_kw["aborts"] = [rd["future"].cancelled for rd in rds]
                if app.state.engine_kw.get("batch_traces"):
                    batch_kw["traces"] = [rd.get("trace") for rd in rds]
                t0 = time.time()
                answers = await asyncio.to_thread(
                    lambda: app.state.engine.create_chat_completions(
                        batch_messages,
                        temperature=settings.temperature,
                        top_p=settings.top_p,
                        frequency_penalty=settings.frequency_penalty,
                        presence_penalty=settings.presence_penalty,
                        **batch_kw,
                    ))
                m.observe("generation_seconds", time.time() - t0,
                          model=_model_label(next(
                              (a for a in answers if isinstance(a, dict)),
                              None)))
                m.inc("batched_generations_total")
                m.observe("batch_occupancy", len(batch_messages))
                _observe_engine_timings(
                    m, next((a for a in answers
                             if isinstance(a, dict) and "lfkt_timings" in a),
                            None))
                out = []
                for answer in answers:
                    if isinstance(answer, dict) and "error" in answer:
                        out.append(HTTPException(
                            status_code=500,
                            detail="Error during message generation: "
                                   f"{answer['error'].get('message', 'unknown')}"))
                        continue
                    try:
                        out.append(_answer_to_text(answer, m))
                    except HTTPException as e:
                        out.append(e)
                return out
            except EngineUnavailable as e:
                m.inc("engine_unavailable_total")
                logger.error("Engine unavailable: %s", e)
                raise HTTPException(
                    status_code=503, detail=f"Engine unavailable: {e}") from e
            except Exception as e:  # noqa: BLE001 — 500 semantics, api.py:76-78
                m.inc("engine_errors_total")
                logger.error("Error during batched generation: %s", e)
                raise HTTPException(
                    status_code=500,
                    detail=f"Error during message generation: {str(e)}",
                ) from e

    async def _stream_task(rd):
        """Continuous mode: stream via a scheduler lane (no global semaphore —
        lanes already bound concurrency). Holds one inflight permit."""
        try:
            await _truncate_and_stream(rd, None)
        except Exception as e:  # noqa: BLE001 — surfaced on the SSE channel
            logger.error("Error during streamed generation: %s", e)
            try:
                rd["stream_queue"].put_nowait(e)
            except Exception:  # noqa: BLE001
                pass
        finally:
            app.state.inflight.release()

    async def _forward_to_scheduler(rd):
        """Continuous mode: one request → one scheduler lane, no barrier.
        Holds one ``app.state.inflight`` permit (acquired by the consumer).
        If the client's future is cancelled (408 timeout / disconnect) the
        lane is abandoned so it frees at the next chunk boundary instead of
        decoding to budget."""
        m = app.state.metrics
        try:
            try:
                if rd.get("raw"):
                    messages = rd["messages"]
                else:
                    messages = truncate_messages_to_fit_context(
                        rd["messages"], settings.max_context_tokens)
                t0 = time.time()
                engine = app.state.engine
                sub_kw = _gen_kwargs(rd)
                if app.state.engine_kw.get("submit_deadline"):
                    sub_kw["deadline"] = rd.get("deadline")
                if app.state.engine_kw.get("submit_trace"):
                    sub_kw["trace"] = rd.get("trace")
                if app.state.engine_kw.get("submit_model"):
                    sub_kw["model"] = rd.get("model")
                engine_fut = engine.submit(  # lfkt: transfers[engine_fut] -- the scheduler owns the lane: it resolves/reclaims the future via its _items registry even when a failure here skips the await (PR-2 semantics)
                    messages,
                    **sub_kw,
                )
                if hasattr(engine, "abandon"):
                    rd["future"].add_done_callback(
                        lambda f: engine.abandon(engine_fut)
                        if f.cancelled() else None)
                answer = await asyncio.wrap_future(engine_fut)
                m.observe("generation_seconds", time.time() - t0,
                          model=_model_label(answer))
                _observe_engine_timings(m, answer)
                result = _finish_answer(rd, answer, m)
                err = None
            except HTTPException as e:
                result, err = None, e
            except ValueError as e:
                if rd.get("openai"):
                    result, err = None, HTTPException(status_code=400,
                                                      detail=str(e))
                else:
                    m.inc("engine_errors_total")
                    logger.error("Error during message generation: %s", e)
                    result, err = None, HTTPException(
                        status_code=500,
                        detail=f"Error during message generation: {str(e)}")
            except EngineUnavailable as e:
                # watchdog trip failed this future / scheduler restarting:
                # retryable 503 (the reference's only answer was pod death)
                m.inc("engine_unavailable_total")
                logger.error("Engine unavailable: %s", e)
                result, err = None, HTTPException(
                    status_code=503, detail=f"Engine unavailable: {e}")
            except DeadlineExceeded:
                m.inc("requests_timed_out_total")
                result, err = None, HTTPException(
                    status_code=408, detail="Generation timed out")
            except Exception as e:  # noqa: BLE001 — 500 semantics, api.py:76-78
                m.inc("engine_errors_total")
                logger.error("Error during message generation: %s", e)
                result, err = None, HTTPException(
                    status_code=500,
                    detail=f"Error during message generation: {str(e)}")
            if rd["future"].cancelled():
                logger.info("Future cancelled during processing; result dropped.")
            elif err is not None:
                rd["future"].set_exception(err)
            else:
                rd["future"].set_result(result)
        finally:
            app.state.inflight.release()

    async def _truncate_and_stream(rd, semaphore):
        """Run one streaming generation, forwarding engine chunks to the
        handler's queue from the worker thread.

        ``semaphore=None`` (continuous mode) streams through a scheduler
        lane with no global serialization.  When the client abandons the
        stream (timeout/disconnect cancels ``rd["future"]``) the engine
        iterator is closed, which frees the lane/slot at the next chunk
        boundary — on EVERY engine: serial engines used to run to
        completion with chunks dropped (the reference's
        no-mid-generation-abort behavior, api.py:97-100, affordable only
        because its engine idles anyway), but a serial engine here blocks
        the whole consumer while it decodes to budget for nobody."""
        m = app.state.metrics
        chunk_q = rd["stream_queue"]
        loop = asyncio.get_running_loop()
        timings_box: list = []

        async def _go():
            if rd.get("raw"):
                messages = rd["messages"]
            else:
                messages = truncate_messages_to_fit_context(
                    rd["messages"], settings.max_context_tokens)

            def run():
                try:
                    ckw = _gen_kwargs(rd)
                    if app.state.engine_kw.get("model"):
                        ckw["model"] = rd.get("model")
                    it = app.state.engine.create_chat_completion(
                        messages=messages,
                        stream=True,
                        **ckw,
                        **_resilience_kw(rd))
                    try:
                        for chunk in it:
                            if rd["future"].cancelled():
                                return   # closes it → engine frees the lane
                            t = chunk.pop("lfkt_timings", None)
                            if t is not None:
                                timings_box.append(t)
                                # the /v1 stream's optional usage chunk
                                # (stream_options.include_usage) reads the
                                # finished request's token counts off here
                                rd["timings"] = t
                            loop.call_soon_threadsafe(chunk_q.put_nowait, chunk)
                        loop.call_soon_threadsafe(
                            chunk_q.put_nowait, _STREAM_DONE)
                    finally:
                        it.close()
                except Exception as e:  # noqa: BLE001 — surfaced as SSE error
                    loop.call_soon_threadsafe(chunk_q.put_nowait, e)

            t0 = time.time()
            await asyncio.to_thread(run)
            m.observe("generation_seconds", time.time() - t0,
                      model=_model_label(
                          timings_box[0] if timings_box else None))
            m.inc("streamed_generations_total")
            _observe_engine_timings(
                m, {"lfkt_timings": timings_box[0]} if timings_box else None)
            if timings_box:
                # streamed responses never pass through _answer_to_text:
                # meter them from the engine's own timings rider
                t = timings_box[0]
                _meter_tokens(m, t.get("prompt_tokens", 0),
                              t.get("completion_tokens", 0),
                              _model_label(t))

        if semaphore is None:
            await _go()
        else:
            async with semaphore:
                await _go()

    @app.on_event("startup")
    async def startup_event():
        app.state.queue = asyncio.Queue(maxsize=settings.max_queue_size)
        app.state.semaphore = asyncio.Semaphore(1)
        # continuous mode: at most batch_size forwarded-but-unfinished
        # requests, so the bounded queue stays the back-pressure surface
        app.state.inflight = asyncio.Semaphore(max(1, settings.batch_size))
        app.state.health.transition(STARTING, "model loading")
        if app.state.engine is None:
            factory = engine_factory or _default_engine_factory(settings)
            loop = asyncio.get_running_loop()
            app.state.engine = await loop.run_in_executor(None, factory)
        engine = app.state.engine
        # which resilience kwargs this engine accepts (probed once; fakes
        # and out-of-tree engines may predate the deadline/abort contract)
        ccc = getattr(engine, "create_chat_completion", None)
        # multi-model routing: ONLY a registry (has_model is its marker)
        # takes the model= kwarg — plain engines never see it (the alias
        # was validated at admission, so not forwarding is correct), and
        # a signature probe would lie for engines with **kwargs
        # passthroughs (ContinuousEngine.create_chat_completion forwards
        # **kw into submit/submit_stream, which refuse model=)
        is_registry = callable(getattr(engine, "has_model", None))
        app.state.engine_kw = {
            "deadline": ccc is not None and _accepts_kwarg(ccc, "deadline"),
            "abort": ccc is not None and _accepts_kwarg(ccc, "abort"),
            "trace": ccc is not None and _accepts_kwarg(ccc, "trace"),
            "model": ccc is not None and is_registry,
            "submit_deadline": hasattr(engine, "submit") and _accepts_kwarg(
                engine.submit, "deadline"),
            "submit_trace": hasattr(engine, "submit") and _accepts_kwarg(
                engine.submit, "trace"),
            "submit_model": hasattr(engine, "submit") and is_registry,
            "batch_deadlines": hasattr(engine, "create_chat_completions")
            and _accepts_kwarg(engine.create_chat_completions, "deadlines"),
            "batch_traces": hasattr(engine, "create_chat_completions")
            and _accepts_kwarg(engine.create_chat_completions, "traces"),
        }
        # engines observe prefill-slice timings straight into the app's
        # registry (obs/catalog.py prefill_slice_seconds); attribute
        # injection, not an import, so library/bench engines stay free
        if hasattr(engine, "metrics_sink"):
            engine.metrics_sink = app.state.metrics
        # hand the flight recorder the process context its bundles carry
        # (weakly held; obs/flightrec.py) — a later app wins, which is
        # exactly the live serving app.  The fleet provider is read
        # lazily at capture time so it sees the migration manager built
        # a few lines below (and its last-served affinity-key digest —
        # the attribution linking a replica's bundle to the conversation
        # and peers involved in the incident).
        def _replica_fleet_context(state=app.state):
            out = {"role": "replica",
                   "self": settings.migrate_self or None}
            mig = getattr(state, "migration", None)
            if mig is not None:
                out["migration"] = mig.status()
            return out

        _flightrec.FLIGHTREC.install(health=app.state.health, engine=engine,
                                     fleet=_replica_fleet_context)
        # disaggregated prefill/decode (serving/disagg/): arm the page
        # service and/or the remote-prefill client.  Misconfiguration
        # (no paged pool, registry, missing peer) refuses startup loudly
        # — the LFKT_WORKERS idiom — instead of serving half a fleet.
        if settings.disagg_role != "off":
            from ..serving.disagg import build_roles

            app.state.disagg = build_roles(
                settings.disagg_role, engine, settings,
                metrics=app.state.metrics, health=app.state.health)
        # fleet KV migration (serving/fleet/migrate.py): page service +
        # pull client, then scale-out warm-up BEFORE the READY flip so a
        # freshly scaled replica's first routed turn lands on a warm
        # radix tree.  Warm-up is bounded by the drain budget and every
        # failed pull inside it degrades with attribution — a cold or
        # absent fleet delays readiness by at most the budget.
        if settings.migrate:
            from ..serving.fleet.migrate import build_migration

            app.state.migration = await asyncio.to_thread(
                build_migration, engine, settings,
                metrics=app.state.metrics, health=app.state.health)
            await asyncio.to_thread(app.state.migration.warm_up)
        app.state.ready = True
        app.state.health.transition(READY, "engine loaded")
        if settings.watchdog and getattr(engine, "heartbeat", None) is None \
                and callable(getattr(engine, "models", None)):
            # multi-model registry: the engine watchdog is single-engine
            # (one heartbeat, one recovery contract) and gates off here
            # with attribution; per-engine scheduler deaths still surface
            # as EngineUnavailable 503s on their own submit paths
            logger.info("multi-model registry loaded: engine watchdog "
                        "gates off (single-engine contract — "
                        "docs/MULTIMODEL.md)")
        if settings.watchdog and getattr(engine, "heartbeat", None) is not None:
            # local import: engine.watchdog pulls the (jax-heavy) engine
            # package, which this module otherwise defers to the factory
            from ..engine.watchdog import Watchdog

            app.state.watchdog = Watchdog(
                engine, app.state.health, app.state.metrics,
                stall_seconds=settings.watchdog_stall_seconds,
                poll_seconds=settings.watchdog_poll_seconds,
                max_recoveries=settings.watchdog_max_recoveries,
                error_burst=settings.watchdog_error_burst,
                error_window=settings.watchdog_error_window,
                backoff_seconds=settings.watchdog_backoff_seconds,
                backoff_max=settings.watchdog_backoff_max,
            ).start()
        app.state.consumer_task = asyncio.create_task(consumer())
        # SIGHUP = re-read LFKT_MODELS and converge the running registry
        # to it (the POST /admin/models/reload twin for operators who
        # patch the pod env / mounted config rather than POSTing —
        # docs/MULTIMODEL.md "Live manifest reload").  Registered only
        # where signals are available (main thread); no-op refusal with
        # attribution on single-model pods.
        if hasattr(signal, "SIGHUP"):
            try:
                asyncio.get_running_loop().add_signal_handler(
                    signal.SIGHUP,
                    lambda: _spawn(_reload_from_env("SIGHUP")))
            except (NotImplementedError, RuntimeError, ValueError):
                # non-main thread (tests/embedding) or unsupported
                # platform: the admin route remains the reload surface
                pass

    @app.on_event("shutdown")
    async def shutdown_event():
        if app.state.watchdog is not None:
            # stop() joins the watchdog thread — a blocking wait that
            # must not run on the event loop (lfkt-lint ASY001): the
            # loop keeps draining in-flight responses while the join
            # rides a worker thread
            watchdog, app.state.watchdog = app.state.watchdog, None
            await asyncio.to_thread(watchdog.stop)
        if app.state.disagg is not None:
            disagg, app.state.disagg = app.state.disagg, None
            await asyncio.to_thread(disagg.close)
        if app.state.migration is not None:
            migration, app.state.migration = app.state.migration, None
            await asyncio.to_thread(migration.close)

    def _enqueue_rd(request: Request, messages: list[dict],
                    extra: dict | None = None, *, model: str | None = None,
                    params: dict | None = None, raw: bool = False,
                    openai: bool = False) -> dict:
        """Admission core shared by /response and the /v1 facade: enqueue
        ``messages`` with a future, 503 on overflow.  ``raw`` skips the
        reference truncation quirks (OpenAI clients own their history);
        ``params`` carries per-request sampling overrides; ``openai``
        shapes the result as the full completion dict."""
        queue = request.app.state.queue
        m = request.app.state.metrics
        now = time.time()
        # per-request deadline: the admission timeout (or the stream's
        # wall-clock budget) becomes an absolute deadline threaded into the
        # engine (deadline propagation), so a timed-out request frees its
        # lane/slot within one decode step instead of generating for nobody
        budget = (settings.stream_deadline_seconds
                  if extra and "stream_queue" in extra
                  else settings.timeout_seconds)
        trace = request.scope.get("lfkt.trace")
        rd = {
            "messages": messages,
            "future": asyncio.get_running_loop().create_future(),
            "enqueued_at": now,
            "deadline": now + budget,
            "trace": trace,
            "model": model,
            "params": params,
            "raw": raw,
            "openai": openai,
            **(extra or {}),
        }
        try:
            queue.put_nowait(rd)
        except asyncio.QueueFull:
            m.inc("requests_rejected_total")
            if trace is not None:
                trace.event("admission_rejected", queue_depth=queue.qsize())
            raise HTTPException(status_code=503,
                                detail="Server too busy. Please try again later.")
        if trace is not None:
            trace.note(deadline=rd["deadline"])
            if model is not None:
                trace.note(model=model)
        m.set_gauge("queue_depth", queue.qsize())
        return rd

    async def _migrate_hook(request: Request, messages: list[dict],
                            raw: bool = False) -> None:
        """Pull-on-remap (serving/fleet/migrate.py): when the fleet
        router stamped this request, record the conversation's affinity
        key (graceful drain's candidate set) and — if a prior owner is
        named — pull its radix pages over the disagg wire BEFORE the
        prefill that would otherwise recompute them.  Never raises and
        never blocks past the migration hop budget: a failed pull is an
        attributed degrade to a colder (but correct) local prefill."""
        mgr = request.app.state.migration
        if mgr is None:
            return
        headers = request.headers
        key = headers.get(AFFINITY_KEY_HEADER, "")
        prior = headers.get(PRIOR_OWNER_HEADER, "")
        if not key and not prior:
            return
        engine = request.app.state.engine
        tokenize = getattr(engine, "tokenize_messages", None)
        if tokenize is None:
            return
        try:
            # mirror the prompt the engine will actually see: the
            # reference truncation mutates in place, so feed it copies
            msgs = messages if raw else truncate_messages_to_fit_context(
                [dict(m) for m in messages], settings.max_context_tokens)
            ids = await asyncio.to_thread(tokenize, msgs)
        except Exception:  # noqa: BLE001 — a tokenizer quirk must not
            # fail admission; the request just prefills cold
            return
        ns = str(getattr(engine, "_kv_ns", "") or "")
        if key:
            mgr.record_prompt(key, ns, ids)
        if prior:
            await asyncio.to_thread(
                mgr.pull_for_request, prior, ns, ids,
                time.time() + settings.timeout_seconds,
                request.scope.get("lfkt.trace"))

    async def _admit(request_body: BotMessageRequest, request: Request,
                     extra: dict | None = None) -> dict:
        """Shared admission for both response endpoints: assemble messages
        (system prompt inserted at index 1 — quirk preserved from reference
        api.py:147), validate the optional model alias (400 in the existing
        {"detail": ...} shape), enqueue with a future, 503 on overflow."""
        model = _validate_model(request_body.model)
        messages = [
            {"role": message.turn, "content": message.message}
            for message in request_body.context
        ]
        system_prompt = build_system_prompt(request_body.bot_profile)
        messages.insert(1, {"role": "system", "content": system_prompt})
        await _migrate_hook(request, messages)
        return _enqueue_rd(request, messages, extra, model=model)

    @app.post("/response")
    async def generate_response(request_body: BotMessageRequest, request: Request):
        m = request.app.state.metrics
        rd = await _admit(request_body, request)
        future = rd["future"]
        try:
            response = await asyncio.wait_for(future, timeout=settings.timeout_seconds)
            return {"response": response}
        except asyncio.TimeoutError:
            logger.warning("Generation timed out")
            m.inc("requests_timed_out_total")
            future.cancel()
            raise HTTPException(status_code=408, detail="Generation timed out")
        except HTTPException:
            raise
        except Exception as e:  # noqa: BLE001 — api.py:171-173
            logger.error("Internal server error: %s", e)
            raise HTTPException(status_code=500,
                                detail=f"Internal server error: {str(e)}")

    @app.post("/response/stream")
    async def generate_response_stream(request_body: BotMessageRequest,
                                       request: Request):
        """Streaming variant of ``/response`` (BASELINE config "streaming
        completion"): same admission control (queue slot, 503 on overflow),
        same prompt assembly; emits server-sent events with OpenAI chunk
        dicts, terminated by ``data: [DONE]``.  Two timeouts bound the
        stream: the per-chunk gap (timeout_seconds, like the non-stream 408)
        AND a total wall-clock deadline (stream_deadline_seconds) so a
        slow-dripping generation cannot hold its queue slot forever."""
        m = request.app.state.metrics
        rd = await _admit(request_body, request,
                          extra={"stream_queue": asyncio.Queue()})
        loop = asyncio.get_running_loop()
        deadline = loop.time() + settings.stream_deadline_seconds
        trace = rd.get("trace")

        async def sse():
            # the SSE write phase outlives the middleware (chunks are sent
            # after the handler returns), so the stream span AND the trace
            # itself are closed here, in the generator's finally
            sspan = trace.span("stream") if trace is not None else None
            n_events = 0
            try:
                while True:
                    gap = min(settings.timeout_seconds, deadline - loop.time())
                    try:
                        if gap <= 0:
                            raise asyncio.TimeoutError
                        chunk = await asyncio.wait_for(
                            rd["stream_queue"].get(), timeout=gap)
                    except asyncio.TimeoutError:
                        m.inc("requests_timed_out_total")
                        if sspan is not None:
                            sspan.event("stream_timeout")
                        yield ("data: "
                               + json.dumps({"error": "Generation timed out"})
                               + "\n\n")
                        return
                    if chunk is _STREAM_DONE:
                        yield "data: [DONE]\n\n"
                        return
                    if isinstance(chunk, Exception):
                        yield ("data: "
                               + json.dumps({"error": str(chunk)}) + "\n\n")
                        return
                    n_events += 1
                    yield "data: " + json.dumps(chunk) + "\n\n"
            finally:
                # runs on timeout, error, AND client disconnect (the ASGI
                # layer closes this generator when the transport drops):
                # cancelling the future is the one signal every engine path
                # watches, so the lane/slot is reclaimed within one decode
                # step instead of streaming to a dead socket until budget
                if not rd["future"].done():
                    rd["future"].cancel()
                if sspan is not None:
                    sspan.set(events=n_events)
                    sspan.end()
                app.state.tracer.finish(trace)

        return StreamingResponse(sse())

    # -- OpenAI-compatible facade (docs/MULTIMODEL.md) ---------------------
    # Same admission path as /response (bounded queue → 503, future
    # timeout → 408, scheduler lanes in continuous mode) behind the wire
    # contract OpenAI SDKs speak: model routing, chat.completion /
    # chat.completion.chunk envelopes, usage counts from the engine's own
    # timings, and the {"error": {...}} body on every failure.

    @app.get("/v1/models")
    async def v1_models():
        """The served model manifest, OpenAI list-shaped: one row per
        ROUTABLE registry alias (single-model pods list their one model).
        Mid-reload rows — ``loading`` (weights still coming up) and
        ``draining`` (leaving; new requests already 400) — are /health's
        business: advertising them here would invite traffic the router
        cannot place."""
        eng = app.state.engine
        models_fn = getattr(eng, "models", None)
        if callable(models_fn):
            names = [r["name"] for r in models_fn()
                     if r.get("state") in (None, "ready", "loaded")]
        else:
            names = [getattr(eng, "model_name", None)
                     or app.state.settings.model_name]
        return {
            "object": "list",
            "data": [{"id": n, "object": "model",
                      "created": app.state.created, "owned_by": "lfkt"}
                     for n in names],
        }

    # -- live manifest reload (serving/registry.py; docs/MULTIMODEL.md) ----
    async def _do_reload(manifest: str, default: str) -> dict:
        """Run one registry reload on a worker thread (loads/warmups take
        seconds-minutes; traffic on the live models keeps flowing)."""
        eng = app.state.engine
        reload_fn = getattr(eng, "reload_manifest", None)
        if not callable(reload_fn):
            raise HTTPException(
                status_code=400,
                detail="live reload requires manifest serving: this pod "
                       "runs a single engine (set LFKT_MODELS — "
                       "docs/MULTIMODEL.md)")
        if not manifest:
            raise HTTPException(
                status_code=400,
                detail="no manifest: pass {\"models\": \"name=path,...\"} "
                       "or set LFKT_MODELS on the pod")
        return await asyncio.to_thread(
            reload_fn, manifest, default,
            drain_seconds=settings.reload_drain_seconds)

    async def _reload_from_env(origin: str) -> None:
        """The SIGHUP path: env is re-read at signal time, so editing the
        pod's LFKT_MODELS (mounted-config pattern) then HUPing converges
        the registry without a restart."""
        from ..utils.config import get_settings as _fresh_settings

        live = _fresh_settings()
        async with app.state.reload_busy:
            try:
                doc = await _do_reload(live.models, live.default_model)
                # model names may come from a POSTed manifest
                logger.info("%s reload: added=%s removed=%s default=%s",
                            origin, sanitize_text(str(doc["added"])),
                            sanitize_text(
                                str([r["name"] for r in doc["removed"]])),
                            sanitize_text(doc["default_model"]))
            except HTTPException as e:
                logger.error("%s reload refused: %s", origin, e.detail)
            except Exception as e:  # noqa: BLE001 — a failed background
                # reload must be loud but never kill the serving loop
                logger.error("%s reload failed: %s", origin, e)

    @app.post("/admin/models/reload")
    async def admin_models_reload(request: Request):
        """Diff a new ``LFKT_MODELS`` manifest against the running
        registry and converge to it live: additions load under the fit
        check + weight budget (409 on refusal, running set untouched),
        removals drain their in-flight requests and radix namespace
        before the weights release.  Body (all optional): ``models`` (the
        manifest string; default = the pod's current LFKT_MODELS env,
        re-read), ``default_model``.  Returns the registry's reload
        report.  409 while another reload runs."""
        from ..serving import WeightBudgetError
        from ..utils.config import get_settings as _fresh_settings

        try:
            body = await request.json()
        except ValueError:
            raise HTTPException(status_code=400, detail="body must be JSON")
        body = body if isinstance(body, dict) else {}
        live = _fresh_settings()
        manifest = body.get("models") or live.models
        default = body.get("default_model") or live.default_model
        if app.state.reload_busy.locked():
            raise HTTPException(
                status_code=409,
                detail="a reload is already in progress; retry after it "
                       "completes (/health models rows show the "
                       "transition)")
        async with app.state.reload_busy:
            try:
                return await _do_reload(manifest, default)
            except WeightBudgetError as e:
                raise HTTPException(status_code=409, detail=str(e))
            except ValueError as e:
                raise HTTPException(status_code=400, detail=str(e))

    @app.get("/admin/migrate/hot")
    async def admin_migrate_hot(request: Request):
        """This pod's hottest cached prefixes (``KVPool.hot_prefixes``)
        — what a scale-out peer pre-pulls during warm-up
        (serving/fleet/migrate.py).  ``?k=N`` bounds the list (default
        LFKT_MIGRATE_TOP_K).  404-shaped refusal when migration is off:
        a mixed-rollout fleet must get attribution, not a hang."""
        mgr = app.state.migration
        if mgr is None:
            raise HTTPException(
                status_code=404,
                detail="KV migration is off on this pod (LFKT_MIGRATE=1 "
                       "arms it — docs/RUNBOOK.md 'Surviving pod churn')")
        from urllib.parse import parse_qs

        q = parse_qs(request.url.query)
        try:
            k = int(q.get("k", [mgr.top_k])[0])
        except ValueError:
            raise HTTPException(status_code=400, detail="k must be an int")
        pool = getattr(app.state.engine, "_kvpool", None)
        rows = (await asyncio.to_thread(pool.hot_prefixes, k)
                if pool is not None else [])
        return {"prefixes": rows}

    @app.post("/admin/migrate/pull")
    async def admin_migrate_pull(request: Request):
        """Commanded pull — the receiving half of a peer's graceful
        drain (serving/fleet/migrate.py ``drain_push``): the DRAINING
        pod names itself (``peer`` = its page-service wire addr) and the
        conversation (``namespace`` + ``ids``); this pod pulls the pages
        over the wire while the peer still lives.  Deadline-bounded and
        never a hang; a failed pull answers ``covered: 0`` with the
        degrade attributed in this pod's counters."""
        mgr = app.state.migration
        if mgr is None:
            raise HTTPException(
                status_code=404,
                detail="KV migration is off on this pod (LFKT_MIGRATE=1 "
                       "arms it — docs/RUNBOOK.md 'Surviving pod churn')")
        try:
            body = await request.json()
        except ValueError:
            raise HTTPException(status_code=400, detail="body must be JSON")
        body = body if isinstance(body, dict) else {}
        peer = str(body.get("peer") or "")
        ids = body.get("ids")
        if ":" not in peer or not isinstance(ids, list) or not ids:
            raise HTTPException(
                status_code=400,
                detail="body needs peer (host:port of the drain side's "
                       "page service) and ids (non-empty token list)")
        deadline = body.get("deadline")
        covered = await asyncio.to_thread(
            mgr.pull, peer, [int(t) for t in ids],
            namespace=str(body.get("namespace") or ""), reason="drain",
            deadline=float(deadline) if deadline is not None else None)
        return {"covered": covered}

    def _v1_params(body: ChatCompletionRequest) -> dict:
        """The request's explicitly-set sampling fields (unset ones fall
        back to the pod's serving defaults in _gen_kwargs)."""
        return {k: v for k, v in dict(
            temperature=body.temperature,
            top_p=body.top_p,
            frequency_penalty=body.frequency_penalty,
            presence_penalty=body.presence_penalty,
            max_tokens=body.max_tokens,
            stop=body.stop,
            seed=body.seed,
        ).items() if v is not None}

    def _v1_sse(rd, include_usage: bool):
        """/v1 streaming body: engine chunks as ``chat.completion.chunk``
        SSE events, OpenAI error envelopes on failure, an optional final
        usage chunk (stream_options.include_usage), then ``[DONE]``.
        Mirrors /response/stream's timeout/disconnect reclamation: the
        generator's finally cancels the future, which every engine path
        watches."""
        m = app.state.metrics
        loop = asyncio.get_running_loop()
        deadline = loop.time() + settings.stream_deadline_seconds
        trace = rd.get("trace")

        async def sse():
            sspan = trace.span("stream") if trace is not None else None
            n_events = 0
            last = None
            try:
                while True:
                    gap = min(settings.timeout_seconds, deadline - loop.time())
                    try:
                        if gap <= 0:
                            raise asyncio.TimeoutError
                        chunk = await asyncio.wait_for(
                            rd["stream_queue"].get(), timeout=gap)
                    except asyncio.TimeoutError:
                        m.inc("requests_timed_out_total")
                        if sspan is not None:
                            sspan.event("stream_timeout")
                        yield ("data: " + json.dumps(_openai_error_body(
                            408, "Generation timed out")) + "\n\n")
                        return
                    if chunk is _STREAM_DONE:
                        t = rd.get("timings")
                        if include_usage and t is not None and last is not None:
                            p, c = t.get("prompt_tokens", 0), \
                                t.get("completion_tokens", 0)
                            yield "data: " + json.dumps({
                                "id": last.get("id"),
                                "object": "chat.completion.chunk",
                                "created": last.get("created"),
                                "model": last.get("model"),
                                "choices": [],
                                "usage": {"prompt_tokens": p,
                                          "completion_tokens": c,
                                          "total_tokens": p + c},
                            }) + "\n\n"
                        yield "data: [DONE]\n\n"
                        return
                    if isinstance(chunk, Exception):
                        status = 400 if isinstance(chunk, ValueError) else 500
                        yield ("data: " + json.dumps(_openai_error_body(
                            status, str(chunk))) + "\n\n")
                        return
                    last = chunk
                    n_events += 1
                    yield "data: " + json.dumps(chunk) + "\n\n"
            finally:
                if not rd["future"].done():
                    rd["future"].cancel()
                if sspan is not None:
                    sspan.set(events=n_events)
                    sspan.end()
                app.state.tracer.finish(trace)

        return StreamingResponse(sse())

    @app.post("/v1/chat/completions")
    async def v1_chat_completions(body: ChatCompletionRequest,
                                  request: Request):
        """OpenAI-compatible chat completions: non-streaming returns the
        engine's completion dict (usage counts from its timings);
        ``stream: true`` emits ``chat.completion.chunk`` SSE.  Unknown
        ``model`` → 400 with code ``model_not_found``."""
        m = request.app.state.metrics
        try:
            if body.n != 1:
                raise HTTPException(
                    status_code=400,
                    detail="n must be 1: this server returns a single "
                           "choice per request")
            if not body.messages:
                raise HTTPException(status_code=400,
                                    detail="messages must be non-empty")
            model = _validate_model(body.model)
            params = _v1_params(body)
            messages = [{"role": msg.role, "content": msg.content}
                        for msg in body.messages]
            await _migrate_hook(request, messages, raw=True)
            if body.stream:
                rd = _enqueue_rd(request, messages,
                                 {"stream_queue": asyncio.Queue()},
                                 model=model, params=params, raw=True,
                                 openai=True)
                return _v1_sse(rd, include_usage=bool(
                    body.stream_options and
                    body.stream_options.include_usage))
            rd = _enqueue_rd(request, messages, model=model, params=params,
                             raw=True, openai=True)
            try:
                answer = await asyncio.wait_for(
                    rd["future"], timeout=settings.timeout_seconds)
            except asyncio.TimeoutError:
                logger.warning("Generation timed out")
                m.inc("requests_timed_out_total")
                rd["future"].cancel()
                raise HTTPException(status_code=408,
                                    detail="Generation timed out")
            return JSONResponse(answer)
        except HTTPException as e:
            return _openai_http_error(e)
        except Exception as e:  # noqa: BLE001 — facade contract: every
            # failure wears the OpenAI error envelope, including bugs
            logger.error("Internal server error: %s", e)
            return _openai_http_error(HTTPException(
                status_code=500, detail=f"Internal server error: {str(e)}"))

    def _resilience_info() -> dict:
        """Error-taxonomy + watchdog block for /health: the state machine,
        the trip/recovery counters, and the last engine error."""
        st = app.state
        info: dict = {"health": st.health.snapshot()}
        wd = st.watchdog
        if wd is not None:
            info["watchdog"] = {
                "trips": wd.trips,
                "recoveries": wd.recoveries,
                "max_recoveries": wd.max_recoveries,
                "last_trip_reason": wd.last_trip_reason,
                "stall_seconds": wd.stall_seconds,
            }
        hb = getattr(st.engine, "heartbeat", None)
        if hb is not None:
            info["engine_errors"] = {
                "total": hb.errors_total,
                "last": hb.last_error,
            }
        if FAULTS.armed():        # drills only: never present in production
            info["faults_armed"] = FAULTS.stats()
        return info

    @app.get("/health/ready")
    async def health_ready():
        """Readiness probe: 200 only in READY — a DEGRADED or DRAINING pod
        sheds traffic (503) while staying alive.  Helm's readinessProbe
        and startupProbe point here (helm/templates/deployment.yaml)."""
        h = app.state.health
        ok = h.ready()
        snap = h.snapshot()
        body = {"ready": ok, "state": snap["state"], "reason": snap["reason"]}
        return JSONResponse(body, 200 if ok else 503)

    @app.get("/health/live")
    async def health_live():
        """Liveness probe: 503 only in DEAD (recovery budget exhausted) —
        a briefly degraded pod recovering in-process must NOT be killed
        mid-recovery.  Helm's livenessProbe points here."""
        h = app.state.health
        ok = h.alive()
        snap = h.snapshot()
        body = {"alive": ok, "state": snap["state"], "reason": snap["reason"]}
        return JSONResponse(body, 200 if ok else 503)

    @app.get("/health")
    async def health():
        """Advertised by the reference README (README.md:14) but never
        implemented (SURVEY.md §3.5); the operator-facing health document.
        k8s probes use the split routes (/health/ready, /health/live) so
        "briefly degraded" and "kill me" are distinct answers."""
        st = app.state
        queue_depth = st.queue.qsize() if hasattr(st, "queue") else None
        if not st.ready:
            raise HTTPException(status_code=503, detail="model loading")
        eng = st.engine
        engine_info = None
        if eng is not None:
            cfg = getattr(eng, "cfg", None)
            # which linear layout each weight group actually serves with
            # (fused kernels may have probe-degraded to int8 — visible here)
            fmt = None
            params = getattr(eng, "params", None)
            if isinstance(params, dict) and "layers" in params:
                kinds = {"qs": "q4k-fused", "q5s": "q5k-fused",
                         "q5p": "q5k-fused-pre",
                         "q4": "q6k-fused", "q6p": "q6k-fused-pre",
                         "q8": "q8-fused", "q": "int8", "w": "bf16"}
                fmt = {
                    name: next((v for k, v in kinds.items() if k in leaf), "?")
                    for name, leaf in params["layers"].items()
                    if isinstance(leaf, dict)
                }
            engine_info = {
                "model": getattr(eng, "model_name", None),
                "n_ctx": getattr(cfg, "n_ctx", None),
                "attn_impl": getattr(cfg, "attn_impl", None),
                "weight_formats": fmt,
                # KV-cache dtype + resident HBM bytes: the kv_dtype=int8
                # capacity win, verifiable per pod (docs/KV_CACHE.md)
                "kv_dtype": getattr(cfg, "kv_dtype", None),
                "kv_cache_bytes": getattr(eng, "kv_cache_bytes", None),
                # layer-looped decode (ops/pallas/decode_loop.py): the
                # EFFECTIVE layers-per-launch this pod serves (-1/K are
                # clamped to the real divisor; 0 after any degrade, with
                # the reason in /debug/compiles)
                "decode_layer_unroll": _effective_unroll(cfg),
            }
            # paged KV pool occupancy (LFKT_KV_PAGED): pages used/free/
            # pinned, the spill tier, and the hit/eviction counters —
            # the "is my pool sized right" answer next to kv_cache_bytes
            # (docs/RUNBOOK.md "Sizing the KV page pool")
            occ = getattr(eng, "kv_pool_occupancy", None)
            if callable(occ):
                engine_info["kv_pool"] = occ()
            # multi-model registry: one row per served model (name, quant,
            # weight bytes, load state — docs/MULTIMODEL.md) next to the
            # kv_pool block; absent on single-model pods, whose /health is
            # byte-for-byte the pre-registry document
            models_fn = getattr(eng, "models", None)
            if callable(models_fn):
                engine_info["models"] = models_fn()
                engine_info["default_model"] = getattr(
                    eng, "default_model", None)
            # spec_decode="auto": the measured-RTT decision and its inputs
            # (engine/spec_auto.py) — operators verify the resolution here
            if getattr(eng, "spec_auto_decision", None) is not None:
                engine_info["spec_auto"] = eng.spec_auto_decision
        doc = {
            "status": "ok",
            "state": st.health.state,
            "model_loaded": eng is not None,
            "queue_depth": queue_depth,
            "max_queue_size": st.settings.max_queue_size,
            "engine": engine_info,
            "resilience": _resilience_info(),
        }
        # disaggregated prefill/decode tier block (serving/disagg/): the
        # role, the page service's counters, and — on the decode side —
        # the peer state + the attributed reason pages stopped coming
        # (docs/RUNBOOK.md "Operating a split prefill/decode fleet");
        # absent on role=off pods, whose /health is byte-for-byte the
        # pre-disagg document
        if st.disagg is not None:
            doc["disagg"] = st.disagg.status()
        # fleet KV migration block (serving/fleet/migrate.py): the page
        # service's wire addr (peers resolve it through THIS document —
        # ephemeral ports are discovery, not config), every pull/push
        # counter, and the last attributed degrade; absent with
        # LFKT_MIGRATE off, keeping /health byte-identical
        if st.migration is not None:
            doc["migration"] = st.migration.status()
        return doc

    @app.get("/metrics")
    async def metrics():
        m = app.state.metrics
        if hasattr(app.state, "queue"):
            m.set_gauge("queue_depth", app.state.queue.qsize())
        # health/resilience gauges (error taxonomy counters — timeouts,
        # 503s, watchdog trips/recoveries — are inc'd at their sites)
        m.set_gauge("health_state", STATE_CODES[app.state.health.state])
        hb = getattr(app.state.engine, "heartbeat", None)
        if hb is not None:
            m.set_gauge("engine_inflight", hb.busy_count())
            m.set_gauge("engine_error_count", hb.errors_total)
        kv_bytes = getattr(app.state.engine, "kv_cache_bytes", None)
        if kv_bytes is not None:
            m.set_gauge("kv_cache_bytes", kv_bytes)
        # multi-model capacity gauges (docs/MULTIMODEL.md): how many
        # models this pod serves and each one's resident weight bytes
        models_fn = getattr(app.state.engine, "models", None)
        if callable(models_fn):
            rows = models_fn()
            m.set_gauge("models_loaded", len(rows))
            for r in rows:
                m.set_gauge("model_weight_bytes", r["weight_bytes"],
                            model=r["name"])
        elif app.state.engine is not None:
            m.set_gauge("models_loaded", 1)
            wb = getattr(app.state.engine, "weight_bytes", 0)
            if wb:
                m.set_gauge("model_weight_bytes", wb,
                            model=_model_label())
        # paged KV pool occupancy gauges (the event counters —
        # misses/evictions/spills/restores + the reuse histogram — are
        # inc'd at event time by the pool through the injected sink)
        occ = getattr(app.state.engine, "kv_pool_occupancy", None)
        pool = occ() if callable(occ) else None
        if pool is not None:
            m.set_gauge("kv_pool_pages_used", pool["pages_used"])
            m.set_gauge("kv_pool_pages_free", pool["pages_free"])
        stats = getattr(app.state.engine, "scheduler_stats", None)
        if stats is not None:
            snap = stats()
            for k, v in snap.items():
                if isinstance(v, dict):   # nested stats (e.g. spec): flatten
                    for kk, vv in v.items():  # — a dict-valued gauge renders
                        m.set_gauge(f"scheduler_{k}_{kk}", vv)  # invalid lines
                else:
                    m.set_gauge(f"scheduler_{k}", v)
            # first-class prefill-pipeline gauges (obs/catalog.py): the
            # admission controller's live budget + cumulative idle
            # lane-seconds, promoted out of the scheduler_ prefix family
            # so dashboards need no family-scrape to alert on them
            if "adm_budget_tokens" in snap:
                m.set_gauge("admission_budget_tokens",
                            snap["adm_budget_tokens"])
            if "lane_idle_seconds" in snap:
                m.set_gauge("lane_idle_seconds", snap["lane_idle_seconds"])
        # lfkt-mem: live HBM attribution gauges (obs/memledger.py) — one
        # series per (component, model), residual = ground truth minus the
        # attributed sum, headroom only where the backend reports limits.
        # The families are rebuilt WHOLE from the ledger each scrape: a
        # vanished row (drained spill tier, collected engine) must drop
        # its series, not freeze at its last value.  The reset→rebuild→
        # render sequence is atomic because this handler has NO await
        # between here and render() (one event loop, LFKT_WORKERS=1) —
        # inserting an await in between would let a concurrent scrape
        # render the family half-built
        m.reset_family("hbm_bytes")
        m.reset_family("hbm_headroom_bytes")
        if _memledger.MEMLEDGER.armed:
            mdoc = _memledger.MEMLEDGER.snapshot()
            for row in mdoc["components"]:
                m.set_gauge("hbm_bytes", row["bytes"],
                            component=row["component"], model=row["model"])
            if mdoc["residual_bytes"] is not None:
                m.set_gauge("hbm_bytes", mdoc["residual_bytes"],
                            component="residual", model="")
            if mdoc["headroom"] is not None:
                m.set_gauge("hbm_headroom_bytes", mdoc["headroom"]["bytes"])
        if _flightrec.FLIGHTREC.armed:
            m.set_gauge("incidents_total",
                        _flightrec.FLIGHTREC.recorded_total)
        # disagg wire liveness (the event counters — pages/bytes/
        # fallbacks — are inc'd at event time by the roles via the sink)
        dis = app.state.disagg
        if dis is not None and dis.client is not None:
            m.set_gauge("disagg_peer_connected",
                        1.0 if dis.client.connected() else 0.0)
        tstats = app.state.tracer.stats()
        m.set_gauge("trace_ring_used", tstats["ring_used"])
        m.set_gauge("traces_started_total", tstats["started_total"])
        m.set_gauge("traces_sampled_out_total", tstats["sampled_out_total"])
        # compile/dispatch attribution (obs/devtime.py): per-program
        # counters as snapshots, compile walls replayed into the histogram
        # exactly once via the app's event cursor
        for prog, c in DEVTIME.counters().items():
            m.set_gauge("xla_compiles_total", c["compiles"], program=prog)
            m.set_gauge("jit_dispatches_total", c["dispatches"],
                        program=prog)
        m.set_gauge("xla_recompile_storms_total", DEVTIME.storms_total)
        cursor, events = DEVTIME.events_since(app.state.devtime_cursor)
        app.state.devtime_cursor = cursor
        for ev in events:
            m.observe("xla_compile_seconds", ev["wall_s"],
                      program=ev["program"])
        m.set_gauge("xla_compile_events_dropped_total",
                    DEVTIME.events_dropped)
        # SLO burn rates over the series recorded above (obs/slo.py)
        app.state.slo.export()
        return PlainTextResponse(m.render())

    # -- lfkt-obs debug surface (docs/OBSERVABILITY.md) --------------------
    @app.get("/debug/traces")
    async def debug_traces():
        """Recent completed traces (newest first) + tracer stats; feed the
        JSON to tools/trace_report.py for latency waterfalls."""
        t = app.state.tracer
        return {"stats": t.stats(), "traces": t.traces()}

    @app.get("/debug/traces/{trace_id}")
    async def debug_trace(trace_id: str):
        """One trace's full span tree (in-flight or completed)."""
        tr = app.state.tracer.get(trace_id)
        if tr is None:
            raise HTTPException(status_code=404,
                                detail=f"no trace {trace_id!r} in the ring")
        return tr.to_dict()

    @app.get("/debug/requests")
    async def debug_requests():
        """In-flight request snapshot: engine, slot/lane, deadline
        remaining, tokens so far — the live answer to "what is this pod
        doing right now"."""
        return {"requests": app.state.tracer.inflight()}

    @app.get("/debug/compiles")
    async def debug_compiles():
        """The devtime program registry (obs/devtime.py): every registered
        jit program with its compile count, dispatch count, and the
        static-shape signatures it compiled — the "what is this pod
        recompiling" answer (docs/RUNBOOK.md recompile-storm runbook)."""
        return DEVTIME.snapshot()

    @app.get("/debug/slo")
    async def debug_slo():
        """The SLO verdict document (obs/slo.py; docs/SLO.md): per-SLO
        multi-window burn rates with per-series detail, plus the devtime
        recompile-storm state.  ``verdict`` is the pod's one-word answer:
        ok | warn | breach."""
        return app.state.slo.evaluate()

    @app.get("/debug/memory")
    async def debug_memory():
        """The live HBM memory ledger (obs/memledger.py): per-component
        attribution with a residual line reconciled against device ground
        truth, headroom, and — when the paged KV pool serves — arena
        fragmentation (largest contiguous free run vs free pages).  The
        "where did my HBM go" answer (docs/RUNBOOK.md 'Diagnosing HBM
        OOM')."""
        doc = _memledger.MEMLEDGER.snapshot()
        occ = getattr(app.state.engine, "kv_pool_occupancy", None)
        pool = occ() if callable(occ) else None
        if pool is not None and doc.get("armed"):
            free = pool.get("pages_free")
            run = pool.get("largest_free_run")
            doc["kv_pool"] = pool
            if free and run is not None:
                doc["fragmentation"] = {
                    "pages_free": free,
                    "largest_free_run": run,
                    # 0 = one contiguous run; →1 = maximally shattered
                    "ratio": round(1.0 - run / free, 4),
                }
        return doc

    @app.get("/debug/incidents")
    async def debug_incidents():
        """The incident flight recorder's on-disk ring (obs/flightrec.py):
        bundle summaries, newest first.  Empty (armed: false) until
        LFKT_INCIDENT_DIR is set."""
        fr = _flightrec.FLIGHTREC
        # bundle summaries come off DISK (full-ring reads, potentially
        # MBs of traces): a worker thread, never the event loop — this
        # endpoint gets hit exactly when the pod is already degraded
        incidents = await asyncio.to_thread(fr.list) if fr.armed else []
        return {"armed": fr.armed,
                "recorded_total": fr.recorded_total,
                "debounced_total": fr.debounced_total,
                "incidents": incidents}

    @app.get("/debug/incidents/{incident_id}")
    async def debug_incident(incident_id: str):
        """One full incident bundle read back from disk: memory ledger,
        in-flight traces at capture time, scheduler stats, health
        transitions, recompile-storm state, log tail."""
        doc = await asyncio.to_thread(_flightrec.FLIGHTREC.get, incident_id)
        if doc is None:
            raise HTTPException(
                status_code=404,
                detail=f"no incident {incident_id!r} in the ring")
        return doc

    @app.get("/debug/profile")
    async def debug_profile(request: Request):
        """Bounded on-demand XProf capture (utils/tracing.py).  Opt-in:
        403 until LFKT_PROFILE_DIR is set; 409 while a capture runs;
        ``?seconds=`` clamps to the capture bounds.  The capture blocks a
        worker thread, never the event loop."""
        from urllib.parse import parse_qs

        from ..utils.tracing import (
            ProfileBusy,
            ProfileDisabled,
            capture_profile,
        )

        q = parse_qs(request.url.query)
        try:
            seconds = float(q.get("seconds", ["2.0"])[0])
        except ValueError:
            raise HTTPException(status_code=400,
                                detail="seconds must be a number")
        if not math.isfinite(seconds):
            # nan/inf slide through min() clamps (nan<x is False) and
            # would hold the exclusive capture lock for the full maximum
            raise HTTPException(status_code=400,
                                detail="seconds must be finite")
        try:
            return await asyncio.to_thread(capture_profile, seconds)
        except ProfileDisabled as e:
            raise HTTPException(status_code=403, detail=str(e))
        except ProfileBusy as e:
            raise HTTPException(status_code=409, detail=str(e))

    @app.get("/items/{item_id}")
    async def read_item(item_id: int):
        # vestigial echo route kept for OpenAPI-surface parity (api.py:175-177)
        return {"item_id": item_id}

    def _route_template(method: str, path: str) -> str:
        """The matched route's path template — the bounded-cardinality
        ``route`` label value (``/items/{item_id}``, never ``/items/7``)."""
        for route in app.router.routes:
            if route.method == method and route.match(method, path) is not None:
                return route.path
        return "unmatched"

    @app.middleware("http")
    async def log_request_time(request: Request, call_next):
        start_time = time.time()
        tracer = app.state.tracer
        # request identity: ingest the client's W3C traceparent (its trace
        # id becomes ours) or mint one; sampled-out requests still get a
        # request id for log stamping, just no span tree
        trace = tracer.start("request", t0=start_time,
                             traceparent=request.headers.get("traceparent"))
        rid = trace.trace_id if trace is not None else uuid.uuid4().hex
        request.scope["lfkt.trace"] = trace
        route = _route_template(request.method, request.url.path)
        if trace is not None:
            trace.root.set(method=request.method, route=route)
            trace.note(route=route)
            httpd_read = request.scope.get("lfkt.httpd_read")
            if httpd_read is not None:
                # the in-tree httpd's head+body read window (slowloris
                # territory), handed through the ASGI scope
                trace.span("httpd.read", t0=httpd_read[0]).end(httpd_read[1])
        def finalize(status: int) -> None:
            time_of_day = datetime.now().strftime("%Y-%m-%d %H:%M:%S")
            process_time = time.time() - start_time
            app.state.metrics.observe("request_seconds", process_time,
                                      route=route)
            app.state.metrics.inc("http_requests_total", route=route,
                                  code=str(status))
            # structured access line: JSON under setup_json_logging, and
            # the request id rides every record either way
            access_logger.info(
                "Request at %s: %s %s completed in %.4fs",
                time_of_day, request.method, request.url, process_time,
                extra={"route": route, "method": request.method,
                       "status": status,
                       "duration_s": round(process_time, 6)},
            )
            if trace is not None:
                trace.root.set(status=status)

        with bind_request_id(rid):
            try:
                response = await call_next(request)
            except BaseException:
                # a middleware-layer failure: the outer handler shapes the
                # response; account for the request and close its trace
                finalize(500)
                tracer.finish(trace)
                raise
            finalize(response.status_code)
        response.headers.setdefault("x-request-id", rid)
        if trace is not None:
            response.headers.setdefault("traceparent", trace.traceparent())
            if not isinstance(response, StreamingResponse):
                # streaming responses finish their trace in the SSE
                # generator's finally (the body outlives this middleware)
                tracer.finish(trace)
        return response

    return app


def _effective_unroll(cfg):
    """The decode layers-per-launch ``cfg`` actually serves — the
    ``-1`` / nearest-divisor clamp applied (ops/pallas/decode_loop.py)
    — or None for engines whose config predates the field (fakes)."""
    if getattr(cfg, "decode_layer_unroll", None) is None:
        return None
    from ..ops.pallas.decode_loop import effective_unroll

    try:
        return effective_unroll(cfg)
    except (AttributeError, TypeError, ValueError):
        return 0


def _base_engine_kwargs(settings: Settings) -> dict:
    """Engine-constructor kwargs shared by the single-model factory and
    every registry entry (which then applies its manifest overrides)."""
    return dict(
        n_ctx=settings.max_context_tokens,
        weight_format=settings.weight_format,
        decode_chunk=settings.decode_chunk,
        prefill_buckets=settings.prefill_bucket_list,
        max_gen_tokens=settings.max_gen_tokens,
        attn_impl=settings.attn_impl,
        kv_dtype=settings.kv_dtype,
        decode_layer_unroll=settings.decode_layer_unroll,
        spec_decode=settings.spec_decode,
        spec_draft=settings.spec_draft,
        prefix_cache=settings.prefix_cache,
        prefill_chunk=settings.prefill_chunk,
        prefill_overlap=settings.prefill_overlap,
        kv_paged=settings.kv_paged,
        kv_page_tokens=settings.kv_page_tokens,
        kv_pool_pages=settings.kv_pool_pages,
        kv_spill_pages=settings.kv_spill_pages,
    )


def _registry_factory(settings: Settings):
    """LFKT_MODELS is set: load the manifest into a ModelRegistry
    (serving/registry.py) — N engines sharing the chip, the paged KV pool
    (per-model namespaces) and an explicit HBM weight budget, all with
    the SAME scheduler shape (lanes/chunks/admission come from the
    process-wide knobs; per-model overrides are whitelisted engine knobs
    only — serving/manifest.py)."""
    from ..serving import ModelRegistry, parse_manifest, pick_default

    specs = parse_manifest(settings.models)
    default = pick_default(specs, settings.default_model)
    if settings.mesh_sp > 1 and settings.batch_size > 1:
        # mirror the single-model factory's refusal exactly — a 1-entry
        # manifest must not soften any serving-shape validation
        raise ValueError(
            "LFKT_MESH_SP > 1 serves sequence-parallel (serial); "
            "set LFKT_BATCH_SIZE=1 or use dp/tp batching instead")
    if len(specs) > 1 and settings.mesh_sp > 1:
        raise ValueError(
            "LFKT_MESH_SP > 1 gates off multi-model serving: the "
            "sp-sharded ring serves one model per mesh (run one model "
            "per pod, or drop to mesh_sp=1)")
    if len(specs) > 1 and settings.batch_size > 1 \
            and settings.scheduler != "continuous":
        raise ValueError(
            "LFKT_SCHEDULER=cycle gates off multi-model serving: a "
            "mesh-batched cycle coalesces its whole batch into ONE "
            "shared device program, which cannot interleave models — "
            "use the continuous scheduler (docs/MULTIMODEL.md)")

    def build(spec, path, shared_pool):
        from ..engine import ContinuousEngine, Engine, MeshEngine, SPEngine

        kw = _base_engine_kwargs(settings)
        kw.update(spec.overrides)
        kw["kv_pool"] = shared_pool
        kw["kv_namespace"] = spec.name
        if settings.mesh_sp > 1:
            return SPEngine(path, sp=settings.mesh_sp, tp=settings.mesh_tp,
                            **kw)
        if settings.batch_size > 1:
            if settings.scheduler == "continuous":
                kw.pop("prefill_chunk")
                return ContinuousEngine(
                    path, tp=settings.mesh_tp,
                    batch_size=settings.batch_size,
                    prefill_chunk=settings.prefill_chunk,
                    adm_budget=settings.adm_budget,
                    adm_controller=settings.adm_controller,
                    adm_ema_alpha=settings.adm_ema_alpha,
                    lane_prefix_cache=settings.lane_prefix_cache, **kw)
            # cycle scheduler, single-entry manifest: the same
            # MeshEngine the non-manifest factory builds — a 1-entry
            # LFKT_MODELS migration must not silently swap schedulers
            return MeshEngine(path, tp=settings.mesh_tp,
                              batch_size=settings.batch_size, **kw)
        return Engine(path, **kw)

    reg = ModelRegistry.from_specs(
        specs, build, default_model=default, model_dir=settings.model_dir,
        weight_budget_bytes=int(settings.hbm_weight_budget_mb * 1e6))
    reg.warmup()
    return reg


def _default_engine_factory(settings: Settings):
    def factory():
        from ..engine import ContinuousEngine, Engine, MeshEngine, SPEngine

        if settings.scheduler not in ("continuous", "cycle"):
            raise ValueError(
                f"LFKT_SCHEDULER must be 'continuous' or 'cycle', "
                f"got {settings.scheduler!r}")
        if settings.models:
            # multi-model manifest: the registry replaces the single
            # engine; empty LFKT_MODELS keeps this path byte-for-byte
            return _registry_factory(settings)
        kw = _base_engine_kwargs(settings)
        if settings.mesh_sp > 1:
            # long-context serving: n_ctx sharded over the sp ring
            if settings.batch_size > 1:
                raise ValueError(
                    "LFKT_MESH_SP > 1 serves sequence-parallel (serial); "
                    "set LFKT_BATCH_SIZE=1 or use dp/tp batching instead")
            eng = SPEngine(settings.model_path, sp=settings.mesh_sp,
                           tp=settings.mesh_tp, **kw)
        elif settings.batch_size > 1:
            if settings.scheduler == "continuous":
                ckw = dict(kw)
                ckw.pop("prefill_chunk")   # named explicitly below
                eng = ContinuousEngine(
                    settings.model_path, tp=settings.mesh_tp,
                    batch_size=settings.batch_size,
                    prefill_chunk=settings.prefill_chunk,
                    adm_budget=settings.adm_budget,
                    adm_controller=settings.adm_controller,
                    adm_ema_alpha=settings.adm_ema_alpha,
                    lane_prefix_cache=settings.lane_prefix_cache, **ckw)
            else:
                eng = MeshEngine(settings.model_path, tp=settings.mesh_tp,
                                 batch_size=settings.batch_size, **kw)
        else:
            eng = Engine(settings.model_path, **kw)
        eng.warmup()
        return eng
    return factory


app = create_app()

"""Request/response schemas — field-for-field the reference's
``data/requests.py:4-19`` so existing clients keep working unchanged."""

from __future__ import annotations

from typing import Optional

from pydantic import BaseModel


class ChatMessage(BaseModel):
    turn: str
    message: str


class BotProfile(BaseModel):
    name: str
    appearance: str
    system_prompt: Optional[str] = ""


class UserProfile(BaseModel):
    name: str


class BotMessageRequest(BaseModel):
    bot_profile: BotProfile
    user_profile: UserProfile
    context: list[ChatMessage]

"""Request/response schemas — field-for-field the reference's
``data/requests.py:4-19`` so existing clients keep working unchanged,
plus the OpenAI-compatible ``/v1/chat/completions`` request shape
(docs/MULTIMODEL.md facade mapping table)."""

from __future__ import annotations

from typing import Optional, Union

from pydantic import BaseModel


class ChatMessage(BaseModel):
    turn: str
    message: str


class BotProfile(BaseModel):
    name: str
    appearance: str
    system_prompt: Optional[str] = ""


class UserProfile(BaseModel):
    name: str


class BotMessageRequest(BaseModel):
    bot_profile: BotProfile
    user_profile: UserProfile
    context: list[ChatMessage]
    # multi-model routing (docs/MULTIMODEL.md): which manifest alias
    # serves this request; None = the pod's default model.  Absent from
    # the reference schema, so existing clients are unchanged — and an
    # unknown name 400s in the existing {"detail": ...} error shape.
    model: Optional[str] = None


# ---------------------------------------------------------------------------
# OpenAI-compatible facade (POST /v1/chat/completions)
# ---------------------------------------------------------------------------

class OpenAIChatMessage(BaseModel):
    role: str
    content: str


class StreamOptions(BaseModel):
    include_usage: bool = False


class ChatCompletionRequest(BaseModel):
    """The OpenAI chat-completions request subset this server honors.
    Sampling fields left unset fall back to the pod's serving defaults
    (LFKT_TEMPERATURE & co.) — the mapping table lives in
    docs/MULTIMODEL.md."""

    messages: list[OpenAIChatMessage]
    model: Optional[str] = None
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    stop: Optional[Union[str, list[str]]] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    n: int = 1
    user: Optional[str] = None

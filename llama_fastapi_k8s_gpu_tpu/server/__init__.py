from .app import create_app  # noqa: F401
from .schemas import BotMessageRequest, BotProfile, ChatMessage, UserProfile  # noqa: F401

"""``python -m llama_fastapi_k8s_gpu_tpu.server`` — run the service.

Uses uvicorn when available (the production image installs it, mirroring the
reference's gunicorn+UvicornWorker, reference docker/Dockerfile.app:12);
otherwise falls back to the in-tree dependency-free ``httpd``.  Either way
there is exactly one worker process: the model is loaded once per process, so
``-w 1`` is load-bearing (SURVEY.md §1 L4).
"""

def main():
    from ..utils.config import env_bool, force_cpu_if_requested, knob

    # The reference scales with `gunicorn -w N` (reference
    # docker/Dockerfile.app:12).  On TPU that is the wrong axis: a chip
    # admits ONE claimant process, and N interchangeable workers would
    # load N copies of the model.  The principled axes are in-process
    # lanes (LFKT_BATCH_SIZE) within one chip, ROLE-SPECIALIZED
    # processes (LFKT_DISAGG_ROLE: a prefill tier streaming KV pages to
    # decode replicas — serving/disagg/) across chips on one host, and
    # k8s `replicas` across hosts — so any request for >1 worker is
    # refused loudly instead of silently serialized.
    workers = knob("LFKT_WORKERS")
    if workers != 1:
        raise SystemExit(
            f"LFKT_WORKERS={workers} refused: one worker per process is "
            "load-bearing (a TPU chip admits a single claimant; the model "
            "loads once per process). Scale within a chip with "
            "LFKT_BATCH_SIZE lanes; scale across processes by ROLE, not "
            "by copy — LFKT_DISAGG_ROLE=prefill|decode splits prefill "
            "and decode into cooperating processes streaming KV pages "
            "(docs/RUNBOOK.md 'Operating a split prefill/decode "
            "fleet'); scale across chips with k8s replicas.")
    host = knob("LFKT_HOST")
    port = knob("LFKT_PORT")
    # structured serving logs: one JSON object per line, every record
    # stamped with the active request id (obs/logctx.py) — the k8s log
    # pipeline's ingest format; the text format stays for in-tree dev runs
    if env_bool("LFKT_JSON_LOGS", default=True):
        import logging

        from ..obs.logctx import setup_json_logging

        root = logging.getLogger()
        for h in list(root.handlers):   # replace basicConfig's text handler
            root.removeHandler(h)
        setup_json_logging()
    # fleet router (serving/fleet/; docs/RUNBOOK.md "Running a replica
    # fleet"): the THIRD process role after serving and disagg tiers —
    # a prefix-affinity proxy over the replica fleet.  Checked BEFORE any
    # model machinery (even the CPU pin): a router pod has no engine, no
    # jax, no uvicorn — it is a placement process.
    fleet_role = knob("LFKT_FLEET_ROLE", default="off")
    if fleet_role == "router":
        import logging

        from ..serving.fleet import run_router

        logging.basicConfig(level=logging.INFO)
        run_router(host, port)
        return
    if fleet_role != "off":
        from ..serving.fleet import FLEET_ROLES

        raise SystemExit(
            f"LFKT_FLEET_ROLE must be one of {'|'.join(FLEET_ROLES)}, "
            f"got {fleet_role!r}: replicas stay role=off; only the "
            "router process changes type (docs/RUNBOOK.md 'Running a "
            "replica fleet')")
    force_cpu_if_requested()   # site-hook defense (one copy: utils/config)
    try:
        import uvicorn
    except ImportError:
        from .app import app
        from .httpd import run

        run(app, host, port)
        return
    # the same graceful-drain budget the in-tree httpd honors: without it
    # uvicorn's SIGTERM handling applies no bounded drain and the
    # documented LFKT_DRAIN_SECONDS knob would be a no-op in the
    # production (uvicorn-installed) image.  The kwarg exists since
    # uvicorn 0.20 (requirements.txt floats); degrade rather than refuse
    # to serve on an older pin.
    import inspect
    import math

    from ..utils.config import get_settings

    drain = get_settings().drain_seconds
    kw = {}
    if "timeout_graceful_shutdown" in inspect.signature(
            uvicorn.Config).parameters:
        # uvicorn takes whole seconds; never truncate a small budget to an
        # immediate-cancel 0
        kw["timeout_graceful_shutdown"] = max(1, math.ceil(drain))
    uvicorn.run("llama_fastapi_k8s_gpu_tpu.server.app:app",
                host=host, port=port, workers=1, **kw)


if __name__ == "__main__":
    main()

"""``python -m llama_fastapi_k8s_gpu_tpu.server`` — run the service.

Uses uvicorn when available (the production image installs it, mirroring the
reference's gunicorn+UvicornWorker, reference docker/Dockerfile.app:12);
otherwise falls back to the in-tree dependency-free ``httpd``.  Either way
there is exactly one worker process: the model is loaded once per process, so
``-w 1`` is load-bearing (SURVEY.md §1 L4).
"""

import os


def main():
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # a site hook may pre-register a device platform and override the
        # env var at startup; the post-import config update wins if no
        # backend is initialized yet (same defense as tests/conftest.py
        # and bench.py — without it, JAX_PLATFORMS=cpu silently attaches
        # to the accelerator anyway)
        import jax

        jax.config.update("jax_platforms", "cpu")
    host = os.environ.get("LFKT_HOST", "0.0.0.0")
    port = int(os.environ.get("LFKT_PORT", "8000"))
    try:
        import uvicorn
    except ImportError:
        from .app import app
        from .httpd import run

        run(app, host, port)
        return
    uvicorn.run("llama_fastapi_k8s_gpu_tpu.server.app:app",
                host=host, port=port, workers=1)


if __name__ == "__main__":
    main()

"""In-tree ASGI micro-framework (FastAPI-compatible subset).

The reference builds on FastAPI + uvicorn + gunicorn (reference
docker/requirements.txt:1-4, Dockerfile.app:12).  This module provides the
subset of that surface the service actually uses — decorator routing with
path parameters, pydantic request-body validation (422s), ``HTTPException``
with a ``{"detail": ...}`` body, ``@app.middleware("http")``,
``@app.on_event("startup")``, ``app.state`` — as a plain ASGI app with zero
dependencies beyond pydantic.  The app runs under any ASGI server (uvicorn in
the production image, the in-tree ``httpd`` for dev/test) and is driven
in-process by ``httpx.ASGITransport`` in tests.

An ``/openapi.json`` document and a minimal ``/docs`` page are generated from
the registered routes, preserving the reference's advertised OpenAPI surface
(reference README.md:14).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import re
import traceback
import logging
import typing
from typing import Any, Awaitable, Callable

import pydantic

logger = logging.getLogger(__name__)


class HTTPException(Exception):
    def __init__(self, status_code: int, detail: Any = None):
        self.status_code = status_code
        self.detail = detail
        super().__init__(detail)


class State:
    """Attribute bag (FastAPI's app.state)."""


class URL:
    def __init__(self, scope: dict):
        self.path = scope.get("path", "/")
        self.query = scope.get("query_string", b"").decode()
        host = dict(scope.get("headers") or {}).get(b"host", b"").decode()
        self.scheme = scope.get("scheme", "http")
        self._str = f"{self.scheme}://{host}{self.path}" + (
            f"?{self.query}" if self.query else ""
        )

    def __str__(self):
        return self._str


class Request:
    def __init__(self, app: "MicroAPI", scope: dict, body: bytes):
        self.app = app
        self.scope = scope
        self.method = scope.get("method", "GET")
        self.url = URL(scope)
        self.path_params: dict[str, Any] = {}
        self._body = body
        self._headers: dict[str, str] | None = None

    @property
    def headers(self) -> dict[str, str]:
        """Lower-cased header map (FastAPI's ``request.headers`` subset) —
        built lazily; the tracer reads ``traceparent`` from it."""
        if self._headers is None:
            self._headers = {
                k.decode("latin-1").lower(): v.decode("latin-1")
                for k, v in (self.scope.get("headers") or [])
            }
        return self._headers

    async def body(self) -> bytes:
        return self._body

    async def json(self):
        return json.loads(self._body or b"null")


class Response:
    media_type = "application/octet-stream"

    def __init__(self, content: Any = b"", status_code: int = 200,
                 headers: dict[str, str] | None = None,
                 media_type: str | None = None):
        self.status_code = status_code
        self.headers = dict(headers or {})
        self.media_type = media_type or self.media_type
        self.body = self.render(content)

    def render(self, content) -> bytes:
        if isinstance(content, bytes):
            return content
        return str(content).encode()


class PlainTextResponse(Response):
    media_type = "text/plain; charset=utf-8"


class HTMLResponse(Response):
    media_type = "text/html; charset=utf-8"


class JSONResponse(Response):
    media_type = "application/json"

    def render(self, content) -> bytes:
        return json.dumps(content).encode()


class StreamingResponse(Response):
    """Incremental body from a sync or async iterator of str/bytes chunks.

    Sent as multiple ``http.response.body`` messages with ``more_body``;
    uvicorn and the in-tree httpd (chunked transfer-encoding) both consume
    that shape.  Default media type suits server-sent events.
    """

    media_type = "text/event-stream"

    def __init__(self, iterator, status_code: int = 200,
                 headers: dict[str, str] | None = None,
                 media_type: str | None = None):
        self.status_code = status_code
        self.headers = dict(headers or {})
        self.media_type = media_type or type(self).media_type
        self.iterator = iterator
        self.body = b""

    async def chunks(self):
        it = self.iterator
        if hasattr(it, "__aiter__"):
            async for chunk in it:
                yield chunk if isinstance(chunk, bytes) else str(chunk).encode()
        else:
            for chunk in it:
                yield chunk if isinstance(chunk, bytes) else str(chunk).encode()


class _Route:
    _PARAM_RE = re.compile(r"{(\w+)}")

    def __init__(self, method: str, path: str, handler: Callable):
        self.method = method
        self.path = path
        self.handler = handler
        pattern = self._PARAM_RE.sub(r"(?P<\1>[^/]+)", path)
        self.regex = re.compile(f"^{pattern}$")
        self.signature = inspect.signature(handler)
        # resolve string annotations (PEP 563 `from __future__ import annotations`)
        try:
            self.annotations = typing.get_type_hints(handler)
        except Exception:  # noqa: BLE001 — fall back to raw annotations
            self.annotations = {
                n: p.annotation for n, p in self.signature.parameters.items()
            }

    def annotation(self, name: str):
        return self.annotations.get(name, inspect.Parameter.empty)

    def match(self, method: str, path: str):
        m = self.regex.match(path)
        if not m:
            return None
        return m.groupdict()


class _Router:
    """Holds routes + lifecycle hooks; exposes startup()/shutdown() like
    starlette's router (used directly by in-process tests)."""

    def __init__(self):
        self.routes: list[_Route] = []
        self.on_startup: list[Callable] = []
        self.on_shutdown: list[Callable] = []

    async def startup(self):
        for fn in self.on_startup:
            res = fn()
            if inspect.isawaitable(res):
                await res

    async def shutdown(self):
        for fn in self.on_shutdown:
            res = fn()
            if inspect.isawaitable(res):
                await res


class MicroAPI:
    def __init__(self, title: str = "app", version: str = "0.1.0"):
        self.title = title
        self.version = version
        self.state = State()
        self.router = _Router()
        self._middlewares: list[Callable] = []
        self._add_builtin_routes()

    # -- registration ------------------------------------------------------
    def _register(self, method: str, path: str):
        def deco(fn):
            self.router.routes.append(_Route(method, path, fn))
            return fn
        return deco

    def get(self, path: str):
        return self._register("GET", path)

    def post(self, path: str):
        return self._register("POST", path)

    def on_event(self, name: str):
        def deco(fn):
            if name == "startup":
                self.router.on_startup.append(fn)
            elif name == "shutdown":
                self.router.on_shutdown.append(fn)
            return fn
        return deco

    def middleware(self, kind: str):
        assert kind == "http"

        def deco(fn):
            self._middlewares.append(fn)
            return fn
        return deco

    # -- request handling --------------------------------------------------
    async def _dispatch(self, request: Request) -> Response:
        path = request.url.path
        matched_path = False
        for route in self.router.routes:
            params = route.match(request.method, path)
            if params is None:
                continue
            matched_path = True
            if route.method != request.method:
                continue
            request.path_params = params
            return await self._call_handler(route, request)
        if matched_path:
            return JSONResponse({"detail": "Method Not Allowed"}, 405)
        return JSONResponse({"detail": "Not Found"}, 404)

    async def _call_handler(self, route: _Route, request: Request) -> Response:
        kwargs: dict[str, Any] = {}
        for name, param in route.signature.parameters.items():
            ann = route.annotation(name)
            if ann is Request or name == "request":
                kwargs[name] = request
            elif isinstance(ann, type) and issubclass(ann, pydantic.BaseModel):
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    return JSONResponse({"detail": "Invalid JSON body"}, 422)
                try:
                    kwargs[name] = ann.model_validate(payload)
                except pydantic.ValidationError as e:
                    return JSONResponse({"detail": e.errors(include_url=False)}, 422)
            elif name in request.path_params:
                value = request.path_params[name]
                if ann is int:
                    try:
                        value = int(value)
                    except ValueError:
                        return JSONResponse(
                            {"detail": f"Invalid int path param {name!r}"}, 422)
                kwargs[name] = value
        result = route.handler(**kwargs)
        if inspect.isawaitable(result):
            result = await result
        if isinstance(result, Response):
            return result
        return JSONResponse(result)

    async def _handle(self, request: Request) -> Response:
        async def endpoint(req: Request) -> Response:
            try:
                return await self._dispatch(req)
            except HTTPException as e:
                return JSONResponse({"detail": e.detail}, e.status_code)
            except Exception:  # noqa: BLE001
                logger.error("Unhandled error:\n%s", traceback.format_exc())
                return JSONResponse({"detail": "Internal Server Error"}, 500)

        call_next: Callable[[Request], Awaitable[Response]] = endpoint
        for mw in reversed(self._middlewares):
            call_next = _bind_middleware(mw, call_next)
        try:
            return await call_next(request)
        except HTTPException as e:
            # a middleware may surface handler HTTPExceptions
            return JSONResponse({"detail": e.detail}, e.status_code)

    # -- ASGI --------------------------------------------------------------
    async def __call__(self, scope, receive, send):
        if scope["type"] == "lifespan":
            while True:
                message = await receive()
                if message["type"] == "lifespan.startup":
                    try:
                        await self.router.startup()
                        await send({"type": "lifespan.startup.complete"})
                    except Exception as e:  # noqa: BLE001
                        await send({"type": "lifespan.startup.failed",
                                    "message": str(e)})
                elif message["type"] == "lifespan.shutdown":
                    try:
                        await self.router.shutdown()
                        await send({"type": "lifespan.shutdown.complete"})
                    except Exception as e:  # noqa: BLE001
                        await send({"type": "lifespan.shutdown.failed",
                                    "message": str(e)})
                    return
            # unreachable
        if scope["type"] != "http":
            raise RuntimeError(f"unsupported ASGI scope {scope['type']}")

        body = b""
        while True:
            message = await receive()
            if message["type"] == "http.request":
                body += message.get("body", b"")
                if not message.get("more_body"):
                    break
            elif message["type"] == "http.disconnect":
                return

        request = Request(self, scope, body)
        response = await self._handle(request)
        if isinstance(response, StreamingResponse):
            headers = [(b"content-type", response.media_type.encode()),
                       (b"cache-control", b"no-cache")]
            headers += [(k.encode(), v.encode())
                        for k, v in response.headers.items()]
            await send({"type": "http.response.start",
                        "status": response.status_code, "headers": headers})
            chunks = response.chunks()
            try:
                async for chunk in chunks:
                    await send({"type": "http.response.body", "body": chunk,
                                "more_body": True})
                await send({"type": "http.response.body", "body": b""})
            finally:
                # deterministic close: when a disconnected client makes
                # send() raise, the app's generator must see GeneratorExit
                # NOW (its finally reclaims the engine lane/slot), not at
                # some later garbage-collection pass
                await chunks.aclose()
            return
        headers = [(b"content-type", response.media_type.encode()),
                   (b"content-length", str(len(response.body)).encode())]
        headers += [(k.encode(), v.encode()) for k, v in response.headers.items()]
        await send({"type": "http.response.start",
                    "status": response.status_code, "headers": headers})
        await send({"type": "http.response.body", "body": response.body})

    # -- openapi -----------------------------------------------------------
    def openapi(self) -> dict:
        paths: dict[str, dict] = {}
        for route in self.router.routes:
            if route.path in ("/openapi.json", "/docs"):
                continue
            entry = paths.setdefault(route.path, {})
            op: dict[str, Any] = {
                "summary": (route.handler.__doc__ or "").strip().split("\n")[0],
                "operationId": route.handler.__name__,
                "responses": {"200": {"description": "Successful Response"}},
            }
            for name, param in route.signature.parameters.items():
                ann = route.annotation(name)
                if isinstance(ann, type) and issubclass(ann, pydantic.BaseModel):
                    op["requestBody"] = {
                        "content": {"application/json": {
                            "schema": ann.model_json_schema()}},
                        "required": True,
                    }
            params = _Route._PARAM_RE.findall(route.path)
            if params:
                op["parameters"] = [
                    {"name": p, "in": "path", "required": True,
                     "schema": {"type": "integer"
                                if route.annotation(p) is int else "string"}}
                    for p in params
                ]
            entry[route.method.lower()] = op
        return {
            "openapi": "3.1.0",
            "info": {"title": self.title, "version": self.version},
            "paths": paths,
        }

    def _add_builtin_routes(self):
        @self.get("/openapi.json")
        async def openapi_json():
            return JSONResponse(self.openapi())

        @self.get("/docs")
        async def docs():
            rows = []
            for route in self.router.routes:
                if route.path in ("/openapi.json", "/docs"):
                    continue
                doc = (route.handler.__doc__ or "").strip().split("\n")[0]
                rows.append(
                    f"<tr><td><code>{route.method}</code></td>"
                    f"<td><code>{route.path}</code></td><td>{doc}</td></tr>")
            html = (
                f"<html><head><title>{self.title} — docs</title></head><body>"
                f"<h1>{self.title} <small>{self.version}</small></h1>"
                f"<p>OpenAPI JSON: <a href='/openapi.json'>/openapi.json</a></p>"
                f"<table border=1 cellpadding=6><tr><th>method</th><th>path</th>"
                f"<th>summary</th></tr>{''.join(rows)}</table></body></html>"
            )
            return HTMLResponse(html)


def _bind_middleware(mw, nxt):
    async def bound(request: Request) -> Response:
        return await mw(request, nxt)
    return bound

"""Dependency-free asyncio HTTP/1.1 server for ASGI apps.

Stands in for uvicorn (reference docker/Dockerfile.app:12) when serving the
in-tree ASGI app without external packages: persistent connections,
Content-Length framing, graceful shutdown via the ASGI lifespan protocol.
One process, one event loop — the reference's single-worker model
(``gunicorn -w 1``) is preserved by construction.

Shutdown mirrors gunicorn's graceful stop: on SIGTERM/SIGINT the listener
closes, idle keep-alive connections are closed immediately, in-flight
requests (counted from their first COMPLETE request line, so a mid-upload
header/body is covered; a partial request line at stop is treated as idle)
get up to ``LFKT_DRAIN_SECONDS`` to complete with a ``connection: close``
response, and only then does the ASGI shutdown hook run.  Surviving
connections are force-closed AND their handler tasks cancelled after the
drain budget, so ``Server.wait_closed`` (which on Python ≥3.12.1 waits for
ALL connection handlers — including ones blocked inside the app, not on
socket I/O) cannot hang the process past its pod termination grace period.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import time

logger = logging.getLogger(__name__)

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 405: "Method Not Allowed",
    408: "Request Timeout", 409: "Conflict", 422: "Unprocessable Entity",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable",
}


async def _reject(writer, status: int, detail: str) -> bool:
    """Minimal error response for requests the server won't parse further
    (malformed/conflicting Content-Length → 400, chunked transfer-coding →
    501).  ``Connection: close`` is honest: the remaining request bytes are
    unread, so the connection cannot be reused — but unlike the former
    silent close the client gets told WHY (RFC 9112 §6.1/§6.3).  Returns
    False so the caller drops the connection."""
    body = (detail + "\n").encode()
    writer.write(
        f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"
        f"content-length: {len(body)}\r\n"
        "connection: close\r\n\r\n".encode() + body)
    try:
        await writer.drain()
    except (ConnectionResetError, BrokenPipeError):
        pass
    return False


async def _handle_request(app, reader, writer, peer, request_line,
                          state, t_read0=None) -> bool:
    """Serve one request on an open connection.  Returns False when the
    connection must close (malformed request, read deadline, or draining).
    ``t_read0`` is when this request's bytes started arriving; the
    completed read window rides the ASGI scope (``lfkt.httpd_read``) so
    the app's tracer can render an ``httpd.read`` span — a slow client
    (or a slowloris probe) then shows up as read time, not app time."""
    try:
        method, target, _version = request_line.decode().split()
    except ValueError:
        return False

    async def _read_head_and_body():
        headers = []
        content_length = None
        chunked = False
        close_requested = False
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode().partition(":")
            name = name.strip().lower()
            value = value.strip()
            headers.append((name.encode(), value.encode()))
            if name == "connection" and "close" in value.lower():
                # honor the client's one-request intent (RFC 9112 §9.6):
                # proxies (the fleet router) and strict HTTP/1.1 clients
                # frame "response ends" as "connection closes" — before
                # this the server kept the socket open and such callers
                # hung waiting for an EOF that never came
                close_requested = True
            if name == "content-length":
                try:
                    cl = int(value)
                except ValueError:  # malformed framing: say so, then close
                    return await _reject(writer, 400,
                                         "invalid Content-Length")
                if cl < 0:
                    return await _reject(writer, 400,
                                         "invalid Content-Length")
                if content_length is not None and cl != content_length:
                    # conflicting lengths (RFC 9112 §6.3: unrecoverable —
                    # never last-one-wins)
                    return await _reject(writer, 400,
                                         "conflicting Content-Length")
                content_length = cl
            elif name == "transfer-encoding":
                chunked = True
        if chunked:
            # chunked request bodies are not implemented; serving the
            # request with an empty body would leave the chunk stream in
            # the buffer to be misparsed as the next request line — close
            # (with attribution) instead
            return await _reject(writer, 501,
                                 "chunked transfer-coding not supported")
        content_length = content_length or 0
        body = (await reader.readexactly(content_length)
                if content_length else b"")
        return headers, body, close_requested

    # slowloris guard: once the request line has arrived, the rest of the
    # head + body must finish arriving within the read deadline — a client
    # dribbling one header byte per minute gets an honest 408 and a closed
    # socket instead of holding a connection (and, during drain, a slot in
    # the shutdown accounting) forever
    try:
        got = await asyncio.wait_for(_read_head_and_body(),
                                     state["read_timeout"])
    except asyncio.TimeoutError:
        return await _reject(writer, 408, "request read timeout")
    if got is False:
        return False                     # _reject already answered
    headers, body, close_requested = got

    path, _, query = target.partition("?")
    scope = {
        "type": "http",
        "asgi": {"version": "3.0"},
        "http_version": "1.1",
        "method": method.upper(),
        "path": path,
        "query_string": query.encode(),
        "headers": headers,
        "client": peer,
        "scheme": "http",
    }
    if t_read0 is not None:
        scope["lfkt.httpd_read"] = (t_read0, time.time())

    messages = [{"type": "http.request", "body": body, "more_body": False}]

    async def receive():
        if messages:
            return messages.pop(0)
        return {"type": "http.disconnect"}

    # Buffered by default; switches to chunked transfer-encoding the
    # moment the app sends a body part with more_body=True (streaming
    # responses — SSE /response/stream).
    response = {"status": 500, "headers": [], "body": b"",
                "streaming": False}

    def _write_head(chunked: bool):
        status = response["status"]
        head = [f"HTTP/1.1 {status} {_REASONS.get(status, '')}".encode()]
        has_length = False
        for k, v in response["headers"]:
            if k.lower() == b"content-length":
                has_length = True
            head.append(k + b": " + v)
        if chunked:
            head.append(b"transfer-encoding: chunked")
        elif not has_length:
            head.append(
                b"content-length: " + str(len(response["body"])).encode())
        # honest connection signaling: during drain — or when the client
        # itself sent "connection: close" — the handler closes the socket
        # after this response, so clients must not reuse it
        head.append(b"connection: close"
                    if state["draining"] or close_requested
                    else b"connection: keep-alive")
        writer.write(b"\r\n".join(head) + b"\r\n\r\n")

    async def send(message):
        if message["type"] == "http.response.start":
            response["status"] = message["status"]
            response["headers"] = message.get("headers", [])
        elif message["type"] == "http.response.body":
            body = message.get("body", b"")
            if message.get("more_body"):
                if not response["streaming"]:
                    response["streaming"] = True
                    _write_head(chunked=True)
                if body:
                    writer.write(
                        f"{len(body):x}\r\n".encode() + body + b"\r\n")
                    await writer.drain()
            elif response["streaming"]:
                if body:
                    writer.write(
                        f"{len(body):x}\r\n".encode() + body + b"\r\n")
                writer.write(b"0\r\n\r\n")
                await writer.drain()
            else:
                response["body"] += body

    await app(scope, receive, send)

    if not response["streaming"]:
        _write_head(chunked=False)
        writer.write(response["body"])
        await writer.drain()
    return not state["draining"] and not close_requested


async def _handle_connection(app, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter, state: dict):
    peer = writer.get_extra_info("peername")
    state["conns"].add(writer)
    state["tasks"].add(asyncio.current_task())
    first_request = True
    try:
        while True:
            if state["draining"]:
                break   # shutdown: no new requests on this connection
            if first_request:
                # a FRESH connection must produce a complete request line
                # within the read deadline — a dribbled partial line would
                # otherwise dodge the header/body slowloris guard entirely
                # (it never reaches _handle_request)
                t_read0 = time.time()
                try:
                    request_line = await asyncio.wait_for(
                        reader.readline(), state["read_timeout"])
                except asyncio.TimeoutError:
                    await _reject(writer, 408, "request read timeout")
                    break
                first_request = False
            else:
                # established keep-alive: idling between requests stays
                # unbounded (as before), but once the first BYTE of a new
                # request line arrives the rest must complete within the
                # read deadline — otherwise one cheap valid request would
                # buy an attacker an unguarded dribble slot
                lead = await reader.read(1)
                if not lead:
                    break
                t_read0 = time.time()   # idle keep-alive wait excluded
                try:
                    request_line = lead + await asyncio.wait_for(
                        reader.readline(), state["read_timeout"])
                except asyncio.TimeoutError:
                    await _reject(writer, 408, "request read timeout")
                    break
            if not request_line:
                break
            # count the request from its first complete request line: a
            # request mid-upload (headers/body still arriving) when
            # shutdown starts must be inside the drain accounting
            state["active"] += 1
            state["busy"].add(writer)
            try:
                keep = await _handle_request(app, reader, writer, peer,
                                             request_line, state,
                                             t_read0=t_read0)
            finally:
                state["active"] -= 1
                state["busy"].discard(writer)
                if state["draining"] and state["active"] == 0:
                    state["idle"].set()
            if not keep:
                break
    except (asyncio.IncompleteReadError, ConnectionResetError):
        pass
    finally:
        state["conns"].discard(writer)
        state["busy"].discard(writer)
        state["tasks"].discard(asyncio.current_task())
        try:
            writer.close()
            await writer.wait_closed()
        except Exception:  # noqa: BLE001
            pass


def _close_conns(state: dict, only_idle: bool):
    for w in list(state["conns"]):
        if only_idle and w in state["busy"]:
            continue
        try:
            w.close()
        except Exception:  # noqa: BLE001
            pass


async def serve(app, host: str = "0.0.0.0", port: int = 8000,
                ready_event: asyncio.Event | None = None,
                stop_event: asyncio.Event | None = None,
                drain_seconds: float | None = None,
                read_timeout: float | None = None):
    """Serve until SIGINT/SIGTERM (or ``stop_event``), then drain.

    ``drain_seconds`` defaults to ``LFKT_DRAIN_SECONDS`` (30 — gunicorn's
    graceful_timeout, the reference's termination behavior at
    docker/Dockerfile.app:12; it also bounds the reference-parity 25 s
    generation timeout with headroom).  ``read_timeout`` defaults to
    ``LFKT_READ_TIMEOUT`` (30) — the slowloris guard's header/body read
    deadline (408 + Connection: close).
    """
    if drain_seconds is None or read_timeout is None:
        # one parse site for the knobs (utils/config.py registers them);
        # local import keeps this module's top-level deps stdlib-only
        from ..utils.config import get_settings

        _settings = get_settings()
        if drain_seconds is None:
            drain_seconds = _settings.drain_seconds
        if read_timeout is None:
            read_timeout = _settings.read_timeout
    await app.router.startup()
    state = {"active": 0, "draining": False, "idle": asyncio.Event(),
             "conns": set(), "busy": set(), "tasks": set(),
             "read_timeout": read_timeout}
    server = await asyncio.start_server(
        lambda r, w: _handle_connection(app, r, w, state), host, port)
    logger.info("httpd listening on %s:%d", host, port)
    if ready_event is not None:
        ready_event.set()

    stop = stop_event if stop_event is not None else asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError, ValueError):
            # non-main thread (tests/embedding) or unsupported platform:
            # graceful-shutdown-by-signal just isn't available there
            pass
    async with server:
        await stop.wait()
        state["draining"] = True
        # surface the drain on the health state machine (readiness flips
        # to 503 so k8s stops routing while in-flight requests finish);
        # generic ASGI apps without the resilience layer are untouched
        health = getattr(getattr(app, "state", None), "health", None)
        if health is not None:
            from ..utils.health import DRAINING

            health.transition(DRAINING, "shutdown signal received")
        # a draining prefill tier stops admitting NEW page-wire peers
        # (serving/disagg/): in-flight page transfers ride the drain like
        # HTTP requests; decode replicas re-dial the Service and land on
        # a live pod.  Full teardown happens in the app's shutdown hook.
        disagg = getattr(getattr(app, "state", None), "disagg", None)
        if disagg is not None and disagg.server is not None:
            disagg.server.stop_accepting()
        server.close()            # stop accepting; existing tasks continue
        # one short tick before closing "idle" connections: a request whose
        # bytes are already buffered but whose handler is still parked in
        # readline() would otherwise be closed unserved — the wakeup lets
        # it claim busy status and ride the drain instead
        await asyncio.sleep(0.05)
        _close_conns(state, only_idle=True)   # idle keep-alives: EOF now
        if state["active"]:
            logger.info("httpd draining %d in-flight request(s) (≤%.0fs)",
                        state["active"], drain_seconds)
            try:
                await asyncio.wait_for(state["idle"].wait(), drain_seconds)
            except asyncio.TimeoutError:
                logger.warning("httpd drain timed out after %.0fs; "
                               "%d request(s) abandoned",
                               drain_seconds, state["active"])
        # Whatever survives is force-closed AND cancelled: a handler
        # blocked inside the app (not on socket I/O) never notices a
        # closed transport, and Server.wait_closed waits for it.
        _close_conns(state, only_idle=False)
        for t in list(state["tasks"]):
            t.cancel()
        # graceful drain of the radix cache (serving/fleet/migrate.py):
        # AFTER in-flight requests finished (their pages are committed
        # and included) but BEFORE app shutdown tears the page service
        # down, hand the hottest conversations to their rendezvous
        # successors.  drain_push bounds itself to the drain budget; the
        # wait_for is the belt-and-braces guarantee that a wedged push
        # can never delay termination past budget + 1s (helm's
        # terminationGracePeriodSeconds accounts for both drains).
        migration = getattr(getattr(app, "state", None), "migration", None)
        if migration is not None:
            try:
                pushed = await asyncio.wait_for(
                    asyncio.to_thread(migration.drain_push),
                    migration.drain_budget + 1.0)
                logger.info("httpd drain: migrated %d conversation(s) to "
                            "successor peers", pushed)
            except asyncio.TimeoutError:
                logger.warning("httpd drain: KV page push overran its "
                               "budget; terminating without handoff")
            except Exception as e:  # noqa: BLE001 — a failed handoff
                # degrades to normal termination, never blocks shutdown
                logger.warning("httpd drain: KV page push failed: %s", e)
    await app.router.shutdown()


def run(app, host: str = "0.0.0.0", port: int = 8000):
    asyncio.run(serve(app, host, port))

"""Dependency-free asyncio HTTP/1.1 server for ASGI apps.

Stands in for uvicorn (reference docker/Dockerfile.app:12) when serving the
in-tree ASGI app without external packages: persistent connections,
Content-Length framing, graceful shutdown via the ASGI lifespan protocol.
One process, one event loop — the reference's single-worker model
(``gunicorn -w 1``) is preserved by construction.
"""

from __future__ import annotations

import asyncio
import logging
import signal

logger = logging.getLogger(__name__)

_REASONS = {
    200: "OK", 404: "Not Found", 405: "Method Not Allowed", 408: "Request Timeout",
    422: "Unprocessable Entity", 500: "Internal Server Error",
    503: "Service Unavailable",
}


async def _handle_connection(app, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
    peer = writer.get_extra_info("peername")
    try:
        while True:
            request_line = await reader.readline()
            if not request_line:
                break
            try:
                method, target, _version = request_line.decode().split()
            except ValueError:
                break
            headers = []
            content_length = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                name = name.strip().lower()
                value = value.strip()
                headers.append((name.encode(), value.encode()))
                if name == "content-length":
                    content_length = int(value)
            body = await reader.readexactly(content_length) if content_length else b""

            path, _, query = target.partition("?")
            scope = {
                "type": "http",
                "asgi": {"version": "3.0"},
                "http_version": "1.1",
                "method": method.upper(),
                "path": path,
                "query_string": query.encode(),
                "headers": headers,
                "client": peer,
                "scheme": "http",
            }

            messages = [{"type": "http.request", "body": body, "more_body": False}]

            async def receive():
                if messages:
                    return messages.pop(0)
                return {"type": "http.disconnect"}

            # Buffered by default; switches to chunked transfer-encoding the
            # moment the app sends a body part with more_body=True (streaming
            # responses — SSE /response/stream).
            response = {"status": 500, "headers": [], "body": b"",
                        "streaming": False}

            def _write_head(chunked: bool):
                status = response["status"]
                head = [f"HTTP/1.1 {status} {_REASONS.get(status, '')}".encode()]
                has_length = False
                for k, v in response["headers"]:
                    if k.lower() == b"content-length":
                        has_length = True
                    head.append(k + b": " + v)
                if chunked:
                    head.append(b"transfer-encoding: chunked")
                elif not has_length:
                    head.append(
                        b"content-length: " + str(len(response["body"])).encode())
                head.append(b"connection: keep-alive")
                writer.write(b"\r\n".join(head) + b"\r\n\r\n")

            async def send(message):
                if message["type"] == "http.response.start":
                    response["status"] = message["status"]
                    response["headers"] = message.get("headers", [])
                elif message["type"] == "http.response.body":
                    body = message.get("body", b"")
                    if message.get("more_body"):
                        if not response["streaming"]:
                            response["streaming"] = True
                            _write_head(chunked=True)
                        if body:
                            writer.write(
                                f"{len(body):x}\r\n".encode() + body + b"\r\n")
                            await writer.drain()
                    elif response["streaming"]:
                        if body:
                            writer.write(
                                f"{len(body):x}\r\n".encode() + body + b"\r\n")
                        writer.write(b"0\r\n\r\n")
                        await writer.drain()
                    else:
                        response["body"] += body

            await app(scope, receive, send)

            if not response["streaming"]:
                _write_head(chunked=False)
                writer.write(response["body"])
                await writer.drain()
    except (asyncio.IncompleteReadError, ConnectionResetError):
        pass
    finally:
        try:
            writer.close()
            await writer.wait_closed()
        except Exception:  # noqa: BLE001
            pass


async def serve(app, host: str = "0.0.0.0", port: int = 8000,
                ready_event: asyncio.Event | None = None):
    await app.router.startup()
    server = await asyncio.start_server(
        lambda r, w: _handle_connection(app, r, w), host, port)
    logger.info("httpd listening on %s:%d", host, port)
    if ready_event is not None:
        ready_event.set()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError, ValueError):
            # non-main thread (tests/embedding) or unsupported platform:
            # graceful-shutdown-by-signal just isn't available there
            pass
    async with server:
        await stop.wait()
    await app.router.shutdown()


def run(app, host: str = "0.0.0.0", port: int = 8000):
    asyncio.run(serve(app, host, port))

"""Env-overridable runtime settings.

The reference hardcodes all of these as module constants (reference
api.py:13-19: model dir ``models``, ``MODEL_NAME``, ``MAX_CONTEXT_TOKENS=1024``,
``TIMEOUT_SECONDS=25``, ``MAX_QUEUE_SIZE=5``) and its Helm values never reach
the app as env vars (SURVEY.md §5 "Config / flag system").  Here the same
defaults are preserved, but every knob can be overridden through the
environment so the Helm chart can parameterize the app.
"""

from __future__ import annotations

import dataclasses
import os


def _env(name: str, default, cast=str):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return cast(raw)


def force_cpu_if_requested() -> bool:
    """THE site-hook defense (one copy): when the caller asked for the CPU
    backend (``JAX_PLATFORMS=cpu``) but a site hook may have pre-registered
    the tunneled device platform and overridden the env var, re-pin the
    platform via ``jax.config`` — which wins while no backend is
    initialized.  Without this, a "CPU" test/dryrun silently attaches to
    the single-session accelerator and can hold its claim (observed
    2026-07-31 and again 2026-08-01).  Call BEFORE the first
    ``jax.devices()``/computation; returns True when the pin was applied.
    Callers: tests/conftest.py, __graft_entry__.py, server/__main__.py,
    bench.py."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True


def env_bool(name: str, default: bool = False) -> bool:
    """THE truthy-env convention (one parser: '1'/'true'/'yes'/'on').
    Direct-engine-construction paths (bench_server.py, models/params.py)
    must use this instead of re-implementing the tuple and silently
    diverging on accepted spellings."""
    return _env(name, default, bool)


@dataclasses.dataclass(frozen=True)
class Settings:
    # Identical defaults to reference api.py:13-19.
    model_dir: str = "models"
    model_name: str = "Lexi-Llama-3-8B-Uncensored_Q4_K_M.gguf"
    max_context_tokens: int = 1024
    timeout_seconds: float = 25.0
    max_queue_size: int = 5
    # total wall-clock bound for one /response/stream response; the
    # per-chunk-gap timeout alone would let a slow-dripping generation hold
    # its queue slot indefinitely (no reference equivalent: it has no
    # streaming at all, reference api.py:58)
    stream_deadline_seconds: float = 300.0
    # graceful-shutdown budget: on SIGTERM in-flight requests get this long
    # to finish (gunicorn graceful_timeout analogue — the reference's
    # termination behavior at docker/Dockerfile.app:12).  Honored by both
    # the in-tree httpd and the uvicorn path; keep the pod's
    # terminationGracePeriodSeconds above it (helm derives grace from the
    # same values knob)
    drain_seconds: float = 30.0
    # slowloris guard (in-tree httpd): once a request line has arrived the
    # headers+body must finish arriving within this window, else 408 +
    # Connection: close instead of holding the socket forever
    read_timeout: float = 30.0

    # -- resilience layer (docs/RUNBOOK.md "Degraded-mode operations") -----
    # engine watchdog: detects stalled decode/hung device calls (no beat
    # for stall_seconds while work is in flight), exception bursts, and a
    # dead scheduler loop; trips to DEGRADED (readiness 503, liveness 200),
    # fails in-flight futures with 503, and runs bounded in-process
    # recovery with exponential backoff — escalating to DEAD (liveness
    # 503 → pod restart) after watchdog_max_recoveries trips.
    watchdog: bool = True
    watchdog_stall_seconds: float = 60.0
    watchdog_poll_seconds: float = 1.0
    watchdog_max_recoveries: int = 3
    watchdog_error_burst: int = 5
    watchdog_error_window: float = 30.0
    watchdog_backoff_seconds: float = 1.0
    watchdog_backoff_max: float = 60.0

    # Fixed sampling parameters the reference passes at api.py:59-62; the
    # remaining knobs take llama-cpp-python 0.2.77 defaults (top_k=40,
    # min_p=0.05, repeat_penalty=1.1) because the reference omits them.
    temperature: float = 1.2
    top_p: float = 0.9
    frequency_penalty: float = 0.7
    presence_penalty: float = 0.8
    top_k: int = 40
    min_p: float = 0.05
    repeat_penalty: float = 1.1

    # TPU-native knobs (no reference equivalent).
    max_gen_tokens: int = 512
    decode_chunk: int = 8           # device-side tokens per host round-trip.
    # Measured trade-off (docs/bench 2026-07-30): single-stream decode
    # rises mildly with chunk size (+~1% at 1k ctx, +4.7% at 8k for 32 vs
    # 8 — bench.py reports its sweep's best either way), but the chunk is
    # ALSO the continuous scheduler's admission/stream cadence: at 16 the
    # 8-lane aggregate dropped 160 -> 108 tok/s and stream TTFT doubled
    # (209 -> 407 ms).  8 is the serving default; single-stream batch
    # callers can raise LFKT_DECODE_CHUNK.
    prefill_buckets: str = "128,256,512,1024"  # padded prompt shapes to bound recompiles
    weight_format: str = "auto"     # auto | bf16 | int8 | q4k
    attn_impl: str = "auto"         # auto | xla | pallas (prefill flash kernel)
    kv_dtype: str = "bf16"          # bf16 | int8 — int8 halves KV-cache HBM
    #                                 (values int8 + per-head per-token f32
    #                                 scales) and streams int8 through the
    #                                 attention reads; docs/KV_CACHE.md
    spec_decode: str = "off"        # off | lookup | auto — prompt-lookup
    #                                 speculation; "auto" measures the
    #                                 deployment's dispatch RTT at startup
    #                                 and enables lookup iff its breakeven
    #                                 acceptance < LFKT_SPEC_AUTO_ACCEPT
    #                                 (engine/spec_auto.py)
    spec_draft: int = 8             # draft tokens per verify step
    # serial-engine prompt-prefix KV reuse (llama.cpp's prompt-cache
    # analogue): when consecutive prompts share a token prefix — the
    # reference workload re-sends persona + full history every turn —
    # prefill only the suffix.  Mesh/SP/lane engines ignore it.
    prefix_cache: bool = True
    # the continuous scheduler's analogue: admissions whose prompt shares
    # a freed lane's conversation history snapshot that lane's KV and
    # prefill only the suffix slices (chunk-aligned).  Off by default —
    # the admission path is the scheduler's measured bottleneck, so flip
    # this knob deliberately per deployment.
    lane_prefix_cache: bool = False
    prefill_chunk: int = 256        # continuous-scheduler admission slice size
    adm_budget: int = 512           # admission prefill tokens per scheduler
    #                                 iteration (several short admissions,
    #                                 or slices of one long prompt)
    # >1 switches the server to mesh-batched serving — the v5e-4
    # "concurrent /response load" config.  scheduler picks the flavor:
    #   cycle      — MeshEngine: coalesce up to batch_size queued requests
    #                per generation cycle (barrier between cycles)
    #   continuous — ContinuousEngine: slot-based continuous batching;
    #                free lanes admit new requests at every chunk boundary
    batch_size: int = 1
    scheduler: str = "continuous"
    mesh_tp: int = 1                # tensor-parallel width across the mesh
    # >1 serves with the sequence-parallel engine (engine/sp.py): the KV
    # cache's n_ctx dim shards over an sp-axis ring (ring attention for
    # prefill, sharded-LSE decode), scaling max context linearly with the
    # ring size.  Serial serving (batch_size must stay 1).
    mesh_sp: int = 1

    @property
    def model_path(self) -> str:
        return os.path.join(self.model_dir, self.model_name)

    @property
    def prefill_bucket_list(self) -> list[int]:
        return sorted(int(x) for x in self.prefill_buckets.split(",") if x.strip())


def get_settings() -> Settings:
    return Settings(
        model_dir=_env("LFKT_MODEL_DIR", Settings.model_dir),
        model_name=_env("LFKT_MODEL_NAME", Settings.model_name),
        max_context_tokens=_env("LFKT_MAX_CONTEXT_TOKENS", Settings.max_context_tokens, int),
        timeout_seconds=_env("LFKT_TIMEOUT_SECONDS", Settings.timeout_seconds, float),
        drain_seconds=_env("LFKT_DRAIN_SECONDS", Settings.drain_seconds, float),
        read_timeout=_env("LFKT_READ_TIMEOUT", Settings.read_timeout, float),
        watchdog=_env("LFKT_WATCHDOG", Settings.watchdog, bool),
        watchdog_stall_seconds=_env("LFKT_WATCHDOG_STALL_SECONDS",
                                    Settings.watchdog_stall_seconds, float),
        watchdog_poll_seconds=_env("LFKT_WATCHDOG_POLL_SECONDS",
                                   Settings.watchdog_poll_seconds, float),
        watchdog_max_recoveries=_env("LFKT_WATCHDOG_MAX_RECOVERIES",
                                     Settings.watchdog_max_recoveries, int),
        watchdog_error_burst=_env("LFKT_WATCHDOG_ERROR_BURST",
                                  Settings.watchdog_error_burst, int),
        watchdog_error_window=_env("LFKT_WATCHDOG_ERROR_WINDOW",
                                   Settings.watchdog_error_window, float),
        watchdog_backoff_seconds=_env("LFKT_WATCHDOG_BACKOFF_SECONDS",
                                      Settings.watchdog_backoff_seconds, float),
        watchdog_backoff_max=_env("LFKT_WATCHDOG_BACKOFF_MAX",
                                  Settings.watchdog_backoff_max, float),
        max_queue_size=_env("LFKT_MAX_QUEUE_SIZE", Settings.max_queue_size, int),
        stream_deadline_seconds=_env("LFKT_STREAM_DEADLINE_SECONDS",
                                     Settings.stream_deadline_seconds, float),
        temperature=_env("LFKT_TEMPERATURE", Settings.temperature, float),
        top_p=_env("LFKT_TOP_P", Settings.top_p, float),
        frequency_penalty=_env("LFKT_FREQUENCY_PENALTY", Settings.frequency_penalty, float),
        presence_penalty=_env("LFKT_PRESENCE_PENALTY", Settings.presence_penalty, float),
        top_k=_env("LFKT_TOP_K", Settings.top_k, int),
        min_p=_env("LFKT_MIN_P", Settings.min_p, float),
        repeat_penalty=_env("LFKT_REPEAT_PENALTY", Settings.repeat_penalty, float),
        max_gen_tokens=_env("LFKT_MAX_GEN_TOKENS", Settings.max_gen_tokens, int),
        decode_chunk=_env("LFKT_DECODE_CHUNK", Settings.decode_chunk, int),
        prefill_buckets=_env("LFKT_PREFILL_BUCKETS", Settings.prefill_buckets),
        weight_format=_env("LFKT_WEIGHT_FORMAT", Settings.weight_format),
        attn_impl=_env("LFKT_ATTN_IMPL", Settings.attn_impl),
        kv_dtype=_env("LFKT_KV_DTYPE", Settings.kv_dtype),
        spec_decode=_env("LFKT_SPEC_DECODE", Settings.spec_decode),
        spec_draft=_env("LFKT_SPEC_DRAFT", Settings.spec_draft, int),
        prefix_cache=_env("LFKT_PREFIX_CACHE", Settings.prefix_cache, bool),
        lane_prefix_cache=_env("LFKT_LANE_PREFIX_CACHE",
                               Settings.lane_prefix_cache, bool),
        prefill_chunk=_env("LFKT_PREFILL_CHUNK", Settings.prefill_chunk, int),
        adm_budget=_env("LFKT_ADM_BUDGET", Settings.adm_budget, int),
        batch_size=_env("LFKT_BATCH_SIZE", Settings.batch_size, int),
        scheduler=_env("LFKT_SCHEDULER", Settings.scheduler),
        mesh_tp=_env("LFKT_MESH_TP", Settings.mesh_tp, int),
        mesh_sp=_env("LFKT_MESH_SP", Settings.mesh_sp, int),
    )

"""Env-overridable runtime settings — THE ``LFKT_*`` knob registry.

The reference hardcodes all of these as module constants (reference
api.py:13-19: model dir ``models``, ``MODEL_NAME``, ``MAX_CONTEXT_TOKENS=1024``,
``TIMEOUT_SECONDS=25``, ``MAX_QUEUE_SIZE=5``) and its Helm values never reach
the app as env vars (SURVEY.md §5 "Config / flag system").  Here the same
defaults are preserved, but every knob can be overridden through the
environment so the Helm chart can parameterize the app.

Every knob the package reads is declared ONCE, in :data:`KNOBS` below.
Package code reads knobs only through this module — :func:`get_settings`
for the Settings-backed ones, :func:`knob` / :func:`env_bool` for ad-hoc
reads — never ``os.environ`` directly.  That single-source-of-truth is
machine-enforced by lfkt-lint (rules CFG001-005, docs/LINT.md): a raw
``os.environ`` read of an LFKT_ name, an unregistered accessor call, an
undocumented registered knob, and a helm-chart reference to a name this
registry doesn't know are all tier-1 test failures.  The full catalog with
defaults and help text: docs/CONFIG.md.
"""

from __future__ import annotations

import dataclasses
import os


def _env(name: str, default, cast=str):  # lfkt: noqa[JIT001] -- trace-time read: kernel-variant knobs are read while jit traces and the value is keyed into every jit/lru cache (ops/pallas/qmatmul._env_variant)
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return cast(raw)


def force_cpu_if_requested() -> bool:
    """THE site-hook defense (one copy): when the caller asked for the CPU
    backend (``JAX_PLATFORMS=cpu``) but a site hook may have pre-registered
    the tunneled device platform and overridden the env var, re-pin the
    platform via ``jax.config`` — which wins while no backend is
    initialized.  Without this, a "CPU" test/dryrun silently attaches to
    the single-session accelerator and can hold its claim (observed
    2026-07-31 and again 2026-08-01).  Call BEFORE the first
    ``jax.devices()``/computation; returns True when the pin was applied.
    Callers: tests/conftest.py, __graft_entry__.py, server/__main__.py,
    bench.py."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True


@dataclasses.dataclass(frozen=True)
class Settings:
    # Identical defaults to reference api.py:13-19.
    model_dir: str = "models"
    model_name: str = "Lexi-Llama-3-8B-Uncensored_Q4_K_M.gguf"
    # -- multi-model serving (docs/MULTIMODEL.md; ROADMAP item 5) ----------
    # declarative model manifest: name=path[:knob=value;...] entries,
    # comma-separated (serving/manifest.py).  Empty (the default) keeps the
    # single-model LFKT_MODEL_DIR/LFKT_MODEL_NAME path byte-for-byte.
    models: str = ""
    # the alias served when a request names no model= (default: the
    # manifest's first entry)
    default_model: str = ""
    # HBM budget for the fleet's WEIGHTS, in MB (0 = unlimited): the
    # registry refuses at load time, with per-model attribution, when the
    # manifest cannot fit — instead of OOMing at first traffic
    hbm_weight_budget_mb: float = 0.0
    max_context_tokens: int = 1024
    timeout_seconds: float = 25.0
    max_queue_size: int = 5
    # total wall-clock bound for one /response/stream response; the
    # per-chunk-gap timeout alone would let a slow-dripping generation hold
    # its queue slot indefinitely (no reference equivalent: it has no
    # streaming at all, reference api.py:58)
    stream_deadline_seconds: float = 300.0
    # graceful-shutdown budget: on SIGTERM in-flight requests get this long
    # to finish (gunicorn graceful_timeout analogue — the reference's
    # termination behavior at docker/Dockerfile.app:12).  Honored by both
    # the in-tree httpd and the uvicorn path; keep the pod's
    # terminationGracePeriodSeconds above it (helm derives grace from the
    # same values knob)
    drain_seconds: float = 30.0
    # slowloris guard (in-tree httpd): once a request line has arrived the
    # headers+body must finish arriving within this window, else 408 +
    # Connection: close instead of holding the socket forever
    read_timeout: float = 30.0

    # -- resilience layer (docs/RUNBOOK.md "Degraded-mode operations") -----
    # engine watchdog: detects stalled decode/hung device calls (no beat
    # for stall_seconds while work is in flight), exception bursts, and a
    # dead scheduler loop; trips to DEGRADED (readiness 503, liveness 200),
    # fails in-flight futures with 503, and runs bounded in-process
    # recovery with exponential backoff — escalating to DEAD (liveness
    # 503 → pod restart) after watchdog_max_recoveries trips.
    watchdog: bool = True
    watchdog_stall_seconds: float = 60.0
    watchdog_poll_seconds: float = 1.0
    watchdog_max_recoveries: int = 3
    watchdog_error_burst: int = 5
    watchdog_error_window: float = 30.0
    watchdog_backoff_seconds: float = 1.0
    watchdog_backoff_max: float = 60.0

    # Fixed sampling parameters the reference passes at api.py:59-62; the
    # remaining knobs take llama-cpp-python 0.2.77 defaults (top_k=40,
    # min_p=0.05, repeat_penalty=1.1) because the reference omits them.
    temperature: float = 1.2
    top_p: float = 0.9
    frequency_penalty: float = 0.7
    presence_penalty: float = 0.8
    top_k: int = 40
    min_p: float = 0.05
    repeat_penalty: float = 1.1

    # TPU-native knobs (no reference equivalent).
    max_gen_tokens: int = 512
    # layer-looped decode (ops/pallas/decode_loop.py; ROADMAP item 2):
    # transformer layers fused per Pallas launch on the single-token
    # decode step.  0 = off (the per-layer kernel chain), -1 = ALL layers
    # in one launch, K > 0 = K layers per launch (clamped to a divisor of
    # n_layers).  Engines compile-probe the looped kernel at their ring
    # geometry and degrade to per-layer decode with attribution on any
    # refusal (docs/RUNBOOK.md "Tuning layer-looped decode").
    decode_layer_unroll: int = 0
    decode_chunk: int = 8           # device-side tokens per host round-trip.
    # Measured trade-off (docs/bench 2026-07-30): single-stream decode
    # rises mildly with chunk size (+~1% at 1k ctx, +4.7% at 8k for 32 vs
    # 8 — bench.py reports its sweep's best either way), but the chunk is
    # ALSO the continuous scheduler's admission/stream cadence: at 16 the
    # 8-lane aggregate dropped 160 -> 108 tok/s and stream TTFT doubled
    # (209 -> 407 ms).  8 is the serving default; single-stream batch
    # callers can raise LFKT_DECODE_CHUNK.
    prefill_buckets: str = "128,256,512,1024"  # padded prompt shapes to bound recompiles
    weight_format: str = "auto"     # auto | bf16 | int8 | q4k
    attn_impl: str = "auto"         # auto | xla | pallas (prefill flash kernel)
    kv_dtype: str = "bf16"          # bf16 | int8 — int8 halves KV-cache HBM
    #                                 (values int8 + per-head per-token f32
    #                                 scales) and streams int8 through the
    #                                 attention reads; docs/KV_CACHE.md
    spec_decode: str = "off"        # off | lookup | auto — prompt-lookup
    #                                 speculation; "auto" measures the
    #                                 deployment's dispatch RTT at startup
    #                                 and enables lookup iff its breakeven
    #                                 acceptance < LFKT_SPEC_AUTO_ACCEPT
    #                                 (engine/spec_auto.py)
    spec_draft: int = 8             # draft tokens per verify step
    # serial-engine prompt-prefix KV reuse (llama.cpp's prompt-cache
    # analogue): when consecutive prompts share a token prefix — the
    # reference workload re-sends persona + full history every turn —
    # prefill only the suffix.  Mesh/SP/lane engines ignore it.
    prefix_cache: bool = True
    # the continuous scheduler's analogue: admissions whose prompt shares
    # a freed lane's conversation history snapshot that lane's KV and
    # prefill only the suffix slices (chunk-aligned).  ON by default since
    # the admission controller closed the admission/decode interference
    # gap (round 6); explicit-seed requests still bypass it (the
    # reproducibility contract) and spec decode still excludes it.
    lane_prefix_cache: bool = True
    # block-paged KV pool + shared radix-tree prefix cache
    # (parallel/kvpool.py; docs/RUNBOOK.md "Sizing the KV page pool"):
    # KV pages live in one preallocated arena fronted by a radix tree
    # keyed on token prefixes, so shared system prompts prefill once per
    # process and multi-turn requests resume from their last committed
    # page regardless of lane.  OFF by default — the dense per-lane ring
    # stays the A/B control (greedy decode is bit-identical either way,
    # pinned by tests/test_kv_paged_engines.py).
    kv_paged: bool = False
    kv_page_tokens: int = 128       # token slots per pool page
    kv_pool_pages: int = 0          # arena size in pages (0 = auto:
    #                                 4 full contexts' worth)
    kv_spill_pages: int = 0         # host-RAM spill tier capacity in
    #                                 pages (0 = evictions discard)
    prefill_chunk: int = 256        # prefill slice size: the continuous
    #                                 scheduler's admission slices AND the
    #                                 serial engine's overlapped bucket
    #                                 slices (docs/RUNBOOK.md "Tuning
    #                                 long-context TTFT")
    # serial-engine overlapped chunked prefill: how many un-synced prefill
    # slices may queue on the device at once (slice i+1's host prep +
    # dispatch overlap slice i's compute).  0 restores monolithic
    # bucket-sized prefill; slicing only engages when the prompt bucket
    # exceeds prefill_chunk, so short prompts are untouched either way.
    prefill_overlap: int = 2
    adm_budget: int = 512           # admission prefill tokens per scheduler
    #                                 wave: the static value when the
    #                                 admission controller is off, and the
    #                                 controller's initial/base budget when
    #                                 it is on
    # admission controller (engine/continuous.py AdmissionController):
    # derives each wave's prefill-token budget from an EMA of measured
    # lane-idle fraction and decode slack (harvest-fetch wait) instead of
    # the static adm_budget — budget rises while lanes sit idle, shrinks
    # under decode pressure, and never drops below one slice per wave (a
    # deadline-bearing admission always makes progress).
    adm_controller: bool = True
    adm_ema_alpha: float = 0.25     # EMA weight of the controller's signals
    # >1 switches the server to mesh-batched serving — the v5e-4
    # "concurrent /response load" config.  scheduler picks the flavor:
    #   cycle      — MeshEngine: coalesce up to batch_size queued requests
    #                per generation cycle (barrier between cycles)
    #   continuous — ContinuousEngine: slot-based continuous batching;
    #                free lanes admit new requests at every chunk boundary
    batch_size: int = 1
    scheduler: str = "continuous"
    mesh_tp: int = 1                # tensor-parallel width across the mesh
    # >1 serves with the sequence-parallel engine (engine/sp.py): the KV
    # cache's n_ctx dim shards over an sp-axis ring (ring attention for
    # prefill, sharded-LSE decode), scaling max context linearly with the
    # ring size.  Serial serving (batch_size must stay 1).
    mesh_sp: int = 1
    # -- disaggregated prefill/decode (serving/disagg/; docs/RUNBOOK.md
    # "Operating a split prefill/decode fleet") ----------------------------
    # role of this process in a split fleet: "off" (default — the single-
    # process serving path, byte-for-byte unchanged), "prefill" (runs the
    # KV-page service: prefills prompts and streams finished pages),
    # "decode" (forwards admitted prompts to the prefill peer, restores
    # the returned pages into its paged arena, decodes), or "both" (the
    # in-process loopback: page service + client on one engine — the
    # tier-1-testable / bench-A/B arm).  prefill/decode/both require
    # LFKT_KV_PAGED=1: pages ARE the wire format.
    disagg_role: str = "off"
    # decode role: the prefill tier's page service, "host:port"
    disagg_peer: str = ""
    # prefill role: page-service bind address and port (0 = ephemeral,
    # loopback/tests)
    disagg_bind: str = "0.0.0.0"
    disagg_port: int = 8470
    # per-hop wire budget: a remote prefill that cannot complete within
    # min(this, the request's remaining deadline) aborts on both sides and
    # the decode replica falls back to LOCAL prefill with attribution
    disagg_timeout_seconds: float = 5.0
    # bounded page-frame send queue per peer connection (backpressure: a
    # slow wire blocks the prefill tier's page export, never grows memory;
    # the buffered bytes are the memory ledger's disagg_txbuf component)
    disagg_queue_frames: int = 32
    # -- fleet tier (serving/fleet/; docs/RUNBOOK.md "Running a replica
    # fleet") --------------------------------------------------------------
    # "router" turns this process into the prefix-affinity proxy in front
    # of the replica fleet (no engine, no jax): requests key on their
    # conversation/system-prompt prefix and rendezvous-hash to the
    # replica whose radix cache is warm for them.  "off" (default) = a
    # plain serving replica.
    fleet_role: str = "off"
    # router: static replica list, host:port comma-separated (tests,
    # docker-compose).  In k8s prefer fleet_dns.
    fleet_peers: str = ""
    # router: headless-Service DNS name:port, re-resolved every probe
    # cycle — one A record per ready pod, so scale-out/in needs no
    # router restart
    fleet_dns: str = ""
    # router placement policy: "affinity" (rendezvous on the prefix key)
    # or "roundrobin" (the A/B control arm — bench_server.py fleet arm)
    fleet_policy: str = "affinity"
    # router: peer /health/ready probe period
    fleet_probe_seconds: float = 2.0
    # router: first ejection backoff (doubles per consecutive failure)
    fleet_eject_backoff_seconds: float = 1.0
    fleet_eject_backoff_max: float = 30.0
    # router: backend connect + response-head deadline; body progress
    # rides stream_deadline_seconds
    fleet_proxy_timeout_seconds: float = 5.0
    # router: per-request spill-replay budget — replicas tried beyond the
    # rendezvous owner before the router answers 503 + Retry-After
    # (fleet_spills_total{reason="budget"}) instead of walking the whole
    # rendezvous order on a poisoned request
    fleet_max_spills: int = 3
    # -- fleet KV migration (serving/fleet/migrate.py; docs/RUNBOOK.md
    # "Surviving pod churn") -----------------------------------------------
    # arm warm-page migration on this replica: the page service +
    # pull-on-remap client + graceful drain-push + scale-out warm-up
    # (requires LFKT_KV_PAGED=1; off = all paths byte-for-byte unchanged)
    migrate: bool = False
    # migration page-service bind address
    migrate_bind: str = "0.0.0.0"
    # migration page-service port (0 = ephemeral; peers discover the
    # bound port through the /health "migration" block, never by config)
    migrate_port: int = 8471
    # this replica's own fleet address (the host:port peers reach its
    # HTTP port on) — excluded from drain-successor ranking; in k8s the
    # downward-API pod IP (helm/templates/deployment.yaml)
    migrate_self: str = ""
    # one migration wire hop's budget; pulls are additionally clipped to
    # the request's remaining deadline (a dead peer costs milliseconds,
    # never a hang)
    migrate_timeout_seconds: float = 2.0
    # hottest radix prefixes moved per peer (scale-out warm-up pulls
    # them, graceful drain pushes them)
    migrate_top_k: int = 8
    # graceful drain: total budget for pushing hot prefixes to the
    # rendezvous successors before termination proceeds (added to the
    # pod's terminationGracePeriodSeconds by the chart)
    migrate_drain_seconds: float = 5.0
    # router: a peer added or readmitted within this window is "fresh"
    # (cold cache) — requests it owns carry a prior-owner hint so the
    # pod can pull warm pages before prefilling (0 disables the hint)
    migrate_fresh_seconds: float = 600.0
    # live manifest reload (POST /admin/models/reload, SIGHUP): bounded
    # wait for a removed model's in-flight requests and its radix
    # namespace's pinned pages before the weights release
    reload_drain_seconds: float = 30.0

    @property
    def model_path(self) -> str:
        return os.path.join(self.model_dir, self.model_name)

    @property
    def prefill_bucket_list(self) -> list[int]:
        return sorted(int(x) for x in self.prefill_buckets.split(",") if x.strip())


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered env knob.  ``serving=True`` marks knobs a deployment
    must be able to set per-pod — lfkt-lint (CFG003) checks they are
    plumbed or documented in the Helm chart; every knob must additionally
    appear in docs (CFG002, see docs/CONFIG.md)."""

    name: str
    cast: type = str
    help: str = ""
    serving: bool = False
    default: object = None          # ad-hoc knobs only; Settings-backed
    #                                 knobs default from the Settings field
    field: str | None = None        # Settings field (wired in _register)


_SETTINGS_FIELDS = {f.name for f in dataclasses.fields(Settings)}


def _register(*knobs: Knob) -> dict[str, Knob]:
    out: dict[str, Knob] = {}
    for k in knobs:
        field = k.name[len("LFKT_"):].lower()
        if field in _SETTINGS_FIELDS:
            k = dataclasses.replace(k, field=field)
        out[k.name] = k
    return out


#: THE registry: every LFKT_* env var any package code reads.  Settings-
#: backed knobs (the majority) take their default and docstring context
#: from the Settings field of the same lowercased name; ad-hoc knobs carry
#: an explicit ``default``.  docs/CONFIG.md mirrors this table.
KNOBS: dict[str, Knob] = _register(
    # -- Settings-backed (reference-parity serving surface) ----------------
    Knob("LFKT_MODEL_DIR", str, "GGUF directory", serving=True),
    Knob("LFKT_MODEL_NAME", str, "GGUF file name", serving=True),
    # -- multi-model serving (docs/MULTIMODEL.md) --------------------------
    Knob("LFKT_MODELS", str,
         "multi-model manifest: name=path[:knob=value;...],... "
         "(empty = single-model LFKT_MODEL_NAME)", serving=True),
    Knob("LFKT_DEFAULT_MODEL", str,
         "alias served when a request names no model= "
         "(default: first manifest entry)", serving=True),
    Knob("LFKT_HBM_WEIGHT_BUDGET_MB", float,
         "HBM budget for the fleet's weights, MB (0 = unlimited); "
         "exceeded = load-time refusal with attribution", serving=True),
    Knob("LFKT_MAX_CONTEXT_TOKENS", int, "context window", serving=True),
    Knob("LFKT_TIMEOUT_SECONDS", float, "admission future timeout (408)",
         serving=True),
    Knob("LFKT_MAX_QUEUE_SIZE", int, "admission queue bound (503)",
         serving=True),
    Knob("LFKT_STREAM_DEADLINE_SECONDS", float,
         "total wall budget of one SSE stream"),
    Knob("LFKT_DRAIN_SECONDS", float, "graceful-shutdown budget",
         serving=True),
    Knob("LFKT_READ_TIMEOUT", float, "httpd slowloris guard (408)"),
    # -- watchdog / resilience --------------------------------------------
    Knob("LFKT_WATCHDOG", bool, "enable the engine watchdog"),
    Knob("LFKT_WATCHDOG_STALL_SECONDS", float, "stalled-decode trip bound"),
    Knob("LFKT_WATCHDOG_POLL_SECONDS", float, "watchdog sampling period"),
    Knob("LFKT_WATCHDOG_MAX_RECOVERIES", int, "trips before DEAD"),
    Knob("LFKT_WATCHDOG_ERROR_BURST", int, "errors per window that trip"),
    Knob("LFKT_WATCHDOG_ERROR_WINDOW", float, "burst window seconds"),
    Knob("LFKT_WATCHDOG_BACKOFF_SECONDS", float, "first recovery backoff"),
    Knob("LFKT_WATCHDOG_BACKOFF_MAX", float, "recovery backoff ceiling"),
    # -- sampling (reference api.py:59-62 + llama-cpp-python defaults) -----
    Knob("LFKT_TEMPERATURE", float, "sampling temperature"),
    Knob("LFKT_TOP_P", float, "nucleus sampling mass"),
    Knob("LFKT_FREQUENCY_PENALTY", float, "frequency penalty"),
    Knob("LFKT_PRESENCE_PENALTY", float, "presence penalty"),
    Knob("LFKT_TOP_K", int, "top-k cutoff"),
    Knob("LFKT_MIN_P", float, "min-p cutoff"),
    Knob("LFKT_REPEAT_PENALTY", float, "repetition penalty"),
    # -- TPU-native engine knobs -------------------------------------------
    Knob("LFKT_MAX_GEN_TOKENS", int, "default completion budget"),
    Knob("LFKT_DECODE_LAYER_UNROLL", int,
         "layers fused per decode-step Pallas launch (0 = per-layer, "
         "-1 = all layers; ops/pallas/decode_loop.py)", serving=True),
    Knob("LFKT_DECODE_CHUNK", int, "device tokens per host round-trip"),
    Knob("LFKT_PREFILL_BUCKETS", str, "padded prompt shapes (csv)"),
    Knob("LFKT_WEIGHT_FORMAT", str, "auto|bf16|int8|q4k"),
    Knob("LFKT_ATTN_IMPL", str, "auto|xla|pallas"),
    Knob("LFKT_KV_DTYPE", str, "bf16|int8 KV cache (docs/KV_CACHE.md)"),
    Knob("LFKT_SPEC_DECODE", str, "off|lookup|auto speculation"),
    Knob("LFKT_SPEC_DRAFT", int, "draft tokens per verify step"),
    Knob("LFKT_PREFIX_CACHE", bool, "serial-engine prompt-prefix KV reuse"),
    Knob("LFKT_LANE_PREFIX_CACHE", bool, "lane-claim admission KV reuse"),
    Knob("LFKT_KV_PAGED", bool,
         "block-paged KV pool + radix-tree prefix cache (0 = dense ring)"),
    Knob("LFKT_KV_PAGE_TOKENS", int, "token slots per KV pool page"),
    Knob("LFKT_KV_POOL_PAGES", int, "KV pool arena size in pages (0 = auto)"),
    Knob("LFKT_KV_SPILL_PAGES", int,
         "host-RAM KV spill tier capacity in pages (0 = off)"),
    Knob("LFKT_PREFILL_CHUNK", int, "prefill slice tokens (admission + "
         "serial overlapped prefill)"),
    Knob("LFKT_PREFILL_OVERLAP", int,
         "overlapped-prefill depth (0 = monolithic bucket prefill)"),
    Knob("LFKT_ADM_BUDGET", int,
         "admission tokens per wave (controller base / static value)"),
    Knob("LFKT_ADM_CONTROLLER", bool,
         "EMA admission controller for the per-wave prefill budget"),
    Knob("LFKT_ADM_EMA_ALPHA", float,
         "admission-controller EMA weight"),
    Knob("LFKT_BATCH_SIZE", int, "serving lanes (mesh/continuous batching)"),
    Knob("LFKT_SCHEDULER", str, "continuous|cycle batching flavor"),
    Knob("LFKT_MESH_TP", int, "tensor-parallel width"),
    Knob("LFKT_MESH_SP", int, "sequence-parallel ring size"),
    # -- disaggregated prefill/decode (serving/disagg/) --------------------
    Knob("LFKT_DISAGG_ROLE", str,
         "off|prefill|decode|both — split prefill/decode fleet role "
         "(serving/disagg/; requires LFKT_KV_PAGED=1 when not off)",
         serving=True),
    Knob("LFKT_DISAGG_PEER", str,
         "decode role: prefill tier page service, host:port", serving=True),
    Knob("LFKT_DISAGG_BIND", str, "prefill role: page-service bind address"),
    Knob("LFKT_DISAGG_PORT", int,
         "prefill role: page-service port (0 = ephemeral)", serving=True),
    Knob("LFKT_DISAGG_TIMEOUT_SECONDS", float,
         "per-hop wire budget before the decode side falls back to "
         "local prefill"),
    Knob("LFKT_DISAGG_QUEUE_FRAMES", int,
         "bounded page-frame send queue per peer (backpressure)"),
    # -- fleet tier (serving/fleet/) ---------------------------------------
    Knob("LFKT_FLEET_ROLE", str,
         "off|router — router runs the prefix-affinity proxy over the "
         "replica fleet instead of a serving engine (serving/fleet/)",
         serving=True),
    Knob("LFKT_FLEET_PEERS", str,
         "router: static replica list host:port[,host:port...]",
         serving=True),
    Knob("LFKT_FLEET_DNS", str,
         "router: headless-Service name:port resolved per probe cycle "
         "(one A record per ready replica)", serving=True),
    Knob("LFKT_FLEET_POLICY", str,
         "router placement: affinity (rendezvous on the prefix key) | "
         "roundrobin (A/B control)", serving=True),
    Knob("LFKT_FLEET_PROBE_SECONDS", float,
         "router: peer /health/ready probe period", serving=True),
    Knob("LFKT_FLEET_EJECT_BACKOFF_SECONDS", float,
         "router: first ejection backoff (doubles per failure)"),
    Knob("LFKT_FLEET_EJECT_BACKOFF_MAX", float,
         "router: ejection backoff ceiling"),
    Knob("LFKT_FLEET_PROXY_TIMEOUT_SECONDS", float,
         "router: backend connect + response-head deadline",
         serving=True),
    Knob("LFKT_FLEET_MAX_SPILLS", int,
         "router: spill replays per request before 503 + Retry-After "
         "(fleet_spills_total{reason=budget})", serving=True),
    # -- fleet KV migration (serving/fleet/migrate.py) ---------------------
    Knob("LFKT_MIGRATE", bool,
         "warm KV-page migration: pull-on-remap + graceful drain-push + "
         "scale-out warm-up (requires LFKT_KV_PAGED=1)", serving=True),
    Knob("LFKT_MIGRATE_BIND", str, "migration page-service bind address"),
    Knob("LFKT_MIGRATE_PORT", int,
         "migration page-service port (0 = ephemeral; discovered via "
         "/health)", serving=True),
    Knob("LFKT_MIGRATE_SELF", str,
         "this replica's own fleet address host:port (drain-successor "
         "self-exclusion)", serving=True),
    Knob("LFKT_MIGRATE_TIMEOUT_SECONDS", float,
         "one migration wire hop's budget; pulls also clip to the "
         "request's remaining deadline", serving=True),
    Knob("LFKT_MIGRATE_TOP_K", int,
         "hottest prefixes moved per peer (warm-up pulls, drain pushes)",
         serving=True),
    Knob("LFKT_MIGRATE_DRAIN_SECONDS", float,
         "graceful drain: total hot-page push budget before termination "
         "proceeds", serving=True),
    Knob("LFKT_MIGRATE_FRESH_SECONDS", float,
         "router: peers (re)admitted within this window carry a "
         "prior-owner hint for pull-on-remap (0 disables)", serving=True),
    Knob("LFKT_RELOAD_DRAIN_SECONDS", float,
         "live model removal: bounded wait for in-flight requests + "
         "pinned namespace pages before weights release", serving=True),
    # -- ad-hoc knobs (read via knob()/env_bool(), not Settings) -----------
    Knob("LFKT_HOST", str, "bind address (server/__main__.py)",
         default="0.0.0.0"),
    Knob("LFKT_PORT", int, "bind port (server/__main__.py)", default=8000),
    Knob("LFKT_WORKERS", int, "must stay 1: one model per process",
         default=1),
    Knob("LFKT_COMPILE_CACHE_DIR", str,
         "persistent XLA compile cache (utils/jaxcache.py)", serving=True,
         default=""),
    Knob("LFKT_PROFILE_DIR", str,
         "capture XProf traces per generation (utils/tracing.py) and via "
         "GET /debug/profile", serving=True, default=""),
    # -- lfkt-perf (obs/devtime.py + obs/slo.py; docs/SLO.md) --------------
    Knob("LFKT_DEVTIME", bool,
         "per-program compile/dispatch attribution (obs/devtime.py; "
         "0 disarms the registry)", serving=True, default=True),
    Knob("LFKT_RECOMPILE_BUDGET", int,
         "distinct jit signatures per program before a recompile storm "
         "is flagged", serving=True, default=32),
    Knob("LFKT_SLO_TTFT_P95_S", float,
         "SLO: TTFT bound (seconds) 95% of requests must beat, per "
         "prefill bucket", serving=True, default=1.0),
    Knob("LFKT_SLO_DECODE_FLOOR_TPS", float,
         "SLO: decode tok/s floor 95% of requests must clear",
         serving=True, default=10.0),
    Knob("LFKT_SLO_ERROR_RATE", float,
         "SLO: 5xx error-rate budget over each burn window",
         serving=True, default=0.01),
    Knob("LFKT_SLO_QUEUE_P95_S", float,
         "SLO: admission-queue wait bound (seconds) at the 95th percentile",
         serving=True, default=0.5),
    Knob("LFKT_SLO_WINDOWS", str,
         "SLO burn-rate windows, csv seconds (short,long)",
         serving=True, default="300,3600"),
    # -- lfkt-mem (obs/memledger.py + obs/flightrec.py; docs/RUNBOOK.md
    # "Diagnosing HBM OOM") -------------------------------------------------
    Knob("LFKT_MEM_LEDGER", bool,
         "live HBM memory ledger: component attribution + /debug/memory "
         "+ hbm_bytes gauges (0 disarms; obs/memledger.py)",
         serving=True, default=True),
    Knob("LFKT_MEM_PRESSURE_FRACTION", float,
         "device HBM headroom fraction below which the admission "
         "controller treats memory as pressure and cuts its budget",
         serving=True, default=0.05),
    Knob("LFKT_INCIDENT_DIR", str,
         "incident flight-recorder directory (empty = recorder off; "
         "mount a pod volume so bundles survive restarts)",
         serving=True, default=""),
    Knob("LFKT_INCIDENT_RING", int,
         "max incident bundles kept on disk (oldest pruned)",
         serving=True, default=16),
    Knob("LFKT_INCIDENT_DEBOUNCE_S", float,
         "per-kind minimum seconds between incident bundles (a burst "
         "records once, not once per error)", default=30.0),
    Knob("LFKT_INCIDENT_LOG_LINES", int,
         "structured log lines retained for a bundle's log_tail",
         default=100),
    # -- lfkt-obs (obs/trace.py; docs/OBSERVABILITY.md) --------------------
    Knob("LFKT_TRACE_SAMPLE", float,
         "fraction of requests traced (0 disarms the tracer)",
         serving=True, default=1.0),
    Knob("LFKT_TRACE_RING", int,
         "completed traces kept for /debug/traces", serving=True,
         default=256),
    Knob("LFKT_JSON_LOGS", bool,
         "JSON access/serving logs with request ids (server/__main__.py)",
         default=True),
    Knob("LFKT_NATIVE", bool, "C++ GGUF load path (0 forces numpy)",
         default=True),
    Knob("LFKT_LOAD_OVERLAP", bool,
         "overlap per-layer host→device transfer with dequant",
         default=True),
    Knob("LFKT_HBM_GBPS", float,
         "assumed HBM bandwidth for spec_decode=auto breakeven",
         default=819.0),
    Knob("LFKT_SPEC_AUTO_ACCEPT", float,
         "assumed lookup acceptance for spec_decode=auto", default=1.0),
    Knob("LFKT_FAULTS", str,
         "fault-injection arming spec (utils/faults.py; drills only)",
         default=""),
    Knob("LFKT_FLASH_KV_UNROLL", int,
         "flash-attention fused KV sub-blocks per grid step "
         "(ops/pallas/attention.py)", default=4),
    Knob("LFKT_Q4K_KERNEL", str, "fused Q4_K kernel variant (A/B)",
         default=""),
    Knob("LFKT_Q5K_KERNEL", str, "fused Q5_K kernel variant (A/B)",
         default=""),
    Knob("LFKT_Q6K_KERNEL", str, "fused Q6_K kernel variant (A/B)",
         default=""),
)


def knob(name: str, default=None, cast=None):
    """Registered ad-hoc env read — the ONLY way package code outside this
    module reads an ``LFKT_*`` var (lfkt-lint CFG001/CFG005).  ``default``
    overrides the registry default at call sites whose natural default is
    contextual (e.g. kernel-variant tables)."""
    k = KNOBS.get(name)
    if k is None:
        raise KeyError(
            f"{name} is not in the LFKT knob registry (utils/config.py); "
            "register it before reading it")
    if default is None:
        # Settings-backed knobs keep their documented default even through
        # this accessor (their Knob.default is None by construction);
        # ad-hoc knobs carry theirs on the Knob entry
        default = k.default if k.field is None else getattr(Settings, k.field)
    return _env(name, default, cast or k.cast)


def env_bool(name: str, default: bool = False) -> bool:
    """THE truthy-env convention (one parser: '1'/'true'/'yes'/'on').
    Direct-engine-construction paths (bench_server.py, models/params.py)
    must use this instead of re-implementing the tuple and silently
    diverging on accepted spellings.  LFKT_* names must be registered."""
    if name.startswith("LFKT_") and name not in KNOBS:
        raise KeyError(
            f"{name} is not in the LFKT knob registry (utils/config.py); "
            "register it before reading it")
    return _env(name, default, bool)


def get_settings() -> Settings:
    """Build Settings from the registry: every Settings-backed knob reads
    its env var with the Settings field's default — the registry and the
    dataclass cannot drift (tests/test_lint.py pins the mapping)."""
    kw = {}
    for name, k in KNOBS.items():
        if k.field is not None:
            kw[k.field] = _env(name, getattr(Settings, k.field), k.cast)
    return Settings(**kw)

"""Bench-artifact provenance stamps (lfkt-perf regression sentinel).

Every JSON line ``bench.py``/``bench_server.py`` emits carries a
``provenance`` block: the git commit it measured, the device it ran on,
and the full ``LFKT_*`` environment fingerprint — so a banked artifact
can never again be ambiguous about *what* produced it, and
``tools/perf_gate.py`` can refuse to compare numbers measured under
different knob sets without anyone having to remember.  Schema validated
by ``tools/check_manifest.py`` over the whole banked corpus (tier-1).

Everything here is best-effort metadata: a missing git binary or a
jax-less process degrades fields to ``"unknown"`` rather than failing
the bench that asked for the stamp.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import subprocess

#: provenance schema version (tools/check_manifest.py validates this shape)
SCHEMA = 1


# memoized: commit and device cannot change within one bench process, and
# a sweep emits one stamped line per grid point — no git subprocess per line
@functools.lru_cache(maxsize=None)
def _git_commit(cwd: str | None = None) -> str:
    if cwd is None:
        # the repo checkout this package lives in (best effort)
        cwd = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=cwd,
                             capture_output=True, text=True, timeout=10)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except Exception:  # noqa: BLE001 — metadata must never fail a bench
        pass
    return "unknown"


@functools.lru_cache(maxsize=None)
def _device_kind() -> str:
    try:
        import jax

        d = jax.devices()[0]
        return f"{d.platform}:{getattr(d, 'device_kind', '?')}"
    except Exception:  # noqa: BLE001
        return "unknown"


#: knobs that place a run but cannot move a measured number — bind
#: address/port, filesystem locations, log format.  Excluded from the
#: fingerprint so a bench run from a different checkout dir or port does
#: not cry "knob drift" on every perf_gate comparison (the warning must
#: stay rare enough that operators read it).
VOLATILE_KNOBS = frozenset({
    "LFKT_HOST", "LFKT_PORT", "LFKT_MODEL_DIR", "LFKT_PROFILE_DIR",
    "LFKT_JSON_LOGS",
})


def knob_fingerprint() -> dict:
    """The perf-relevant ``LFKT_*`` environment as set for this process,
    plus a short stable hash — two artifacts with equal ``knob_hash``
    were measured under byte-identical knob sets (modulo
    :data:`VOLATILE_KNOBS`)."""
    knobs = {k: v for k, v in sorted(os.environ.items())
             if k.startswith("LFKT_") and k not in VOLATILE_KNOBS}
    digest = hashlib.sha256(
        json.dumps(knobs, sort_keys=True).encode()).hexdigest()[:12]
    return {"knobs": knobs, "knob_hash": digest}


def mem_stats() -> dict:
    """The stamp's memory axis: process peak RSS and (where the backend
    reports memory_stats) device peak HBM bytes.  NOT memoized — peaks
    only grow, and each emitted line should carry the peak as of ITS
    measurement.  Best-effort like everything here: a field that cannot
    be read is omitted, never faked."""
    out: dict = {}
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KB on Linux, bytes on macOS
        out["rss_peak_bytes"] = int(ru) * (1 if sys.platform == "darwin"
                                           else 1024)
    except Exception:  # noqa: BLE001 — metadata must never fail a bench
        pass
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak:
            out["device_peak_bytes"] = int(peak)
    except Exception:  # noqa: BLE001
        pass
    return out


def stamp(cwd: str | None = None) -> dict:
    """The full provenance block for one bench JSON line."""
    return {"schema": SCHEMA,
            "git_commit": _git_commit(cwd),
            "device": _device_kind(),
            "mem": mem_stats(),
            **knob_fingerprint()}

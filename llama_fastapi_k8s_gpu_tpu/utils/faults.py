"""Env-armed fault injection — inert by default, deterministic when armed.

The resilience layer (utils/health.py, engine/watchdog.py) is only
trustworthy if its trip → degrade → recover path is *exercised*, not just
written.  This module plants named injection points on the engine hot
paths (``decode_step``, ``prefill``, ``load``, ``recover``) that cost one
dict lookup when disarmed and fire scripted faults when armed — driving
the deterministic CPU suite (tests/test_resilience.py) and the live drill
(tools/fault_drill.py) without a real device failure.

Arming grammar (``LFKT_FAULTS`` or :meth:`FaultInjector.arm`): a
comma-separated list of specs, each ``point:mode[:key=value]*``::

    LFKT_FAULTS="decode_step:error:after=3:times=1"
    LFKT_FAULTS="decode_step:slow:delay=2.5,load:oom"

modes
    ``error``  raise :class:`FaultError` (a generic engine exception)
    ``oom``    raise :class:`SimulatedOOM` (RESOURCE_EXHAUSTED-shaped)
    ``slow``   sleep ``delay`` seconds (default 1.0) — a slow/hung step

keys
    ``after=N``  pass through the first N hits before firing (default 0)
    ``times=N``  fire at most N times, then fall inert (default 1;
                 ``times=0`` means unlimited)
    ``delay=S``  sleep length for ``slow``

Production safety: the module-level :data:`FAULTS` singleton is built from
the environment at import; with ``LFKT_FAULTS`` unset every ``fire()`` is
a no-op returning on the first branch.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger(__name__)

#: the valid injection-point names (typos in a spec must fail loudly at
#: arm time, not silently never fire).  The disagg points drive the
#: split-fleet drills (serving/disagg/): ``slow_wire`` (slow mode) stalls
#: a frame send, ``peer_dead`` (error mode) hard-closes the page stream
#: mid-transfer, ``truncated_frame`` (error mode) ships a deliberately
#: short frame then closes — each must leave the decode replica
#: DEGRADED-but-serving via local-prefill fallback, never hung.
POINTS = ("decode_step", "prefill", "load", "recover",
          "peer_dead", "slow_wire", "truncated_frame",
          # KV migration (serving/fleet/migrate.py): ``migrate_pull``
          # fires inside the puller's wire hop (a remap-triggered page
          # pull degrades to local recompute), ``migrate_push`` inside
          # the migration page service's send path (a peer pulling from
          # this pod sees a torn stream), ``drain_push`` inside the
          # DRAINING pod's push loop (a failed handoff degrades to
          # normal termination) — every mode must leave serving correct
          # and the shutdown budget honored, never a hang.
          "migrate_pull", "migrate_push", "drain_push")
_MODES = ("error", "oom", "slow")


class FaultError(RuntimeError):
    """An injected engine fault (fault-injection framework, utils/faults.py)."""


class SimulatedOOM(FaultError):
    """An injected device-OOM, message-shaped like XLA's RESOURCE_EXHAUSTED
    so log-driven triage drills read realistically."""


class _Fault:
    __slots__ = ("point", "mode", "after", "times", "delay", "seen", "fired")

    def __init__(self, point: str, mode: str, after: int = 0,
                 times: int = 1, delay: float = 1.0):
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r} (valid: {POINTS})")
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r} (valid: {_MODES})")
        self.point = point
        self.mode = mode
        self.after = int(after)
        self.times = int(times)
        self.delay = float(delay)
        self.seen = 0
        self.fired = 0


class FaultInjector:
    """Holds armed faults; engines call :meth:`fire` at injection points."""

    # arm/disarm (test threads) race fire (engine threads): the table is
    # written under _lock; fire's first read is a deliberate lock-free
    # dict probe (disarmed is the hot path) — reads aren't write-checked
    _GUARDED_BY = {"_by_point": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._by_point: dict[str, _Fault] = {}

    @classmethod
    def from_env(cls, var: str = "LFKT_FAULTS") -> "FaultInjector":
        from .config import knob

        inj = cls()
        spec = knob(var, default="").strip()
        if spec:
            inj.arm(spec)
            logger.warning("fault injection ARMED from %s=%r", var, spec)
        return inj

    def arm(self, spec: str) -> None:
        """Arm one or more ``point:mode[:key=value]*`` specs."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(
                    f"fault spec {part!r} needs at least point:mode")
            kw: dict = {}
            for f in fields[2:]:
                k, _, v = f.partition("=")
                if k not in ("after", "times", "delay") or not v:
                    raise ValueError(f"bad fault option {f!r} in {part!r}")
                kw[k] = float(v) if k == "delay" else int(v)
            fault = _Fault(fields[0], fields[1], **kw)
            with self._lock:
                self._by_point[fault.point] = fault

    def disarm(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._by_point.clear()
            else:
                self._by_point.pop(point, None)

    def armed(self) -> bool:
        with self._lock:
            return bool(self._by_point)

    def stats(self) -> dict:
        with self._lock:
            return {
                p: {"mode": f.mode, "seen": f.seen, "fired": f.fired}
                for p, f in self._by_point.items()
            }

    def fire(self, point: str) -> None:
        """Run the injection point: no-op unless a fault is armed there and
        its after/times script says this hit fires."""
        fault = self._by_point.get(point)   # no lock: plain dict read, and
        if fault is None:                   # disarmed is the hot path
            return
        with self._lock:
            fault.seen += 1
            if fault.seen <= fault.after:
                return
            if fault.times and fault.fired >= fault.times:
                return
            fault.fired += 1
            mode, delay = fault.mode, fault.delay
        logger.warning("fault injection FIRING %s at %r (hit %d)",
                       mode, point, fault.seen)
        # drills only (inert path never reaches here): stamp the injected
        # fault onto every in-flight trace so the drill's slow/failed
        # requests are self-explaining in /debug/traces (lfkt-obs).
        # Local import: faults must stay importable before obs is.
        from ..obs.trace import annotate_all_inflight

        annotate_all_inflight("fault_fired", point=point, mode=mode)
        if mode == "slow":
            time.sleep(delay)
        elif mode == "oom":
            raise SimulatedOOM(
                f"RESOURCE_EXHAUSTED: simulated OOM injected at {point!r}")
        else:
            raise FaultError(f"injected fault at {point!r}")


#: process-wide singleton the engine hot paths consult; inert unless
#: LFKT_FAULTS was set at import (tests arm/disarm it programmatically)
FAULTS = FaultInjector.from_env()

"""Health state machine + engine progress heartbeat (resilience layer).

The reference advertises k8s probes but never implements them and handles
every engine failure by crash-looping the pod (SURVEY.md §2C); before this
module our probes conflated "briefly degraded" with "kill me" — one
``/health`` endpoint served readiness AND liveness.  This module is the
shared vocabulary for the in-process resilience layer:

- :class:`HealthMonitor` — the pod-level state machine
  ``STARTING → READY ⇄ DEGRADED → DEAD`` (plus ``DRAINING`` on SIGTERM),
  with reason codes and a transition log.  Readiness (route traffic here?)
  is true only in READY; liveness (restart the pod?) is false only in
  DEAD.  A watchdog trip therefore sheds traffic without inviting a
  restart, and only exhausted recovery budgets escalate to the pod kill
  the reference used as its *first* resort.
- :class:`Heartbeat` — the progress pulse every engine publishes (one
  ``beat()`` per device step, busy counts, an error ring) and the
  watchdog samples (engine/watchdog.py).  Engines never import the
  watchdog; the heartbeat is the entire interface between them.
- :class:`EngineUnavailable` / :class:`DeadlineExceeded` — the error
  taxonomy the server maps to 503 / 408 (server/app.py), distinct from
  the generic engine-bug 500.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..obs import flightrec as _flightrec
from ..obs.trace import annotate_all_inflight

# -- states (string constants: JSON-friendly, no enum dependency) ----------
STARTING = "STARTING"    # model loading / warmup: not ready, alive
READY = "READY"          # serving: ready, alive
DEGRADED = "DEGRADED"    # watchdog tripped, recovery in flight: not ready, alive
DRAINING = "DRAINING"    # SIGTERM received, finishing in-flight: not ready, alive
DEAD = "DEAD"            # recovery budget exhausted: not ready, NOT alive

#: numeric codes for the /metrics gauge (dashboards alert on > 1)
STATE_CODES = {STARTING: 0, READY: 1, DEGRADED: 2, DRAINING: 3, DEAD: 4}

_TERMINAL = frozenset({DEAD})


class EngineUnavailable(RuntimeError):
    """The engine cannot serve right now (watchdog trip, recovery in
    progress, scheduler restart).  The server maps this to 503 — retryable
    against another replica — instead of the generic 500 that means
    "this request hit a bug"."""


class DeadlineExceeded(TimeoutError):
    """A request's propagated deadline expired inside the engine; its
    lane/slot has been reclaimed.  Maps to the reference-parity 408."""


class Heartbeat:
    """Engine progress pulse sampled by the watchdog (thread-safe).

    Writers (the engine's own threads) call :meth:`beat` once per device
    step/prefill slice, bracket work with :meth:`enter`/:meth:`leave` (or
    :meth:`set_busy` for schedulers that own an occupancy number), and
    :meth:`record_error` on engine-side exceptions.  The reader (watchdog)
    uses :meth:`idle_for`, :meth:`busy_count` and :meth:`error_burst`:
    "busy but no beat for N seconds" is the stall signal that catches both
    a wedged decode loop and a hung device call, with zero cost on the
    no-fault path beyond a lock-guarded float store."""

    # every field is written by engine threads and read by the watchdog
    # thread: all access goes through _lock (lfkt-lint LOCK001)
    _GUARDED_BY = {
        "_last_beat": "_lock", "_busy": "_lock", "_errors": "_lock",
        "beats_total": "_lock", "errors_total": "_lock",
        "last_error": "_lock",
    }

    def __init__(self, error_keep: int = 32):
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._busy = 0
        self._errors: deque[float] = deque(maxlen=error_keep)
        self.beats_total = 0
        self.errors_total = 0
        self.last_error: str | None = None

    def beat(self) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self.beats_total += 1

    def enter(self) -> None:
        with self._lock:
            self._busy += 1
            self._last_beat = time.monotonic()

    def leave(self) -> None:
        with self._lock:
            self._busy = max(0, self._busy - 1)
            self._last_beat = time.monotonic()

    def set_busy(self, n: int) -> None:
        with self._lock:
            self._busy = max(0, int(n))

    def record_error(self, exc: BaseException) -> None:
        msg = f"{type(exc).__name__}: {exc}"
        with self._lock:
            self._errors.append(time.monotonic())
            self.errors_total += 1
            self.last_error = msg
        # device OOM is THE incident the flight recorder exists for: by
        # the time the watchdog trips on the burst, the allocation state
        # that caused it is gone — bundle it at first sight.  Outside the
        # lock; disarmed this is one attribute read inside record(), and
        # the per-kind debounce keeps an OOM burst at one bundle.
        if _flightrec.OOM_SIGNATURE in msg:
            _flightrec.record_incident(
                "resource_exhausted", msg,
                extra={"errors_total": self.errors_total})

    def clear_errors(self) -> None:
        """Consume the burst evidence (watchdog trip handled): a re-trip
        must require NEW errors, or one transient burst re-trips every
        poll until the recovery budget is spent."""
        with self._lock:
            self._errors.clear()

    def reset(self) -> None:
        """Post-recovery: clear stall/burst evidence so the old incident
        cannot immediately re-trip the watchdog against the fresh engine."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._busy = 0
            self._errors.clear()

    # -- watchdog-side reads ------------------------------------------------
    def busy_count(self) -> int:
        with self._lock:
            return self._busy

    def idle_for(self) -> float:
        with self._lock:
            return time.monotonic() - self._last_beat

    def error_burst(self, n: int, window_seconds: float) -> bool:
        """True when ≥ ``n`` errors were recorded in the last ``window``."""
        cutoff = time.monotonic() - window_seconds
        with self._lock:
            return sum(1 for t in self._errors if t >= cutoff) >= n


class HealthMonitor:
    """Thread-safe pod health state machine with reason codes.

    DEAD is terminal: once the recovery budget is spent the only exit is a
    pod restart (liveness probe fails), so nothing may transition out of
    it.  Every transition is recorded (bounded log) for /health."""

    # probe handlers, the watchdog and SIGTERM handling all race on the
    # state: every read/write goes through _lock (lfkt-lint LOCK001)
    _GUARDED_BY = {
        "_state": "_lock", "_reason": "_lock", "_since": "_lock",
        "_log": "_lock",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._state = STARTING
        self._reason = "initializing"
        self._since = time.time()
        self._log: deque[dict] = deque(maxlen=16)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def transition(self, state: str, reason: str = "") -> bool:
        """Move to ``state``; returns False when refused (DEAD is terminal,
        and DRAINING only yields to DEAD — a draining pod that degrades
        must not re-advertise readiness)."""
        if state not in STATE_CODES:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            if self._state in _TERMINAL and state != self._state:
                return False
            if self._state == DRAINING and state not in (DRAINING, DEAD):
                return False
            if state == self._state and reason == self._reason:
                return True
            self._log.append({
                "at": time.time(), "from": self._state, "to": state,
                "reason": reason,
            })
            prev = self._state
            self._state = state
            self._reason = reason
            self._since = time.time()
        # outside _lock: the tracer takes its own lock, and a state change
        # is a process-level fact every in-flight trace should carry
        # (lfkt-obs — a request slowed by a DEGRADED window says so)
        annotate_all_inflight("health_transition", from_state=prev,
                              to_state=state, reason=reason)
        return True

    # -- probe semantics ----------------------------------------------------
    def ready(self) -> bool:
        """Readiness: should traffic route here?  Only READY qualifies —
        DEGRADED/DRAINING shed load while staying alive."""
        with self._lock:
            return self._state == READY

    def alive(self) -> bool:
        """Liveness: should k8s restart the pod?  Only DEAD answers no —
        a briefly degraded pod recovering in-process must not be killed
        mid-recovery (that is the reference's crash-loop, reinstated)."""
        with self._lock:
            return self._state != DEAD

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "reason": self._reason,
                "since": self._since,
                "transitions": list(self._log),
            }

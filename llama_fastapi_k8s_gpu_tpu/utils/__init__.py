from .config import Settings, get_settings  # noqa: F401

"""Minimal Prometheus-text metrics registry.

The reference README advertises "metrics, alerts" (reference README.md:9) with
no implementation (SURVEY.md §5 "Metrics"); this makes the claim true: queue
depth, request counters, and latency/TTFT summaries exposed at ``/metrics``.
No external client library — the text exposition format is trivial.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class Metrics:
    # inc/observe run on handler+engine+watchdog threads concurrently with
    # the /metrics render: every store goes through _lock (lfkt-lint LOCK001)
    _GUARDED_BY = {"_counters": "_lock", "_gauges": "_lock",
                   "_summaries": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        # name -> (sum, count, min, max)
        self._summaries: dict[str, list[float]] = {}

    def inc(self, name: str, value: float = 1.0):
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float):
        with self._lock:
            s = self._summaries.setdefault(name, [0.0, 0.0, float("inf"), float("-inf")])
            s[0] += value
            s[1] += 1
            s[2] = min(s[2], value)
            s[3] = max(s[3], value)

    def render(self) -> str:
        lines = []
        with self._lock:
            for name, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {v}")
            for name, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {v}")
            for name, (total, count, mn, mx) in sorted(self._summaries.items()):
                lines.append(f"# TYPE {name} summary")
                lines.append(f"{name}_sum {total}")
                lines.append(f"{name}_count {count}")
                if count:
                    lines.append(f"{name}_min {mn}")
                    lines.append(f"{name}_max {mx}")
                    lines.append(f"{name}_avg {total / count}")
        return "\n".join(lines) + "\n"

"""Prometheus-text metrics registry: labeled series + true histograms.

The first cut of this module rendered ad-hoc ``{name}_min/_max/_avg``
lines under a ``summary`` TYPE with no ``# HELP`` — non-standard
exposition a real Prometheus scraper rejects, and min/max/avg cannot
answer tail-latency questions anyway.  Now:

- every family the package may expose is declared ONCE in the metric
  catalog (obs/catalog.py — the same single-source-of-truth pattern as
  the LFKT_* knob registry), with type, help text, allowed label keys and
  histogram buckets; an unregistered name raises ``KeyError`` here at
  runtime and fails lfkt-lint OBS001 statically;
- ``observe`` feeds an explicit-bucket **histogram** (cumulative
  ``_bucket{le="..."}`` + ``_sum`` + ``_count``) and the render derives
  p50/p95/p99 gauges per series (``{name}_p50`` ...) via the standard
  intra-bucket linear interpolation, replacing the summary hack for
  ``request_seconds``, ``engine_ttft_seconds``,
  ``engine_decode_tokens_per_sec`` and ``queue_wait_seconds``;
- counters/gauges/histograms all accept **labels** (keyword arguments
  matching the catalog's declared label keys), rendered as
  ``name{k="v"}`` series;
- the exposition text is legal: one ``# HELP`` + one ``# TYPE`` per
  family, families contiguous, values finite-formatted — asserted by the
  format-validation test in tests/test_obs.py.
"""

from __future__ import annotations

import bisect
import threading

from ..obs.catalog import COUNTER, GAUGE, HISTOGRAM, Metric, lookup

#: derived-quantile gauge suffixes rendered for every histogram series
QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers without the trailing .0."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Series:
    """One labelset's storage: a scalar for counter/gauge, buckets+sum for
    a histogram."""

    __slots__ = ("value", "buckets", "total", "count")

    def __init__(self, metric: Metric):
        self.value = 0.0
        if metric.mtype == HISTOGRAM:
            self.buckets = [0] * (len(metric.buckets) + 1)  # + the +Inf bucket
            self.total = 0.0
            self.count = 0

    def copy(self) -> "_Series":
        """Numeric snapshot for the render path (copy-then-release)."""
        c = _Series.__new__(_Series)
        c.value = self.value
        if hasattr(self, "buckets"):
            c.buckets = list(self.buckets)
            c.total = self.total
            c.count = self.count
        return c

    def quantile(self, metric: Metric, q: float) -> float:
        """histogram_quantile(): linear interpolation inside the bucket the
        q-th observation falls in — between that bucket's OWN bounds (the
        lower bound is the previous bucket's bound even when every lower
        bucket is empty); the +Inf bucket clamps to the largest finite
        bound (Prometheus convention)."""
        if not self.count:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            if n and cum + n >= rank:
                if i >= len(metric.buckets):        # +Inf bucket
                    return float(metric.buckets[-1])
                hi = float(metric.buckets[i])
                lo = float(metric.buckets[i - 1]) if i else 0.0
                return lo + (hi - lo) * ((rank - cum) / n)
            cum += n
        return float(metric.buckets[-1])


class Metrics:
    # inc/observe run on handler+engine+watchdog threads concurrently with
    # the /metrics render: every store goes through _lock (lfkt-lint LOCK001)
    _GUARDED_BY = {"_series": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        #: name -> { labels_tuple -> _Series }
        self._series: dict[str, dict[tuple, _Series]] = {}

    # ------------------------------------------------------------------
    @staticmethod
    def _resolve(name: str, mtype: str, labels: dict) -> tuple[Metric, tuple]:
        metric = lookup(name)
        if metric is None:
            raise KeyError(
                f"metric {name!r} is not in the catalog (obs/catalog.py); "
                "register it before recording it")
        if metric.mtype != mtype:
            raise KeyError(
                f"metric {name!r} is a {metric.mtype}, recorded as {mtype}")
        if set(labels) != set(metric.labels):
            raise KeyError(
                f"metric {name!r} takes labels {metric.labels}, "
                f"got {tuple(labels)}")
        key = tuple(str(labels[k]) for k in metric.labels)
        return metric, key

    def _get(self, name: str, mtype: str,
             labels: dict) -> tuple[Metric, _Series]:  # lfkt: holds[_lock]
        metric, key = self._resolve(name, mtype, labels)
        by_label = self._series.setdefault(name, {})
        s = by_label.get(key)
        if s is None:
            s = by_label[key] = _Series(metric)
        return metric, s

    # -- producer API ---------------------------------------------------
    def inc(self, name: str, value: float = 1.0, **labels):
        with self._lock:
            self._get(name, COUNTER, labels)[1].value += value

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._get(name, GAUGE, labels)[1].value = float(value)

    def observe(self, name: str, value: float, **labels):
        """Record one observation into the name's histogram."""
        with self._lock:
            metric, s = self._get(name, HISTOGRAM, labels)
            s.buckets[bisect.bisect_left(metric.buckets, float(value))] += 1
            s.total += float(value)
            s.count += 1

    def reset_family(self, name: str) -> None:
        """Drop every series of a snapshot-style GAUGE family before a
        re-export.  Families rebuilt whole from a registry at scrape time
        (the memory ledger's ``hbm_bytes`` rows) must forget series whose
        source row vanished — a drained spill tier or a collected
        engine's rows would otherwise report their last value forever.
        Counters/histograms are cumulative by contract and must never be
        reset this way."""
        metric = lookup(name)
        if metric is None or metric.mtype != GAUGE:
            raise KeyError(
                f"reset_family is for cataloged gauge families; "
                f"{name!r} is not one")
        with self._lock:
            self._series.pop(name, None)

    # -- programmatic reads (obs/slo.py burn-rate evaluation) ------------
    def snapshot(self) -> dict:
        """Point-in-time copy of every series' raw storage:
        ``{name: {labels_tuple: value | {"buckets", "sum", "count"}}}``.
        The SLO engine diffs two snapshots to get windowed rates — the
        histogram buckets here are cumulative-since-boot, so deltas over a
        window are exact event counts, not samples."""
        out: dict = {}
        with self._lock:
            for name, series in self._series.items():
                metric = lookup(name)
                per: dict = {}
                for key, s in series.items():
                    if metric.mtype == HISTOGRAM and not metric.prefix:
                        per[key] = {"buckets": list(s.buckets),
                                    "sum": s.total, "count": s.count}
                    else:
                        per[key] = s.value
                out[name] = per
        return out

    # -- exposition ------------------------------------------------------
    @staticmethod
    def _label_str(metric: Metric, key: tuple, extra: str = "") -> str:
        parts = [f'{k}="{_escape(v)}"' for k, v in zip(metric.labels, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> str:
        # copy-then-release (lfkt-lint LOCK006): the numeric state is
        # snapshotted under the lock in O(series); the O(n log n) sort
        # and all exposition string work run OFF it, so a /metrics
        # scrape never stalls a hot-path inc() behind formatting
        with self._lock:
            snap = {name: {key: s.copy() for key, s in by_label.items()}
                    for name, by_label in self._series.items()}
        lines: list[str] = []
        for name in sorted(snap):
            metric = lookup(name)
            mtype = metric.mtype if not metric.prefix else GAUGE
            series = snap[name]
            lines.append(f"# HELP {name} {metric.help}")
            lines.append(f"# TYPE {name} {mtype}")
            if mtype != HISTOGRAM:
                for key in sorted(series):
                    lines.append(
                        f"{name}{self._label_str(metric, key)} "
                        f"{_fmt(series[key].value)}")
                continue
            for key in sorted(series):
                s = series[key]
                cum = 0
                for bound, n in zip(metric.buckets, s.buckets):
                    cum += n
                    le = f'le="{_fmt(bound)}"'
                    lines.append(
                        f"{name}_bucket"
                        f"{self._label_str(metric, key, le)} {cum}")
                inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{self._label_str(metric, key, inf)} "
                    f"{s.count}")
                lines.append(
                    f"{name}_sum{self._label_str(metric, key)} "
                    f"{_fmt(s.total)}")
                lines.append(
                    f"{name}_count{self._label_str(metric, key)} "
                    f"{s.count}")
            # derived quantiles: separate gauge families (legal — a
            # histogram family itself may not carry quantile samples)
            for suffix, q in QUANTILES:
                lines.append(
                    f"# HELP {name}_{suffix} derived {q:.2f} quantile "
                    f"of {name}")
                lines.append(f"# TYPE {name}_{suffix} gauge")
                for key in sorted(series):
                    lines.append(
                        f"{name}_{suffix}{self._label_str(metric, key)} "
                        f"{_fmt(series[key].quantile(metric, q))}")
        return "\n".join(lines) + "\n"

"""Persistent XLA compilation cache setup (SURVEY.md §5 "Checkpoint/resume").

Cuts the jit-warmup cost of a process restart from minutes to seconds — the
serving analogue of the reference's model-artifact reuse across pod restarts
(reference helm/templates/deployment.yaml:26-49 initContainer).  Off unless
LFKT_COMPILE_CACHE_DIR is set.  Shared by the Engine (engine/engine.py) and
the bench children (bench.py / bench_server.py), whose per-step processes
otherwise each pay the full remote-compile cost of the same programs.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


def setup_compile_cache() -> None:
    from .config import knob

    d = knob("LFKT_COMPILE_CACHE_DIR")
    if not d:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # noqa: BLE001 — older jax: serve without the cache
        logger.warning("compilation cache unavailable: %s", e)

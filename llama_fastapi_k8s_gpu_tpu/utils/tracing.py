"""Profiling hooks: per-phase wall timers and optional XProf trace capture.

The reference's sole instrument is a request-timing log middleware (reference
api.py:179-194).  SURVEY.md §5 "Tracing / profiling" calls for per-phase
timers (queue wait, prefill/TTFT, decode tokens/sec — implemented in
engine/engine.py and server/app.py against utils/metrics.py) plus optional
``jax.profiler`` capture; this module provides the capture: set
``LFKT_PROFILE_DIR`` and every generation records a TensorBoard/XProf trace
there (device kernels + host dispatch), zero overhead when unset.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time

logger = logging.getLogger(__name__)

#: /debug/profile capture bounds: a runaway ``seconds=`` must not park the
#: profiler on a serving pod
MAX_CAPTURE_SECONDS = 30.0
MIN_CAPTURE_SECONDS = 0.05

#: one capture at a time (jax.profiler keeps process-global state; a
#: second start_trace while one runs raises deep inside the profiler)
_CAPTURE_LOCK = threading.Lock()


class ProfileDisabled(RuntimeError):
    """LFKT_PROFILE_DIR is unset — profiling is opt-in, off by default."""


class ProfileBusy(RuntimeError):
    """A capture is already running (the exclusive-capture guard)."""


def capture_profile(seconds: float) -> dict:
    """Bounded on-demand XProf capture (the ``GET /debug/profile`` body):
    start ``jax.profiler`` into ``LFKT_PROFILE_DIR``, hold it for a
    clamped window, stop, and report where the trace landed.  Blocking —
    callers run it in a worker thread.  Raises :class:`ProfileDisabled`
    when the knob is unset and :class:`ProfileBusy` when a capture is
    already in flight; profiler-internal failures are reported in the
    result rather than raised (capture is best-effort, serving is not)."""
    d = profile_dir()
    if not d:
        raise ProfileDisabled(
            "set LFKT_PROFILE_DIR to enable /debug/profile captures")
    seconds = max(MIN_CAPTURE_SECONDS, min(MAX_CAPTURE_SECONDS,
                                           float(seconds)))
    if not _CAPTURE_LOCK.acquire(blocking=False):
        raise ProfileBusy("a profiler capture is already running")
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        t0 = time.time()
        try:
            jax.profiler.start_trace(d)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            logger.warning("profiler capture unavailable (%s)", e)
            return {"ok": False, "error": str(e), "dir": d}
        try:
            time.sleep(seconds)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                logger.warning("profiler teardown failed (%s)", e)
                return {"ok": False, "error": str(e), "dir": d,
                        "seconds": seconds,
                        "wall_s": round(time.time() - t0, 3)}
        # "seconds" is the clamped capture window; "wall_s" additionally
        # counts start/stop_trace itself — the teardown serializes every
        # event the profiler retained and can dwarf a short window on a
        # long-lived process, so the two must not be conflated
        return {"ok": True, "dir": d, "seconds": seconds,
                "wall_s": round(time.time() - t0, 3)}
    finally:
        _CAPTURE_LOCK.release()


def profile_dir() -> str | None:
    from .config import knob

    return knob("LFKT_PROFILE_DIR") or None


@contextlib.contextmanager
def maybe_profile(tag: str = "generate"):
    """jax.profiler trace scope when LFKT_PROFILE_DIR is set; no-op otherwise.

    Profiler start/stop failures are swallowed (profiling must never break
    serving); exceptions raised by the profiled body itself propagate
    unchanged.
    """
    d = profile_dir()
    if not d:
        yield
        return

    trace = None
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        trace = jax.profiler.trace(d)
        trace.__enter__()
    except Exception as e:  # noqa: BLE001 — capture is best-effort
        logger.warning("profiler capture unavailable (%s); continuing", e)
        trace = None
    try:
        yield
    finally:
        if trace is not None:
            try:
                trace.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001
                logger.warning("profiler teardown failed (%s); trace dropped", e)

"""Profiling hooks: per-phase wall timers and optional XProf trace capture.

The reference's sole instrument is a request-timing log middleware (reference
api.py:179-194).  SURVEY.md §5 "Tracing / profiling" calls for per-phase
timers (queue wait, prefill/TTFT, decode tokens/sec — implemented in
engine/engine.py and server/app.py against utils/metrics.py) plus optional
``jax.profiler`` capture; this module provides the capture: set
``LFKT_PROFILE_DIR`` and every generation records a TensorBoard/XProf trace
there (device kernels + host dispatch), zero overhead when unset.
"""

from __future__ import annotations

import contextlib
import logging
import os

logger = logging.getLogger(__name__)


def profile_dir() -> str | None:
    from .config import knob

    return knob("LFKT_PROFILE_DIR") or None


@contextlib.contextmanager
def maybe_profile(tag: str = "generate"):
    """jax.profiler trace scope when LFKT_PROFILE_DIR is set; no-op otherwise.

    Profiler start/stop failures are swallowed (profiling must never break
    serving); exceptions raised by the profiled body itself propagate
    unchanged.
    """
    d = profile_dir()
    if not d:
        yield
        return

    trace = None
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        trace = jax.profiler.trace(d)
        trace.__enter__()
    except Exception as e:  # noqa: BLE001 — capture is best-effort
        logger.warning("profiler capture unavailable (%s); continuing", e)
        trace = None
    try:
        yield
    finally:
        if trace is not None:
            try:
                trace.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001
                logger.warning("profiler teardown failed (%s); trace dropped", e)

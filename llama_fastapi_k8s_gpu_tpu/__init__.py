"""llama_fastapi_k8s_gpu_tpu — a TPU-native LLM serving framework.

A ground-up JAX/XLA/Pallas re-implementation of the capabilities of the
reference service `dzatulin/llama-fastapi-k8s-gpu` (FastAPI + llama.cpp/cuBLAS
on GPU, see /root/reference/api.py).  Where the reference delegates the entire
model runtime to the external native dependency ``llama-cpp-python==0.2.77``
(reference docker/Dockerfile.base:30-32), this package implements that runtime
in-tree, TPU-first:

- ``gguf``       — GGUF v2/v3 container parsing (mmap, zero-copy) and K-quant
                   (Q4_K/Q5_K/Q6_K/Q8_0/...) reference codecs.
- ``tokenizer``  — Llama-3 byte-level BPE and SentencePiece-style tokenizers
                   reconstructed from GGUF metadata, plus chat templates.
- ``models``     — the transformer itself (Llama / Mistral families) as pure
                   JAX functions: jit'd prefill + on-device decode with a
                   persistent, donated KV cache.
- ``ops``        — TPU compute primitives: Pallas kernels (dequant, flash
                   attention, fused quantized matmul) and XLA-native
                   quantized-matmul paths.
- ``sampling``   — llama.cpp-parity sampling chain (repetition/frequency/
                   presence penalties, top-k, top-p, min-p, temperature).
- ``engine``     — the drop-in replacement for ``llama_cpp.Llama``:
                   ``Engine.create_chat_completion`` with OpenAI-shaped
                   responses and streaming.
- ``parallel``   — device meshes, tensor/data/sequence-parallel shardings via
                   ``jax.sharding`` + XLA collectives over ICI.
- ``server``     — the FastAPI layer preserving the reference's externally
                   observable behavior (routes, admission queue, timeouts),
                   plus the advertised-but-missing ``/health`` and ``/metrics``.
- ``utils``      — config, logging, metrics plumbing.
"""

__version__ = "0.1.0"

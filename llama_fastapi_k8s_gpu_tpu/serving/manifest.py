"""The ``LFKT_MODELS`` manifest grammar — N models per serving process.

The reference (and every round before this one) serves exactly one GGUF
per process, named by ``LFKT_MODEL_DIR``/``LFKT_MODEL_NAME``.  The
multi-model registry (docs/MULTIMODEL.md; ROADMAP item 5) loads a fleet
of them from a single declarative env string:

    LFKT_MODELS=name=path[:knob=value[;knob=value...]][,name=path...]

- ``name``  — the serving alias requests route on (``model=`` in
  ``/response`` and ``/v1/chat/completions``; the ``id`` rows of
  ``GET /v1/models``).  ``[A-Za-z0-9._-]+``, unique across the manifest.
- ``path``  — the GGUF file.  Relative paths resolve against
  ``LFKT_MODEL_DIR`` (the existing single-model convention).
- overrides — per-model engine knobs after a ``:``, ``;``-separated
  ``knob=value`` pairs drawn from :data:`OVERRIDE_KEYS` (a deliberate
  whitelist: scheduler-level knobs like ``LFKT_BATCH_SIZE`` stay
  process-wide — every model gets the same lane count — so overrides
  can never make two engines disagree about the shared serving shape).

Example::

    LFKT_MODELS=llama8b=Llama-3-8B.Q4_K_M.gguf:n_ctx=2048;kv_dtype=int8,mistral7b=/models/mistral.gguf

``LFKT_DEFAULT_MODEL`` names the alias served when a request carries no
``model=``; it defaults to the manifest's FIRST entry.
"""

from __future__ import annotations

import dataclasses
import os
import re

#: per-model engine-constructor overrides the manifest may set.  Keys are
#: the Engine kwarg names; values cast the override string.
OVERRIDE_KEYS: dict[str, type] = {
    "n_ctx": int,
    "weight_format": str,
    "kv_dtype": str,
    "attn_impl": str,
    # per-model layer-looping: co-resident models differ in depth/ring
    # geometry, so one may loop while another's probe degrades it
    "decode_layer_unroll": int,
    "decode_chunk": int,
    "max_gen_tokens": int,
    "spec_decode": str,
    "spec_draft": int,
}

_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """One manifest entry: serving alias, GGUF path, engine overrides."""

    name: str
    path: str
    overrides: dict = dataclasses.field(default_factory=dict)

    def resolved_path(self, model_dir: str) -> str:
        """Absolute-or-relative resolution against the model dir (the
        single-model ``LFKT_MODEL_DIR``/``LFKT_MODEL_NAME`` convention).

        Relative paths must stay UNDER the model dir after symlink/..
        resolution: manifests arrive over the network via ``POST
        /admin/models/reload``, so an unconstrained join would let a
        ``../../`` entry read any file the pod can (lfkt-lint TAINT002
        pins this containment check).  Absolute paths remain the
        explicit operator escape hatch — they name the file outright
        rather than smuggling a traversal through the join."""
        if os.path.isabs(self.path):
            return self.path
        joined = os.path.join(model_dir, self.path)
        base = os.path.realpath(model_dir)
        real = os.path.realpath(joined)
        if real != base and not real.startswith(base + os.sep):
            raise ValueError(
                f"model {self.name!r}: path {self.path!r} escapes the "
                f"model dir {model_dir!r} after resolution — relative "
                "manifest paths must stay under LFKT_MODEL_DIR "
                "(docs/MULTIMODEL.md)")
        return joined


def parse_manifest(spec: str) -> list[ModelSpec]:
    """Parse ``LFKT_MODELS`` into validated :class:`ModelSpec` rows.

    Raises ``ValueError`` with attribution (the offending entry, the
    offending key) on every grammar violation — a typo'd manifest must
    fail the pod at startup, not serve a half-fleet silently."""
    out: list[ModelSpec] = []
    seen: set[str] = set()
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        head, sep, tail = entry.partition("=")
        name = head.strip()
        if not sep or not tail:
            raise ValueError(
                f"LFKT_MODELS entry {entry!r}: expected name=path"
                "[:knob=value;...] (docs/MULTIMODEL.md)")
        if not _NAME_RE.match(name):
            raise ValueError(
                f"LFKT_MODELS entry {entry!r}: model name {name!r} must "
                "match [A-Za-z0-9._-]+")
        if name in seen:
            raise ValueError(
                f"LFKT_MODELS entry {entry!r}: duplicate model name "
                f"{name!r}")
        path, osep, otail = tail.partition(":")
        path = path.strip()
        if not path:
            raise ValueError(
                f"LFKT_MODELS entry {entry!r}: empty model path")
        overrides: dict = {}
        if osep:
            for pair in otail.split(";"):
                pair = pair.strip()
                if not pair:
                    continue
                k, psep, v = pair.partition("=")
                k = k.strip()
                if not psep or not v.strip():
                    raise ValueError(
                        f"LFKT_MODELS entry {entry!r}: override {pair!r} "
                        "must be knob=value")
                cast = OVERRIDE_KEYS.get(k)
                if cast is None:
                    raise ValueError(
                        f"LFKT_MODELS entry {entry!r}: unknown override "
                        f"{k!r} (allowed: {', '.join(sorted(OVERRIDE_KEYS))})")
                try:
                    overrides[k] = cast(v.strip())
                except ValueError as e:
                    raise ValueError(
                        f"LFKT_MODELS entry {entry!r}: override {k}={v!r} "
                        f"does not cast to {cast.__name__}") from e
        seen.add(name)
        out.append(ModelSpec(name=name, path=path, overrides=overrides))
    if not out:
        raise ValueError("LFKT_MODELS is set but names no models")
    return out


def pick_default(specs: list[ModelSpec], requested: str = "") -> str:
    """Resolve ``LFKT_DEFAULT_MODEL``: the requested alias (validated
    against the manifest) or the first entry."""
    if requested:
        if not any(s.name == requested for s in specs):
            raise ValueError(
                f"LFKT_DEFAULT_MODEL={requested!r} is not in the "
                f"LFKT_MODELS manifest ({', '.join(s.name for s in specs)})")
        return requested
    return specs[0].name

"""Multi-model, multi-tenant serving (docs/MULTIMODEL.md; ROADMAP item 5)
and the disaggregated prefill/decode subsystem (``serving/disagg/``,
docs/RUNBOOK.md "Operating a split prefill/decode fleet" — imported
lazily by server/app.py, never here: the page-wire CLI and wire-only
consumers must not pay the registry's imports)."""

from .manifest import OVERRIDE_KEYS, ModelSpec, parse_manifest, pick_default  # noqa: F401
from .registry import ModelRegistry, UnknownModelError, WeightBudgetError  # noqa: F401

"""Multi-model, multi-tenant serving (docs/MULTIMODEL.md; ROADMAP item 5)."""

from .manifest import OVERRIDE_KEYS, ModelSpec, parse_manifest, pick_default  # noqa: F401
from .registry import ModelRegistry, UnknownModelError, WeightBudgetError  # noqa: F401

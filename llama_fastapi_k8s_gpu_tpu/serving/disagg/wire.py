"""The disagg page wire format — versioned, geometry-checked, leaf-generic.

A finished prefill under ``LFKT_KV_PAGED=1`` is already a set of
self-contained pages (parallel/kvpool.py: per "BitDecoding", PAPERS.md,
compact low-bit KV blocks with their scales riding along are exactly the
unit you want on a wire — our int8 four-leaf page layout IS that unit,
and the bf16 ``{k, v}`` layout serializes through the same leaf-generic
path).  This module defines what crosses the socket between a prefill
tier and a decode replica (serving/disagg/transport.py carries it):

frame layout (all integers big-endian)::

    u32  frame length N (bytes after this field; bounded by MAX_FRAME)
    u8   frame type (FRAME_* below)
    u32  header length H
    H    UTF-8 JSON header
    *    raw payload (PAGE frames: concatenated leaf page stacks)

Conversation: the client opens with HELLO carrying the wire schema
version + its pool's page geometry (page_tokens + per-leaf page shape
and dtype — ``KVPool.page_spec()``); the server answers HELLO_OK or an
ERR with attribution and closes — two pools that cannot bit-exactly
exchange pages must REFUSE at the handshake, never corrupt KV.  Each
REQ (token ids + namespace + absolute deadline) is answered by zero or
more PAGE frames (groups of up to :data:`PAGE_GROUP` pages; payload =
every cache leaf's page stack concatenated in tree-leaf order, raw
bytes) and one DONE (tokens covered, total pages, advisory greedy first
token).  Any malformed, truncated, or oversized frame raises
:class:`WireError` — the decode side degrades to local prefill, it
never guesses.

The schema is PINNED: ``python -m ...serving.disagg.wire --schema``
prints the machine-readable descriptor, and tools/ci_gate.py's
``disagg-wire-schema`` check compares it against the committed golden
header ``docs/disagg_wire_schema.json`` (the incident-schema idiom) so
a drive-by edit here cannot silently strand a mixed-version fleet —
bump :data:`WIRE_SCHEMA` and regenerate the golden deliberately.
"""

from __future__ import annotations

import json
import struct

import numpy as np

#: bump on ANY change to the frame layout, header fields, or page payload
#: encoding — a version mismatch refuses at the handshake with attribution
#: (2: REQ carries an optional ``trace`` W3C traceparent so the serving
#: side opens span trees linked to the originating request id)
WIRE_SCHEMA = 2

#: hard bound on one frame (length prefix sanity: a corrupt/hostile length
#: must not allocate gigabytes before the JSON parse even runs)
MAX_FRAME = 1 << 30

#: pages per PAGE frame: bounds per-frame memory on both sides and gives
#: the fault drills a mid-stream grain (a multi-page transfer is several
#: frames, so peer-death/truncation can land BETWEEN pages)
PAGE_GROUP = 4

FRAME_HELLO = 1      # client → server: schema + page geometry
FRAME_HELLO_OK = 2   # server → client: handshake accepted
FRAME_REQ = 3        # client → server: one prefill request
FRAME_PAGE = 4       # server → client: one group of pages
FRAME_DONE = 5       # server → client: request complete
FRAME_ERR = 6        # either direction: refusal/failure with attribution

FRAME_NAMES = {
    FRAME_HELLO: "HELLO", FRAME_HELLO_OK: "HELLO_OK", FRAME_REQ: "REQ",
    FRAME_PAGE: "PAGE", FRAME_DONE: "DONE", FRAME_ERR: "ERR",
}

_HEAD = struct.Struct("!BI")      # type, header length (inside the frame)
_LEN = struct.Struct("!I")        # frame length prefix


class WireError(ValueError):
    """A malformed, truncated, oversized or version-incompatible frame —
    the decode side treats every instance as 'this transfer is void:
    degrade to local prefill', never as data."""


def encode_frame(ftype: int, header: dict, payload: bytes = b"") -> bytes:
    """One wire frame, length prefix included."""
    if ftype not in FRAME_NAMES:
        raise WireError(f"unknown frame type {ftype}")
    h = json.dumps(header, separators=(",", ":")).encode("utf-8")
    n = _HEAD.size + len(h) + len(payload)
    if n > MAX_FRAME:
        raise WireError(f"frame of {n} bytes exceeds MAX_FRAME {MAX_FRAME}")
    return _LEN.pack(n) + _HEAD.pack(ftype, len(h)) + h + payload


def decode_frame(buf: bytes) -> tuple[int, dict, bytes]:
    """(ftype, header, payload) from one frame's post-length bytes.
    Raises :class:`WireError` on anything that is not an exact, valid
    frame — a truncated read upstream shows up here as a hard error."""
    if len(buf) < _HEAD.size:
        raise WireError(f"truncated frame: {len(buf)} bytes < header")
    ftype, hlen = _HEAD.unpack_from(buf)
    if ftype not in FRAME_NAMES:
        raise WireError(f"unknown frame type {ftype}")
    if _HEAD.size + hlen > len(buf):
        raise WireError(
            f"truncated frame: header claims {hlen} bytes, "
            f"{len(buf) - _HEAD.size} present")
    try:
        header = json.loads(buf[_HEAD.size:_HEAD.size + hlen])
    except ValueError as e:
        raise WireError(f"frame header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise WireError("frame header must be a JSON object")
    return ftype, header, buf[_HEAD.size + hlen:]


# ---------------------------------------------------------------------------
# geometry handshake
# ---------------------------------------------------------------------------

def pool_geometry(pool) -> dict:
    """The HELLO geometry block for one KVPool: page size + per-leaf page
    shape/dtype (``KVPool.page_spec()``), plus the wire schema version."""
    return {
        "wire_schema": WIRE_SCHEMA,
        "page_tokens": pool.page_tokens,
        "page_bytes": pool.page_nbytes,
        "leaves": [{"shape": list(shape), "dtype": dtype}
                   for shape, dtype in pool.page_spec()],
    }


def geometry_mismatch(mine: dict, theirs: dict) -> str | None:
    """Attribution message when two geometry blocks cannot exchange pages
    bit-exactly (None = compatible).  Schema version is checked FIRST: a
    newer peer's geometry encoding may not even be comparable."""
    if theirs.get("wire_schema") != mine.get("wire_schema"):
        return (f"wire schema mismatch: peer speaks "
                f"{theirs.get('wire_schema')!r}, this pool speaks "
                f"{mine.get('wire_schema')!r} — upgrade the older tier")
    for field in ("page_tokens", "leaves"):
        if theirs.get(field) != mine.get(field):
            return (f"page geometry mismatch on {field!r}: peer has "
                    f"{theirs.get(field)!r}, this pool has "
                    f"{mine.get(field)!r} — prefill and decode tiers "
                    "must serve the same model/kv_dtype/page_tokens")
    return None


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype for a geometry dtype string, including the ml_dtypes
    extension types jax caches use (bfloat16)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def leaf_nbytes(geometry: dict) -> list[int]:
    """Per-PAGE byte size of each leaf, in leaf order — the payload
    partitioning both sides derive from the handshake geometry alone
    (nothing about sizes ever rides a PAGE frame's header)."""
    out = []
    for leaf in geometry["leaves"]:
        size = _np_dtype(leaf["dtype"]).itemsize
        for d in leaf["shape"]:
            size *= int(d)
        out.append(size)
    return out


# ---------------------------------------------------------------------------
# page payload codec (leaf-generic, raw bytes — bitwise round trip)
# ---------------------------------------------------------------------------

def encode_pages(leaves: list) -> bytes:
    """PAGE payload for one group: every leaf's page stack (leading axis =
    page), concatenated raw in leaf order.  Bit-exact by construction —
    no float conversion touches the bytes."""
    return b"".join(np.ascontiguousarray(leaf).tobytes() for leaf in leaves)


def decode_pages(payload: bytes, n_pages: int, geometry: dict) -> list:
    """Rebuild one PAGE group's leaf stacks from the raw payload.  The
    expected length is fully determined by (n_pages, geometry); any
    mismatch is a :class:`WireError` (a truncated or padded payload must
    never be reshaped into plausible-looking KV)."""
    sizes = leaf_nbytes(geometry)
    want = n_pages * sum(sizes)
    if len(payload) != want:
        raise WireError(
            f"page payload is {len(payload)} bytes, geometry demands "
            f"{want} for {n_pages} page(s) — truncated or corrupt frame")
    out = []
    off = 0
    for leaf, size in zip(geometry["leaves"], sizes):
        n = n_pages * size
        arr = np.frombuffer(payload, dtype=_np_dtype(leaf["dtype"]),
                            count=n // _np_dtype(leaf["dtype"]).itemsize,
                            offset=off)
        out.append(arr.reshape((n_pages,) + tuple(leaf["shape"])))
        off += n
    return out


# ---------------------------------------------------------------------------
# pinned schema descriptor (ci_gate: disagg-wire-schema)
# ---------------------------------------------------------------------------

def schema_descriptor() -> dict:
    """The machine-readable wire contract — compared byte-for-byte (as
    canonical JSON) against docs/disagg_wire_schema.json by ci_gate, so
    any drive-by change to the format fails tier-1 until the schema
    version is bumped and the golden regenerated."""
    return {
        "wire_schema": WIRE_SCHEMA,
        "framing": "u32 len | u8 type | u32 hlen | json header | payload",
        "max_frame_bytes": MAX_FRAME,
        "page_group": PAGE_GROUP,
        "frame_types": {name: code for code, name in FRAME_NAMES.items()},
        "headers": {
            "HELLO": ["wire_schema", "page_tokens", "page_bytes", "leaves"],
            "HELLO_OK": ["wire_schema"],
            "REQ": ["rid", "namespace", "ids", "deadline", "trace"],
            "PAGE": ["rid", "seq", "n_pages"],
            "DONE": ["rid", "tokens", "n_pages", "first_token"],
            "ERR": ["rid", "error", "code"],
        },
        "page_payload": "leaf page stacks concatenated in tree-leaf order, "
                        "raw bytes; per-leaf sizes derived from the HELLO "
                        "geometry",
    }


def canonical_schema_json() -> str:
    return json.dumps(schema_descriptor(), indent=1, sort_keys=True) + "\n"


def main(argv=None) -> int:
    import argparse
    import os

    ap = argparse.ArgumentParser(prog="disagg.wire")
    ap.add_argument("--schema", action="store_true",
                    help="print the canonical wire schema descriptor")
    ap.add_argument("--check-golden", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="compare the descriptor against the committed "
                         "golden header (default docs/disagg_wire_schema"
                         ".json); exit 1 on drift")
    args = ap.parse_args(argv)
    if args.check_golden is not None:
        path = args.check_golden
        if not path:
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
            path = os.path.join(repo, "docs", "disagg_wire_schema.json")
        try:
            with open(path, encoding="utf-8") as f:
                golden = f.read()
        except OSError as e:
            print(f"disagg-wire-schema: golden header unreadable: {e}")
            return 1
        if golden != canonical_schema_json():
            print("disagg-wire-schema: DRIFT — serving/disagg/wire.py no "
                  f"longer matches {path}.\nIf the change is deliberate, "
                  "bump WIRE_SCHEMA and regenerate the golden with:\n  "
                  "python -m llama_fastapi_k8s_gpu_tpu.serving.disagg.wire "
                  f"--schema > {path}")
            return 1
        print(f"disagg-wire-schema: OK (schema {WIRE_SCHEMA})")
        return 0
    print(canonical_schema_json(), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""The prefill tier's page service (``LFKT_DISAGG_ROLE=prefill``).

One listening socket; per peer connection: a geometry handshake
(serving/disagg/wire.py — incompatible pools refuse with attribution,
they never exchange bytes), then a request loop.  Each REQ runs
:meth:`~...engine.engine.Engine.prefill_to_pages` on the local engine —
which consults the tier's OWN radix index first, so a system prompt hot
across many decode replicas prefills once per prefill pod, not once per
replica — and streams the resulting page stacks back as PAGE frames
through a bounded :class:`~.transport.FrameSender` (backpressure: a
slow decode replica throttles this tier's export instead of growing its
memory; the queued bytes are the memory ledger's ``disagg_txbuf``
component), finishing with a DONE frame.

Failure semantics: a per-request engine failure answers an ERR frame
and keeps the connection; a protocol violation or transport failure
drops the connection (the decode side reconnects with backoff).  The
``peer_dead`` fault-injection point fires between PAGE groups, so the
drills can kill a transfer mid-stream deterministically.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

from ...obs.logctx import sanitize_text
from ...obs.memledger import register_component
from ...obs.trace import TRACER
from ...utils.faults import FAULTS, FaultError
from . import wire
from .transport import FrameConn, FrameSender

logger = logging.getLogger(__name__)

#: handshake must complete promptly; the REQ loop then waits unbounded
#: (an idle decode replica holding its connection open is normal)
_HANDSHAKE_TIMEOUT_S = 30.0


class PrefillServer:
    """Serves KV pages to decode replicas over the disagg wire."""

    # accept loop + one handler thread per peer; the sender registry and
    # counters cross threads under one mutex.  The listener/stop flag are
    # written once at construction/stop (reference stores).
    _GUARDED_BY = {"_senders": "_lock", "counters": "_lock"}
    _THREAD_ENTRIES = ("_accept_loop", "_serve_conn")
    _SHARED_ATOMIC = ("_stop", "_sock", "port", "metrics", "_tracer")

    def __init__(self, engine, host: str = "0.0.0.0", port: int = 0,
                 queue_frames: int = 32, metrics=None, tracer=None):
        pool = getattr(engine, "_kvpool", None)
        if pool is None:
            raise ValueError(
                "LFKT_DISAGG_ROLE=prefill requires LFKT_KV_PAGED=1: "
                "finished prefills ship as KV pages, and only the paged "
                "arena produces them (docs/RUNBOOK.md 'Operating a split "
                "prefill/decode fleet')")
        self.engine = engine
        self._pool = pool
        self._geometry = wire.pool_geometry(pool)
        self._queue_frames = max(1, int(queue_frames))
        self.metrics = metrics
        # the process tracer unless a test injects a private one; the
        # REQ's ``trace`` field (wire schema 2) links this tier's span
        # fragments under the originating request's id
        self._tracer = tracer if tracer is not None else TRACER
        self._lock = threading.Lock()
        self._senders: dict[int, FrameSender] = {}
        self.counters = {"peers_total": 0, "prefills_served": 0,
                         "pages_sent": 0, "bytes_sent": 0,
                         "handshake_refusals": 0, "request_errors": 0}
        self._stop = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        # lfkt-mem: the bounded send queues' buffered bytes — host RAM
        # held between export and the wire (obs/catalog.py disagg_txbuf)
        register_component("disagg_txbuf", self, PrefillServer._ledger_txbuf)
        self._thread = threading.Thread(
            target=self._accept_loop, name="lfkt-disagg-accept", daemon=True)
        self._thread.start()
        logger.info("disagg prefill service listening on %s:%d "
                    "(page_tokens=%d, page_bytes=%d)", host, self.port,
                    pool.page_tokens, pool.page_nbytes)

    # -- telemetry (never fails serving; the KVPool idiom) -----------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self.metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail serving
            pass

    def _ledger_txbuf(self) -> int:
        with self._lock:
            senders = list(self._senders.values())
        return sum(s.buffered_bytes() for s in senders)

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def status(self) -> dict:
        """/health ``disagg.prefill_service`` block."""
        with self._lock:
            out = dict(self.counters)
            out["peers_connected"] = len(self._senders)
        out["port"] = self.port
        out["page_tokens"] = self._pool.page_tokens
        out["txbuf_bytes"] = self._ledger_txbuf()
        return out

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, peer = self._sock.accept()
            except OSError:
                return          # listener closed: stop()
            self._count("peers_total")
            threading.Thread(target=self._serve_conn, args=(sock, peer),
                             name="lfkt-disagg-peer", daemon=True).start()

    def _serve_conn(self, sock: socket.socket, peer) -> None:
        conn = FrameConn(sock)
        sender = None
        try:
            conn.settimeout(_HANDSHAKE_TIMEOUT_S)
            ftype, hello, _ = conn.recv_frame()
            if ftype != wire.FRAME_HELLO:
                conn.send_frame(wire.FRAME_ERR, {
                    "rid": None, "code": "protocol",
                    "error": f"expected HELLO, got "
                             f"{wire.FRAME_NAMES.get(ftype, ftype)}"})
                return
            mismatch = wire.geometry_mismatch(self._geometry, hello)
            if mismatch is not None:
                # the load-bearing refusal: two pools that cannot exchange
                # pages bit-exactly must never try — attribution instead
                # of corrupted KV
                self._count("handshake_refusals")
                self._emit("inc", "disagg_handshake_refusals_total")
                logger.error("disagg handshake refused for %s: %s",
                             peer, sanitize_text(mismatch))
                conn.send_frame(wire.FRAME_ERR, {
                    "rid": None, "code": "geometry", "error": mismatch})
                return
            conn.send_frame(wire.FRAME_HELLO_OK,
                            {"wire_schema": wire.WIRE_SCHEMA})
            conn.settimeout(None)
            sender = FrameSender(conn, self._queue_frames)
            with self._lock:
                self._senders[id(sender)] = sender
            logger.info("disagg peer connected: %s", peer)
            while not self._stop:
                ftype, hdr, _ = conn.recv_frame()
                if ftype != wire.FRAME_REQ:
                    raise wire.WireError(
                        f"expected REQ, got "
                        f"{wire.FRAME_NAMES.get(ftype, ftype)}")
                self._serve_request(sender, hdr)
        except ConnectionError:
            logger.info("disagg peer left: %s", peer)
        except (wire.WireError, OSError, FaultError) as e:
            # includes the peer_dead drill (FaultError raised through
            # _serve_request's page loop): hard-close mid-stream — the
            # decode side must degrade to local prefill, never hang
            logger.warning("disagg peer %s dropped: %s", peer, e)
        except Exception:  # noqa: BLE001 — one peer must not kill the service
            logger.exception("disagg peer handler failed for %s", peer)
        finally:
            if sender is not None:
                with self._lock:
                    self._senders.pop(id(sender), None)
                sender.close(join_timeout=0.5)
            conn.close()

    def _serve_request(self, sender: FrameSender, hdr: dict) -> None:
        # server-side fragment of the originating request's trace: the
        # REQ's ``trace`` field (wire schema 2) carries the decode side's
        # span context.  start_linked returns None unless this process
        # samples AND the field parsed — the untraced hot path pays two
        # cheap guards, no lock, no allocation (zero-cost contract).
        trace = self._tracer.start_linked("disagg.prefill",
                                          hdr.get("trace"))
        try:
            self._serve_request_traced(sender, hdr, trace)
        finally:
            # None-tolerant; sweeps spans an error path left open
            # (auto_closed) so a torn transfer still exports a fragment
            self._tracer.finish(trace)

    def _serve_request_traced(self, sender: FrameSender, hdr: dict,
                              trace) -> None:
        rid = hdr.get("rid")
        ids = hdr.get("ids")
        ns = str(hdr.get("namespace") or "")
        deadline = hdr.get("deadline")
        if not isinstance(ids, list) or not ids \
                or not all(isinstance(t, int) for t in ids):
            if trace is not None:
                trace.root.set(error="request: bad ids")
            sender.put(wire.FRAME_ERR, {
                "rid": rid, "code": "request",
                "error": "REQ ids must be a non-empty list of ints"})
            return
        if trace is not None:
            # rid/namespace are peer-supplied — sanitize before they
            # ride the /debug/traces export and the waterfall renderer
            trace.root.set(rid=sanitize_text(rid, limit=64),
                           namespace=sanitize_text(ns, limit=64),
                           tokens=len(ids))

        def put_timeout() -> float:
            # backpressure bound: a send queue still full past the
            # request's own deadline means the wire cannot carry this
            # transfer in time — tear it down rather than stall the tier
            if deadline is not None:
                return max(0.1, float(deadline) - time.time())
            return 30.0

        if deadline is not None and time.time() > float(deadline):
            # PR-2 deadline propagation spans the hop: an expired request
            # must not occupy the prefill engine — the decode side has
            # already abandoned it and freed its pages
            if trace is not None:
                trace.root.set(error="deadline expired")
            sender.put(wire.FRAME_ERR, {
                "rid": rid, "code": "deadline",
                "error": "deadline expired before remote prefill"})
            return
        sp = trace.span("engine.prefill") if trace is not None else None
        try:
            got = self.engine.prefill_to_pages(ids, namespace=ns,
                                               deadline=deadline)
        except Exception as e:  # noqa: BLE001 — per-request isolation: the
            # decode side degrades to local prefill with this attribution
            self._count("request_errors")
            logger.warning("disagg prefill request failed: %s", e)
            if sp is not None:
                sp.set(error=sanitize_text(
                    f"{type(e).__name__}: {e}", limit=256)).end()
            sender.put(wire.FRAME_ERR, {
                "rid": rid, "code": "prefill",
                "error": f"{type(e).__name__}: {e}"})
            return
        if sp is not None:
            sp.end()
        if got is None:
            sender.put(wire.FRAME_DONE, {"rid": rid, "tokens": 0,
                                         "n_pages": 0, "first_token": None})
            return
        leaves, tokens, first_token = got
        n_pages = tokens // self._pool.page_tokens
        # one span per wire transfer, one kv_pages event per PAGE group —
        # the waterfall's ▓ bar covers exactly the bytes-on-the-wire time
        sp_send = trace.span("wire.send") if trace is not None else None
        sent_bytes = 0
        off = seq = 0
        while off < n_pages:
            # drill point: a prefill peer dying MID-STREAM (FaultError
            # propagates to _serve_conn, which hard-closes the socket
            # between page groups — the decode side sees a torn transfer)
            FAULTS.fire("peer_dead")
            g = min(wire.PAGE_GROUP, n_pages - off)
            payload = wire.encode_pages(
                [leaf[off:off + g] for leaf in leaves])
            sender.put(wire.FRAME_PAGE,
                       {"rid": rid, "seq": seq, "n_pages": g},
                       payload, timeout=put_timeout())
            self._count("pages_sent", g)
            self._count("bytes_sent", len(payload))
            self._emit("inc", "disagg_pages_sent_total", g)
            self._emit("inc", "disagg_bytes_sent_total", len(payload))
            if sp_send is not None:
                sent_bytes += len(payload)
                sp_send.event("kv_pages", seq=seq, pages=g,
                              bytes=len(payload))
            off += g
            seq += 1
        sender.put(wire.FRAME_DONE,
                   {"rid": rid, "tokens": tokens, "n_pages": n_pages,
                    "first_token": first_token}, timeout=put_timeout())
        if sp_send is not None:
            sp_send.set(pages=n_pages, bytes=sent_bytes).end()
        self._count("prefills_served")
        self._emit("inc", "disagg_prefills_served_total")

    def stop_accepting(self) -> None:
        """Close the listener only: no NEW page-wire peers, in-flight
        transfers keep streaming — the drain semantics (server/httpd.py
        calls this when SIGTERM flips the pod to DRAINING, so a decode
        replica re-resolving the Service lands on a live prefill pod)."""
        try:
            self._sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stop = True
        self.stop_accepting()
        with self._lock:
            senders = list(self._senders.values())
            self._senders.clear()
        for s in senders:
            s.close(join_timeout=0.5)

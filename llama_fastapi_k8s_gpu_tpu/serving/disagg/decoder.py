"""The decode replica's remote-prefill client (``LFKT_DISAGG_ROLE=decode``).

One lazily-dialed connection to the prefill tier; per admitted prompt
one bounded **hop**: send the token ids, receive PAGE frames, import
the page stacks into the local :class:`~...parallel.kvpool.KVPool`
under the request's radix namespace (multi-model streams stay isolated
by construction), so the engine's existing paged-reuse machinery —
lease, restore into the front of the ring, local suffix prefill —
serves the request exactly as if the pages had been committed locally.
A restored prefix therefore ALSO warms the local radix: the next turn
of the same conversation skips the hop entirely (the warm-local check
is the first thing :meth:`DisaggClient.prefetch` does).

Degrade paths — the whole point.  :meth:`prefetch` NEVER raises and
never hangs: every hop is bounded by ``min(LFKT_DISAGG_TIMEOUT_SECONDS,
the request's remaining deadline)``, and every failure — peer dead
mid-stream, truncated frame, handshake refusal, timeout — falls back to
LOCAL prefill with attribution: a ``disagg_local_fallbacks_total``
counter labeled by reason, a health transition to DEGRADED with a
``disagg:`` reason (restored to READY by the next successful hop), and
a ``disagg_peer_dead`` flight-recorder bundle on the rising edge.
Reconnects back off exponentially; a geometry/schema refusal is
PERMANENT for the process (reconnecting cannot fix a mis-deployed
fleet — the attribution names the fix).
"""

from __future__ import annotations

import logging
import socket
import threading
import time

import numpy as np

from ...obs import flightrec as _flightrec
from ...obs.logctx import sanitize_text
from ...obs.trace import span_traceparent
from ...utils.health import DEGRADED, READY
from . import wire
from .transport import connect

logger = logging.getLogger(__name__)

_BACKOFF_START_S = 1.0
_BACKOFF_MAX_S = 30.0


class DisaggClient:
    """Remote-prefill client bound to one prefill peer and one KVPool."""

    # hops are serialized by _hop_lock (one framed connection: interleaved
    # requests would interleave frames); counters/last_error cross between
    # requesting threads and /health readers under _lock.
    _GUARDED_BY = {"counters": "_lock", "last_error": "_lock",
                   "_degraded": "_lock"}
    _SHARED_ATOMIC = ("_conn", "_refused", "_next_retry", "_backoff",
                      "metrics", "_closed")

    def __init__(self, peer: str, pool, timeout_s: float = 5.0,
                 metrics=None, health=None):
        host, _, port = str(peer).rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"LFKT_DISAGG_PEER must be host:port, got {peer!r}")
        self.peer = peer
        self._host, self._port = host, int(port)
        self._pool = pool
        self._geometry = wire.pool_geometry(pool)
        self._timeout = max(0.1, float(timeout_s))
        self.metrics = metrics
        self._health = health
        self._lock = threading.Lock()
        self._hop_lock = threading.Lock()
        self._conn = None
        self._rid = 0
        self._refused: str | None = None   # permanent handshake refusal
        self._next_retry = 0.0
        self._backoff = _BACKOFF_START_S
        self._closed = False
        self._degraded = False   # we hold a disagg DEGRADED on the monitor
        self.counters = {"remote_prefills": 0, "remote_tokens": 0,
                         "remote_misses": 0, "local_fallbacks": 0,
                         "warm_local_skips": 0, "reconnects": 0}
        self.last_error: str | None = None

    # -- telemetry (never fails serving) -----------------------------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self.metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail serving
            pass

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def connected(self) -> bool:
        return self._conn is not None

    def status(self) -> dict:
        """/health ``disagg.peer`` block: where the pages come from, and
        why they stopped coming when they did."""
        with self._lock:
            out = dict(self.counters)
            out["last_error"] = self.last_error
        out["peer"] = self.peer
        out["connected"] = self.connected()
        out["handshake_refused"] = self._refused
        return out

    # ------------------------------------------------------------------
    def prefetch(self, ids, *, namespace: str = "", deadline=None,
                 span=None) -> int:
        """Ensure the local radix covers the whole-page prefix of ``ids``
        via the prefill peer.  Returns the tokens the index covers after
        the hop (0 = nothing imported: warm locally already handled, too
        short, or a degrade — the caller's local prefill serves either
        way).  NEVER raises, never exceeds the hop budget."""
        if self._refused is not None or self._closed:
            return 0
        pool = self._pool
        T = pool.page_tokens
        n = len(ids)
        target = ((n - 1) // T) * T      # max page-aligned USABLE prefix
        if target < T:
            return 0                     # prompt shorter than one page
        if pool.match_len(ids, namespace=namespace) >= target:
            # multi-turn warm path: the imported prefix of an earlier hop
            # (or a local commit) already covers it — no wire round trip
            self._count("warm_local_skips")
            return 0
        hop_err: BaseException | None = None
        with self._hop_lock:
            # budget is computed AFTER the hop lock: hops serialize (one
            # framed connection), and time spent waiting for another
            # request's hop must neither eat this hop's wire budget nor
            # be misread as peer death; a deadline that expired in the
            # wait is a plain skip, not a failure
            budget = self._timeout
            if deadline is not None:
                budget = min(budget, float(deadline) - time.time())
            if budget <= 0.05:
                return 0                 # not worth opening a hop for
            if pool.match_len(ids, namespace=namespace) >= target:
                # the hop we waited behind imported this very prefix
                # (concurrent requests of one conversation)
                self._count("warm_local_skips")
                return 0
            t0 = time.time()
            fresh_dial = self._conn is None
            conn = self._ensure_conn(budget)  # lfkt: blocks-under[_hop_lock] -- hops serialize on one framed connection: the hop lock IS that serialization, and every wire op is budget-bounded
            if conn is None:
                if self._refused is None:
                    self._fallback("peer_unreachable",
                                   self.last_error or "connect failed")
                return 0
            if span is not None and fresh_dial:
                # the handshake's cost is part of THIS hop's story: a
                # waterfall showing a slow first turn must name the dial
                span.event("handshake", peer=self.peer,
                           host_s=round(time.time() - t0, 6))
            try:
                self._rid += 1
                rid = self._rid
                conn.settimeout(max(0.1, budget))
                # wire schema 2: the REQ carries the caller's span context
                # (None when sampled out) so the prefill tier's span tree
                # links under the originating request's trace id
                conn.send_frame(wire.FRAME_REQ, {  # lfkt: blocks-under[_hop_lock] -- hops serialize on one framed connection: the hop lock IS that serialization, and every wire op is budget-bounded
                    "rid": rid, "namespace": namespace,
                    "ids": [int(t) for t in ids], "deadline": deadline,
                    "trace": span_traceparent(span)})
                groups: list[list] = []
                got_pages = 0
                bytes_in = 0
                while True:
                    remaining = budget - (time.time() - t0)
                    if remaining <= 0:
                        raise socket.timeout("disagg hop budget exhausted")
                    conn.settimeout(remaining)
                    ftype, hdr, payload = conn.recv_frame()  # lfkt: blocks-under[_hop_lock] -- hops serialize on one framed connection: the hop lock IS that serialization, and every wire op is budget-bounded
                    if hdr.get("rid") not in (rid, None):
                        raise wire.WireError(
                            f"frame for rid {hdr.get('rid')} inside "
                            f"rid {rid}'s transfer")
                    if ftype == wire.FRAME_PAGE:
                        g = int(hdr.get("n_pages", 0))
                        groups.append(
                            wire.decode_pages(payload, g, self._geometry))
                        got_pages += g
                        bytes_in += len(payload)
                        continue
                    if ftype == wire.FRAME_DONE:
                        tokens = int(hdr.get("tokens") or 0)
                        if got_pages * T != tokens:
                            raise wire.WireError(
                                f"DONE claims {tokens} tokens but "
                                f"{got_pages} page(s) arrived")
                        break
                    if ftype == wire.FRAME_ERR:
                        code = str(hdr.get("code") or "peer_error")
                        msg = str(hdr.get("error") or "")
                        if code in ("geometry", "schema"):
                            self._refuse(msg)
                        elif code == "deadline":
                            # both sides agree the request is dead — not
                            # a peer failure, no health change
                            self._count("remote_misses")
                        else:
                            self._fallback(code, msg)
                        return 0
                    raise wire.WireError(
                        f"unexpected "
                        f"{wire.FRAME_NAMES.get(ftype, ftype)} frame")
            except (wire.WireError, ConnectionError, OSError) as e:
                # socket.timeout is an OSError: one handler for peer
                # death, torn frames, and a wire too slow for the budget.
                # The connection LATCH (drop + backoff) happens here,
                # still under the hop lock — the next hop's _ensure_conn
                # must never race a half-torn connection — but the
                # flight-recorder bundle and health transition run after
                # the lock releases (below): a slow incident-volume
                # write must never stall the NEXT request's hop behind
                # disk I/O (lfkt-lint LOCK006, ISSUE 15;
                # tests/test_disagg.py::test_peer_dead_bundle_off_hop_lock)
                self._drop_conn()
                hop_err = e
        if hop_err is not None:
            self._peer_dead_report(hop_err)
            return 0
        covered = 0
        if got_pages:
            leaves = [np.concatenate([g[i] for g in groups], axis=0)
                      for i in range(len(groups[0]))] \
                if len(groups) > 1 else groups[0]
            try:
                covered = pool.import_pages(ids[:tokens], leaves,
                                            namespace=namespace, span=span)
            except Exception as e:  # noqa: BLE001 — an import that cannot
                # index (pool churn, geometry drift) degrades to local
                # prefill like every other failure
                self._fallback("import", f"{type(e).__name__}: {e}")
                return 0
        dt = time.time() - t0
        if span is not None:
            span.event("disagg_recv", pages=got_pages, tokens=tokens,
                       bytes=bytes_in, host_s=round(dt, 6))
        self._emit("observe", "disagg_transfer_seconds", dt)
        if got_pages:
            self._emit("inc", "disagg_pages_received_total", got_pages)
            self._emit("inc", "disagg_bytes_received_total", bytes_in)
        if covered:
            self._count("remote_prefills")
            self._count("remote_tokens", covered)
            self._emit("inc", "disagg_remote_prefills_total")
        else:
            self._count("remote_misses")
        self._recovered()
        return covered

    # -- connection lifecycle ------------------------------------------
    def _ensure_conn(self, budget: float):
        if self._conn is not None:
            return self._conn
        now = time.time()
        if now < self._next_retry:
            return None                  # inside reconnect backoff
        try:
            conn = connect(self._host, self._port,
                           timeout=min(budget, self._timeout))
            conn.settimeout(min(budget, self._timeout))
            conn.send_frame(wire.FRAME_HELLO, self._geometry)
            ftype, hdr, _ = conn.recv_frame()
            if ftype == wire.FRAME_ERR:
                conn.close()
                self._refuse(str(hdr.get("error") or "handshake refused"))
                return None
            if ftype != wire.FRAME_HELLO_OK:
                raise wire.WireError(
                    f"expected HELLO_OK, got "
                    f"{wire.FRAME_NAMES.get(ftype, ftype)}")
        except (wire.WireError, ConnectionError, OSError) as e:
            with self._lock:
                self.last_error = f"{type(e).__name__}: {e}"
            self._next_retry = now + self._backoff
            self._backoff = min(self._backoff * 2, _BACKOFF_MAX_S)
            return None
        self._conn = conn
        self._backoff = _BACKOFF_START_S
        self._count("reconnects")
        logger.info("disagg prefill peer connected: %s", self.peer)
        return conn

    def _refuse(self, msg: str) -> None:
        """Permanent handshake refusal (schema/geometry): reconnecting
        cannot fix a mis-deployed fleet — pin the attribution, serve
        local prefill for the process lifetime."""
        # msg may quote peer-supplied frame fields (wire "error" text);
        # it reaches the log and the /health echo
        msg = sanitize_text(msg)
        self._refused = msg
        logger.error("disagg handshake refused — serving LOCAL prefill "
                     "for the process lifetime: %s", msg)
        self._emit("inc", "disagg_handshake_refusals_total")
        self._fallback("refused", msg)

    def _drop_conn(self) -> None:
        """Latch a dead connection: drop it and arm the reconnect
        backoff.  Runs UNDER the hop lock (prefetch's except handler):
        the swap must not race a concurrent hop's _ensure_conn — an
        off-lock drop could close a freshly re-established healthy
        connection out from under the next hop."""
        conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()
        self._next_retry = time.time() + self._backoff
        self._backoff = min(self._backoff * 2, _BACKOFF_MAX_S)

    def _peer_dead_report(self, exc: BaseException) -> None:
        """Attribution for a transport/wire failure mid-hop: degrade
        with a flight-recorder bundle.  Runs OFF the hop lock (lfkt-lint
        LOCK006): the bundle is disk I/O and must not stall the next
        request's hop."""
        msg = f"{type(exc).__name__}: {exc}"
        # the black box: by the time an operator looks, the socket state
        # is gone — bundle the ledger/traces/stats at the moment of death
        # (per-kind debounce keeps a flapping wire at one bundle per window)
        _flightrec.record_incident(
            "disagg_peer_dead",
            f"prefill peer {self.peer} died mid-transfer: {msg}",
            extra={"peer": self.peer, "client": self.status()})
        self._fallback("peer_dead", msg)

    def _fallback(self, reason: str, msg: str) -> None:
        # both can carry peer-supplied frame bytes (the ERR "code" field
        # flows into reason); they reach the log, /health and a metric
        # label
        reason = sanitize_text(reason, limit=64)
        msg = sanitize_text(msg)
        with self._lock:
            self.counters["local_fallbacks"] += 1
            self.last_error = f"{reason}: {msg}"
        self._emit("inc", "disagg_local_fallbacks_total", reason=reason)
        logger.warning("disagg remote prefill degraded to LOCAL prefill "
                       "(%s): %s", reason, msg)
        h = self._health
        if h is not None:
            # DEGRADED-but-serving: readiness sheds new traffic while the
            # local-prefill fallback keeps answering what arrives; the
            # next successful hop restores READY below
            if h.transition(DEGRADED,
                            f"disagg: prefill peer {self.peer} "
                            f"unavailable ({reason}) — serving "
                            "local-prefill fallback"):
                with self._lock:
                    self._degraded = True

    def _recovered(self) -> None:
        h = self._health
        with self._lock:
            was = self._degraded
            self._degraded = False
        if h is not None and was:
            h.transition(READY, "disagg: prefill peer restored")
            logger.info("disagg prefill peer restored: %s", self.peer)

    def close(self) -> None:
        self._closed = True
        conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()

"""Disaggregated prefill/decode: KV-page streaming between tiers.

ROADMAP item 4, the AccLLM-style co-design endpoint (PAPERS.md): the
AdmissionController (PR 5) *arbitrates* prefill/decode interference on
one chip; this subsystem *removes* it — a **prefill tier** runs
chunked/overlapped prefill and streams finished KV pages to **decode
replicas**, so each chip runs only the phase it is roofline-efficient
at.  The block-paged KV pool (PR 6) makes the wire format free: a
finished prefill is already a set of self-contained pages (int8 scales
ride the page — "BitDecoding", PAPERS.md), and restore-into-the-ring is
the machinery multi-turn reuse already pins bit-identical.

Pieces (each its own module):

- wire.py       — versioned frame format + geometry handshake (pinned
                  by ci_gate's ``disagg-wire-schema`` golden check)
- transport.py  — stdlib sockets, length-prefixed frames, bounded send
                  queue with backpressure (memory ledger: disagg_txbuf)
- prefiller.py  — the page service (``LFKT_DISAGG_ROLE=prefill``)
- decoder.py    — the remote-prefill client (``role=decode``); every
                  failure degrades to LOCAL prefill with attribution

``LFKT_DISAGG_ROLE=both`` arms BOTH halves on one engine over loopback
— the tier-1-testable / bench-A/B configuration (CPU, no second
process, the full wire still crossed).  Operations guide:
docs/RUNBOOK.md "Operating a split prefill/decode fleet".
"""

from __future__ import annotations

# NOTE: submodules import lazily (build_roles) — `python -m
# ...serving.disagg.wire` (the ci_gate schema check) must not find wire
# pre-imported by this package (runpy warning), and a wire-only consumer
# must not pay the prefiller/decoder (numpy/obs) imports.

#: valid LFKT_DISAGG_ROLE values (utils/config.py)
ROLES = ("off", "prefill", "decode", "both")


class DisaggRoles:
    """This process's armed disagg halves + the /health tier block."""

    def __init__(self, role: str, server=None, client=None):
        self.role = role
        self.server = server
        self.client = client

    def status(self) -> dict:
        out: dict = {"role": self.role}
        if self.server is not None:
            out["prefill_service"] = self.server.status()
        if self.client is not None:
            out["peer"] = self.client.status()
        return out

    def close(self) -> None:
        if self.client is not None:
            self.client.close()
        if self.server is not None:
            self.server.stop()


def build_roles(role: str, engine, settings, metrics=None,
                health=None) -> DisaggRoles | None:
    """Arm the configured disagg role(s) on ``engine`` (server startup,
    server/app.py).  Misconfiguration refuses LOUDLY at startup — the
    LFKT_WORKERS idiom — instead of silently serving a half-armed fleet:

    - any non-off role needs the paged pool (pages ARE the wire format);
    - the multi-model registry gates off (one model per tier — the two
      pools' geometries must match EXACTLY, which the manifest cannot
      promise across N models);
    - role=decode needs a peer address.
    """
    if role not in ROLES:
        raise ValueError(
            f"LFKT_DISAGG_ROLE must be one of {'|'.join(ROLES)}, "
            f"got {role!r}")
    if role == "off":
        return None
    if callable(getattr(engine, "models", None)):
        raise ValueError(
            "LFKT_DISAGG_ROLE gates off multi-model registry serving: a "
            "split fleet runs one model per tier pair (the page wire "
            "demands one exact cache geometry) — drop LFKT_MODELS or "
            "set LFKT_DISAGG_ROLE=off (docs/RUNBOOK.md)")
    pool = getattr(engine, "_kvpool", None)
    if pool is None:
        raise ValueError(
            f"LFKT_DISAGG_ROLE={role} requires LFKT_KV_PAGED=1 on a "
            "pool-capable engine: finished prefills ship as KV pages, "
            "and only the paged arena produces/receives them "
            "(docs/RUNBOOK.md 'Operating a split prefill/decode fleet')")
    from .decoder import DisaggClient
    from .prefiller import PrefillServer

    server = client = None
    if role in ("prefill", "both"):
        server = PrefillServer(
            engine,
            host="127.0.0.1" if role == "both" else settings.disagg_bind,
            port=0 if role == "both" else settings.disagg_port,
            queue_frames=settings.disagg_queue_frames, metrics=metrics)
    if role in ("decode", "both"):
        peer = (f"127.0.0.1:{server.port}" if role == "both"
                else settings.disagg_peer)
        if not peer:
            if server is not None:
                server.stop()
            raise ValueError(
                "LFKT_DISAGG_ROLE=decode requires LFKT_DISAGG_PEER="
                "host:port (the prefill tier's page service)")
        try:
            client = DisaggClient(
                peer, pool, timeout_s=settings.disagg_timeout_seconds,
                metrics=metrics, health=health)
            engine.install_disagg(client)
        except Exception:
            if server is not None:
                server.stop()
            raise
    return DisaggRoles(role, server, client)

"""Disagg transfer layer: stdlib sockets, length-prefixed frames,
bounded send queue with backpressure.

Two small pieces, both deliberately boring:

- :class:`FrameConn` — one blocking TCP connection speaking the
  serving/disagg/wire.py frame format.  Reads are exact (a short read IS
  a :class:`~.wire.WireError`, never a silent partial); the length
  prefix is sanity-bounded before any allocation.  The fault-injection
  points ``slow_wire`` and ``truncated_frame`` (utils/faults.py) live on
  the send path so the drills exercise a stalling and a torn wire
  without a real network fault.

- :class:`FrameSender` — a bounded queue + one sender thread per peer
  connection.  ``put()`` BLOCKS when the queue is full: a slow or
  wedged wire applies backpressure to the prefill tier's page export
  instead of buffering unboundedly (the buffered bytes are reported
  into the memory ledger as the ``disagg_txbuf`` component by the
  owning PrefillServer).  A send failure latches: every later ``put``
  raises immediately, so a producer mid-stream learns the peer is gone
  within one frame.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import socket
import struct
import threading

from ...utils.faults import FAULTS, FaultError
from .wire import MAX_FRAME, WireError, decode_frame, encode_frame

logger = logging.getLogger(__name__)

_LEN = struct.Struct("!I")


class FrameConn:
    """One framed, blocking socket.  Not thread-safe per direction: one
    reader thread and one writer thread at most (the roles use exactly
    that shape — FrameSender owns the writes)."""

    def __init__(self, sock: socket.socket):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._sock = sock

    def settimeout(self, t: float | None) -> None:
        self._sock.settimeout(t)

    def _recv_exact(self, n: int) -> bytes:
        parts = []
        got = 0
        while got < n:
            chunk = self._sock.recv(min(n - got, 1 << 20))
            if not chunk:
                raise WireError(
                    f"connection closed mid-frame ({got}/{n} bytes)")
            parts.append(chunk)
            got += len(chunk)
        return b"".join(parts)

    def recv_frame(self) -> tuple[int, dict, bytes]:
        """(ftype, header, payload) — raises :class:`WireError` on a
        truncated/oversized/malformed frame, ``socket.timeout``/``OSError``
        on transport failure.  EOF between frames raises ConnectionError
        (clean close), EOF inside one raises WireError (torn)."""
        head = self._sock.recv(_LEN.size)
        if not head:
            raise ConnectionError("peer closed the connection")
        if len(head) < _LEN.size:
            head += self._recv_exact(_LEN.size - len(head))
        (n,) = _LEN.unpack(head)
        if n > MAX_FRAME:
            raise WireError(f"frame length {n} exceeds MAX_FRAME")
        return decode_frame(self._recv_exact(n))

    def send_raw(self, buf: bytes) -> None:
        """Write one pre-encoded frame.  THE injection site: ``slow_wire``
        (mode slow stalls here) and ``truncated_frame`` (mode error ships
        a deliberately torn frame, then closes — the receiving side must
        refuse it, never restore partial KV)."""
        FAULTS.fire("slow_wire")
        try:
            FAULTS.fire("truncated_frame")
        except FaultError:
            try:
                self._sock.sendall(buf[:max(_LEN.size + 1, len(buf) // 2)])
            finally:
                self.close()
            raise WireError("truncated frame injected (drill)") from None
        self._sock.sendall(buf)

    def send_frame(self, ftype: int, header: dict,
                   payload: bytes = b"") -> None:
        self.send_raw(encode_frame(ftype, header, payload))

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class FrameSender:
    """Bounded async frame writer for one connection (the prefill tier's
    page stream).  The producer's ``put()`` blocks once ``max_frames``
    are queued — THE backpressure contract: a slow wire throttles page
    export instead of growing the process."""

    # put() runs on the handler thread, _loop on the sender thread; the
    # byte counter and the latched error cross between them under _lock.
    # The queue itself is the stdlib's (internally locked).
    _GUARDED_BY = {"_buffered": "_lock", "_error": "_lock"}
    _THREAD_ENTRIES = ("_loop",)
    _SHARED_ATOMIC = ("_q", "_closed")

    def __init__(self, conn: FrameConn, max_frames: int = 32):
        self._conn = conn
        self._q: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, int(max_frames)))
        self._lock = threading.Lock()
        self._buffered = 0
        self._error: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="lfkt-disagg-send", daemon=True)
        self._thread.start()

    def buffered_bytes(self) -> int:
        """Queued-but-unsent frame bytes (memory ledger: disagg_txbuf)."""
        with self._lock:
            return self._buffered

    def _set_error(self, e: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = e

    def put(self, ftype: int, header: dict, payload: bytes = b"",
            timeout: float = 30.0) -> None:
        """Queue one frame; blocks (bounded by ``timeout``, never
        unbounded — a producer must not wedge behind a dead wire) when
        the queue is full — backpressure.  Raises the sender thread's
        latched error (the wire is dead: stop producing pages) or
        ``queue.Full`` when the wire is too slow for the timeout."""
        buf = encode_frame(ftype, header, payload)
        # account BEFORE the enqueue: the sender thread can only see (and
        # decrement) a frame whose increment already happened, so the
        # disagg_txbuf gauge can never drift upward on a fast wire
        with self._lock:
            if self._error is not None:
                raise self._error
            self._buffered += len(buf)
        try:
            self._q.put(buf, timeout=timeout)
        except queue_mod.Full:
            with self._lock:
                self._buffered = max(0, self._buffered - len(buf))
            raise
        with self._lock:
            if self._error is not None:
                # the wire died while we enqueued: this frame will never
                # send (the error-path drain may already have missed it)
                self._buffered = max(0, self._buffered - len(buf))
                raise self._error

    def _drain(self) -> None:
        """Empty the queue after a latched error: frames are
        undeliverable, and a producer blocked in ``put`` on a full queue
        must get its slot back so it can observe the error and stop."""
        while True:
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                return

    def _loop(self) -> None:
        while True:
            buf = self._q.get()
            if buf is None:
                return
            try:
                self._conn.send_raw(buf)
            except BaseException as e:  # noqa: BLE001 — latch, drain, stop:
                # the producer sees the error on its next put(); frames
                # already queued are undeliverable and dropped
                self._set_error(e)
                self._conn.close()
                self._drain()
                with self._lock:
                    self._buffered = 0
                return
            finally:
                with self._lock:
                    self._buffered = max(0, self._buffered - len(buf))

    def close(self, join_timeout: float = 2.0) -> None:
        """Stop the sender after the queued frames drain (or its error)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put(None, timeout=join_timeout)
        except queue_mod.Full:
            self._set_error(RuntimeError("sender queue wedged at close"))
            self._conn.close()
        self._thread.join(timeout=join_timeout)


def connect(host: str, port: int, timeout: float) -> FrameConn:
    """Dial the prefill tier's page service (decode side)."""
    return FrameConn(socket.create_connection((host, port), timeout=timeout))

"""THE wire-surface registry — every trust boundary the fleet exposes,
declared once.

Same single-source-of-truth pattern as the metric catalog
(obs/catalog.py) and the ``LFKT_*`` knob registry (utils/config.py): any
``x-lfkt-*`` HTTP header and any page-wire / migration frame-header
field the package puts on (or reads off) a socket must be declared here
with its direction and **trust class**.  PR 17 fixed, by hand, a hole
where inbound copies of the router's internal stamps could command a
replica to pull KV pages from an attacker-chosen address — this module
turns that one-off fix into a statically enforced invariant (lfkt-lint
WIRE001-003, lint/wire.py):

- **WIRE001** — an ``x-lfkt-*`` header literal or frame-header field
  used anywhere in the package but not declared here;
- **WIRE002** — a declared ingress point with a CFG path that forwards
  bytes upstream without first stripping every ``internal-stamped``
  header (deleting the router's strip loop fires this);
- **WIRE003** — drift between these declarations and the generated
  docs/WIRESURFACE.md table (pinned byte-for-byte, the OBS002 idiom).

Trust classes:

- ``client-settable`` — clients may send it; every consumer must treat
  the value as attacker-controlled (taint source for lint/taint.py);
- ``internal-stamped-must-strip`` — stamped by our own tier on egress;
  inbound copies MUST be stripped at every declared ingress so a client
  can never impersonate the stamp;
- ``peer-only`` — rides the mTLS'd/NetworkPolicy'd intra-fleet wire,
  never a client connection; still parsed defensively (a compromised
  peer is in scope for taint analysis), but no ingress strip applies.

The declarations below are pure literals on purpose: lint/wire.py parses
this file with ``ast`` (never imports it), the same static-read contract
as the metric catalog and the env-knob registry.
"""

from __future__ import annotations

import dataclasses

CLIENT_SETTABLE = "client-settable"
INTERNAL_STAMPED = "internal-stamped-must-strip"
PEER_ONLY = "peer-only"

#: every legal trust class, render order for the docs table
TRUST_CLASSES = (CLIENT_SETTABLE, INTERNAL_STAMPED, PEER_ONLY)


@dataclasses.dataclass(frozen=True)
class WireHeader:
    """One declared ``x-lfkt-*`` HTTP header.  ``direction`` says who
    emits it (``inbound`` = clients, ``internal`` = our own tiers)."""

    name: str
    direction: str
    trust: str
    summary: str


@dataclasses.dataclass(frozen=True)
class WireField:
    """One declared page-wire / migration frame-header field.  ``frames``
    names the frame types that carry it (the wire.py schema descriptor
    is the framing-level source of truth; this row carries the trust
    annotation the schema descriptor lacks)."""

    name: str
    frames: str
    trust: str
    summary: str


@dataclasses.dataclass(frozen=True)
class WireIngress:
    """One declared ingress point: a function that accepts client bytes
    and forwards them upstream.  ``function`` is ``module:qualname``
    inside the package; ``forward`` is the dotted call tail that puts
    bytes on the upstream socket.  lint/wire.py proves (CFG
    must-analysis) that every path from entry to a ``forward`` call
    strips every ``internal-stamped`` header first."""

    function: str
    forward: str
    summary: str


HEADERS: tuple[WireHeader, ...] = (
    WireHeader("x-lfkt-affinity", "inbound", "client-settable",
               "explicit client-side affinity pin (a conversation id); "
               "folded into the rendezvous key, sanitized before it "
               "reaches any log or forwarded header"),
    WireHeader("x-lfkt-affinity-key", "internal", "internal-stamped-must-strip",
               "router -> replica: the computed affinity key, recorded "
               "for graceful drain; inbound copies are stripped so a "
               "client cannot forge drain-manifest rows"),
    WireHeader("x-lfkt-prior-owner", "internal", "internal-stamped-must-strip",
               "router -> replica: the peer whose radix tree likely "
               "holds this conversation's KV pages (pull-on-remap); "
               "inbound copies are stripped so a client cannot command "
               "a KV pull from an arbitrary address"),
)


FIELDS: tuple[WireField, ...] = (
    WireField("rid", "REQ|PAGE|DONE|ERR", "peer-only",
              "per-connection request id correlating frames"),
    WireField("namespace", "REQ", "peer-only",
              "radix namespace (model name) the pages belong to"),
    WireField("ids", "REQ", "peer-only",
              "token ids of the prefix whose pages are requested"),
    WireField("deadline", "REQ", "peer-only",
              "absolute wall deadline; both sides abandon the transfer "
              "past it"),
    WireField("trace", "REQ", "peer-only",
              "W3C traceparent of the originating request (None when "
              "sampled out); the serving side opens a span tree linked "
              "to the same request id — never trusted for anything but "
              "trace correlation"),
    WireField("seq", "PAGE", "peer-only",
              "page-group sequence number within one transfer"),
    WireField("n_pages", "PAGE|DONE", "peer-only",
              "page count in this group / whole transfer"),
    WireField("tokens", "DONE", "peer-only",
              "token count covered by the transferred pages "
              "(cross-checked against n_pages * page_tokens)"),
    WireField("first_token", "DONE", "peer-only",
              "first sampled token from the remote prefill (None on "
              "migration pulls)"),
    WireField("code", "ERR", "peer-only",
              "machine-readable refusal reason (geometry | schema | "
              "deadline | request | export | prefill | protocol)"),
    WireField("error", "ERR", "peer-only",
              "human-readable refusal detail; sanitized before logging "
              "(a peer-supplied string is a log-injection vector)"),
    WireField("wire_schema", "HELLO|HELLO_OK", "peer-only",
              "wire schema version; mismatch refuses the handshake"),
    WireField("page_tokens", "HELLO", "peer-only",
              "tokens per KV page (geometry compatibility check)"),
    WireField("page_bytes", "HELLO", "peer-only",
              "payload bytes per page (geometry compatibility check)"),
    WireField("leaves", "HELLO", "peer-only",
              "per-leaf page shape/dtype list (geometry compatibility "
              "check)"),
    WireField("shape", "HELLO", "peer-only",
              "one leaf's per-page array shape (inside leaves[])"),
    WireField("dtype", "HELLO", "peer-only",
              "one leaf's dtype string (inside leaves[])"),
)


INGRESSES: tuple[WireIngress, ...] = (
    WireIngress("serving.fleet.router:FleetRouter._route",
                "_proxy_attempt",
                "the fleet router's client-facing routing path: raw "
                "request bytes in, proxied verbatim to a replica after "
                "the internal-stamp strip"),
)


def internal_stamped_headers() -> tuple[str, ...]:
    """The header names every declared ingress must strip."""
    return tuple(h.name for h in HEADERS if h.trust == INTERNAL_STAMPED)


def markdown_table() -> str:
    """The docs/WIRESURFACE.md tables — generated, never hand edited
    (lfkt-lint WIRE003 + a tier-1 test pin the docs block to this
    output byte-for-byte)."""
    rows = ["### HTTP headers", "",
            "| header | direction | trust | summary |",
            "|---|---|---|---|"]
    for h in HEADERS:
        rows.append(f"| `{h.name}` | {h.direction} | {h.trust} | "
                    f"{h.summary} |")
    rows += ["", "### Frame-header fields", "",
             "| field | frames | trust | summary |",
             "|---|---|---|---|"]
    for f in FIELDS:
        rows.append(f"| `{f.name}` | {f.frames} | {f.trust} | "
                    f"{f.summary} |")
    rows += ["", "### Ingress points", "",
             "| function | forwards via | summary |",
             "|---|---|---|"]
    for i in INGRESSES:
        rows.append(f"| `{i.function}` | `{i.forward}` | {i.summary} |")
    return "\n".join(rows)


if __name__ == "__main__":
    print(markdown_table())

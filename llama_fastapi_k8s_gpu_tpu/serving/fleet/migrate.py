"""Fleet-global KV survivability: warm-page migration between replicas.

The radix prefix cache (parallel/kvpool.py) is the fleet's most
valuable soft state, and without this module it dies with its pod:
every SIGKILL, scale event or deploy turns a warm conversation into a
cold prefill storm.  This module fuses the PR-13 KV-page wire
(serving/disagg/wire.py — HELLO/REQ/PAGE/DONE/ERR, unchanged schema)
with the PR-14 affinity router (serving/fleet/affinity.py — the router
knows which replica owns a conversation) so committed pages outlive
any single pod:

pull-on-remap
    When affinity remaps a conversation (owner died, was ejected, or
    the fleet scaled), the router stamps ``x-lfkt-prior-owner`` on the
    forwarded request and the newly-assigned replica pulls that
    conversation's radix pages from the prior owner BEFORE prefilling
    (server/app.py admission path).  ``KVPool.import_pages`` dedups
    against anything already cached; every wire failure degrades to
    local recompute with attribution
    (``kv_migration_failures_total{reason}`` + the /health
    ``migration`` block), bounded by the request's remaining deadline
    — never a hang.

graceful drain
    A DRAINING pod (SIGTERM → server/httpd.py, helm ``preStop``)
    pushes its hottest conversations to their rendezvous-successor
    peers before termination: for each recorded affinity key the
    successor is ``rendezvous_rank(key, fleet - self)[0]``, and the
    push is a COMMANDED PULL — ``POST /admin/migrate/pull`` on the
    successor, which pulls the pages over the wire from this pod's
    still-running page service.  Push failures degrade to normal
    termination with attribution; the whole loop is bounded by
    ``LFKT_MIGRATE_DRAIN_SECONDS``, never delaying shutdown past the
    budget.

scale-out warm-up
    A new replica pre-pulls the fleet's hottest shared prefixes
    (``GET /admin/migrate/hot`` on each peer → ``KVPool.hot_prefixes``)
    before going READY, so a scale-out event starts warm instead of
    absorbing a cold-start storm.

The page service (:class:`MigrationServer`) mirrors the disagg prefill
service (serving/disagg/prefiller.py) but serves ALREADY-COMMITTED
pages — ``match_len`` → ``acquire`` (pin) → ``export_pages`` → PAGE
frames — so it never touches the engine and a cold miss answers a
cheap ``DONE tokens=0``.  The ``migrate_push`` fault point fires
between PAGE groups (a puller sees a torn stream); ``migrate_pull``
fires inside the pull hop; ``drain_push`` inside the drain loop — all
drill-able via LFKT_FAULTS (tools/chaos_drill.py, tests/test_chaos.py).

Everything here is armed by ``LFKT_MIGRATE=1`` (requires
LFKT_KV_PAGED=1) and documented in docs/RUNBOOK.md "Surviving pod
churn".
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import socket
import threading
import time
from collections import OrderedDict

import numpy as np

from ...obs.logctx import sanitize_text
from ...obs.trace import TRACER, span_traceparent
from ...utils.faults import FAULTS, FaultError
from ..disagg import wire
from ..disagg.transport import FrameConn, FrameSender, connect
from .affinity import rendezvous_rank

logger = logging.getLogger(__name__)

#: handshake must complete promptly; the REQ loop then waits unbounded
#: (a peer holding its connection open between pulls is normal)
_HANDSHAKE_TIMEOUT_S = 30.0

#: affinity keys remembered for drain (newest evicts oldest): bounds the
#: drain candidate set AND this map's host RAM — ids are token lists, so
#: 512 entries of a 32k conversation is ~130 MB worst case, fine for a
#: serving pod and irrelevant for tests
_RECORD_CAP = 512


class MigrationServer:
    """Serves this replica's committed KV pages to pulling peers.

    Same wire conversation as the disagg prefill service — HELLO
    geometry handshake (incompatible pools refuse with attribution,
    never exchange bytes), then REQ → PAGE* → DONE — but backed by the
    pool's radix index instead of the engine: a request for ids this
    pod never cached answers ``DONE tokens=0`` without touching a
    device.  Pages are pinned (``acquire``) for exactly the export
    copy, so eviction can never tear an in-flight transfer.
    """

    # accept loop + one handler thread per peer; the sender registry and
    # counters cross threads under one mutex.  The listener/stop flag are
    # written once at construction/stop (reference stores).
    _GUARDED_BY = {"_senders": "_lock", "counters": "_lock"}
    _THREAD_ENTRIES = ("_accept_loop", "_serve_conn")
    _SHARED_ATOMIC = ("_stop", "_sock", "port", "metrics", "_tracer")

    def __init__(self, pool, host: str = "0.0.0.0", port: int = 0,
                 queue_frames: int = 32, metrics=None, tracer=None):
        self._pool = pool
        self._geometry = wire.pool_geometry(pool)
        self._queue_frames = max(1, int(queue_frames))
        self.metrics = metrics
        # the process tracer unless a test injects a private one; the
        # REQ's ``trace`` field (wire schema 2) links this pod's serve-
        # side span fragments under the pulling request's trace id
        self._tracer = tracer if tracer is not None else TRACER
        self._lock = threading.Lock()
        self._senders: dict[int, FrameSender] = {}
        self.counters = {"peers_total": 0, "pulls_served": 0,
                         "pulls_cold": 0, "pages_sent": 0, "bytes_sent": 0,
                         "handshake_refusals": 0, "request_errors": 0}
        self._stop = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(
            target=self._accept_loop, name="lfkt-migrate-accept",
            daemon=True)
        self._thread.start()
        logger.info("kv migration page service listening on %s:%d "
                    "(page_tokens=%d)", host, self.port, pool.page_tokens)

    # -- telemetry (never fails serving; the KVPool idiom) -----------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self.metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail serving
            pass

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def status(self) -> dict:
        """The /health ``migration.service`` block."""
        with self._lock:
            out = dict(self.counters)
            out["peers_connected"] = len(self._senders)
        out["port"] = self.port
        return out

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, peer = self._sock.accept()
            except OSError:
                return          # listener closed: stop()
            self._count("peers_total")
            threading.Thread(target=self._serve_conn, args=(sock, peer),
                             name="lfkt-migrate-peer", daemon=True).start()

    def _serve_conn(self, sock: socket.socket, peer) -> None:
        conn = FrameConn(sock)
        sender = None
        try:
            conn.settimeout(_HANDSHAKE_TIMEOUT_S)
            ftype, hello, _ = conn.recv_frame()
            if ftype != wire.FRAME_HELLO:
                conn.send_frame(wire.FRAME_ERR, {
                    "rid": None, "code": "protocol",
                    "error": f"expected HELLO, got "
                             f"{wire.FRAME_NAMES.get(ftype, ftype)}"})
                return
            mismatch = wire.geometry_mismatch(self._geometry, hello)
            if mismatch is not None:
                # the load-bearing refusal: two pools that cannot exchange
                # pages bit-exactly must never try — attribution instead
                # of corrupted KV
                self._count("handshake_refusals")
                logger.error("kv migration handshake refused for %s: %s",
                             peer, sanitize_text(mismatch))
                conn.send_frame(wire.FRAME_ERR, {
                    "rid": None, "code": "geometry", "error": mismatch})
                return
            conn.send_frame(wire.FRAME_HELLO_OK,
                            {"wire_schema": wire.WIRE_SCHEMA})
            conn.settimeout(None)
            sender = FrameSender(conn, self._queue_frames)
            with self._lock:
                self._senders[id(sender)] = sender
            while not self._stop:
                ftype, hdr, _ = conn.recv_frame()
                if ftype != wire.FRAME_REQ:
                    raise wire.WireError(
                        f"expected REQ, got "
                        f"{wire.FRAME_NAMES.get(ftype, ftype)}")
                self._serve_request(sender, hdr)
        except ConnectionError:
            logger.debug("kv migration peer left: %s", peer)
        except (wire.WireError, OSError, FaultError) as e:
            # includes the migrate_push drill (FaultError raised through
            # _serve_request's page loop): hard-close mid-stream — the
            # pulling side must degrade to local recompute, never hang
            logger.warning("kv migration peer %s dropped: %s", peer, e)
        except Exception:  # noqa: BLE001 — one peer must not kill the service
            logger.exception("kv migration peer handler failed for %s", peer)
        finally:
            if sender is not None:
                with self._lock:
                    self._senders.pop(id(sender), None)
                sender.close(join_timeout=0.5)
            conn.close()

    def _serve_request(self, sender: FrameSender, hdr: dict) -> None:
        # server-side fragment of the pulling request's trace: the REQ's
        # ``trace`` field (wire schema 2) carries the puller's span
        # context.  start_linked returns None unless this process samples
        # AND the field parsed — untraced pulls pay two cheap guards.
        trace = self._tracer.start_linked("kv.migrate.serve",
                                          hdr.get("trace"))
        try:
            self._serve_request_traced(sender, hdr, trace)
        finally:
            # None-tolerant; sweeps spans an error path left open
            # (auto_closed) so a torn transfer still exports a fragment
            self._tracer.finish(trace)

    def _serve_request_traced(self, sender: FrameSender, hdr: dict,
                              trace) -> None:
        rid = hdr.get("rid")
        ids = hdr.get("ids")
        ns = str(hdr.get("namespace") or "")
        deadline = hdr.get("deadline")
        if not isinstance(ids, list) or not ids \
                or not all(isinstance(t, int) for t in ids):
            if trace is not None:
                trace.root.set(error="request: bad ids")
            sender.put(wire.FRAME_ERR, {
                "rid": rid, "code": "request",
                "error": "REQ ids must be a non-empty list of ints"})
            return
        if trace is not None:
            # rid/namespace are peer-supplied — sanitize before they
            # ride the /debug/traces export and the waterfall renderer
            trace.root.set(rid=sanitize_text(rid, limit=64),
                           namespace=sanitize_text(ns, limit=64),
                           tokens=len(ids))

        def put_timeout() -> float:
            # backpressure bound: a send queue still full past the pull's
            # own deadline means the wire cannot carry this transfer in
            # time — tear it down rather than stall the pod
            if deadline is not None:
                return max(0.1, float(deadline) - time.time())
            return 30.0

        if deadline is not None and time.time() > float(deadline):
            sender.put(wire.FRAME_ERR, {
                "rid": rid, "code": "deadline",
                "error": "deadline expired before page export"})
            return
        pool = self._pool
        matched = pool.match_len(ids, namespace=ns)
        lease = (pool.acquire(ids[:matched], matched, namespace=ns)
                 if matched else None)
        if lease is None:
            # cold (or the pages were evicted between peek and pin): a
            # cheap honest miss — the puller recomputes locally
            self._count("pulls_cold")
            if trace is not None:
                trace.root.set(cold=True)
            sender.put(wire.FRAME_DONE, {"rid": rid, "tokens": 0,
                                         "n_pages": 0, "first_token": None},
                       timeout=put_timeout())
            return
        sp = trace.span("pool.export") if trace is not None else None
        try:
            try:
                leaves = pool.export_pages(lease)
            finally:
                # the export already holds host copies; unpin before the
                # (possibly slow) wire send so a stalled peer never holds
                # this pod's arena pages hostage
                pool.release(lease)
        except Exception as e:  # noqa: BLE001 — per-request isolation: the
            # pulling side degrades to local recompute with this attribution
            self._count("request_errors")
            logger.warning("kv migration export failed: %s", e)
            if sp is not None:
                sp.set(error=sanitize_text(
                    f"{type(e).__name__}: {e}", limit=256)).end()
            sender.put(wire.FRAME_ERR, {
                "rid": rid, "code": "export",
                "error": f"{type(e).__name__}: {e}"})
            return
        if sp is not None:
            sp.end()
        tokens = lease.tokens
        n_pages = tokens // pool.page_tokens
        # one span per wire transfer, one kv_pages event per PAGE group —
        # the waterfall's ▓ bar covers exactly the bytes-on-the-wire time
        sp_send = trace.span("wire.send") if trace is not None else None
        sent_bytes = 0
        off = seq = 0
        while off < n_pages:
            # drill point: the warm side dying MID-STREAM (FaultError
            # propagates to _serve_conn, which hard-closes the socket
            # between page groups — the puller sees a torn transfer)
            FAULTS.fire("migrate_push")
            g = min(wire.PAGE_GROUP, n_pages - off)
            payload = wire.encode_pages(
                [leaf[off:off + g] for leaf in leaves])
            sender.put(wire.FRAME_PAGE,
                       {"rid": rid, "seq": seq, "n_pages": g},
                       payload, timeout=put_timeout())
            self._count("pages_sent", g)
            self._count("bytes_sent", len(payload))
            if sp_send is not None:
                sent_bytes += len(payload)
                sp_send.event("kv_pages", seq=seq, pages=g,
                              bytes=len(payload))
            off += g
            seq += 1
        sender.put(wire.FRAME_DONE,
                   {"rid": rid, "tokens": tokens, "n_pages": n_pages,
                    "first_token": None}, timeout=put_timeout())
        if sp_send is not None:
            sp_send.set(pages=n_pages, bytes=sent_bytes).end()
        self._count("pulls_served")
        self._emit("inc", "kv_migration_pushes_total")
        self._emit("inc", "kv_migration_pages_total", n_pages,
                   reason="pushed")

    def stop_accepting(self) -> None:
        """Close the listener only: no NEW pullers, in-flight transfers
        keep streaming — a DRAINING pod's successors are still pulling
        from it (server/httpd.py drain window)."""
        try:
            self._sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stop = True
        self.stop_accepting()
        with self._lock:
            senders = list(self._senders.values())
            self._senders.clear()
        for s in senders:
            s.close(join_timeout=0.5)


class MigrationManager:
    """One replica's migration brain: the pull client, the affinity-key
    record used to find drain successors, warm-up and drain
    orchestration, and the /health ``migration`` block.

    Every public entry point is NEVER-RAISE and deadline-bounded: a
    migration that cannot complete degrades (to local recompute, or to
    plain termination) with an attributed reason — it must not take the
    serving path down with it.
    """

    # request-handler threads pull concurrently while the drain/warm-up
    # paths read the record map; one mutex guards the shared dicts and
    # counters.  Pull hops use a FRESH connection each (no shared conn
    # state), so no hop lock exists to rank against.
    _GUARDED_BY = {"_records": "_lock", "_wire_cache": "_lock",
                   "counters": "_lock", "last_error": "_lock",
                   "_last_key_digest": "_lock"}
    _SHARED_ATOMIC = ("metrics", "_closed")

    def __init__(self, pool, settings, metrics=None, health=None,
                 server: MigrationServer | None = None):
        self._pool = pool
        self._geometry = wire.pool_geometry(pool)
        self.settings = settings
        self.metrics = metrics
        self.health = health
        self.server = server
        self.timeout = float(settings.migrate_timeout_seconds)
        self.top_k = int(settings.migrate_top_k)
        self.drain_budget = float(settings.migrate_drain_seconds)
        self._lock = threading.Lock()
        #: affinity key -> (namespace, ids tuple), newest last (LRU)
        self._records: OrderedDict[str, tuple[str, tuple]] = OrderedDict()
        #: peer HTTP addr -> wire "host:port" (dropped on pull failure)
        self._wire_cache: dict[str, str] = {}
        self.counters = {"pulls": 0, "pulled_pages": 0, "pulled_tokens": 0,
                         "skipped_warm": 0, "failures": 0,
                         "drain_pushes": 0, "drain_failures": 0,
                         "warmup_pulls": 0}
        self.last_error = None
        #: digest of the most recent router-stamped affinity key — the
        #: incident-bundle attribution linking a replica's capture to the
        #: conversation it was serving (never the raw client-settable key)
        self._last_key_digest = None
        self._closed = False

    # -- identity ----------------------------------------------------------
    @property
    def wire_addr(self) -> str:
        """This pod's page-service address as PEERS can reach it: the
        fleet-visible host (LFKT_MIGRATE_SELF) + the service's actual
        bound port (ephemeral ports work in tests)."""
        host = (self.settings.migrate_self.rpartition(":")[0]
                or (self.settings.migrate_bind
                    if self.settings.migrate_bind not in ("", "0.0.0.0")
                    else "127.0.0.1"))
        port = self.server.port if self.server is not None else 0
        return f"{host}:{port}"

    def _others(self) -> list[str]:
        """The OTHER replicas' HTTP addrs — warm-up sources and drain
        successors.  LFKT_FLEET_PEERS minus LFKT_MIGRATE_SELF; when the
        static list is empty, one headless-Service DNS resolution
        (LFKT_FLEET_DNS, the peers.py discovery idiom) so k8s replicas
        need no peer list baked into the pod spec."""
        me = self.settings.migrate_self.strip()
        out = [a.strip() for a in self.settings.fleet_peers.split(",")
               if a.strip() and a.strip() != me]
        if not out and self.settings.fleet_dns:
            name, _, port = self.settings.fleet_dns.rpartition(":")
            try:
                infos = socket.getaddrinfo(name, int(port),
                                           type=socket.SOCK_STREAM)
            except (OSError, ValueError) as e:
                self._fail("resolve",
                           f"fleet DNS {self.settings.fleet_dns}: {e}")
                return []
            out = sorted({f"{info[4][0]}:{port}" for info in infos}
                         - {me})
        return out

    # -- telemetry ---------------------------------------------------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self.metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail serving
            pass

    def _fail(self, reason: str, msg: str, *, drain: bool = False) -> int:
        """Attribute one degraded migration attempt; always returns 0 so
        callers can ``return self._fail(...)``."""
        # msg (and sometimes reason — callers pass the wire-frame "code"
        # field through) carries peer-supplied bytes — sanitize before
        # the log line and the /health last_error echo
        reason = sanitize_text(reason, limit=64)
        msg = sanitize_text(msg)
        with self._lock:
            self.counters["drain_failures" if drain else "failures"] += 1
            self.last_error = f"{reason}: {msg}"
        self._emit("inc", "kv_migration_failures_total", reason=reason)
        logger.warning("kv migration degraded (%s): %s", reason, msg)
        return 0

    def status(self) -> dict:
        """The /health ``migration`` block: the wire addr peers resolve
        through, every counter, and the last attributed failure."""
        with self._lock:
            out = {"addr": self.wire_addr, "counters": dict(self.counters),
                   "records": len(self._records),
                   "last_affinity_key": self._last_key_digest,
                   "last_error": self.last_error}
        if self.server is not None:
            out["service"] = self.server.status()
        return out

    # -- conversation recording (drain's candidate set) --------------------
    def record_prompt(self, key: str, namespace: str, ids) -> None:
        """Remember the latest prompt ids for an affinity key — the
        router stamps ``x-lfkt-affinity-key`` on every proxied request,
        and graceful drain replays this map to the keys'
        rendezvous-successor peers."""
        if not key or not ids:
            return
        # digest, never the raw key: affinity keys can carry raw
        # client-settable header bytes, and this value rides /health and
        # the incident bundle's fleet block
        digest = hashlib.sha256(str(key).encode(
            "utf-8", "replace")).hexdigest()[:16]
        with self._lock:
            self._records.pop(key, None)
            self._records[key] = (str(namespace), tuple(ids))
            self._last_key_digest = digest
            while len(self._records) > _RECORD_CAP:
                self._records.popitem(last=False)

    # -- peer resolution ---------------------------------------------------
    def _http_json(self, addr: str, method: str, path: str,
                   body: dict | None, timeout: float) -> dict:
        """One bounded JSON round-trip to a peer's HTTP port (raises on
        any failure — callers attribute)."""
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload,
                         headers={"content-type": "application/json"}
                         if payload else {})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise OSError(f"{method} {path} -> {resp.status}")
            return json.loads(data)
        finally:
            conn.close()

    def _resolve_wire(self, http_addr: str, budget: float) -> str | None:  # lfkt: sanitizes[peer-http] -- http_addr comes from the admitted PeerTable (or the router's commanded drain), so the /health doc it fetches is as trusted as the peer set itself; the addr:port shape check below bounds what a misbehaving peer can redirect a pull to
        """A peer's page-service wire addr, via its /health ``migration``
        block (cached; ephemeral ports make this discovery, not config)."""
        with self._lock:
            cached = self._wire_cache.get(http_addr)
        if cached:
            return cached
        try:
            doc = self._http_json(http_addr, "GET", "/health", None,
                                  max(0.1, budget))
            addr = doc.get("migration", {}).get("addr")
        except (OSError, ValueError, http.client.HTTPException) as e:
            self._fail("resolve", f"{http_addr}: {type(e).__name__}: {e}")
            return None
        if not addr or ":" not in str(addr):
            self._fail("resolve", f"{http_addr} has no migration service "
                                  "(LFKT_MIGRATE off or mixed rollout)")
            return None
        with self._lock:
            self._wire_cache[http_addr] = str(addr)
        return str(addr)

    def _drop_wire(self, http_addr: str | None) -> None:
        if http_addr is None:
            return
        with self._lock:
            self._wire_cache.pop(http_addr, None)

    # -- the pull hop ------------------------------------------------------
    def pull(self, peer_wire: str, ids, *, namespace: str = "",
             reason: str = "remap", deadline: float | None = None,
             span=None) -> int:
        """Pull the whole-page prefix of ``ids`` from ``peer_wire``
        (``host:port`` of a peer's page service) into the local pool.
        Returns tokens now covered locally; NEVER raises — every failure
        path attributes a reason and returns 0 (the caller's local
        recompute is always correct, just colder).  Budget = the hop
        knob clipped to the request's remaining ``deadline``."""
        if self._closed:
            return 0
        pool = self._pool
        T = pool.page_tokens
        n = len(ids)
        # a remap pull feeds an imminent prefill, which needs >= 1
        # uncached token; warm-up/drain rows are whole cached runs
        target = (((n - 1) // T) * T) if reason == "remap" else ((n // T) * T)
        if target < T:
            return 0
        if pool.match_len(ids[:target], namespace=namespace) >= target:
            with self._lock:
                self.counters["skipped_warm"] += 1
            return target
        budget = self.timeout
        if deadline is not None:
            budget = min(budget, deadline - time.time())
        if budget <= 0:
            return self._fail("deadline", f"no time left to pull from "
                                          f"{peer_wire}")
        self._emit("inc", "kv_migration_pulls_total", reason=reason)
        with self._lock:
            self.counters["pulls"] += 1
            if reason == "warmup":
                self.counters["warmup_pulls"] += 1
        host, _, port = peer_wire.rpartition(":")
        t0 = time.time()
        conn = None
        rid = f"mig-{reason}-{t0:.6f}"
        try:
            # drill point: error mode degrades this hop with attribution,
            # slow mode eats the budget (the deadline math below must
            # still bound the hop — never a hang)
            FAULTS.fire("migrate_pull")
            conn = connect(host, int(port), max(0.1, budget))
            conn.send_frame(wire.FRAME_HELLO, self._geometry)
            ftype, hdr, _ = conn.recv_frame()
            if ftype == wire.FRAME_ERR:
                return self._fail(str(hdr.get("code") or "refused"),
                                  f"{peer_wire}: {hdr.get('error')}")
            if ftype != wire.FRAME_HELLO_OK:
                return self._fail("protocol",
                                  f"{peer_wire}: expected HELLO_OK, got "
                                  f"{wire.FRAME_NAMES.get(ftype, ftype)}")
            # wire schema 2: the REQ carries the caller's span context
            # (None when sampled out) so the warm side's span tree links
            # under the pulling request's trace id
            conn.send_frame(wire.FRAME_REQ, {
                "rid": rid, "namespace": namespace,
                "ids": [int(t) for t in ids[:target]],
                "deadline": time.time() + max(0.1,
                                              budget - (time.time() - t0)),
                "trace": span_traceparent(span)})
            groups: list[list] = []
            got_pages = 0
            wire_bytes = 0
            while True:
                remaining = budget - (time.time() - t0)
                if remaining <= 0:
                    return self._fail("deadline",
                                      f"pull from {peer_wire} overran its "
                                      f"{budget:.1f}s budget")
                conn.settimeout(remaining)
                ftype, hdr, payload = conn.recv_frame()
                if ftype == wire.FRAME_PAGE:
                    g = int(hdr.get("n_pages") or 0)
                    groups.append(wire.decode_pages(payload, g,
                                                    self._geometry))
                    got_pages += g
                    wire_bytes += len(payload)
                    continue
                if ftype == wire.FRAME_ERR:
                    return self._fail(str(hdr.get("code") or "refused"),
                                      f"{peer_wire}: {hdr.get('error')}")
                if ftype == wire.FRAME_DONE:
                    tokens = int(hdr.get("tokens") or 0)
                    if tokens != got_pages * T:
                        return self._fail(
                            "wire", f"{peer_wire}: DONE claims {tokens} "
                                    f"tokens but {got_pages} pages arrived")
                    break
                return self._fail("protocol",
                                  f"{peer_wire}: unexpected "
                                  f"{wire.FRAME_NAMES.get(ftype, ftype)}")
            if tokens <= 0:
                return 0        # honest cold miss on the far side
            leaves = [np.concatenate([g[i] for g in groups], axis=0)
                      for i in range(len(groups[0]))]
            try:
                covered = pool.import_pages(ids[:tokens], leaves,
                                            namespace=namespace, span=span)
            except Exception as e:  # noqa: BLE001 — a rejected import is
                # one degraded pull, not a pod failure
                return self._fail("import", f"{type(e).__name__}: {e}")
            dt = time.time() - t0
            with self._lock:
                self.counters["pulled_pages"] += got_pages
                self.counters["pulled_tokens"] += covered
            self._emit("inc", "kv_migration_pages_total", got_pages,
                       reason="pulled")
            self._emit("observe", "kv_migration_seconds", dt)
            if span is not None:
                try:
                    span.event("kv_migrate_pull", peer=peer_wire,
                               reason=reason, pages=got_pages,
                               tokens=covered, bytes=wire_bytes,
                               host_s=round(dt, 6))
                except Exception:  # noqa: BLE001 — tracing never fails pulls
                    pass
            return covered
        except (wire.WireError, ConnectionError, OSError, FaultError) as e:
            return self._fail("wire", f"{peer_wire}: {type(e).__name__}: {e}")
        finally:
            if conn is not None:
                conn.close()

    # -- the three triggers ------------------------------------------------
    def pull_for_request(self, prior_http: str, namespace: str, ids,
                         deadline: float | None = None, span=None) -> int:
        """Pull-on-remap (server/app.py admission): ``prior_http`` is the
        router's ``x-lfkt-prior-owner`` stamp (an HTTP addr)."""
        budget = self.timeout
        if deadline is not None:
            budget = min(budget, deadline - time.time())
        peer = self._resolve_wire(prior_http, budget)
        if peer is None:
            return 0
        got = self.pull(peer, ids, namespace=namespace, reason="remap",
                        deadline=deadline, span=span)
        if got == 0:
            # a dead prior owner must not poison the cache for the next
            # remap (its replacement pod will answer /health afresh)
            self._drop_wire(prior_http)
        return got

    def warm_up(self) -> int:
        """Scale-out warm-up (server/app.py startup, BEFORE READY):
        pre-pull every peer's hottest prefixes.  Bounded by the drain
        budget — a slow fleet delays readiness by at most that, never
        indefinitely.  Returns tokens pulled."""
        t0 = time.time()
        total = 0
        for peer_http in self._others():
            remaining = self.drain_budget - (time.time() - t0)
            if remaining <= 0:
                self._fail("deadline", "warm-up budget exhausted with "
                                       "peers left unvisited")
                break
            try:
                doc = self._http_json(peer_http, "GET",
                                      f"/admin/migrate/hot?k={self.top_k}",
                                      None, max(0.1, min(remaining,
                                                         self.timeout)))
                rows = doc.get("prefixes") or []
            except (OSError, ValueError, http.client.HTTPException) as e:
                self._fail("resolve",
                           f"{peer_http}: {type(e).__name__}: {e}")
                continue
            peer_wire = self._resolve_wire(
                peer_http, max(0.1, min(remaining, self.timeout)))
            if peer_wire is None:
                continue
            for row in rows:
                remaining = self.drain_budget - (time.time() - t0)
                if remaining <= 0:
                    break
                total += self.pull(peer_wire, list(row.get("ids") or []),
                                   namespace=str(row.get("namespace") or ""),
                                   reason="warmup",
                                   deadline=time.time() + remaining)
        if total:
            logger.info("kv migration warm-up pulled %d tokens in %.2fs",
                        total, time.time() - t0)
        return total

    def drain_push(self) -> int:
        """Graceful drain (server/httpd.py SIGTERM window): command each
        recorded conversation's rendezvous successor to pull it from
        this pod's still-open page service.  Bounded by
        LFKT_MIGRATE_DRAIN_SECONDS; every failure degrades to normal
        termination with attribution.  Returns conversations handed
        off."""
        self._closed = True          # no new outbound pulls from this pod
        others = self._others()
        if not others:
            return 0
        with self._lock:
            newest_first = list(reversed(self._records.items()))
        rows = [(key, ns, list(ids))
                for key, (ns, ids) in newest_first[:self.top_k]]
        if not rows:
            # no router-stamped traffic seen (direct serving): hand the
            # pool's hottest runs to the first peer so they survive anyway
            rows = [(None, str(r["namespace"]), list(r["ids"]))
                    for r in self._pool.hot_prefixes(self.top_k)]
        t0 = time.time()
        pushed = 0
        for key, ns, ids in rows:
            remaining = self.drain_budget - (time.time() - t0)
            if remaining <= 0:
                self._fail("deadline", "drain budget exhausted with "
                                       f"{len(rows) - pushed} conversations "
                                       "left", drain=True)
                break
            successor = (rendezvous_rank(key, others)[0] if key
                         else others[0])
            try:
                # drill point: a failed handoff must degrade to normal
                # termination (attributed), never delay shutdown
                FAULTS.fire("drain_push")
                self._http_json(
                    successor, "POST", "/admin/migrate/pull",
                    {"namespace": ns, "ids": [int(t) for t in ids],
                     "peer": self.wire_addr,
                     "deadline": time.time() + max(0.1, min(remaining,
                                                            self.timeout))},
                    max(0.1, min(remaining, self.timeout)))
            except (OSError, ValueError, http.client.HTTPException,
                    FaultError) as e:
                self._fail("drain_push",
                           f"{successor}: {type(e).__name__}: {e}",
                           drain=True)
                continue
            pushed += 1
            with self._lock:
                self.counters["drain_pushes"] += 1
        logger.info("kv migration drain pushed %d/%d conversations in "
                    "%.2fs", pushed, len(rows), time.time() - t0)
        return pushed

    def close(self) -> None:
        self._closed = True
        if self.server is not None:
            self.server.stop()


def build_migration(engine, settings, metrics=None,
                    health=None) -> MigrationManager:
    """Arm warm-page migration for one replica (``LFKT_MIGRATE=1``):
    the page service + the manager, warm-up NOT yet run (the caller
    runs it before flipping READY).  Misconfiguration refuses loudly —
    a fleet silently serving cold is the failure mode this module
    exists to kill."""
    pool = getattr(engine, "_kvpool", None)
    if pool is None:
        raise ValueError(
            "LFKT_MIGRATE=1 requires LFKT_KV_PAGED=1: migration moves "
            "radix KV pages, and only the paged arena has them "
            "(docs/RUNBOOK.md 'Surviving pod churn')")
    server = MigrationServer(pool, host=settings.migrate_bind,
                             port=settings.migrate_port, metrics=metrics)
    return MigrationManager(pool, settings, metrics=metrics, health=health,
                            server=server)

"""Prefix-affinity keys + rendezvous hashing — the router's brain.

The radix prefix cache (parallel/kvpool.py) makes a replica *warm* for
the conversations it has served: the persona/system prompt and the whole
history sit as committed KV pages.  k8s round-robin scatters a
conversation's turns across replicas, so every turn is cold somewhere.
The fix is a STABLE key per conversation, derived from exactly the
content the radix tree keys on — the request's prefix:

- an explicit ``x-lfkt-affinity`` header wins (clients that know their
  conversation id pin themselves);
- ``/response``/``/response/stream`` bodies key on the bot profile (the
  persona IS the system prompt) plus the conversation's FIRST user
  message — both are byte-stable across every later turn, while the
  tail of the history grows;
- ``/v1/chat/completions`` bodies key on the OpenAI ``user`` field when
  set, else on (model, first system message, first user message) — the
  same stable-prefix argument;
- anything else falls back to a digest of the body (or the path for
  bodyless requests), which is at least deterministic: retries of one
  request land on one replica.

The key then picks its owner by **rendezvous (HRW) hashing** over the
replica set: each (key, peer) pair scores ``sha256(key|peer)`` and the
highest score owns the key.  Properties the router leans on: stable
under peer-set changes (removing a peer remaps ONLY that peer's keys —
no mass cache invalidation, unlike modulo hashing), and the sorted
score order IS the spill order — when the owner is ejected the request
goes to rendezvous-next, which will own the key again after the next
ejection, so a flapping fleet still concentrates each conversation on
as few replicas as possible.  sha256, not ``hash()``: Python's string
hash is per-process salted and the ranking must agree across router
restarts (and between the router and anyone reproducing a routing
decision from a log).
"""

from __future__ import annotations

import hashlib
import json

from ...obs.logctx import sanitize_text

#: explicit client-side affinity pin (e.g. a conversation id)
AFFINITY_HEADER = "x-lfkt-affinity"

#: router → replica migration stamps (serving/fleet/migrate.py): the
#: computed affinity key (the replica records it for graceful drain) and
#: the peer whose radix tree likely still holds this conversation's KV
#: pages (the replica pulls from it before prefilling — pull-on-remap).
#: Router-owned: inbound copies of these are stripped before forwarding,
#: so a client can never command a replica to pull from an arbitrary
#: address.
AFFINITY_KEY_HEADER = "x-lfkt-affinity-key"
PRIOR_OWNER_HEADER = "x-lfkt-prior-owner"

#: stable-prefix bytes folded into a derived key: enough to separate
#: conversations, bounded so a megabyte opener doesn't cost a megabyte
#: of hashing per request
_PREFIX_CHARS = 512


def _sha(*parts: str) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode("utf-8", "replace"))
        h.update(b"\x00")
    return h.hexdigest()[:32]


def _first_content(messages, role: str) -> str:
    for m in messages:
        if isinstance(m, dict) and m.get("role") == role:
            return str(m.get("content", ""))[:_PREFIX_CHARS]
    return ""


def affinity_key(path: str, headers: dict, body: bytes) -> tuple[str, str]:
    """(key, source) for one request.  ``source`` labels how the key was
    derived (``header`` | ``conversation`` | ``prefix`` | ``opaque``) —
    the router's ``fleet_requests_total`` attribution.  Never raises:
    an unparseable body degrades to the opaque digest."""
    hdr = headers.get(AFFINITY_HEADER, "")
    if hdr:
        # client-settable bytes that ride into the forwarded
        # x-lfkt-affinity-key stamp and the access log — strip control
        # bytes (header splitting / log forging) before either
        return "h:" + sanitize_text(hdr, limit=128), "header"
    doc = None
    if body:
        try:
            doc = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            doc = None
    if isinstance(doc, dict):
        if path.startswith("/v1/"):
            user = doc.get("user")
            if isinstance(user, str) and user:
                # body-supplied bytes that, like the explicit header,
                # ride into the forwarded stamp and the access log
                return "u:" + sanitize_text(user, limit=128), "conversation"
            msgs = doc.get("messages") or []
            sys_c = _first_content(msgs, "system")
            usr_c = _first_content(msgs, "user")
            if sys_c or usr_c:
                return ("p:" + _sha(str(doc.get("model", "")), sys_c,
                                    usr_c), "prefix")
        else:
            bp = doc.get("bot_profile") or {}
            ctx = doc.get("context") or []
            opener = ""
            if ctx and isinstance(ctx[0], dict):
                opener = str(ctx[0].get("message", ""))[:_PREFIX_CHARS]
            name = str(bp.get("name", "")) if isinstance(bp, dict) else ""
            sysp = (str(bp.get("system_prompt", ""))[:_PREFIX_CHARS]
                    if isinstance(bp, dict) else "")
            if name or sysp or opener:
                return "p:" + _sha(name, sysp, opener), "prefix"
    if body:
        return "o:" + hashlib.sha256(body).hexdigest()[:32], "opaque"
    return "o:" + _sha(path), "opaque"


def rendezvous_rank(key: str, peers: list[str]) -> list[str]:
    """Peers ordered by rendezvous score for ``key``, best first.  The
    head is the key's owner; the tail is the spill order when the owner
    is ejected."""
    return sorted(
        peers,
        key=lambda p: hashlib.sha256(f"{key}|{p}".encode()).digest(),
        reverse=True)

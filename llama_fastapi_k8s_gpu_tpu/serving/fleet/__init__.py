"""The fleet tier: prefix-affinity routing + live-reload plumbing above
the pod (ROADMAP item 5; docs/RUNBOOK.md "Running a replica fleet").

The radix prefix cache (PR 6) banked a 0.917 hit ratio and a 4x warm-
TTFT win — per POD.  Above one replica, k8s round-robin scatters a
conversation's turns across pods, so the warm pages sit on replica A
while the turn lands on replica B and the win evaporates.  This package
is the layer above the pod:

- affinity.py — stable per-conversation keys from the request's prefix
  content + rendezvous (HRW) hashing over the replica set
- peers.py    — the health-aware peer table: ``LFKT_FLEET_PEERS`` or
  headless-Service DNS discovery, ``/health/ready`` probing, ejection
  with exponential backoff, re-admission
- router.py   — the proxy process (``LFKT_FLEET_ROLE=router``): raw
  streaming passthrough (routed bytes == direct bytes, pinned by the
  ci_gate ``fleet-route-parity`` check), spill-to-rendezvous-next with
  attribution, never a hang or a fleet-wide 502 for one dead pod
- admin.py    — the live-reload client (``python -m ...fleet.admin``)
  for the replica-side ``POST /admin/models/reload`` surface
  (serving/registry.py ``reload_manifest``)

The replica side of the story — manifest diff/reload, namespace drain,
``loading|ready|draining`` model states — lives in serving/registry.py
and parallel/kvpool.py; the router needs none of it (and none of jax:
a router pod is a few MB of stdlib).
"""

from __future__ import annotations

#: valid LFKT_FLEET_ROLE values (utils/config.py).  Replicas are plain
#: serving pods (role stays "off"); only the router changes process type.
FLEET_ROLES = ("off", "router")


def build_router(settings, metrics=None, tracer=None):
    """A ready-to-serve :class:`FleetRouter` from the fleet knobs, with
    the peer table probed once synchronously (the router never starts
    blind).  Misconfiguration refuses loudly — the LFKT_WORKERS idiom —
    instead of routing into an empty fleet.  ``tracer`` (an
    obs.trace.Tracer; the process-wide one honours ``LFKT_TRACE_*``)
    arms router-side span production and the fleet trace collector."""
    from .peers import PeerTable
    from .router import FleetRouter

    peers = [p.strip() for p in settings.fleet_peers.split(",")
             if p.strip()]
    table = PeerTable(
        peers=peers, dns=settings.fleet_dns,
        probe_seconds=settings.fleet_probe_seconds,
        backoff_seconds=settings.fleet_eject_backoff_seconds,
        backoff_max=settings.fleet_eject_backoff_max,
        probe_timeout=settings.fleet_proxy_timeout_seconds,
        metrics=metrics).start()
    return FleetRouter(
        table, policy=settings.fleet_policy, metrics=metrics,
        proxy_timeout=settings.fleet_proxy_timeout_seconds,
        stream_timeout=settings.stream_deadline_seconds,
        max_spills=settings.fleet_max_spills,
        fresh_seconds=settings.migrate_fresh_seconds,
        tracer=tracer)


def run_router(host: str, port: int) -> None:
    """``LFKT_FLEET_ROLE=router`` entry point (server/__main__.py): build
    the peer table + router from settings and serve until SIGTERM.  No
    engine, no jax — the router is a placement process."""
    import asyncio

    from ...obs.flightrec import FLIGHTREC
    from ...obs.trace import TRACER
    from ...utils.config import get_settings
    from ...utils.metrics import Metrics

    settings = get_settings()
    # the process-wide tracer honours LFKT_TRACE_SAMPLE/LFKT_TRACE_RING
    # (helm plumbs both onto the router pod); incident bundles recorded
    # by the router carry its fleet identity
    router = build_router(settings, metrics=Metrics(), tracer=TRACER)
    FLIGHTREC.install(fleet=lambda: {
        "role": "router",
        "policy": router.policy,
        "peers": router.peers.snapshot()})
    asyncio.run(router.serve(host, port))

"""Fleet admin client — drive a replica's live-reload surface from a
terminal (or a CI job) without remembering the wire shapes:

    # swap the manifest on one replica (no pod restart)
    python -m llama_fastapi_k8s_gpu_tpu.serving.fleet.admin \\
        --peer 10.0.0.7:8000 reload \\
        --models "llama8b=Llama-3-8B.Q4_K_M.gguf,phi=phi.gguf"

    # re-read the replica's own LFKT_MODELS env (the SIGHUP twin)
    python -m ...fleet.admin --peer host:port reload

    # the live model set / the health document
    python -m ...fleet.admin --peer host:port models
    python -m ...fleet.admin --peer host:port health

``reload`` POSTs ``/admin/models/reload`` (server/app.py) and prints the
replica's reload report; nonzero exit on refusal (HTTP 4xx/5xx), with
the replica's attributed reason on stderr — a weight-budget refusal
names the model and the byte table, a grammar error names the offending
manifest entry.  Rolling a fleet = this command per replica, behind the
router's health-aware ejection (a reloading replica that drops READY is
routed around automatically).  Operations guide: docs/RUNBOOK.md
"Running a replica fleet".
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys


def _request(peer: str, method: str, path: str, body: dict | None = None,
             timeout: float = 600.0) -> tuple[int, dict | str]:
    """One HTTP round trip to ``peer``; (status, parsed-or-raw body).
    The generous default timeout covers a multi-GB model load — reload
    answers only after the added engines are warm."""
    host, _, port = peer.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        raw = resp.read().decode("utf-8", "replace")
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, raw
    finally:
        conn.close()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m llama_fastapi_k8s_gpu_tpu.serving.fleet.admin",
        description="live-reload admin client for a serving replica")
    ap.add_argument("--peer", required=True, help="replica host:port")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="HTTP timeout (reload waits for the load+warmup)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rl = sub.add_parser("reload", help="POST /admin/models/reload")
    rl.add_argument("--models", default="",
                    help="new LFKT_MODELS manifest (empty = the replica "
                         "re-reads its own env)")
    rl.add_argument("--default-model", default="",
                    help="new default alias (empty = first manifest entry)")
    sub.add_parser("models", help="GET /v1/models")
    sub.add_parser("health", help="GET /health")
    args = ap.parse_args(argv)

    if args.cmd == "reload":
        body: dict = {}
        if args.models:
            body["models"] = args.models
        if args.default_model:
            body["default_model"] = args.default_model
        status, doc = _request(args.peer, "POST", "/admin/models/reload",
                               body, timeout=args.timeout)
    elif args.cmd == "models":
        status, doc = _request(args.peer, "GET", "/v1/models",
                               timeout=args.timeout)
    else:
        status, doc = _request(args.peer, "GET", "/health",
                               timeout=args.timeout)

    text = json.dumps(doc, indent=1) if isinstance(doc, dict) else str(doc)
    if status >= 400:
        print(f"{args.peer} -> HTTP {status}\n{text}", file=sys.stderr)
        return 1
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""The fleet router: a thin prefix-affinity HTTP proxy over the replicas.

``LFKT_FLEET_ROLE=router`` runs this instead of a serving app — no
model, no jax, one asyncio loop.  Every request is keyed by
:func:`..fleet.affinity.affinity_key` and proxied to the replica that
rendezvous-hashing says owns the key (``serving/fleet/affinity.py``),
so a conversation's turns keep landing on the replica whose radix tree
already holds their KV pages.  ``policy="roundrobin"`` is the A/B
control arm (``bench_server.py`` fleet arm; ``LFKT_FLEET_POLICY``).

Proxying is RAW: the backend's status line, headers (minus hop-by-hop
connection signaling) and body bytes are relayed verbatim as they
arrive — so streaming SSE passes through chunk by chunk, and a greedy
completion through the router is byte-identical to direct-to-replica
(pinned by tests/test_fleet.py + the ci_gate ``fleet-route-parity``
check).  One request per client connection (``connection: close``):
the router's job is placement, not connection pooling.

Failure contract — never a hang, never a 502 for one dead pod:

- connect/head failure BEFORE any response byte reached the client:
  eject the peer with attribution (peers.py) and retry the request on
  the rendezvous-NEXT healthy peer (``fleet_spills_total{reason}``);
  only when EVERY replica refused does the client see a 503.
- failure MID-RESPONSE (bytes already forwarded): the peer is ejected,
  the client connection closes (the router cannot replay a partially
  delivered generation), and the client's retry — a fresh request —
  spills to the survivor.
- every backend read rides a deadline: connect/head on
  ``LFKT_FLEET_PROXY_TIMEOUT_SECONDS``, body progress on the stream
  wall budget (``LFKT_STREAM_DEADLINE_SECONDS``).

The router answers ``/health`` (role, policy, per-peer state with
attributed ejection reasons), ``/health/ready`` (200 iff >= 1 healthy
replica — k8s stops routing to a router whose whole fleet is down),
``/health/live`` and ``/metrics`` (the ``fleet_*`` families) itself;
everything else is proxied.

Fleet observability (lfkt-fleetobs; obs/fleettrace.py):

- the router mints/ingests W3C ``traceparent`` and opens real spans per
  proxy attempt (peer pick, spill/retry, response-head wait, stream
  relay), stamping each outbound hop with the ATTEMPT span as parent —
  so the replica's own trace fragment grafts under the exact attempt
  that carried it.  Sampled out (``LFKT_TRACE_SAMPLE=0``) or
  tracer-less, the relay path constructs no span at all and the inbound
  ``traceparent`` passes through verbatim (zero-cost contract, pinned
  by the poisoned-span test).
- ``GET /debug/fleet/traces/{id}`` pulls that request id's fragments
  from every healthy peer and returns ONE stitched multi-process tree.
- ``GET /metrics/fleet`` federates peer scrapes (counters summed,
  histograms merged bucket-wise, gauges re-labeled by peer) and
  evaluates the SLO catalog over the MERGED distributions —
  ``slo_burn_rate{scope="fleet"}`` rides the same body; ``GET
  /debug/slo`` returns the fleet verdict document.
- every proxy attempt writes one JSON access record (request id, chosen
  peer, spill count) via obs/logctx.py, joinable with replica access
  lines through the shared request id.
- a peer ejection triggers a correlated incident pull
  (``fleet_peer_ejected`` flight-recorder bundle).
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import signal
import time
import uuid

from .affinity import (AFFINITY_KEY_HEADER, PRIOR_OWNER_HEADER,
                       affinity_key, rendezvous_rank)
from ...obs import fleettrace
from ...obs.logctx import access_logger, bind_request_id, sanitize_text
from ...obs.trace import parse_traceparent, span_traceparent

logger = logging.getLogger(__name__)

#: /debug/fleet/traces/{id}: ids are 32-hex by construction (obs/trace);
#: anything else is refused before it can ride an outbound peer URL
_TRACE_ID_RE = re.compile(r"[0-9a-f]{32}")

#: response head elements the proxy rewrites rather than relays:
#: connection signaling is hop-by-hop (RFC 9110 §7.6.1)
_HOP_HEADERS = (b"connection", b"keep-alive", b"proxy-connection")

_READ_CHUNK = 65536


class _BackendError(Exception):
    """One proxy attempt failed against one peer (reason attributed)."""

    def __init__(self, reason: str, mid_stream: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.mid_stream = mid_stream


class FleetRouter:
    """See module docstring.  ``peers`` is a started
    :class:`..fleet.peers.PeerTable`; ``metrics`` a
    :class:`...utils.metrics.Metrics` registry (the catalog enforces the
    ``fleet_*`` families)."""

    def __init__(self, peers, policy: str = "affinity", metrics=None,
                 proxy_timeout: float = 5.0,
                 stream_timeout: float = 300.0,
                 max_spills: int = 3,
                 fresh_seconds: float = 600.0,
                 tracer=None):
        if policy not in ("affinity", "roundrobin"):
            raise ValueError(
                f"LFKT_FLEET_POLICY must be affinity|roundrobin, "
                f"got {policy!r}")
        self.peers = peers
        self.policy = policy
        self.metrics = metrics
        #: obs.trace.Tracer (None = no router-side tracing at all; the
        #: inbound traceparent still relays verbatim)
        self.tracer = tracer
        self.proxy_timeout = proxy_timeout
        self.stream_timeout = stream_timeout
        self.max_spills = max(0, int(max_spills))
        self.fresh_seconds = float(fresh_seconds)
        self._rr = 0
        self.started = int(time.time())
        #: monotonic counters for /health (the /metrics twins are inc'd
        #: at event time); plain ints mutated on the one event loop
        self.counters = {
            "proxied": 0, "spills": 0, "mid_stream_aborts": 0,
            "no_replica_503s": 0, "budget_503s": 0,
        }
        #: federated SLO state (GET /metrics/fleet, GET /debug/slo):
        #: the UNMODIFIED engine evaluates the catalog over the latest
        #: bucket-wise merge of peer scrapes (obs/fleettrace.py)
        from ...obs.slo import SLOEngine

        self._fleet_view = fleettrace.FleetMetricsView()
        self._fleet_slo = SLOEngine(self._fleet_view, scope="fleet")
        # correlated incident capture on ejections (prober-side ones
        # included); no-op while the local flight recorder is disarmed
        if hasattr(peers, "on_eject"):
            peers.on_eject = self._on_peer_eject

    def _on_peer_eject(self, addr: str, reason: str) -> None:
        fleettrace.incident_pull_async(addr, self.peers.healthy(), reason)

    # -- telemetry ---------------------------------------------------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self.metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail routing
            pass

    # -- routing -----------------------------------------------------------
    def rank(self, key: str) -> list[str]:
        """Full preference order for ``key`` over ALL known replicas
        (healthy or not — the caller skips unhealthy ones and counts the
        skip as a spill, so ownership is stable across flaps)."""
        addrs = self.peers.addrs()
        if self.policy == "roundrobin":
            if not addrs:
                return []
            self._rr = (self._rr + 1) % len(addrs)
            return addrs[self._rr:] + addrs[:self._rr]
        return rendezvous_rank(key, addrs)

    # -- local endpoints ---------------------------------------------------
    def _health_doc(self) -> dict:
        return {
            "role": "router",
            "policy": self.policy,
            "started": self.started,
            "counters": dict(self.counters),
            **self.peers.snapshot(),
        }

    def _local_response(self, path: str):
        """(status, content_type, body) for router-owned routes, or None
        to proxy."""
        if path == "/health":
            return 200, "application/json", json.dumps(self._health_doc())
        if path == "/health/ready":
            n = len(self.peers.healthy())
            return (200 if n else 503), "application/json", json.dumps(
                {"ready": bool(n), "role": "router", "healthy_replicas": n})
        if path == "/health/live":
            return 200, "application/json", json.dumps(
                {"alive": True, "role": "router"})
        if path == "/metrics" and self.metrics is not None:
            self._emit("set_gauge", "fleet_peers_healthy",
                       len(self.peers.healthy()))
            return 200, "text/plain; version=0.0.4", self.metrics.render()
        return None

    # -- fleet observability endpoints (blocking peer fetches ride a
    # worker thread; the loop keeps relaying) -------------------------------
    async def _local_async(self, path: str):
        """(status, content_type, body) for the fleet-scope routes that
        must fan out HTTP to peers, or None to proxy."""
        if path == "/metrics/fleet":
            return await asyncio.to_thread(self._fleet_metrics_response)
        if path == "/debug/slo":
            return await asyncio.to_thread(self._fleet_slo_response)
        if path.startswith("/debug/fleet/traces/"):
            trace_id = path.rpartition("/")[2]
            if not _TRACE_ID_RE.fullmatch(trace_id):
                return 404, "application/json", json.dumps(
                    {"detail": "malformed trace id"})
            return await asyncio.to_thread(self._fleet_trace_response,
                                           trace_id)
        return None

    def _scrape_peers(self) -> dict[str, str]:
        texts: dict[str, str] = {}
        for addr in self.peers.healthy():
            text = fleettrace.fetch_text(addr, "/metrics",
                                         timeout=self.proxy_timeout)
            if text:
                texts[addr] = text
        return texts

    def _federate(self) -> dict:
        fed = fleettrace.federate(self._scrape_peers())
        self._fleet_view.update(fed["snapshot"])
        return fed

    def _fleet_metrics_response(self):
        fed = self._federate()
        self._fleet_slo.export()
        body = fed["exposition"] + self._fleet_view.render_gauges()
        return 200, "text/plain; version=0.0.4", body

    def _fleet_slo_response(self):
        fed = self._federate()
        doc = self._fleet_slo.evaluate()
        doc["scope"] = "fleet"
        doc["peers"] = fed["peers"]
        return 200, "application/json", json.dumps(doc)

    def _fleet_trace_response(self, trace_id: str):
        local_doc = None
        if self.tracer is not None:
            tr = self.tracer.get(trace_id)
            if tr is not None:
                local_doc = tr.to_dict()
        frags = fleettrace.collect_fragments(
            trace_id, self.peers.healthy(), timeout=self.proxy_timeout,
            local=local_doc)
        doc = fleettrace.stitch(frags)
        if doc is None:
            return 404, "application/json", json.dumps(
                {"detail": "trace not found on the router or any "
                           "healthy peer"})
        return 200, "application/json", json.dumps(doc)

    # -- one proxy attempt -------------------------------------------------
    async def _proxy_attempt(self, addr: str, head: bytes, body: bytes,
                             writer: asyncio.StreamWriter,
                             sent: list, span=None) -> int:
        """Forward one request to ``addr``, relaying the response to
        ``writer`` as it arrives.  ``sent`` flips truthy once the first
        response byte reaches the client (the no-retry point).  Returns
        the backend status; raises :class:`_BackendError` otherwise.
        ``span`` (the attempt span, None when sampled out) gets
        ``response.head`` / ``stream.relay`` children — the relay span
        ends at the LAST body byte.  Error paths leave them open on
        purpose: the tracer's finish sweep closes them ``auto_closed``
        at the abort instant."""
        host, _, port = addr.rpartition(":")
        try:
            r2, w2 = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)),
                self.proxy_timeout)
        except (OSError, asyncio.TimeoutError) as e:
            raise _BackendError(f"connect: {type(e).__name__}: {e}")
        try:
            sp_head = span.child("response.head") if span is not None \
                else None
            w2.write(head + body)
            try:
                await asyncio.wait_for(w2.drain(), self.proxy_timeout)
                # the status line waits on the STREAM budget, not the
                # connect timeout: a buffered non-streaming /response
                # sends its head only after the full generation, and a
                # 5s head deadline would eject a healthy replica for
                # serving a slow prompt (then replay the generation
                # fleet-wide).  Dead-socket detection stays fast via the
                # prober; a connected-but-silent backend is bounded here.
                status_line = await asyncio.wait_for(
                    r2.readline(), self.stream_timeout)
                resp_head = [status_line]
                while True:
                    line = await asyncio.wait_for(r2.readline(),
                                                  self.proxy_timeout)
                    resp_head.append(line)
                    if line in (b"\r\n", b"\n", b""):
                        break
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as e:
                raise _BackendError(f"head: {type(e).__name__}: {e}")
            try:
                status = int(status_line.split()[1])
            except (IndexError, ValueError):
                raise _BackendError(
                    f"head: malformed status line {status_line!r}")
            content_length = None
            chunked = False
            out = [status_line]
            for line in resp_head[1:-1]:
                name, _, value = line.partition(b":")
                lname = name.strip().lower()
                if lname in _HOP_HEADERS:
                    continue
                if lname == b"content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        pass
                elif lname == b"transfer-encoding" \
                        and b"chunked" in value.lower():
                    chunked = True
                out.append(line)
            out.append(b"connection: close\r\n\r\n")
            writer.write(b"".join(out))
            sent.append(True)
            sp_relay = None
            relayed = 0
            if sp_head is not None:
                sp_head.set(status=status)
                sp_head.end()
                sp_relay = span.child("stream.relay")
            # relay the body VERBATIM (byte-identity is the contract),
            # tracking the backend's own framing to know where the
            # response ends — EOF alone is not a terminator for
            # keep-alive backends
            deadline = time.time() + self.stream_timeout

            async def _read(coro):
                gap = deadline - time.time()
                if gap <= 0:
                    raise _BackendError("body: stream wall budget "
                                        "exhausted", mid_stream=True)
                try:
                    return await asyncio.wait_for(coro, gap)
                except (OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError) as e:
                    raise _BackendError(
                        f"body: {type(e).__name__}: {e}", mid_stream=True)

            if chunked:
                # incremental chunked walk: every byte (size lines, data,
                # the terminal 0-chunk) is relayed untouched; parsing is
                # only for finding the end, so SSE streams flush to the
                # client chunk by chunk as they arrive
                while True:
                    size_line = await _read(r2.readline())
                    if not size_line:
                        raise _BackendError("body: EOF inside chunked "
                                            "stream", mid_stream=True)
                    writer.write(size_line)
                    try:
                        size = int(size_line.strip().split(b";")[0], 16)
                    except ValueError:
                        raise _BackendError(
                            f"body: bad chunk size {size_line!r}",
                            mid_stream=True)
                    data = await _read(r2.readexactly(size + 2))
                    writer.write(data)
                    await writer.drain()
                    if sp_relay is not None:
                        relayed += len(size_line) + len(data)
                    if size == 0:
                        break
            elif content_length is not None:
                remaining = content_length
                while remaining > 0:
                    chunk = await _read(
                        r2.read(min(_READ_CHUNK, remaining)))
                    if not chunk:
                        raise _BackendError("body: EOF mid-response",
                                            mid_stream=True)
                    remaining -= len(chunk)
                    writer.write(chunk)
                    await writer.drain()
                    if sp_relay is not None:
                        relayed += len(chunk)
            else:
                # no framing: the response ends when the backend closes
                while True:
                    chunk = await _read(r2.read(_READ_CHUNK))
                    if not chunk:
                        break
                    writer.write(chunk)
                    await writer.drain()
                    if sp_relay is not None:
                        relayed += len(chunk)
            if sp_relay is not None:
                # ends AT the last relayed byte — the waterfall's relay
                # bar is the stream's true client-visible extent
                sp_relay.set(bytes=relayed)
                sp_relay.end()
            return status
        finally:
            try:
                w2.close()
            except Exception:  # noqa: BLE001
                pass

    # -- one client request ------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            await self._handle_inner(reader, writer)
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 — one bad request must not
            # take the router down; the client sees the closed socket
            logger.error("router request failed: %s", e)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _read_request(reader):
        """(method, target, headers dict, raw header lines, body) or None
        on a malformed/empty request."""
        request_line = await reader.readline()
        if not request_line:
            return None
        try:
            method, target, _version = request_line.decode().split()
        except ValueError:
            return None
        raw_headers = []
        headers: dict[str, str] = {}
        content_length = 0
        chunked = False
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            raw_headers.append(line)
            name, _, value = line.decode("latin-1").partition(":")
            name = name.strip().lower()
            value = value.strip()
            headers[name] = value
            if name == "content-length":
                try:
                    content_length = max(0, int(value))
                except ValueError:
                    return None
            elif name == "transfer-encoding":
                chunked = True
        if chunked:
            # chunked REQUEST bodies are not relayed (the backend httpd
            # refuses them too); forwarding the header with a rewritten
            # content-length would send conflicting framing and silently
            # drop the body — refuse honestly instead
            return "chunked"
        body = (await reader.readexactly(content_length)
                if content_length else b"")
        return method, target, headers, raw_headers, body

    def _write_simple(self, writer, status: int, ctype: str, body,
                      extra_headers: dict | None = None) -> None:
        if isinstance(body, str):
            body = body.encode()
        reason = {200: "OK", 503: "Service Unavailable",
                  408: "Request Timeout", 404: "Not Found",
                  501: "Not Implemented"}.get(status, "")
        extra = "".join(f"{k}: {sanitize_text(v, limit=256)}\r\n"
                        for k, v in (extra_headers or {}).items())
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"  # lfkt: sanitizes[http-request,wire-frame] -- the only request-derived value here is extra (x-request-id and friends), and every extra_headers value passes sanitize_text in the join above; status/reason/ctype/len are internal
            f"content-type: {ctype}\r\n"
            f"content-length: {len(body)}\r\n"
            f"{extra}"
            "connection: close\r\n\r\n".encode() + body)

    async def _handle_inner(self, reader, writer) -> None:
        try:
            got = await asyncio.wait_for(self._read_request(reader),
                                         self.proxy_timeout)
        except asyncio.TimeoutError:
            self._write_simple(writer, 408, "application/json",
                               json.dumps({"detail": "request read "
                                                     "timeout"}))
            return
        if got is None:
            return
        if got == "chunked":
            self._write_simple(
                writer, 501, "application/json",
                json.dumps({"detail": "chunked transfer-coding not "
                                      "supported"}))
            await writer.drain()
            return
        method, target, headers, raw_headers, body = got
        path = target.partition("?")[0]
        local = self._local_response(path)
        if local is None:
            local = await self._local_async(path)
        if local is not None:
            self._write_simple(writer, *local)
            await writer.drain()
            return

        # trace identity: ingest the inbound traceparent (re-validated —
        # only a well-formed one survives as hex, so it can ride logs and
        # outbound headers without a declassifier) or mint a fresh one
        trace = None
        if self.tracer is not None:
            trace = self.tracer.start("fleet.route",
                                      traceparent=headers.get("traceparent"))
        inbound = parse_traceparent(headers.get("traceparent"))
        rid = trace.trace_id if trace is not None else (
            inbound[0] if inbound else uuid.uuid4().hex)
        inbound_tp = f"00-{inbound[0]}-{inbound[1]}-01" if inbound else None
        with bind_request_id(rid):
            try:
                await self._route(method, target, path, headers,
                                  raw_headers, body, writer, trace,
                                  inbound_tp, rid)
            finally:
                if self.tracer is not None:
                    self.tracer.finish(trace)

    async def _route(self, method, target, path, headers, raw_headers,
                     body, writer, trace, inbound_tp, rid) -> None:
        key, source = affinity_key(path, headers, body)
        order = self.rank(key)
        owner = order[0] if order else None
        # forward the request with hop-by-hop headers rewritten: the
        # backend sees connection: close (EOF = end of response) and an
        # exact content-length; everything else (affinity header,
        # content-type) passes through.  traceparent is lifted out and
        # re-appended per ATTEMPT: when traced it names the attempt span
        # (the hop stamp fragments graft under), when sampled out the
        # validated inbound value relays unchanged.  The head is rebuilt
        # per ATTEMPT: the migration stamps below name the peer tried
        base = []
        for line in raw_headers:
            lname = line.split(b":", 1)[0].strip().lower()
            if lname in _HOP_HEADERS + (b"content-length", b"host",
                                        b"traceparent",
                                        AFFINITY_KEY_HEADER.encode(),
                                        PRIOR_OWNER_HEADER.encode()):
                continue
            base.append(line)

        def build_head(addr: str, span=None) -> bytes:
            fwd = [f"{method} {target} HTTP/1.1\r\n".encode()]  # lfkt: sanitizes[http-request] -- method/target are readline-framed: no LF can survive request-line parsing, so they cannot splice a header
            fwd.extend(base)
            fwd.append(f"host: {addr}\r\n".encode())
            if body or method in ("POST", "PUT", "PATCH"):
                fwd.append(f"content-length: {len(body)}\r\n".encode())
            if self.policy == "affinity" and source != "opaque":
                # migration stamps (serving/fleet/migrate.py): the key
                # lets the replica record this conversation for graceful
                # drain; prior-owner names the peer whose radix tree
                # still holds its pages — set when this attempt is OFF
                # the rendezvous owner (spill, ejection), or when the
                # owner itself was (re)admitted recently enough that a
                # restart/scale-out likely left it cold (pull-on-remap)
                fwd.append(f"{AFFINITY_KEY_HEADER}: {key}\r\n".encode())
                prior = None
                if owner is not None and addr != owner:
                    prior = owner
                elif addr == owner and len(order) > 1 \
                        and self.peers.is_fresh(addr, self.fresh_seconds):
                    prior = order[1]
                if prior is not None:
                    fwd.append(
                        f"{PRIOR_OWNER_HEADER}: {prior}\r\n".encode())
            hop_tp = span_traceparent(span) or inbound_tp
            if hop_tp:
                fwd.append(f"traceparent: "
                           f"{sanitize_text(hop_tp, limit=64)}\r\n".encode())
            fwd.append(b"connection: close\r\n\r\n")
            return b"".join(fwd)

        spath = sanitize_text(path, limit=256)
        if trace is not None:
            trace.root.set(method=sanitize_text(method, limit=16),
                           path=spath, policy=self.policy, source=source)
            trace.event("peer_pick", owner=owner,
                        ranked=len(order),
                        healthy=len(self.peers.healthy()))
        sent: list = []
        t0 = time.time()
        spills = 0
        attempt_n = 0
        for addr in order:
            if not self.peers.is_healthy(addr):
                if trace is not None:
                    trace.event("peer_skipped", peer=addr)
                continue
            if spills > self.max_spills:
                # retry budget (LFKT_FLEET_MAX_SPILLS): a request that
                # keeps killing its peer is more likely poison than
                # victim — stop walking the rendezvous order before it
                # fells the whole fleet; the client backs off instead
                self.counters["budget_503s"] += 1
                self._emit("inc", "fleet_spills_total", reason="budget")
                self._write_simple(
                    writer, 503, "application/json",
                    json.dumps({"detail": f"spill budget exhausted after "
                                          f"{spills} failed replays "
                                          "(LFKT_FLEET_MAX_SPILLS)"}),
                    {"retry-after": max(
                        1, int(self.peers.backoff_seconds)),
                     "x-request-id": rid})
                await writer.drain()
                return
            attempt_n += 1
            attempt = None
            if trace is not None:
                attempt = trace.span("proxy.attempt")
                attempt.set(peer=addr, n=attempt_n,
                            owner=(addr == owner))
            try:
                status = await self._proxy_attempt(
                    addr, build_head(addr, attempt), body, writer, sent,
                    span=attempt)
            except _BackendError as e:
                reason = sanitize_text(e.reason, limit=256)
                if attempt is not None:
                    attempt.set(error=reason, mid_stream=e.mid_stream)
                    attempt.end()
                self.peers.eject(addr, f"proxy {e.reason}")
                self._emit("set_gauge", "fleet_peers_healthy",
                           len(self.peers.healthy()))
                access_logger.info(
                    "fleet attempt failed: %s", reason,
                    extra={"route": spath,
                           "method": sanitize_text(method, limit=16),
                           "duration_s": round(time.time() - t0, 6),
                           "peer": addr, "spills": spills,
                           "attempt": attempt_n})
                if sent:
                    # bytes already reached the client: the router cannot
                    # replay a partially delivered response — close, and
                    # let the client's retry spill to a survivor
                    self.counters["mid_stream_aborts"] += 1
                    self._emit("inc", "fleet_spills_total",
                               reason="mid_stream_abort")
                    if trace is not None:
                        trace.event("mid_stream_abort", peer=addr)
                    logger.warning("fleet: %s died mid-response for key "
                                   "%s; client connection closed", addr,
                                   key[:16])
                    return
                self.counters["spills"] += 1
                self._emit("inc", "fleet_spills_total", reason="ejected")
                spills += 1
                if trace is not None:
                    trace.event("spill", peer=addr, reason=reason)
                continue
            # success
            if attempt is not None:
                attempt.set(status=status)
                attempt.end()
            self.counters["proxied"] += 1
            self._emit("inc", "fleet_requests_total", peer=addr,
                       source=source)
            self._emit("observe", "fleet_proxy_seconds", time.time() - t0)
            access_logger.info(
                "fleet proxied", extra={
                    "route": spath,
                    "method": sanitize_text(method, limit=16),
                    "status": status,
                    "duration_s": round(time.time() - t0, 6),
                    "peer": addr, "spills": spills,
                    "attempt": attempt_n})
            if self.policy == "affinity" and addr != owner:
                # served, but off the rendezvous owner: the owner is
                # ejected and this request warmed its spill target
                self.counters["spills"] += 1
                self._emit("inc", "fleet_spills_total", reason="spilled")
            await writer.drain()
            return
        # every replica unhealthy or refused pre-send
        self.counters["no_replica_503s"] += 1
        self._emit("inc", "fleet_spills_total", reason="no_replica")
        self._write_simple(
            writer, 503, "application/json",
            json.dumps({"detail": "no healthy replica (fleet router); "
                                  "see the router's /health for per-peer "
                                  "attribution"}),
            {"x-request-id": rid})
        await writer.drain()

    # -- serving -----------------------------------------------------------
    async def serve(self, host: str = "0.0.0.0", port: int = 8000,
                    ready_event: asyncio.Event | None = None,
                    stop_event: asyncio.Event | None = None) -> None:
        server = await asyncio.start_server(self._handle, host, port)
        logger.info("fleet router listening on %s:%d (%d replicas, "
                    "policy=%s)", host, port, len(self.peers.addrs()),
                    self.policy)
        if ready_event is not None:
            ready_event.set()
        stop = stop_event if stop_event is not None else asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError, ValueError):
                # non-main thread (tests/bench) or unsupported platform
                pass
        async with server:
            await stop.wait()
        # PeerTable.stop() joins the prober thread, which may be inside
        # a probe_timeout-long socket wait — joining ON the loop would
        # freeze every in-flight proxied stream for up to probe_timeout
        # + probe_seconds at shutdown (lfkt-lint ASY001, ISSUE 15):
        # the join rides a worker thread, the loop keeps relaying
        await asyncio.to_thread(self.peers.stop)

"""The fleet router: a thin prefix-affinity HTTP proxy over the replicas.

``LFKT_FLEET_ROLE=router`` runs this instead of a serving app — no
model, no jax, one asyncio loop.  Every request is keyed by
:func:`..fleet.affinity.affinity_key` and proxied to the replica that
rendezvous-hashing says owns the key (``serving/fleet/affinity.py``),
so a conversation's turns keep landing on the replica whose radix tree
already holds their KV pages.  ``policy="roundrobin"`` is the A/B
control arm (``bench_server.py`` fleet arm; ``LFKT_FLEET_POLICY``).

Proxying is RAW: the backend's status line, headers (minus hop-by-hop
connection signaling) and body bytes are relayed verbatim as they
arrive — so streaming SSE passes through chunk by chunk, and a greedy
completion through the router is byte-identical to direct-to-replica
(pinned by tests/test_fleet.py + the ci_gate ``fleet-route-parity``
check).  One request per client connection (``connection: close``):
the router's job is placement, not connection pooling.

Failure contract — never a hang, never a 502 for one dead pod:

- connect/head failure BEFORE any response byte reached the client:
  eject the peer with attribution (peers.py) and retry the request on
  the rendezvous-NEXT healthy peer (``fleet_spills_total{reason}``);
  only when EVERY replica refused does the client see a 503.
- failure MID-RESPONSE (bytes already forwarded): the peer is ejected,
  the client connection closes (the router cannot replay a partially
  delivered generation), and the client's retry — a fresh request —
  spills to the survivor.
- every backend read rides a deadline: connect/head on
  ``LFKT_FLEET_PROXY_TIMEOUT_SECONDS``, body progress on the stream
  wall budget (``LFKT_STREAM_DEADLINE_SECONDS``).

The router answers ``/health`` (role, policy, per-peer state with
attributed ejection reasons), ``/health/ready`` (200 iff >= 1 healthy
replica — k8s stops routing to a router whose whole fleet is down),
``/health/live`` and ``/metrics`` (the ``fleet_*`` families) itself;
everything else is proxied.
"""

from __future__ import annotations

import asyncio
import json
import logging
import signal
import time

from .affinity import (AFFINITY_KEY_HEADER, PRIOR_OWNER_HEADER,
                       affinity_key, rendezvous_rank)

logger = logging.getLogger(__name__)

#: response head elements the proxy rewrites rather than relays:
#: connection signaling is hop-by-hop (RFC 9110 §7.6.1)
_HOP_HEADERS = (b"connection", b"keep-alive", b"proxy-connection")

_READ_CHUNK = 65536


class _BackendError(Exception):
    """One proxy attempt failed against one peer (reason attributed)."""

    def __init__(self, reason: str, mid_stream: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.mid_stream = mid_stream


class FleetRouter:
    """See module docstring.  ``peers`` is a started
    :class:`..fleet.peers.PeerTable`; ``metrics`` a
    :class:`...utils.metrics.Metrics` registry (the catalog enforces the
    ``fleet_*`` families)."""

    def __init__(self, peers, policy: str = "affinity", metrics=None,
                 proxy_timeout: float = 5.0,
                 stream_timeout: float = 300.0,
                 max_spills: int = 3,
                 fresh_seconds: float = 600.0):
        if policy not in ("affinity", "roundrobin"):
            raise ValueError(
                f"LFKT_FLEET_POLICY must be affinity|roundrobin, "
                f"got {policy!r}")
        self.peers = peers
        self.policy = policy
        self.metrics = metrics
        self.proxy_timeout = proxy_timeout
        self.stream_timeout = stream_timeout
        self.max_spills = max(0, int(max_spills))
        self.fresh_seconds = float(fresh_seconds)
        self._rr = 0
        self.started = int(time.time())
        #: monotonic counters for /health (the /metrics twins are inc'd
        #: at event time); plain ints mutated on the one event loop
        self.counters = {
            "proxied": 0, "spills": 0, "mid_stream_aborts": 0,
            "no_replica_503s": 0, "budget_503s": 0,
        }

    # -- telemetry ---------------------------------------------------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self.metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail routing
            pass

    # -- routing -----------------------------------------------------------
    def rank(self, key: str) -> list[str]:
        """Full preference order for ``key`` over ALL known replicas
        (healthy or not — the caller skips unhealthy ones and counts the
        skip as a spill, so ownership is stable across flaps)."""
        addrs = self.peers.addrs()
        if self.policy == "roundrobin":
            if not addrs:
                return []
            self._rr = (self._rr + 1) % len(addrs)
            return addrs[self._rr:] + addrs[:self._rr]
        return rendezvous_rank(key, addrs)

    # -- local endpoints ---------------------------------------------------
    def _health_doc(self) -> dict:
        return {
            "role": "router",
            "policy": self.policy,
            "started": self.started,
            "counters": dict(self.counters),
            **self.peers.snapshot(),
        }

    def _local_response(self, path: str):
        """(status, content_type, body) for router-owned routes, or None
        to proxy."""
        if path == "/health":
            return 200, "application/json", json.dumps(self._health_doc())
        if path == "/health/ready":
            n = len(self.peers.healthy())
            return (200 if n else 503), "application/json", json.dumps(
                {"ready": bool(n), "role": "router", "healthy_replicas": n})
        if path == "/health/live":
            return 200, "application/json", json.dumps(
                {"alive": True, "role": "router"})
        if path == "/metrics" and self.metrics is not None:
            self._emit("set_gauge", "fleet_peers_healthy",
                       len(self.peers.healthy()))
            return 200, "text/plain; version=0.0.4", self.metrics.render()
        return None

    # -- one proxy attempt -------------------------------------------------
    async def _proxy_attempt(self, addr: str, head: bytes, body: bytes,
                             writer: asyncio.StreamWriter,
                             sent: list) -> int:
        """Forward one request to ``addr``, relaying the response to
        ``writer`` as it arrives.  ``sent`` flips truthy once the first
        response byte reaches the client (the no-retry point).  Returns
        the backend status; raises :class:`_BackendError` otherwise."""
        host, _, port = addr.rpartition(":")
        try:
            r2, w2 = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)),
                self.proxy_timeout)
        except (OSError, asyncio.TimeoutError) as e:
            raise _BackendError(f"connect: {type(e).__name__}: {e}")
        try:
            w2.write(head + body)
            try:
                await asyncio.wait_for(w2.drain(), self.proxy_timeout)
                # the status line waits on the STREAM budget, not the
                # connect timeout: a buffered non-streaming /response
                # sends its head only after the full generation, and a
                # 5s head deadline would eject a healthy replica for
                # serving a slow prompt (then replay the generation
                # fleet-wide).  Dead-socket detection stays fast via the
                # prober; a connected-but-silent backend is bounded here.
                status_line = await asyncio.wait_for(
                    r2.readline(), self.stream_timeout)
                resp_head = [status_line]
                while True:
                    line = await asyncio.wait_for(r2.readline(),
                                                  self.proxy_timeout)
                    resp_head.append(line)
                    if line in (b"\r\n", b"\n", b""):
                        break
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as e:
                raise _BackendError(f"head: {type(e).__name__}: {e}")
            try:
                status = int(status_line.split()[1])
            except (IndexError, ValueError):
                raise _BackendError(
                    f"head: malformed status line {status_line!r}")
            content_length = None
            chunked = False
            out = [status_line]
            for line in resp_head[1:-1]:
                name, _, value = line.partition(b":")
                lname = name.strip().lower()
                if lname in _HOP_HEADERS:
                    continue
                if lname == b"content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        pass
                elif lname == b"transfer-encoding" \
                        and b"chunked" in value.lower():
                    chunked = True
                out.append(line)
            out.append(b"connection: close\r\n\r\n")
            writer.write(b"".join(out))
            sent.append(True)
            # relay the body VERBATIM (byte-identity is the contract),
            # tracking the backend's own framing to know where the
            # response ends — EOF alone is not a terminator for
            # keep-alive backends
            deadline = time.time() + self.stream_timeout

            async def _read(coro):
                gap = deadline - time.time()
                if gap <= 0:
                    raise _BackendError("body: stream wall budget "
                                        "exhausted", mid_stream=True)
                try:
                    return await asyncio.wait_for(coro, gap)
                except (OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError) as e:
                    raise _BackendError(
                        f"body: {type(e).__name__}: {e}", mid_stream=True)

            if chunked:
                # incremental chunked walk: every byte (size lines, data,
                # the terminal 0-chunk) is relayed untouched; parsing is
                # only for finding the end, so SSE streams flush to the
                # client chunk by chunk as they arrive
                while True:
                    size_line = await _read(r2.readline())
                    if not size_line:
                        raise _BackendError("body: EOF inside chunked "
                                            "stream", mid_stream=True)
                    writer.write(size_line)
                    try:
                        size = int(size_line.strip().split(b";")[0], 16)
                    except ValueError:
                        raise _BackendError(
                            f"body: bad chunk size {size_line!r}",
                            mid_stream=True)
                    data = await _read(r2.readexactly(size + 2))
                    writer.write(data)
                    await writer.drain()
                    if size == 0:
                        break
            elif content_length is not None:
                remaining = content_length
                while remaining > 0:
                    chunk = await _read(
                        r2.read(min(_READ_CHUNK, remaining)))
                    if not chunk:
                        raise _BackendError("body: EOF mid-response",
                                            mid_stream=True)
                    remaining -= len(chunk)
                    writer.write(chunk)
                    await writer.drain()
            else:
                # no framing: the response ends when the backend closes
                while True:
                    chunk = await _read(r2.read(_READ_CHUNK))
                    if not chunk:
                        break
                    writer.write(chunk)
                    await writer.drain()
            return status
        finally:
            try:
                w2.close()
            except Exception:  # noqa: BLE001
                pass

    # -- one client request ------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            await self._handle_inner(reader, writer)
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        except Exception as e:  # noqa: BLE001 — one bad request must not
            # take the router down; the client sees the closed socket
            logger.error("router request failed: %s", e)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _read_request(reader):
        """(method, target, headers dict, raw header lines, body) or None
        on a malformed/empty request."""
        request_line = await reader.readline()
        if not request_line:
            return None
        try:
            method, target, _version = request_line.decode().split()
        except ValueError:
            return None
        raw_headers = []
        headers: dict[str, str] = {}
        content_length = 0
        chunked = False
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            raw_headers.append(line)
            name, _, value = line.decode("latin-1").partition(":")
            name = name.strip().lower()
            value = value.strip()
            headers[name] = value
            if name == "content-length":
                try:
                    content_length = max(0, int(value))
                except ValueError:
                    return None
            elif name == "transfer-encoding":
                chunked = True
        if chunked:
            # chunked REQUEST bodies are not relayed (the backend httpd
            # refuses them too); forwarding the header with a rewritten
            # content-length would send conflicting framing and silently
            # drop the body — refuse honestly instead
            return "chunked"
        body = (await reader.readexactly(content_length)
                if content_length else b"")
        return method, target, headers, raw_headers, body

    def _write_simple(self, writer, status: int, ctype: str, body,
                      extra_headers: dict | None = None) -> None:
        if isinstance(body, str):
            body = body.encode()
        reason = {200: "OK", 503: "Service Unavailable",
                  408: "Request Timeout",
                  501: "Not Implemented"}.get(status, "")
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"content-type: {ctype}\r\n"
            f"content-length: {len(body)}\r\n"
            f"{extra}"
            "connection: close\r\n\r\n".encode() + body)

    async def _handle_inner(self, reader, writer) -> None:
        try:
            got = await asyncio.wait_for(self._read_request(reader),
                                         self.proxy_timeout)
        except asyncio.TimeoutError:
            self._write_simple(writer, 408, "application/json",
                               json.dumps({"detail": "request read "
                                                     "timeout"}))
            return
        if got is None:
            return
        if got == "chunked":
            self._write_simple(
                writer, 501, "application/json",
                json.dumps({"detail": "chunked transfer-coding not "
                                      "supported"}))
            await writer.drain()
            return
        method, target, headers, raw_headers, body = got
        path = target.partition("?")[0]
        local = self._local_response(path)
        if local is not None:
            self._write_simple(writer, *local)
            await writer.drain()
            return

        key, source = affinity_key(path, headers, body)
        order = self.rank(key)
        owner = order[0] if order else None
        # forward the request with hop-by-hop headers rewritten: the
        # backend sees connection: close (EOF = end of response) and an
        # exact content-length; everything else (traceparent, affinity
        # header, content-type) passes through.  The head is rebuilt per
        # ATTEMPT: the migration stamps below name the peer being tried
        base = []
        for line in raw_headers:
            lname = line.split(b":", 1)[0].strip().lower()
            if lname in _HOP_HEADERS + (b"content-length", b"host",
                                        AFFINITY_KEY_HEADER.encode(),
                                        PRIOR_OWNER_HEADER.encode()):
                continue
            base.append(line)

        def build_head(addr: str) -> bytes:
            fwd = [f"{method} {target} HTTP/1.1\r\n".encode()]  # lfkt: sanitizes[http-request] -- method/target are readline-framed: no LF can survive request-line parsing, so they cannot splice a header
            fwd.extend(base)
            fwd.append(f"host: {addr}\r\n".encode())
            if body or method in ("POST", "PUT", "PATCH"):
                fwd.append(f"content-length: {len(body)}\r\n".encode())
            if self.policy == "affinity" and source != "opaque":
                # migration stamps (serving/fleet/migrate.py): the key
                # lets the replica record this conversation for graceful
                # drain; prior-owner names the peer whose radix tree
                # still holds its pages — set when this attempt is OFF
                # the rendezvous owner (spill, ejection), or when the
                # owner itself was (re)admitted recently enough that a
                # restart/scale-out likely left it cold (pull-on-remap)
                fwd.append(f"{AFFINITY_KEY_HEADER}: {key}\r\n".encode())
                prior = None
                if owner is not None and addr != owner:
                    prior = owner
                elif addr == owner and len(order) > 1 \
                        and self.peers.is_fresh(addr, self.fresh_seconds):
                    prior = order[1]
                if prior is not None:
                    fwd.append(
                        f"{PRIOR_OWNER_HEADER}: {prior}\r\n".encode())
            fwd.append(b"connection: close\r\n\r\n")
            return b"".join(fwd)

        sent: list = []
        t0 = time.time()
        spills = 0
        for addr in order:
            if not self.peers.is_healthy(addr):
                continue
            if spills > self.max_spills:
                # retry budget (LFKT_FLEET_MAX_SPILLS): a request that
                # keeps killing its peer is more likely poison than
                # victim — stop walking the rendezvous order before it
                # fells the whole fleet; the client backs off instead
                self.counters["budget_503s"] += 1
                self._emit("inc", "fleet_spills_total", reason="budget")
                self._write_simple(
                    writer, 503, "application/json",
                    json.dumps({"detail": f"spill budget exhausted after "
                                          f"{spills} failed replays "
                                          "(LFKT_FLEET_MAX_SPILLS)"}),
                    {"retry-after": max(
                        1, int(self.peers.backoff_seconds))})
                await writer.drain()
                return
            try:
                await self._proxy_attempt(addr, build_head(addr), body,
                                          writer, sent)
            except _BackendError as e:
                self.peers.eject(addr, f"proxy {e.reason}")
                self._emit("set_gauge", "fleet_peers_healthy",
                           len(self.peers.healthy()))
                if sent:
                    # bytes already reached the client: the router cannot
                    # replay a partially delivered response — close, and
                    # let the client's retry spill to a survivor
                    self.counters["mid_stream_aborts"] += 1
                    self._emit("inc", "fleet_spills_total",
                               reason="mid_stream_abort")
                    logger.warning("fleet: %s died mid-response for key "
                                   "%s; client connection closed", addr,
                                   key[:16])
                    return
                self.counters["spills"] += 1
                self._emit("inc", "fleet_spills_total", reason="ejected")
                spills += 1
                continue
            # success
            self.counters["proxied"] += 1
            self._emit("inc", "fleet_requests_total", peer=addr,
                       source=source)
            self._emit("observe", "fleet_proxy_seconds", time.time() - t0)
            if self.policy == "affinity" and addr != owner:
                # served, but off the rendezvous owner: the owner is
                # ejected and this request warmed its spill target
                self.counters["spills"] += 1
                self._emit("inc", "fleet_spills_total", reason="spilled")
            await writer.drain()
            return
        # every replica unhealthy or refused pre-send
        self.counters["no_replica_503s"] += 1
        self._emit("inc", "fleet_spills_total", reason="no_replica")
        self._write_simple(
            writer, 503, "application/json",
            json.dumps({"detail": "no healthy replica (fleet router); "
                                  "see the router's /health for per-peer "
                                  "attribution"}))
        await writer.drain()

    # -- serving -----------------------------------------------------------
    async def serve(self, host: str = "0.0.0.0", port: int = 8000,
                    ready_event: asyncio.Event | None = None,
                    stop_event: asyncio.Event | None = None) -> None:
        server = await asyncio.start_server(self._handle, host, port)
        logger.info("fleet router listening on %s:%d (%d replicas, "
                    "policy=%s)", host, port, len(self.peers.addrs()),
                    self.policy)
        if ready_event is not None:
            ready_event.set()
        stop = stop_event if stop_event is not None else asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError, ValueError):
                # non-main thread (tests/bench) or unsupported platform
                pass
        async with server:
            await stop.wait()
        # PeerTable.stop() joins the prober thread, which may be inside
        # a probe_timeout-long socket wait — joining ON the loop would
        # freeze every in-flight proxied stream for up to probe_timeout
        # + probe_seconds at shutdown (lfkt-lint ASY001, ISSUE 15):
        # the join rides a worker thread, the loop keeps relaying
        await asyncio.to_thread(self.peers.stop)

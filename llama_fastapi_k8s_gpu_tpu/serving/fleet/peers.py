"""The router's health-aware replica table.

Replicas come from ``LFKT_FLEET_PEERS`` (a static ``host:port,...``
list) or, in k8s, from resolving a headless Service's DNS name every
probe cycle (``LFKT_FLEET_DNS=name:port`` — a headless Service answers
with one A record per ready pod, so scale-out/in shows up here without
router restarts).

Liveness is decided two ways, both landing in :meth:`eject`:

- a background prober GETs every peer's ``/health/ready`` each cycle
  (``LFKT_FLEET_PROBE_SECONDS``) — a replica that stops answering (or
  answers 503: DEGRADED/DRAINING pods shed traffic) is ejected;
- the router ejects a peer the moment a PROXIED request fails against
  it — the prober's cadence must never be the detection latency for a
  request already in hand.

Ejection backs off exponentially (``LFKT_FLEET_EJECT_BACKOFF_SECONDS``
doubling to ``.._MAX``): an ejected peer is only re-probed after its
backoff expires, and a probe success re-admits it with the backoff
reset.  While ejected, the peer stays in :meth:`addrs` (rendezvous
ranks the FULL set so ownership never migrates behind a flap) but not
in :meth:`healthy` — the router spills its keys to rendezvous-next
with attribution until re-admission.
"""

from __future__ import annotations

import http.client
import logging
import socket
import threading
import time

from ...obs.logctx import sanitize_text

logger = logging.getLogger(__name__)


class _Peer:
    """One replica's liveness record (mutated only under the table lock)."""

    __slots__ = ("addr", "healthy", "ejected_at", "next_probe", "backoff",
                 "last_error", "ejections", "static", "fresh_at")

    def __init__(self, addr: str, static: bool):
        self.addr = addr
        self.healthy = True          # optimistic: the first probe decides
        self.ejected_at = 0.0
        self.next_probe = 0.0
        self.backoff = 0.0
        self.last_error = None
        self.ejections = 0
        self.static = static         # from LFKT_FLEET_PEERS, never pruned
        # when this replica last (re)joined the serving set: DNS
        # scale-out discovery now, re-admission after an ejection later
        # (static boot peers start un-fresh — a cold fleet has no prior
        # owner to pull from).  Drives the router's pull-on-remap stamp
        # (migrate.py): a freshly (re)joined owner probably restarted
        # cold while its conversations' pages live on the spill target.
        self.fresh_at = 0.0 if static else time.time()


class PeerTable:
    """Thread-safe replica set + prober (see module docstring)."""

    # -- lock discipline (lfkt-lint LOCK001-004) ---------------------------
    _GUARDED_BY = {"_peers": "_lock"}
    _THREAD_ENTRIES = ("_probe_loop",)
    # on_eject is written once at router construction, read by the
    # prober thread and the event loop — a single reference swap
    _SHARED_ATOMIC = ("on_eject",)

    def __init__(self, peers: list[str] | None = None, dns: str = "",
                 probe_seconds: float = 2.0, backoff_seconds: float = 1.0,
                 backoff_max: float = 30.0, probe_timeout: float = 2.0,
                 probe_path: str = "/health/ready", metrics=None):
        self._lock = threading.Lock()
        self._peers: dict[str, _Peer] = {}
        self.dns = dns
        self.probe_seconds = probe_seconds
        self.backoff_seconds = backoff_seconds
        self.backoff_max = backoff_max
        self.probe_timeout = probe_timeout
        self.probe_path = probe_path
        self._metrics = metrics
        self._stop = threading.Event()
        self._thread = None
        #: optional rising-edge ejection hook ``(addr, reason)`` —
        #: invoked OFF the table lock (it fans out; the router wires the
        #: correlated incident pull here).  Must never raise-or-block by
        #: contract; guarded anyway.
        self.on_eject = None
        for addr in peers or []:
            addr = addr.strip()
            if addr:
                self._peers[addr] = _Peer(addr, static=True)
        if not self._peers and not dns:
            raise ValueError(
                "PeerTable needs at least one replica: set LFKT_FLEET_PEERS="
                "host:port[,host:port...] or LFKT_FLEET_DNS=name:port "
                "(docs/RUNBOOK.md 'Running a replica fleet')")

    # -- telemetry (never fails routing) -----------------------------------
    def _emit(self, kind: str, name: str, value: float = 1.0, **labels):
        m = self._metrics
        if m is None:
            return
        try:
            getattr(m, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 — telemetry must never fail routing
            pass

    # -- lifecycle ---------------------------------------------------------
    def start(self, probe_now: bool = True) -> "PeerTable":
        """Run one synchronous probe sweep (so the router never starts
        blind-optimistic), then the background prober."""
        if probe_now:
            self._probe_sweep()
        self._thread = threading.Thread(target=self._probe_loop,
                                        name="lfkt-fleet-prober",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.probe_timeout + self.probe_seconds)
            self._thread = None

    # -- the routing surface ----------------------------------------------
    def addrs(self) -> list[str]:
        """EVERY known replica, healthy or not — the rendezvous domain
        (ownership must not migrate while a peer merely flaps)."""
        with self._lock:
            return list(self._peers)

    def healthy(self) -> list[str]:
        with self._lock:
            return [p.addr for p in self._peers.values() if p.healthy]

    def is_healthy(self, addr: str) -> bool:
        with self._lock:
            p = self._peers.get(addr)
            return p is not None and p.healthy

    def is_fresh(self, addr: str, window: float) -> bool:
        """True iff ``addr`` (re)joined the serving set within
        ``window`` seconds — the router's cue that the rendezvous owner
        probably restarted cold and should be stamped with a prior
        owner to pull warm pages from (``LFKT_MIGRATE_FRESH_SECONDS``;
        0 disables)."""
        if window <= 0:
            return False
        with self._lock:
            p = self._peers.get(addr)
            return (p is not None and p.fresh_at > 0
                    and time.time() - p.fresh_at < window)

    def eject(self, addr: str, reason: str) -> None:
        """Mark a replica dead with attribution (prober or router-observed
        failure).  Repeated ejections before a successful probe double the
        backoff, so a hard-down pod costs one probe per backoff window,
        not one per cycle."""
        # reasons can embed peer-response fragments (a probe's error
        # body, an upstream exception message) — sanitize before they
        # reach the log line and the /health peers block
        reason = sanitize_text(reason)
        now = time.time()
        with self._lock:
            p = self._peers.get(addr)
            if p is None:
                return
            first = p.healthy
            p.healthy = False
            p.last_error = reason
            p.ejected_at = now
            p.backoff = (min(self.backoff_max,
                             p.backoff * 2 if p.backoff else
                             self.backoff_seconds))
            p.next_probe = now + p.backoff
            if first:
                p.ejections += 1
        if first:
            logger.warning("fleet: ejected replica %s (%s); re-probe in "
                           "%.1fs", addr, reason, p.backoff)
            self._emit("inc", "fleet_peer_ejections_total", peer=addr)
            hook = self.on_eject
            if hook is not None:
                try:
                    hook(addr, reason)
                except Exception:  # noqa: BLE001 — an observability hook
                    # must never turn an ejection into a router failure
                    logger.exception("fleet: on_eject hook failed")

    def _readmit(self, addr: str) -> None:
        with self._lock:
            p = self._peers.get(addr)
            if p is None:
                return
            was_dead = not p.healthy
            p.healthy = True
            p.backoff = 0.0
            p.last_error = None
            if was_dead:
                p.fresh_at = time.time()
        if was_dead:
            logger.info("fleet: re-admitted replica %s", addr)

    def snapshot(self) -> dict:
        """The router's /health ``peers`` block: per-replica state with
        the attributed ejection reason — a dead pod is named, never
        inferred from traffic shape."""
        with self._lock:
            rows = [{
                "addr": p.addr,
                "healthy": p.healthy,
                "ejections": p.ejections,
                "last_error": p.last_error,
                "backoff_seconds": round(p.backoff, 3) if not p.healthy
                else 0.0,
                "source": "static" if p.static else "dns",
            } for p in self._peers.values()]
        rows.sort(key=lambda r: r["addr"])
        return {
            "replicas": len(rows),
            "healthy": sum(r["healthy"] for r in rows),
            "peers": rows,
        }

    # -- probing -----------------------------------------------------------
    def probe(self, addr: str) -> tuple[bool, str | None]:
        """One GET ``probe_path`` against ``addr``: (ready, error)."""
        host, _, port = addr.rpartition(":")
        try:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self.probe_timeout)
            try:
                conn.request("GET", self.probe_path)
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    return True, None
                return False, f"probe {self.probe_path} -> {resp.status}"
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, ValueError) as e:
            # OSError = dead socket; HTTPException (BadStatusLine...) =
            # a port answering non-HTTP (half-dead process, wrong
            # service) — both are one peer's problem and must never
            # abort the sweep probing the REST of the fleet
            return False, f"probe failed: {type(e).__name__}: {e}"

    def _resolve_dns(self) -> None:
        """Refresh the peer set from the headless Service: one A record
        per ready pod.  Resolution failure keeps the last known set (a
        transient DNS blip must not empty the fleet)."""
        name, _, port = self.dns.rpartition(":")
        try:
            infos = socket.getaddrinfo(name, int(port),
                                       type=socket.SOCK_STREAM)
        except OSError as e:
            logger.warning("fleet: DNS resolution of %s failed (%s); "
                           "keeping the current peer set", self.dns, e)
            return
        live = {f"{info[4][0]}:{port}" for info in infos}
        with self._lock:
            for addr in live:
                if addr not in self._peers:
                    self._peers[addr] = _Peer(addr, static=False)
            for addr in [a for a, p in self._peers.items()
                         if not p.static and a not in live]:
                del self._peers[addr]

    def _probe_sweep(self) -> None:
        if self.dns:
            self._resolve_dns()
        now = time.time()
        with self._lock:
            due = [p.addr for p in self._peers.values()
                   if p.healthy or now >= p.next_probe]
        for addr in due:
            t0 = time.time()
            ok, err = self.probe(addr)
            # success AND failure both observe: a peer whose probes
            # crawl toward probe_timeout is about to be ejected, and the
            # tuning signal must include the timeouts it already hit
            self._emit("observe", "fleet_probe_seconds",
                       time.time() - t0, peer=addr)
            if ok:
                self._readmit(addr)
            else:
                self.eject(addr, err or "probe failed")
        self._emit("set_gauge", "fleet_peers_healthy", len(self.healthy()))

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_seconds):
            try:
                self._probe_sweep()
            except Exception as e:  # noqa: BLE001 — the prober must outlive
                # any single bad cycle; the next sweep re-evaluates
                logger.error("fleet prober sweep failed: %s", e)

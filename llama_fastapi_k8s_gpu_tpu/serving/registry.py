"""The model registry: N named engines served from one process.

ROADMAP item 5's subsystem (docs/MULTIMODEL.md).  The registry

- loads every :class:`~..serving.manifest.ModelSpec` through a
  caller-supplied ``build`` function (the server factory closes over the
  process-wide scheduler settings, so every model gets the same serving
  shape — lanes, chunk cadence, admission control);
- accounts an explicit **HBM weight budget** across the set and refuses
  at load time, with per-model attribution, when the fleet cannot fit
  (``LFKT_HBM_WEIGHT_BUDGET_MB``; a half-loaded fleet OOMing at first
  traffic is the failure mode this converts into a startup error);
- threads one **shared block-paged KV pool** through every compatible
  engine (same per-page cache geometry), so co-resident models partition
  one HBM page budget dynamically instead of each provisioning
  worst-case — with per-model radix **namespaces**, so tenant A's system
  prompt can never produce a phantom prefix hit for tenant B
  (parallel/kvpool.py);
- routes per-request ``model=`` to the named engine.  In continuous mode
  each model owns a scheduler (its own lanes); their device dispatches
  interleave on the chip's single execution queue, so waves of model A
  run between waves of model B — the co-resident-deployment shape of
  "Transformer-Lite" (PAPERS.md).

Gating, with attribution (the SPEngine-paging idiom): the ``cycle``
(mesh-batched) scheduler and the sequence-parallel engine coalesce
requests into one shared device program, which cannot interleave
models — the server factory refuses those combinations at startup.  The
engine watchdog is likewise single-engine (one heartbeat, one recovery
path) and does not run over a multi-model registry; per-engine scheduler
failures still fail fast through ``EngineUnavailable`` on their own
submit paths.
"""

from __future__ import annotations

import logging
import os

from ..obs import memledger as _memledger
from .manifest import ModelSpec

logger = logging.getLogger(__name__)


class UnknownModelError(ValueError):
    """A request named a model the manifest does not serve (HTTP 400)."""

    def __init__(self, model: str, known: list[str]):
        self.model = model
        self.known = list(known)
        super().__init__(
            f"unknown model {model!r}; this pod serves: "
            f"{', '.join(self.known)}")


class WeightBudgetError(RuntimeError):
    """The manifest's weights exceed the declared HBM budget."""


#: weight-group leaf key -> served layout; ORDER MATTERS (specific keys
#: before the generic "q"/"w" fallbacks) — same map /health derives its
#: per-group weight_formats from (server/app.py)
_WEIGHT_KINDS = {"qs": "q4k-fused", "q5s": "q5k-fused",
                 "q5p": "q5k-fused-pre", "q4": "q6k-fused",
                 "q6p": "q6k-fused-pre", "q8": "q8-fused",
                 "q": "int8", "w": "bf16"}


def _quant_summary(engine) -> str | None:
    """One label for how the model's linear weights are served (e.g.
    ``q4k-fused`` or ``bf16+int8`` when groups differ) — the /health
    ``models`` row's ``quant`` field."""
    params = getattr(engine, "params", None)
    if not isinstance(params, dict) or "layers" not in params:
        return None
    fmts = {
        next((v for k, v in _WEIGHT_KINDS.items() if k in leaf), "?")
        for leaf in params["layers"].values() if isinstance(leaf, dict)
    }
    return "+".join(sorted(fmts)) if fmts else None


class ModelRegistry:
    """Named engines behind one engine-shaped facade.

    The server talks to a registry exactly as it talks to a single
    engine (``create_chat_completion`` / ``submit`` / ``scheduler_stats``
    / ``kv_cache_bytes`` ...), plus ``model=`` routing and the
    ``models()`` descriptor that feeds ``GET /v1/models`` and the
    /health ``models`` block.  ``submit``/``submit_stream``/
    ``create_chat_completions`` are installed only when every engine
    provides them, so the server's capability probes keep working.
    """

    def __init__(self, engines: dict[str, object], default_model: str,
                 model_info: list[dict] | None = None):
        if not engines:
            raise ValueError("ModelRegistry needs at least one engine")
        if default_model not in engines:
            raise ValueError(
                f"default model {default_model!r} is not among "
                f"{', '.join(engines)}")
        self._engines = dict(engines)
        for name, eng in self._engines.items():
            # the registry alias IS the serving identity: responses,
            # traces, /debug/requests rows and metric labels all read
            # model_name (from_specs already did this; direct
            # construction — tests, embedders — gets it here)
            try:
                eng.model_name = name
            except AttributeError:   # read-only property: keep its label
                pass
        self.default_model = default_model
        #: single-model-compat surface: responses carry their own model
        #: name; this is only the fallback label (e.g. untimed fakes)
        self.model_name = default_model
        self._model_info = list(model_info or [])
        if not self._model_info:
            self._model_info = [
                self._describe(name, eng, path=None)
                for name, eng in self._engines.items()
            ]
        self._metrics_sink = None
        if all(hasattr(e, "submit") for e in self._engines.values()):
            self.submit = self._submit
        if all(hasattr(e, "submit_stream") for e in self._engines.values()):
            self.submit_stream = self._submit_stream
        if all(hasattr(e, "create_chat_completions")
               for e in self._engines.values()):
            self.create_chat_completions = self._create_chat_completions
        if all(hasattr(e, "scheduler_stats")
               for e in self._engines.values()):
            self.scheduler_stats = self._scheduler_stats

    # ------------------------------------------------------------------
    @staticmethod
    def _describe(name: str, engine, path: str | None) -> dict:
        cfg = getattr(engine, "cfg", None)
        return {
            "name": name,
            "path": path,
            "quant": _quant_summary(engine),
            "weight_bytes": int(getattr(engine, "weight_bytes", 0) or 0),
            "n_ctx": getattr(cfg, "n_ctx", None),
            "kv_dtype": getattr(cfg, "kv_dtype", None),
            "state": "loaded",
        }

    @classmethod
    def from_specs(cls, specs: list[ModelSpec], build, *,
                   default_model: str, model_dir: str = "models",
                   weight_budget_bytes: int = 0) -> "ModelRegistry":
        """Load every spec through ``build(spec, path, shared_pool)``,
        accounting the HBM weight budget as the fleet grows and sharing
        the first paged engine's KV pool with every later compatible one.

        ``build`` must return an un-warmed engine; call
        :meth:`warmup` on the returned registry afterwards (budget
        refusal should cost a load, never a compile sweep)."""
        engines: dict[str, object] = {}
        info: list[dict] = []
        shared_pool = None
        used = 0
        for spec in specs:
            path = spec.resolved_path(model_dir)
            # lfkt-mem pre-load fit check: before a multi-GB load even
            # starts, ask the memory ledger whether the device can hold
            # it (file size lower-bounds the resident weight bytes; the
            # serving layout is never smaller than the quantized file).
            # Where the backend reports no memory_stats (CPU) this is a
            # no-op and the weight BUDGET below stays the only gate.
            try:
                est = os.path.getsize(path)
            except OSError:
                est = 0             # missing file: let build() name it
            refusal = _memledger.MEMLEDGER.fit_check(est, label=spec.name)
            if refusal is not None:
                raise WeightBudgetError(refusal)
            eng = build(spec, path, shared_pool)
            # responses, traces, /debug/requests rows and metric labels
            # all read model_name — the manifest alias IS the serving
            # identity, not the GGUF's embedded general.name
            eng.model_name = spec.name
            row = cls._describe(spec.name, eng, path=path)
            used += row["weight_bytes"]
            if weight_budget_bytes and used > weight_budget_bytes:
                table = ", ".join(
                    f"{r['name']}={r['weight_bytes'] / 1e6:.0f}MB"
                    for r in info + [row])
                raise WeightBudgetError(
                    f"HBM weight budget exhausted loading {spec.name!r}: "
                    f"{used / 1e6:.0f}MB of weights vs "
                    f"LFKT_HBM_WEIGHT_BUDGET_MB="
                    f"{weight_budget_bytes / 1e6:.0f}MB ({table}); shrink "
                    "the manifest, quantize harder, or raise the budget "
                    "(docs/MULTIMODEL.md)")
            engines[spec.name] = eng
            info.append(row)
            if shared_pool is None:
                shared_pool = getattr(eng, "_kvpool", None)
        logger.info(
            "model registry: %d models, %.0fMB weights%s (default=%s)",
            len(engines), used / 1e6,
            f" of {weight_budget_bytes / 1e6:.0f}MB budget"
            if weight_budget_bytes else "", default_model)
        return cls(engines, default_model, model_info=info)

    # -- routing --------------------------------------------------------
    def model_names(self) -> list[str]:
        return list(self._engines)

    def has_model(self, name: str) -> bool:
        return name in self._engines

    def resolve(self, model: str | None):
        """The engine serving ``model`` (None = the default alias)."""
        name = model or self.default_model
        eng = self._engines.get(name)
        if eng is None:
            raise UnknownModelError(name, list(self._engines))
        return eng

    def models(self) -> list[dict]:
        """Manifest descriptor rows — ``GET /v1/models`` and the /health
        ``models`` block (name, quant, weight bytes, load state)."""
        return [dict(r) for r in self._model_info]

    # -- engine-shaped facade -------------------------------------------
    def create_chat_completion(self, messages, stream: bool = False, *,
                               model: str | None = None, **kw):
        return self.resolve(model).create_chat_completion(
            messages, stream=stream, **kw)

    def _submit(self, messages, *, model: str | None = None, **kw):
        eng = self.resolve(model)
        fut = eng.submit(messages, **kw)
        fut._lfkt_engine = eng           # abandon() routes through this
        return fut

    def _submit_stream(self, messages, *, model: str | None = None, **kw):
        return self.resolve(model).submit_stream(messages, **kw)

    def _create_chat_completions(self, batch_messages, *,
                                 model: str | None = None, **kw):
        return self.resolve(model).create_chat_completions(
            batch_messages, **kw)

    def abandon(self, fut) -> None:
        eng = getattr(fut, "_lfkt_engine", None)
        if eng is not None and hasattr(eng, "abandon"):
            eng.abandon(fut)

    def warmup(self) -> None:
        for name, eng in self._engines.items():
            logger.info("warming up model %r", name)
            eng.warmup()

    def shutdown(self) -> None:
        for eng in self._engines.values():
            if hasattr(eng, "shutdown"):
                eng.shutdown()

    # -- telemetry fan-in/out -------------------------------------------
    @property
    def metrics_sink(self):
        return self._metrics_sink

    @metrics_sink.setter
    def metrics_sink(self, sink) -> None:
        self._metrics_sink = sink
        for eng in self._engines.values():
            if hasattr(eng, "metrics_sink"):
                eng.metrics_sink = sink

    def _pools(self) -> list:
        """Distinct KV pools across the fleet (shared pools once)."""
        seen: dict[int, object] = {}
        for eng in self._engines.values():
            pool = getattr(eng, "_kvpool", None)
            if pool is not None:
                seen[id(pool)] = pool
        return list(seen.values())

    @property
    def kv_cache_bytes(self) -> int:
        """Fleet-wide resident KV bytes: per-engine rings/state plus each
        DISTINCT pool arena once (engines sharing a pool each report the
        arena in their own figure — deduplicate it here)."""
        total = 0
        pool_refs: dict[int, list] = {}
        for eng in self._engines.values():
            total += int(getattr(eng, "kv_cache_bytes", 0) or 0)
            pool = getattr(eng, "_kvpool", None)
            if pool is not None:
                entry = pool_refs.setdefault(id(pool), [pool, 0])
                entry[1] += 1
        for pool, n in pool_refs.values():
            total -= (n - 1) * pool.arena_nbytes
        return total

    #: per-pool descriptive (NON-additive) occupancy fields: summing
    #: them across heterogeneous pools would report nonsense geometry —
    #: the merged document lists them per pool instead
    #: (largest_free_run is a within-arena contiguity fact: runs do not
    #: concatenate across arenas)
    _POOL_DESCRIPTIVE = ("page_tokens", "page_bytes", "largest_free_run")

    def kv_pool_occupancy(self) -> dict | None:
        """Merged pool occupancy + counters for /health and the
        ``kv_pool_pages_*`` gauges: the single shared pool verbatim (the
        common case); when geometry split the fleet across pools, the
        additive fields (page/spill counts, byte totals, event counters)
        are summed and the descriptive ones (page geometry) listed per
        pool under ``per_pool`` (``pools`` says how many)."""
        pools = self._pools()
        if not pools:
            return None
        if len(pools) == 1:
            p = pools[0]
            return {**p.occupancy(), **p.stats(), "pools": 1}
        out: dict = {"pools": len(pools), "per_pool": []}
        for p in pools:
            occ = p.occupancy()
            out["per_pool"].append(
                {k: occ[k] for k in self._POOL_DESCRIPTIVE})
            for k, v in {**occ, **p.stats()}.items():
                if k in self._POOL_DESCRIPTIVE:
                    continue
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        return out

    def _scheduler_stats(self) -> dict:
        """Per-model scheduler stats flattened under the model name
        (``scheduler_<model>_<stat>`` gauges), plus the fleet-level
        ``adm_budget_tokens``/``lane_idle_seconds`` the HPA scales on
        (summed: total scheduler pressure across co-resident models)."""
        out: dict = {"models": len(self._engines)}
        budget = 0
        idle = 0.0
        for name, eng in self._engines.items():
            stats = eng.scheduler_stats()
            budget += stats.get("adm_budget_tokens", 0)
            idle += stats.get("lane_idle_seconds", 0.0)
            for k, v in stats.items():
                if isinstance(v, dict):        # nested (spec): one level
                    for kk, vv in v.items():
                        out[f"{name}_{k}_{kk}"] = vv
                else:
                    out[f"{name}_{k}"] = v
        out["adm_budget_tokens"] = budget
        out["lane_idle_seconds"] = round(idle, 3)
        return out

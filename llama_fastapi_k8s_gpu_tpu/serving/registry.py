"""The model registry: N named engines served from one process.

ROADMAP item 5's subsystem (docs/MULTIMODEL.md).  The registry

- loads every :class:`~..serving.manifest.ModelSpec` through a
  caller-supplied ``build`` function (the server factory closes over the
  process-wide scheduler settings, so every model gets the same serving
  shape — lanes, chunk cadence, admission control);
- accounts an explicit **HBM weight budget** across the set and refuses
  at load time, with per-model attribution, when the fleet cannot fit
  (``LFKT_HBM_WEIGHT_BUDGET_MB``; a half-loaded fleet OOMing at first
  traffic is the failure mode this converts into a startup error);
- threads one **shared block-paged KV pool** through every compatible
  engine (same per-page cache geometry), so co-resident models partition
  one HBM page budget dynamically instead of each provisioning
  worst-case — with per-model radix **namespaces**, so tenant A's system
  prompt can never produce a phantom prefix hit for tenant B
  (parallel/kvpool.py);
- routes per-request ``model=`` to the named engine.  In continuous mode
  each model owns a scheduler (its own lanes); their device dispatches
  interleave on the chip's single execution queue, so waves of model A
  run between waves of model B — the co-resident-deployment shape of
  "Transformer-Lite" (PAPERS.md).

Gating, with attribution (the SPEngine-paging idiom): the ``cycle``
(mesh-batched) scheduler and the sequence-parallel engine coalesce
requests into one shared device program, which cannot interleave
models — the server factory refuses those combinations at startup.  The
engine watchdog is likewise single-engine (one heartbeat, one recovery
path) and does not run over a multi-model registry; per-engine scheduler
failures still fail fast through ``EngineUnavailable`` on their own
submit paths.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from ..obs import memledger as _memledger
from ..obs.logctx import sanitize_text
from .manifest import ModelSpec, parse_manifest, pick_default

logger = logging.getLogger(__name__)


class UnknownModelError(ValueError):
    """A request named a model the manifest does not serve (HTTP 400)."""

    def __init__(self, model: str, known: list[str]):
        self.model = model
        self.known = list(known)
        super().__init__(
            f"unknown model {model!r}; this pod serves: "
            f"{', '.join(self.known)}")


class WeightBudgetError(RuntimeError):
    """The manifest's weights exceed the declared HBM budget."""


#: weight-group leaf key -> served layout; ORDER MATTERS (specific keys
#: before the generic "q"/"w" fallbacks) — same map /health derives its
#: per-group weight_formats from (server/app.py)
_WEIGHT_KINDS = {"qs": "q4k-fused", "q5s": "q5k-fused",
                 "q5p": "q5k-fused-pre", "q4": "q6k-fused",
                 "q6p": "q6k-fused-pre", "q8": "q8-fused",
                 "q": "int8", "w": "bf16"}


def _quant_summary(engine) -> str | None:
    """One label for how the model's linear weights are served (e.g.
    ``q4k-fused`` or ``bf16+int8`` when groups differ) — the /health
    ``models`` row's ``quant`` field."""
    params = getattr(engine, "params", None)
    if not isinstance(params, dict) or "layers" not in params:
        return None
    fmts = {
        next((v for k, v in _WEIGHT_KINDS.items() if k in leaf), "?")
        for leaf in params["layers"].values() if isinstance(leaf, dict)
    }
    return "+".join(sorted(fmts)) if fmts else None


class ModelRegistry:
    """Named engines behind one engine-shaped facade.

    The server talks to a registry exactly as it talks to a single
    engine (``create_chat_completion`` / ``submit`` / ``scheduler_stats``
    / ``kv_cache_bytes`` ...), plus ``model=`` routing and the
    ``models()`` descriptor that feeds ``GET /v1/models`` and the
    /health ``models`` block.  ``submit``/``submit_stream``/
    ``create_chat_completions`` are installed only when every engine
    provides them, so the server's capability probes keep working.
    """

    # -- lock discipline (lfkt-lint LOCK001-004): one mutex guards the
    # routing dict, the descriptor rows and the in-flight counters; the
    # separate _reload_lock serializes whole reload operations (loads
    # run OUTSIDE _lock — a multi-GB load must not stall resolve())
    _GUARDED_BY = {
        "_engines": "_lock",
        "_model_info": "_lock",
        "_inflight": "_lock",
        "_specs": "_lock",
    }

    def __init__(self, engines: dict[str, object], default_model: str,
                 model_info: list[dict] | None = None):
        if not engines:
            raise ValueError("ModelRegistry needs at least one engine")
        if default_model not in engines:
            raise ValueError(
                f"default model {default_model!r} is not among "
                f"{', '.join(engines)}")
        self._lock = threading.Lock()
        self._reload_lock = threading.Lock()
        #: in-flight requests per model alias — reload's removal path
        #: waits for a model's count to reach zero before draining its
        #: namespace and releasing its weights
        self._inflight: dict[str, int] = {}
        #: the manifest specs behind each live engine (from_specs fills
        #: this; direct construction leaves it empty, which disables
        #: override-change detection but still allows remove-only reloads)
        self._specs: dict[str, ModelSpec] = {}
        #: reload plumbing (from_specs): the engine builder + its inputs
        self._build = None
        self._model_dir = "models"
        self._weight_budget_bytes = 0
        self._engines = dict(engines)
        for name, eng in self._engines.items():
            # the registry alias IS the serving identity: responses,
            # traces, /debug/requests rows and metric labels all read
            # model_name (from_specs already did this; direct
            # construction — tests, embedders — gets it here)
            try:
                eng.model_name = name
            except AttributeError:   # read-only property: keep its label
                pass
        self.default_model = default_model
        #: single-model-compat surface: responses carry their own model
        #: name; this is only the fallback label (e.g. untimed fakes)
        self.model_name = default_model
        self._model_info = list(model_info or [])
        if not self._model_info:
            self._model_info = [
                self._describe(name, eng, path=None)
                for name, eng in self._engines.items()
            ]
        self._metrics_sink = None
        if all(hasattr(e, "submit") for e in self._engines.values()):
            self.submit = self._submit
        if all(hasattr(e, "submit_stream") for e in self._engines.values()):
            self.submit_stream = self._submit_stream
        if all(hasattr(e, "create_chat_completions")
               for e in self._engines.values()):
            self.create_chat_completions = self._create_chat_completions
        if all(hasattr(e, "scheduler_stats")
               for e in self._engines.values()):
            self.scheduler_stats = self._scheduler_stats

    # ------------------------------------------------------------------
    @staticmethod
    def _describe(name: str, engine, path: str | None,
                  state: str = "ready") -> dict:
        # ``state`` is the live-reload observability surface (ISSUE 14):
        # loading (fit-checked, weights still coming up) -> ready
        # (routable) -> draining (unrouted, in-flight finishing + radix
        # namespace retiring).  /health shows every row; /v1/models lists
        # only routable ones — a half-reloaded pod is observable, never
        # lying.
        cfg = getattr(engine, "cfg", None)
        return {
            "name": name,
            "path": path,
            "quant": _quant_summary(engine),
            "weight_bytes": int(getattr(engine, "weight_bytes", 0) or 0),
            "n_ctx": getattr(cfg, "n_ctx", None),
            "kv_dtype": getattr(cfg, "kv_dtype", None),
            "state": state,
        }

    @classmethod
    def from_specs(cls, specs: list[ModelSpec], build, *,
                   default_model: str, model_dir: str = "models",
                   weight_budget_bytes: int = 0) -> "ModelRegistry":
        """Load every spec through ``build(spec, path, shared_pool)``,
        accounting the HBM weight budget as the fleet grows and sharing
        the first paged engine's KV pool with every later compatible one.

        ``build`` must return an un-warmed engine; call
        :meth:`warmup` on the returned registry afterwards (budget
        refusal should cost a load, never a compile sweep)."""
        engines: dict[str, object] = {}
        info: list[dict] = []
        shared_pool = None
        used = 0
        for spec in specs:
            path = spec.resolved_path(model_dir)
            # lfkt-mem pre-load fit check: before a multi-GB load even
            # starts, ask the memory ledger whether the device can hold
            # it (file size lower-bounds the resident weight bytes; the
            # serving layout is never smaller than the quantized file).
            # Where the backend reports no memory_stats (CPU) this is a
            # no-op and the weight BUDGET below stays the only gate.
            try:
                est = os.path.getsize(path)
            except OSError:
                est = 0             # missing file: let build() name it
            refusal = _memledger.MEMLEDGER.fit_check(est, label=spec.name)
            if refusal is not None:
                raise WeightBudgetError(refusal)
            eng = build(spec, path, shared_pool)
            # responses, traces, /debug/requests rows and metric labels
            # all read model_name — the manifest alias IS the serving
            # identity, not the GGUF's embedded general.name
            eng.model_name = spec.name
            row = cls._describe(spec.name, eng, path=path)
            used += row["weight_bytes"]
            if weight_budget_bytes and used > weight_budget_bytes:
                table = ", ".join(
                    f"{r['name']}={r['weight_bytes'] / 1e6:.0f}MB"
                    for r in info + [row])
                raise WeightBudgetError(
                    f"HBM weight budget exhausted loading {spec.name!r}: "
                    f"{used / 1e6:.0f}MB of weights vs "
                    f"LFKT_HBM_WEIGHT_BUDGET_MB="
                    f"{weight_budget_bytes / 1e6:.0f}MB ({table}); shrink "
                    "the manifest, quantize harder, or raise the budget "
                    "(docs/MULTIMODEL.md)")
            engines[spec.name] = eng
            info.append(row)
            if shared_pool is None:
                shared_pool = getattr(eng, "_kvpool", None)
        logger.info(  # lfkt: sanitizes[manifest] -- used is an integer byte counter (getsize/_describe sums); the only manifest string here is default_model, sanitized below
            "model registry: %d models, %.0fMB weights%s (default=%s)",
            len(engines), used / 1e6,
            f" of {weight_budget_bytes / 1e6:.0f}MB budget"
            if weight_budget_bytes else "",
            # the name may come from a POSTed reload manifest
            sanitize_text(default_model, limit=128))
        reg = cls(engines, default_model, model_info=info)
        # live-reload plumbing (reload_manifest): the SAME builder +
        # budget the startup load used, so a reloaded model is shaped
        # exactly like a boot-loaded one
        reg._build = build
        reg._model_dir = model_dir
        reg._weight_budget_bytes = weight_budget_bytes
        reg._specs = {s.name: s for s in specs}
        return reg

    # -- routing --------------------------------------------------------
    def model_names(self) -> list[str]:
        return list(self._engines)

    def has_model(self, name: str) -> bool:
        return name in self._engines

    def resolve(self, model: str | None):
        """The engine serving ``model`` (None = the default alias)."""
        name = model or self.default_model
        eng = self._engines.get(name)
        if eng is None:
            raise UnknownModelError(name, list(self._engines))
        return eng

    def models(self) -> list[dict]:
        """Manifest descriptor rows — ``GET /v1/models`` and the /health
        ``models`` block (name, quant, weight bytes, load state)."""
        with self._lock:
            return [dict(r) for r in self._model_info]

    # -- in-flight accounting (the reload drain's wait condition) --------
    def _resolve_tracked(self, model: str | None):
        """(name, engine) with the model's in-flight count raised; every
        facade entry pairs this with exactly one :meth:`_track_exit`.
        Lookup and increment share ONE lock acquisition: a reload
        removing the model either happens-before (the request 400s) or
        happens-after (the drain sees the raised count and waits) —
        never in between, where it would shut the engine down under a
        just-admitted request."""
        name = model or self.default_model
        with self._lock:
            eng = self._engines.get(name)
            if eng is not None:
                self._inflight[name] = self._inflight.get(name, 0) + 1
            known = list(self._engines)
        if eng is None:
            raise UnknownModelError(name, known)
        return name, eng

    def _track_exit(self, name: str) -> None:
        with self._lock:
            left = self._inflight.get(name, 0) - 1
            if left > 0:
                self._inflight[name] = left
            else:
                self._inflight.pop(name, None)

    def inflight(self, name: str) -> int:
        with self._lock:
            return self._inflight.get(name, 0)

    def _tracked_iter(self, name: str, it):
        """Stream wrapper: the request stays in-flight until the engine
        iterator finishes OR the caller closes it (disconnect path)."""
        try:
            yield from it
        finally:
            self._track_exit(name)

    # -- engine-shaped facade -------------------------------------------
    def create_chat_completion(self, messages, stream: bool = False, *,
                               model: str | None = None, **kw):
        name, eng = self._resolve_tracked(model)
        if stream:
            try:
                it = eng.create_chat_completion(messages, stream=True,
                                                **kw)
            except BaseException:
                self._track_exit(name)
                raise
            return self._tracked_iter(name, it)
        try:
            return eng.create_chat_completion(messages, stream=False, **kw)
        finally:
            self._track_exit(name)

    def _submit(self, messages, *, model: str | None = None, **kw):
        name, eng = self._resolve_tracked(model)
        try:
            fut = eng.submit(messages, **kw)
        except BaseException:
            self._track_exit(name)
            raise
        fut._lfkt_engine = eng           # abandon() routes through this
        fut.add_done_callback(lambda _f: self._track_exit(name))
        return fut

    def _submit_stream(self, messages, *, model: str | None = None, **kw):
        name, eng = self._resolve_tracked(model)
        try:
            it = eng.submit_stream(messages, **kw)
        except BaseException:
            self._track_exit(name)
            raise
        return self._tracked_iter(name, it)

    def _create_chat_completions(self, batch_messages, *,
                                 model: str | None = None, **kw):
        name, eng = self._resolve_tracked(model)
        try:
            return eng.create_chat_completions(batch_messages, **kw)
        finally:
            self._track_exit(name)

    def abandon(self, fut) -> None:
        eng = getattr(fut, "_lfkt_engine", None)
        if eng is not None and hasattr(eng, "abandon"):
            eng.abandon(fut)

    def warmup(self) -> None:
        for name, eng in self._engines.items():
            logger.info("warming up model %r", name)
            eng.warmup()

    def shutdown(self) -> None:
        for eng in self._engines.values():
            if hasattr(eng, "shutdown"):
                eng.shutdown()

    # -- live manifest reload (ISSUE 14; docs/MULTIMODEL.md) -------------
    #: facade capabilities every engine must share; an added engine
    #: missing one the registry installed at construction would silently
    #: break the server's capability probes mid-flight — refuse instead
    _CAPABILITIES = ("submit", "submit_stream", "create_chat_completions",
                     "scheduler_stats")

    def _emit_reload(self, action: str) -> None:
        m = self._metrics_sink
        if m is None:
            return
        try:
            m.inc("model_reloads_total", action=action)
        except Exception:  # noqa: BLE001 — telemetry must never fail reload
            pass

    def _set_state(self, name: str, state: str) -> None:
        with self._lock:
            for r in self._model_info:
                if r["name"] == name:
                    r["state"] = state

    def reload_manifest(self, manifest: str, default_model: str = "", *,  # lfkt: blocks-under[_reload_lock] -- reloads serialize whole-operation by design; the routing lock (_lock) is never held across loads, so resolve() stays hot
                        drain_seconds: float = 30.0) -> dict:
        """Diff a new ``LFKT_MODELS`` manifest against the running set and
        converge to it WITHOUT a pod restart (``POST /admin/models/reload``
        and SIGHUP — server/app.py):

        - **added** models load under the memory ledger's pre-load fit
          check and the HBM weight budget — a refusal
          (:class:`WeightBudgetError`) unwinds everything this reload
          loaded and leaves the running set untouched;
        - **removed** models first leave the routing table (new requests
          400 with the live model list), then wait out their in-flight
          requests (bounded by ``drain_seconds``), then retire their
          radix namespace through the pool's drain path
          (``KVPool.drain_namespace`` — pages freed, no cross-namespace
          eviction) before the engine (and its weights) is released;
        - **kept** models are untouched — changing a kept model's
          overrides/path is refused with attribution (remove + re-add
          under the new spec, or restart);
        - the default alias re-resolves against the NEW manifest
          (``LFKT_DEFAULT_MODEL`` semantics, pick_default).

        Model rows surface the transition (``loading``/``ready``/
        ``draining``) in /health throughout; /v1/models lists the
        routable set.  Returns the reload report."""
        specs = parse_manifest(manifest)
        default = pick_default(specs, default_model)
        with self._reload_lock:
            return self._reload(specs, default, drain_seconds)

    def _reload(self, specs: list[ModelSpec], default: str,
                drain_seconds: float) -> dict:
        t0 = time.time()
        new_names = {s.name for s in specs}
        added = [s for s in specs if s.name not in self._engines]
        removed = [n for n in self._engines if n not in new_names]
        changed = [s.name for s in specs
                   if s.name in self._specs and self._specs[s.name] != s]
        if changed:
            raise ValueError(
                f"reload cannot change a live model's spec in place: "
                f"{', '.join(sorted(changed))} (remove the alias in one "
                "reload and re-add it under the new path/overrides in the "
                "next, or restart the pod — docs/MULTIMODEL.md)")
        if added and self._build is None:
            raise ValueError(
                "this registry was not built from a manifest "
                "(ModelRegistry.from_specs): it can retire models but "
                "cannot load new ones")

        # -- phase 1: load additions (budget-refusable, running set
        # untouched until every addition is in hand) ----------------------
        loaded: list[tuple[ModelSpec, object, dict]] = []
        try:
            for spec in added:
                path = spec.resolved_path(self._model_dir)
                try:
                    est = os.path.getsize(path)
                except OSError:
                    est = 0         # missing file: let build() name it
                refusal = _memledger.MEMLEDGER.fit_check(est,
                                                         label=spec.name)
                if refusal is not None:
                    raise WeightBudgetError(refusal)
                # the loading row is visible in /health BEFORE the
                # (potentially minutes-long) load — observable, not lying
                placeholder = {"name": spec.name, "path": path,
                               "quant": None, "weight_bytes": 0,
                               "n_ctx": None, "kv_dtype": None,
                               "state": "loading"}
                with self._lock:
                    self._model_info.append(placeholder)
                eng = self._build(spec, path, self._shared_pool())
                eng.model_name = spec.name
                missing = [c for c in self._CAPABILITIES
                           if hasattr(self, c) and not hasattr(eng, c)]
                if missing:
                    raise ValueError(
                        f"added model {spec.name!r} lacks the fleet's "
                        f"shared capabilities ({', '.join(missing)}): "
                        "every co-resident engine must share one serving "
                        "shape (docs/MULTIMODEL.md)")
                row = self._describe(spec.name, eng, path=path,
                                     state="loading")
                budget = self._weight_budget_bytes
                used = self._live_weight_bytes() \
                    + sum(r["weight_bytes"] for _s, _e, r in loaded) \
                    + row["weight_bytes"]
                if budget and used > budget:
                    table = ", ".join(
                        f"{r['name']}={r['weight_bytes'] / 1e6:.0f}MB"
                        for r in self.models() + [row]
                        if r["weight_bytes"])
                    raise WeightBudgetError(
                        f"HBM weight budget exhausted reloading "
                        f"{spec.name!r}: {used / 1e6:.0f}MB of weights vs "
                        f"LFKT_HBM_WEIGHT_BUDGET_MB={budget / 1e6:.0f}MB "
                        f"({table}); the running set is untouched "
                        "(docs/MULTIMODEL.md)")
                # warm INSIDE the refusable phase: a failed compile
                # unwinds like a failed load (running set untouched),
                # instead of leaving earlier additions half-installed.
                # Appended BEFORE warming so the unwind releases this
                # engine too when its own warmup raises.
                loaded.append((spec, eng, row))
                logger.info("reload: warming up model %r", spec.name)
                eng.warmup()
        except Exception:
            # unwind: release everything THIS reload loaded and drop the
            # loading rows — the running set stays exactly as it was
            for _spec, eng, _row in loaded:
                if hasattr(eng, "shutdown"):
                    eng.shutdown()
            with self._lock:
                self._model_info = [
                    r for r in self._model_info
                    if not (r["state"] == "loading"
                            and r["name"] in {s.name for s in added})]
            self._emit_reload("refused")
            raise

        # install: every addition loaded AND warmed (all of phase 1 ran
        # off the routing lock — live traffic never stalled), so turning
        # routable is pure bookkeeping with no failure modes left
        for spec, eng, row in loaded:
            if self._metrics_sink is not None \
                    and hasattr(eng, "metrics_sink"):
                eng.metrics_sink = self._metrics_sink
            row["state"] = "ready"
            with self._lock:
                self._engines[spec.name] = eng
                self._specs[spec.name] = spec
                self._model_info = [
                    r for r in self._model_info
                    if not (r["name"] == spec.name
                            and r["state"] == "loading")] + [row]
            self._emit_reload("add")
            logger.info("reload: model %r ready", spec.name)

        # the default re-resolves against the NEW manifest BEFORE any
        # removal, so there is no instant with a dangling default
        self.default_model = default
        self.model_name = default

        # -- phase 2: removals (drain, then release) ----------------------
        drained: list[dict] = []
        for name in removed:
            with self._lock:
                eng = self._engines.pop(name)
                self._specs.pop(name, None)
            self._set_state(name, "draining")
            deadline = time.time() + drain_seconds
            # in-flight requests on the removed model finish (new ones
            # already 400 — the alias left the routing table above)
            while self.inflight(name) and time.time() < deadline:
                time.sleep(0.05)
            stranded = self.inflight(name)
            if stranded:
                logger.warning(
                    "reload: removing %r with %d request(s) still "
                    "in flight after the %.0fs drain budget", name,
                    stranded, drain_seconds)
            # retire the radix namespace: pages freed (never evicted
            # cross-namespace), polled until in-flight leases release
            pool = getattr(eng, "_kvpool", None)
            remaining = 0
            if pool is not None and hasattr(pool, "drain_namespace"):
                remaining = pool.drain_namespace(name)
                while remaining and time.time() < deadline:
                    time.sleep(0.05)
                    remaining = pool.drain_namespace(name)
            if hasattr(eng, "shutdown"):
                eng.shutdown()
            with self._lock:
                self._model_info = [r for r in self._model_info
                                    if r["name"] != name]
            self._emit_reload("remove")
            drained.append({"name": name, "pages_remaining": remaining,
                            "inflight_at_release": stranded})
            logger.info("reload: model %r removed (namespace drained, "
                        "%d pages remaining)", name, remaining)

        return {
            "added": [s.name for s in added],
            "removed": drained,
            "kept": sorted(n for n in new_names
                           if n not in {s.name for s in added}),
            "default_model": self.default_model,
            "models": self.models(),
            "wall_s": round(time.time() - t0, 3),
        }

    def _live_weight_bytes(self) -> int:
        with self._lock:
            return sum(r["weight_bytes"] for r in self._model_info
                       if r["state"] == "ready")

    def _shared_pool(self):
        """The pool new engines should join: the fleet's first live pool
        (build degrades geometry-incompatible engines to a private pool,
        exactly like the startup path)."""
        pools = self._pools()
        return pools[0] if pools else None

    # -- telemetry fan-in/out -------------------------------------------
    @property
    def metrics_sink(self):
        return self._metrics_sink

    @metrics_sink.setter
    def metrics_sink(self, sink) -> None:
        self._metrics_sink = sink
        for eng in self._engines.values():
            if hasattr(eng, "metrics_sink"):
                eng.metrics_sink = sink

    def _pools(self) -> list:
        """Distinct KV pools across the fleet (shared pools once)."""
        seen: dict[int, object] = {}
        for eng in self._engines.values():
            pool = getattr(eng, "_kvpool", None)
            if pool is not None:
                seen[id(pool)] = pool
        return list(seen.values())

    @property
    def kv_cache_bytes(self) -> int:
        """Fleet-wide resident KV bytes: per-engine rings/state plus each
        DISTINCT pool arena once (engines sharing a pool each report the
        arena in their own figure — deduplicate it here)."""
        total = 0
        pool_refs: dict[int, list] = {}
        for eng in self._engines.values():
            total += int(getattr(eng, "kv_cache_bytes", 0) or 0)
            pool = getattr(eng, "_kvpool", None)
            if pool is not None:
                entry = pool_refs.setdefault(id(pool), [pool, 0])
                entry[1] += 1
        for pool, n in pool_refs.values():
            total -= (n - 1) * pool.arena_nbytes
        return total

    #: per-pool descriptive (NON-additive) occupancy fields: summing
    #: them across heterogeneous pools would report nonsense geometry —
    #: the merged document lists them per pool instead
    #: (largest_free_run is a within-arena contiguity fact: runs do not
    #: concatenate across arenas)
    _POOL_DESCRIPTIVE = ("page_tokens", "page_bytes", "largest_free_run")

    def kv_pool_occupancy(self) -> dict | None:
        """Merged pool occupancy + counters for /health and the
        ``kv_pool_pages_*`` gauges: the single shared pool verbatim (the
        common case); when geometry split the fleet across pools, the
        additive fields (page/spill counts, byte totals, event counters)
        are summed and the descriptive ones (page geometry) listed per
        pool under ``per_pool`` (``pools`` says how many)."""
        pools = self._pools()
        if not pools:
            return None
        if len(pools) == 1:
            p = pools[0]
            return {**p.occupancy(), **p.stats(), "pools": 1}
        out: dict = {"pools": len(pools), "per_pool": []}
        for p in pools:
            occ = p.occupancy()
            out["per_pool"].append(
                {k: occ[k] for k in self._POOL_DESCRIPTIVE})
            for k, v in {**occ, **p.stats()}.items():
                if k in self._POOL_DESCRIPTIVE:
                    continue
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        return out

    def _scheduler_stats(self) -> dict:
        """Per-model scheduler stats flattened under the model name
        (``scheduler_<model>_<stat>`` gauges), plus the fleet-level
        ``adm_budget_tokens``/``lane_idle_seconds`` the HPA scales on
        (summed: total scheduler pressure across co-resident models)."""
        out: dict = {"models": len(self._engines)}
        budget = 0
        idle = 0.0
        for name, eng in self._engines.items():
            stats = eng.scheduler_stats()
            budget += stats.get("adm_budget_tokens", 0)
            idle += stats.get("lane_idle_seconds", 0.0)
            for k, v in stats.items():
                if isinstance(v, dict):        # nested (spec): one level
                    for kk, vv in v.items():
                        out[f"{name}_{k}_{kk}"] = vv
                else:
                    out[f"{name}_{k}"] = v
        out["adm_budget_tokens"] = budget
        out["lane_idle_seconds"] = round(idle, 3)
        return out

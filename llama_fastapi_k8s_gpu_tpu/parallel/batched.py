"""Batched (data-parallel) prefill/decode over a device mesh.

``vmap`` lifts the single-sequence model (models/llama.py) over a batch axis;
NamedShardings place the batch on the ``dp`` mesh axis and the model on
``tp``, so one jit'd program serves B concurrent sequences across the mesh —
the TPU-native replacement for the reference's "4 independent single-GPU
pods" data parallelism (SURVEY.md §2A), and the basis of the v5e-4
"concurrent /response load" config in BASELINE.json.

Every entry point here donates its ``state``/``caches`` pytree: callers
own the rebind-from-result contract, machine-checked at every call site
by lfkt-lint DON001-002 (the donor registry is scraped from these
``donate_argnames`` declarations — docs/LINT.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.llama import forward, init_cache, prefill
from ..obs.devtime import timed_jit
from ..sampling.sample import PENALTY_WINDOW, sample_chain


def init_batched_state(cfg: ModelConfig, batch: int, seed: int = 0) -> dict:
    """Batched generation state: every per-sequence leaf grows a leading
    ``batch`` dim.  The cache leaves' token axis therefore sits at axis 3
    — the paged KV pool's lane-store op (parallel/kvpool.py
    ``_store_lane_pages_jit``) indexes the batch dim away and slices that
    axis directly out of this layout, so a freed lane's conversation is
    committed to the pool without ever materializing a lane-ring copy."""
    cache = init_cache(cfg)
    return {
        "cache": jax.tree.map(lambda x: jnp.broadcast_to(x, (batch,) + x.shape), cache),
        "pos": jnp.zeros(batch, jnp.int32),
        "token": jnp.zeros(batch, jnp.int32),
        "window": jnp.full((batch, PENALTY_WINDOW), -1, jnp.int32),
        "wpos": jnp.zeros(batch, jnp.int32),
        "key": jax.random.split(jax.random.PRNGKey(seed), batch),
    }


def state_nbytes(state: dict | None) -> int:
    """Resident HBM bytes of a batched generation state (cache lanes +
    decode bookkeeping) — the memory ledger's ``kv_lanes`` row
    (obs/memledger.py).  One reduction for the whole ledger: this is
    ``tree_nbytes`` under the name that documents WHAT is being measured
    (``.nbytes`` is shape metadata, safe even while the donating chunk
    jits below hold the buffers in flight)."""
    from ..obs.memledger import tree_nbytes

    return tree_nbytes(state)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("caches",))
def batched_prefill_jit(params, cfg: ModelConfig, tokens, lengths, caches):
    """tokens (B, S) padded; lengths (B,). Returns (logits (B, V), caches)."""
    return jax.vmap(
        lambda t, l, c: prefill(params, cfg, t, l, c)
    )(tokens, lengths, caches)


batched_prefill_jit = timed_jit("batched_prefill", batched_prefill_jit,
                                site="parallel.batched")


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "top_k"),
    donate_argnames=("state",),
)
def batched_generate_chunk_jit(params, cfg: ModelConfig, state: dict, st: dict,
                               n_steps: int, top_k: int = 40):
    """B sequences × n_steps decode+sample steps on device, one shared set
    of sampling knobs.  Returns (state, tokens (n_steps, B))."""

    def one_step(carry, _):
        def single(token, pos, cache, window, wpos, key):
            logits, cache = forward(params, cfg, token[None], pos, cache)
            key, sub = jax.random.split(key)
            tok = sample_chain(logits, window, sub, st, top_k=top_k)
            window = window.at[wpos % PENALTY_WINDOW].set(tok)
            return tok, pos + 1, cache, window, wpos + 1, key

        tok, pos, cache, window, wpos, key = jax.vmap(single)(
            carry["token"], carry["pos"], carry["cache"],
            carry["window"], carry["wpos"], carry["key"],
        )
        new_carry = {"cache": cache, "pos": pos, "token": tok,
                     "window": window, "wpos": wpos, "key": key}
        return new_carry, tok

    return jax.lax.scan(one_step, state, None, length=n_steps)


batched_generate_chunk_jit = timed_jit(
    "batched_decode_chunk", batched_generate_chunk_jit,
    site="parallel.batched")


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "top_k"),
    donate_argnames=("state",),
)
def batched_generate_chunk_perlane_jit(params, cfg: ModelConfig, state: dict,
                                       lane_st: dict, n_steps: int,
                                       top_k: int = 40):
    """Like :func:`batched_generate_chunk_jit` but with **per-lane** sampling
    knobs (``lane_st`` leaves have a leading B dim) — the continuous
    scheduler admits requests with different temperatures/penalties into
    neighboring lanes.  (top_k stays a shared static: ``lax.top_k`` needs a
    static k; see ContinuousEngine.submit.)"""

    def one_step(carry, _):
        def single(token, pos, cache, window, wpos, key, st):
            logits, cache = forward(params, cfg, token[None], pos, cache)
            key, sub = jax.random.split(key)
            tok = sample_chain(logits, window, sub, st, top_k=top_k)
            window = window.at[wpos % PENALTY_WINDOW].set(tok)
            return tok, pos + 1, cache, window, wpos + 1, key

        tok, pos, cache, window, wpos, key = jax.vmap(single)(
            carry["token"], carry["pos"], carry["cache"],
            carry["window"], carry["wpos"], carry["key"], lane_st,
        )
        new_carry = {"cache": cache, "pos": pos, "token": tok,
                     "window": window, "wpos": wpos, "key": key}
        return new_carry, tok

    return jax.lax.scan(one_step, state, None, length=n_steps)


batched_generate_chunk_perlane_jit = timed_jit(
    "lane_decode_chunk", batched_generate_chunk_perlane_jit,
    site="parallel.batched")


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "top_k"),
    donate_argnames=("state",),
)
def batched_spec_verify_perlane_jit(params, cfg: ModelConfig, state: dict,
                                    lane_st: dict, drafts, top_k: int = 40):
    """Per-lane speculative verify: ``drafts`` (B, D) int32, one prompt-
    lookup draft per lane (zeros for lanes with no n-gram hit — they still
    advance by their one true sample).  ONE batched forward verifies every
    lane's draft, so the weight read is amortized over B lanes × up to D+1
    tokens.  Returns (state, toks (B, D+1), counts (B,)): lane ``l`` emits
    ``toks[l, :counts[l]]``.  Per-lane sampler replay is exactly
    models/generate.spec_verify vmapped — distributionally identical to
    sequential decoding per lane."""
    from ..models.generate import spec_verify

    def single(token, pos, cache, window, wpos, key, st, draft):
        s = {"token": token, "pos": pos, "cache": cache,
             "window": window, "wpos": wpos, "key": key}
        ns, toks, cnt = spec_verify(params, cfg, s, st, draft, top_k=top_k)
        return (ns["token"], ns["pos"], ns["cache"], ns["window"],
                ns["wpos"], ns["key"], toks, cnt)

    tok, pos, cache, window, wpos, key, toks, cnt = jax.vmap(single)(
        state["token"], state["pos"], state["cache"],
        state["window"], state["wpos"], state["key"], lane_st, drafts,
    )
    new_state = {"cache": cache, "pos": pos, "token": tok,
                 "window": window, "wpos": wpos, "key": key}
    return new_state, toks, cnt


batched_spec_verify_perlane_jit = timed_jit(
    "lane_spec_verify", batched_spec_verify_perlane_jit,
    site="parallel.batched")

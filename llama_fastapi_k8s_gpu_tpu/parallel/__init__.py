from .mesh import (  # noqa: F401
    make_mesh,
    param_shardings,
    cache_shardings,
    state_shardings,
    shard_params,
)
from .batched import batched_prefill_jit, batched_generate_chunk_jit, init_batched_state  # noqa: F401

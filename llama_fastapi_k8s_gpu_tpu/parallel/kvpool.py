"""Block-paged KV pool with a shared radix-tree prefix index.

The dense engines provision KV worst-case: one ``n_ctx`` ring per lane,
and prefix reuse that dies with its lane (``LFKT_LANE_PREFIX_CACHE``) or
with the next request (the serial claim).  This module turns the KV
budget into a **shared, dynamically partitioned resource**
("Transformer-Lite", PAPERS.md): a preallocated HBM arena of fixed-size
token *pages*, fronted by a radix tree keyed on token prefixes, so

- a shared system prompt prefills ONCE per process and every later
  request restores its pages instead of recomputing them;
- a multi-turn conversation resumes from its last committed page
  regardless of which lane it lands on;
- warm-but-idle conversations spill to host RAM (the K-in-HBM /
  V-offloaded split of "Efficient LLM Inference with Kcache", PAPERS.md,
  generalized to whole pages) and restore on their next hit.

Layout — **page-contiguous**, not gathered: a page is ``page_tokens``
consecutive token slots across ALL layers/heads of the cache pytree
(leaf-generic: the bf16 ``{k, v}`` layout and the int8 four-leaf layout
both slice their token axis, which is axis 2 in every leaf —
models/llama.py ``init_cache``).  On a prefix hit the matched pages are
copied **contiguously** into the front of an ordinary dense ring and the
suffix prefills from there, so every downstream consumer — the jit'd
prefill/decode programs, the flash-attention kernel's ring contract
(ops/pallas/attention.py), the int8 fused-dequant reads — is untouched,
and greedy decode under ``LFKT_KV_PAGED=1`` is bit-identical to the
dense path (pinned by tests/test_kv_paged_engines.py).  The price is one
page copy per hit/commit; the alternative (a page-table-indexed gather
inside the attention kernel) buys nothing until pages stop being
materialized, which is the disaggregated-prefill step (ROADMAP item 6 —
this module's page pytree is that wire format).

Concurrency: one internal lock guards the tree, the free list, the
refcounts and the arena reference; the serial engines call under their
generation mutex, the continuous scheduler from its own thread.  Pages
referenced by an in-flight request are pinned (per-page refcounts) and
can never be evicted; eviction is LRU over unpinned leaf nodes.

Namespaces (multi-model serving, docs/MULTIMODEL.md): every public
index operation takes a ``namespace`` key — one radix root per
namespace, so co-resident models sharing the arena can NEVER match each
other's prefixes (two models produce different KV for the same token
ids, and tenant A's system prompt must not leak into tenant B's cache).
The page arena, free list and LRU clock stay shared: N models partition
the same HBM page budget dynamically instead of each provisioning
worst-case, and eviction pressure from a hot model reclaims a cold
model's pages.  ``compatible()`` says whether another model's cache
geometry can share this arena at all (same leaf shapes/dtypes per page).

Compiled-shape bound: page moves dispatch in groups of at most
``_GROUP`` pages with traced offsets/ids, so the whole pool compiles at
most ``2 * _GROUP`` small copy programs per cache layout — page ops are
NOT part of the engines' warmed serving set and compile on first use.

Machine-checked contracts (lfkt-lint v2, docs/LINT.md): every caller of
:meth:`KVPool.acquire` must release or hand off the lease on every path
(RES001 — the PR-6 leak class), and the donating copy jits below feed
the DON donor registry — ``restore``'s ring parameter is donated
transitively, so engine call sites must rebind or drop their ref across
the call (DON001/DON002).
"""

from __future__ import annotations

import functools
import logging
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..models.config import ModelConfig
from ..models.llama import init_cache
from ..obs.devtime import timed_jit
from ..obs.memledger import register_component

logger = logging.getLogger(__name__)

#: max pages per jitted copy dispatch — bounds the compiled-program set
#: (group sizes 1.._GROUP, for store/restore/lane-store/upload each)
_GROUP = 8


# ---------------------------------------------------------------------------
# jitted page movement (leaf-generic: token axis is 2 in every cache leaf)
# ---------------------------------------------------------------------------

def _block_to_pages(block, n: int, page_tokens: int):
    """(L, n_kv, n*T, ...) token block -> (n, L, n_kv, T, ...) pages."""
    lead = block.shape[:2]
    tail = block.shape[3:]
    pages = block.reshape(lead + (n, page_tokens) + tail)
    perm = (2, 0, 1, 3) + tuple(range(4, 4 + len(tail)))
    return pages.transpose(perm)


def _pages_to_block(pages, n: int, page_tokens: int):
    """(n, L, n_kv, T, ...) pages -> (L, n_kv, n*T, ...) token block."""
    perm = (1, 2, 0, 3) + tuple(range(4, pages.ndim))
    stacked = pages.transpose(perm)
    lead = stacked.shape[:2]
    tail = stacked.shape[4:]
    return stacked.reshape(lead + (n * page_tokens,) + tail)


@functools.partial(jax.jit, donate_argnames=("arena",))
def _store_pages_jit(arena: dict, ring: dict, page_ids, offset):
    """Copy ring token slots [offset, offset + n*T) into arena pages
    ``page_ids`` (n traced via the ids' shape; offset traced)."""
    n = page_ids.shape[0]

    def per_leaf(al, rl):
        T = al.shape[3]
        block = jax.lax.dynamic_slice_in_dim(rl, offset, n * T, axis=2)
        return al.at[page_ids].set(_block_to_pages(block, n, T))

    return jax.tree.map(per_leaf, arena, ring)


_store_pages_jit = timed_jit("kvpool_store", _store_pages_jit,
                             site="parallel.kvpool")


@functools.partial(jax.jit, donate_argnames=("arena",))
def _store_lane_pages_jit(arena: dict, bcache: dict, lane, page_ids, offset):
    """As :func:`_store_pages_jit`, reading lane ``lane`` of a batched
    cache (leading batch dim) — the gather + slice + scatter fuse into one
    program, so no full lane ring is ever materialized (the peak-HBM trap
    the lane-snapshot path hit on 16 GB chips)."""
    n = page_ids.shape[0]

    def per_leaf(al, bl):
        T = al.shape[3]
        rl = jax.lax.dynamic_index_in_dim(bl, lane, axis=0, keepdims=False)
        block = jax.lax.dynamic_slice_in_dim(rl, offset, n * T, axis=2)
        return al.at[page_ids].set(_block_to_pages(block, n, T))

    return jax.tree.map(per_leaf, arena, bcache)


_store_lane_pages_jit = timed_jit("kvpool_lane_store", _store_lane_pages_jit,
                                  site="parallel.kvpool")


@functools.partial(jax.jit, donate_argnames=("ring",))
def _restore_pages_jit(arena: dict, ring: dict, page_ids, offset):
    """Copy arena pages ``page_ids`` into ring token slots
    [offset, offset + n*T), contiguously."""
    n = page_ids.shape[0]

    def per_leaf(al, rl):
        T = al.shape[3]
        block = _pages_to_block(al[page_ids], n, T)
        return jax.lax.dynamic_update_slice_in_dim(rl, block, offset, axis=2)

    return jax.tree.map(per_leaf, arena, ring)


_restore_pages_jit = timed_jit("kvpool_restore", _restore_pages_jit,
                               site="parallel.kvpool")


@functools.partial(jax.jit, donate_argnames=("arena",))
def _upload_pages_jit(arena: dict, pages: dict, page_ids):
    """Write host-restored page stacks back into arena slots (spill tier
    restore path)."""
    return jax.tree.map(lambda al, p: al.at[page_ids].set(p), arena, pages)


_upload_pages_jit = timed_jit("kvpool_upload", _upload_pages_jit,
                              site="parallel.kvpool")


# ---------------------------------------------------------------------------
# radix tree (page-granular: every edge is a run of whole pages)
# ---------------------------------------------------------------------------

class _Node:
    """One radix edge: a run of whole pages.  ``edge`` holds the token
    content as page tuples; ``pages`` the arena page ids (None when the
    node is spilled — ``host`` then holds the page pytree on host RAM).
    Children are keyed by their edge's FIRST page tuple, so two sequences
    diverging mid-page land under different keys (pages are the sharing
    unit: a partially shared page cannot be shared).  ``ns`` is the radix
    namespace the node lives under — the memory ledger's per-model
    attribution key (the tree itself never consults it)."""

    __slots__ = ("edge", "pages", "host", "children", "parent", "stamp",
                 "ns")

    def __init__(self, edge, pages, parent, ns: str = ""):
        self.edge: list[tuple] = edge          # page token tuples
        self.pages: list[int] | None = pages   # arena ids | None (spilled)
        self.host = None                       # host pytree when spilled
        self.children: dict[tuple, _Node] = {}
        self.parent: _Node | None = parent
        self.stamp = 0                         # LRU clock value
        self.ns = ns


class _Lease:
    """Pinned pages backing one in-flight request's prefix reuse."""

    __slots__ = ("tokens", "page_ids")

    def __init__(self, tokens: int, page_ids: list[int]):
        self.tokens = tokens
        self.page_ids = page_ids


class KVPool:
    """The process-wide paged KV arena + radix prefix index.

    ``sink_host`` is the owning engine (or any object with a
    ``metrics_sink`` attribute): hit/miss/eviction/spill/restore events
    are emitted into its metrics registry when the server injected one
    (obs/catalog.py families), and silently dropped otherwise — telemetry
    must never fail serving.
    """

    # -- lock discipline (machine-checked: lfkt-lint LOCK001-004) ----------
    # one mutex guards every mutable: tree, free list, refcounts, arena
    # reference, counters.  Device copies dispatch under the lock (they
    # are async enqueues); callers on any thread.
    _GUARDED_BY = {
        "arena": "_lock",
        "_free": "_lock",
        "_page_refs": "_lock",
        "_roots": "_lock",
        "_clock": "_lock",
        "_spill_used": "_lock",
        "_busy": "_lock",
        "counters": "_lock",
        "_ns_pages": "_lock",
    }

    def __init__(self, cfg: ModelConfig, page_tokens: int = 128,
                 n_pages: int = 0, spill_pages: int = 0, sink_host=None):
        T = int(page_tokens)
        if T < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        if T >= cfg.n_ctx:
            raise ValueError(
                f"page_tokens {T} must be smaller than n_ctx {cfg.n_ctx} "
                "(a usable prefix must leave >= 1 token to prefill)")
        self.page_tokens = T
        if n_pages <= 0:
            # auto: four full contexts' worth of pages — enough for a
            # system prompt + a handful of warm conversations per chip;
            # production sizes via LFKT_KV_POOL_PAGES (docs/RUNBOOK.md
            # "Sizing the KV page pool")
            n_pages = 4 * max(1, cfg.n_ctx // T)
        self.n_pages = int(n_pages)
        self.spill_pages = max(0, int(spill_pages))
        self._sink_host = sink_host
        spec = jax.eval_shape(lambda: init_cache(cfg))
        #: the paged arena: one leaf per cache leaf, page-major
        #: (n_pages, L, n_kv, T[, hd]) — allocated once, updated in place
        #: (the copy jits donate it)
        self.arena = jax.tree.map(
            lambda s: jnp.zeros((self.n_pages,) + s.shape[:2]
                                + (T,) + s.shape[3:], s.dtype), spec)
        self.page_nbytes = sum(
            int(np.prod(s.shape[:2] + (T,) + s.shape[3:]))
            * jnp.dtype(s.dtype).itemsize for s in jax.tree.leaves(spec))
        #: per-page-leaf geometry fingerprint: what another ModelConfig
        #: must reproduce to share this arena (see :meth:`compatible`)
        self._page_spec = tuple(
            (s.shape[:2] + (T,) + s.shape[3:], str(jnp.dtype(s.dtype)))
            for s in jax.tree.leaves(spec))
        self._lock = threading.Lock()
        self._free: list[int] = list(range(self.n_pages))
        self._page_refs: dict[int, int] = {}
        #: one radix root per namespace (model) — prefixes never match
        #: across namespaces; the arena/free-list/LRU stay shared
        self._roots: dict[str, _Node] = {}
        #: DEVICE-resident indexed pages per namespace, maintained
        #: incrementally at the four mutation sites (commit / spill /
        #: drop / spill-restore) so the memory ledger's per-model
        #: attribution is O(namespaces) per scrape instead of a radix DFS
        #: under the allocation lock (invariant pinned by test against a
        #: fresh tree walk)
        self._ns_pages: dict[str, int] = {}
        self._clock = 0
        self._spill_used = 0
        #: node ids an in-progress walk depends on — evict/age must skip
        self._busy: set[int] = set()
        #: monotonic event counters (tests + /health introspection; the
        #: Prometheus families are inc'd at event time via the sink)
        self.counters = {
            "hits": 0, "misses": 0, "reused_tokens": 0, "commits": 0,
            "stored_pages": 0, "evictions": 0, "spills": 0, "restores": 0,
            "store_skips": 0, "exported_pages": 0, "imported_pages": 0,
            "drained_pages": 0,
        }
        # lfkt-mem: attribute the arena into the process memory ledger —
        # indexed pages per namespace (model), the free list, and the
        # host spill tier.  A shared multi-model pool registers ONCE
        # (here, at construction), so the rows never double-count.
        register_component("kv_arena_used", self, KVPool._ledger_used)
        register_component("kv_arena_free", self, KVPool._ledger_free)
        register_component("host_spill", self, KVPool._ledger_spill)

    @property
    def _root(self) -> _Node:
        """Default-namespace radix root (white-box tests and single-model
        introspection; multi-model callers go through ``namespace=``).
        Lock-free — callers may already hold ``_lock`` (the white-box
        tests do); the dict setdefault is GIL-atomic."""
        root = self._roots.get("")
        if root is None:
            root = self._roots.setdefault("", _Node([], [], None, ""))  # lfkt: noqa[LOCK001] -- GIL-atomic setdefault (a losing racer's node is discarded); taking _lock here would deadlock the white-box callers that already hold it
        return root

    # -- telemetry (never fails serving) -----------------------------------
    def _metrics(self):
        host = self._sink_host
        return getattr(host, "metrics_sink", None) if host is not None \
            else None

    def _emit(self, kind: str, name: str, value: float = 1.0) -> None:
        m = self._metrics()
        if m is None:
            return
        try:
            getattr(m, kind)(name, value)
        except Exception:  # noqa: BLE001 — telemetry must never fail serving
            pass

    @property
    def arena_nbytes(self) -> int:
        """HBM bytes of the page arena (shape metadata; donation-safe)."""
        return sum(leaf.nbytes for leaf in jax.tree.leaves(self.arena))

    def compatible(self, cfg: ModelConfig, page_tokens: int | None = None
                   ) -> bool:
        """Whether a model with ``cfg``'s cache geometry can share this
        arena: same page size and the same per-leaf page shapes/dtypes
        (layers, kv heads, head dim, kv dtype layout).  Models that differ
        get their own pool — the registry attributes that at load time
        (docs/MULTIMODEL.md)."""
        if page_tokens is not None and int(page_tokens) != self.page_tokens:
            return False
        T = self.page_tokens
        spec = jax.eval_shape(lambda: init_cache(cfg))
        theirs = tuple(
            (s.shape[:2] + (T,) + s.shape[3:], str(jnp.dtype(s.dtype)))
            for s in jax.tree.leaves(spec))
        return theirs == self._page_spec

    def page_spec(self) -> tuple:
        """The per-leaf page geometry fingerprint ((shape, dtype_str), ...)
        + ``page_tokens`` is everything a peer pool must reproduce to
        exchange pages with this one — the disagg wire handshake's
        geometry block (serving/disagg/wire.py).  Immutable metadata: no
        lock needed."""
        return self._page_spec

    # ------------------------------------------------------------------
    # public surface (each entry point takes the lock once)
    # ------------------------------------------------------------------
    def match_len(self, ids, *, namespace: str = "") -> int:
        """Tokens of ``ids`` covered by cached whole pages (device OR
        spilled) in ``namespace`` — a pure peek: no pin, no counters, no
        restore."""
        with self._lock:
            return self._match(list(ids), namespace)[0] * self.page_tokens

    def note_miss(self) -> None:
        """Count one prefix-cache miss (the engine consulted the index and
        could not use it — no match, too short, or bucket-unfittable)."""
        with self._lock:
            self.counters["misses"] += 1
        self._emit("inc", "prefix_cache_misses_total")

    def acquire(self, ids, tokens: int, span=None, *,  # lfkt: blocks-under[_lock] -- spill-tier restore/evict moves pages device<->host: the radix+refcount walk and the copy must be atomic (bounded by page-group size)
                namespace: str = "") -> _Lease | None:
        """Pin the pages covering ``ids[:tokens]`` (``tokens`` a multiple
        of the page size, at most :meth:`match_len`).  Spilled pages on the
        path are restored into freshly allocated arena slots first; if that
        allocation cannot be satisfied (pool pinned solid) the acquire
        degrades to a miss (None) — requests proceed with a full prefill
        rather than block or OOM.  On success the matched region is
        LRU-touched and counted as a hit."""
        T = self.page_tokens
        want = tokens // T
        if want < 1:
            return None
        with self._lock:
            matched, path = self._match(list(ids), namespace)
            ok = matched >= want
            page_ids: list[int] = []
            if ok:
                # pin AS WE WALK (and mark the whole path busy): a later
                # node's spill-restore may evict, and eviction must never
                # take a page — or unlink a node — this lease is about to
                # reference
                self._busy.update(id(node) for node, _n in path)
                self._clock += 1
                try:
                    for node, n_pages in path:
                        if len(page_ids) >= want:
                            break
                        if node.pages is None and not self._restore_node(
                                node, span=span):
                            ok = False
                            break
                        node.stamp = self._clock
                        take = min(n_pages, want - len(page_ids))
                        for pid in node.pages[:take]:
                            self._page_refs[pid] = \
                                self._page_refs.get(pid, 0) + 1
                            page_ids.append(pid)
                except Exception as e:  # noqa: BLE001 — degrade to a miss
                    # (full prefill); ok=False routes through the unref
                    # cleanup below so pages pinned earlier in the walk
                    # don't leak into a permanently unevictable set
                    logger.warning("paged acquire failed; degrading to a "
                                   "full prefill: %s", e)
                    ok = False
                finally:
                    self._busy.clear()
            if not ok:
                for pid in page_ids:
                    self._unref(pid)
                self.counters["misses"] += 1
                self._emit("inc", "prefix_cache_misses_total")
                return None
            self.counters["hits"] += 1
            self.counters["reused_tokens"] += want * T
        self._emit("observe", "prefix_reuse_tokens", want * T)
        return _Lease(want * T, page_ids)

    def release(self, lease: _Lease | None) -> None:
        """Unpin a lease's pages (idempotent-safe only via the engines'
        single-live-lease bookkeeping — call exactly once per lease)."""
        if lease is None:
            return
        with self._lock:
            for pid in lease.page_ids:
                self._unref(pid)

    def restore(self, lease: _Lease, ring: dict, span=None) -> dict:
        """Copy the lease's pages contiguously into ring slots
        [0, lease.tokens) and return the updated ring (donated in place).
        The ring then serves the suffix prefill exactly as if those
        positions had been prefilled locally."""
        t0 = time.time()
        T = self.page_tokens
        with self._lock:
            off = 0
            ids = lease.page_ids
            while off < len(ids):
                g = ids[off:off + _GROUP]
                ring = _restore_pages_jit(
                    self.arena, ring, jnp.asarray(g, jnp.int32),
                    jnp.int32(off * T))
                off += len(g)
        if span is not None:
            span.event("kv_restore", pages=len(lease.page_ids),
                       tokens=lease.tokens,
                       bytes=len(lease.page_ids) * self.page_nbytes,
                       host_s=round(time.time() - t0, 6))
        return ring

    def export_pages(self, lease: _Lease) -> list:  # lfkt: blocks-under[_lock] -- the export gather is a synchronous DMA exactly like the spill path's; the pin+copy must be atomic against eviction
        """Host copies of the lease's pages, one stacked array per cache
        leaf (leading axis = page, in lease order) — the disagg wire's
        payload unit (serving/disagg/wire.py).  The lease pins the pages,
        so the gather can never race an eviction; the device_get is a
        synchronous DMA exactly like the spill path's."""
        pids = jnp.asarray(lease.page_ids, jnp.int32)
        with self._lock:
            leaves = jax.device_get(
                [al[pids] for al in jax.tree.leaves(self.arena)])
            self.counters["exported_pages"] += len(lease.page_ids)
        return leaves

    def import_pages(self, ids, leaves, *, namespace: str = "",  # lfkt: blocks-under[_lock] -- wire-page upload indexes into the radix as it copies: the index+arena move must be atomic (bounded by page-group size)
                     span=None) -> int:
        """Index externally produced KV pages — the disagg decode side
        (serving/disagg/decoder.py): the whole-page prefix of ``ids``
        arrives as host page stacks (one array per cache leaf, leading
        axis = page, covering ``len(ids)//page_tokens`` pages, the
        :meth:`export_pages` layout).  Pages already cached deduplicate
        (LRU touch only); the new tail uploads into freshly allocated
        arena pages and joins the tree via the SAME index-attach
        machinery as :meth:`commit` (:meth:`_index_tail` — the radix
        invariants cannot drift between local commits and wire imports),
        so the next :meth:`acquire` for this prefix restores it like any
        local commit.  Degrades exactly like commit — to the leading
        portion that fits, or to nothing, when the pool is pinned solid
        or a device copy fails; never blocks, never OOMs.  Returns the
        tokens the tree now covers for this prefix (cached + newly
        imported)."""
        ids = list(ids)
        T = self.page_tokens
        with self._lock:
            n_want = len(ids) // T
            if n_want < 1:
                return 0
            if any(leaf.shape[0] != n_want for leaf in leaves):
                raise ValueError(
                    f"page stacks cover "
                    f"{[leaf.shape[0] for leaf in leaves]} pages, ids "
                    f"cover {n_want} (geometry drift on the wire?)")
            treedef = jax.tree.structure(self.arena)

            def upload(pids: list, matched: int, n_tail: int) -> None:
                off = 0
                while off < n_tail:
                    g = pids[off:off + _GROUP]
                    stack = [
                        jnp.asarray(leaf[matched + off:
                                         matched + off + len(g)])
                        for leaf in leaves]
                    self.arena = _upload_pages_jit(
                        self.arena, jax.tree.unflatten(treedef, stack),
                        jnp.asarray(g, jnp.int32))
                    off += len(g)

            matched, stored = self._index_tail(ids, namespace, span,
                                               upload)
            if stored:
                self.counters["imported_pages"] += stored
            return (matched + stored) * T

    def commit(self, ids, ring: dict, span=None, *,
               namespace: str = "") -> int:
        """Index the whole-page prefix of ``ids`` whose KV sits in ring
        slots [0, len(ids)): pages already cached are deduplicated (LRU
        touch only), the new tail is copied into freshly allocated arena
        pages and inserted into the tree.  When the whole tail cannot be
        allocated (pool smaller than the conversation, or pinned solid)
        the commit degrades to the LEADING portion that fits — a squeezed
        pool still caches the conversation head, which is where the
        shared system prompt lives — and skips entirely only when not
        even one page can be had; serving never blocks on the cache.
        Returns the number of NEW pages stored."""
        return self._commit_impl(list(ids), ring=ring, span=span,
                                 namespace=namespace)

    def commit_lane(self, ids, bcache: dict, lane: int, span=None, *,
                    namespace: str = "") -> int:
        """As :meth:`commit`, reading lane ``lane`` of a batched cache —
        the continuous scheduler's freed-lane path."""
        return self._commit_impl(list(ids), bcache=bcache, lane=lane,
                                 span=span, namespace=namespace)

    def drain_namespace(self, namespace: str) -> int:
        """Retire one namespace's index (live model removal — serving/
        registry.py ``reload_manifest``): DROP every droppable node of
        ``namespace`` — device pages go straight to the free list, spilled
        stacks are released — and return the device pages the namespace
        still holds (pages pinned by in-flight leases, or nodes an
        in-progress walk marked busy; the caller polls until 0 under its
        drain budget).  Dropping, not spilling: the model is leaving, so
        its KV is garbage — and only THIS namespace is touched, so
        retiring a model can never evict a surviving tenant's warm pages
        (no cross-namespace eviction storm — pinned by test).  When the
        namespace empties, its root (and ledger row) is removed; a
        namespace never committed to is a no-op."""
        with self._lock:
            root = self._roots.get(namespace)
            if root is None:
                self._ns_pages.pop(namespace, None)
                return 0
            order: list[_Node] = []
            stack = list(root.children.values())
            while stack:
                n = stack.pop()
                order.append(n)
                stack.extend(n.children.values())
            drained = 0
            # children-first (reversed DFS order): dropping a subtree's
            # leaves turns its interior nodes into droppable leaves within
            # the same pass
            for node in reversed(order):
                if node.children or id(node) in self._busy:
                    continue
                if node.pages is not None:
                    if any(p in self._page_refs for p in node.pages):
                        continue        # pinned by an in-flight lease
                    n = len(node.pages)
                    self._free.extend(node.pages)
                    node.pages = None
                    self._ns_pages[namespace] = max(
                        0, self._ns_pages.get(namespace, 0) - n)
                    drained += n
                else:
                    self._spill_used -= len(node.edge)
                    node.host = None
                self._unlink(node)
            if drained:
                self.counters["drained_pages"] += drained
            if not root.children:
                self._roots.pop(namespace, None)
                self._ns_pages.pop(namespace, None)
                return 0
            return self._ns_pages.get(namespace, 0)

    def reset(self) -> None:
        """Drop the index (EVERY namespace) and free every page (watchdog
        recovery: lane contents are of unknown validity, so nothing
        resident is trustworthy — with a shared multi-model pool, one
        engine's trip resets all tenants' cache, conservatively).  Arena
        contents need no zeroing — unindexed pages are unreachable."""
        with self._lock:
            self._roots = {}
            self._free = list(range(self.n_pages))
            self._page_refs = {}
            self._spill_used = 0
            self._ns_pages = {}
            self._busy.clear()

    # -- memory-ledger providers (obs/memledger.py; called at snapshot
    # time from scrape/incident threads) -----------------------------------
    def _ledger_used(self) -> dict:
        """Indexed device pages per namespace, in bytes — read from the
        incrementally maintained ``_ns_pages`` counters, so a scrape
        holds the allocation lock for O(namespaces), never a radix DFS
        (the occupancy() no-stall rule; counter==tree invariant pinned by
        test).  Pages allocated but not (yet) reachable from any tree —
        an in-flight commit, or a store that failed before indexing —
        land under ``(unindexed)`` so the arena's used+free always sums
        to its full allocation."""
        with self._lock:
            per_ns = {ns: pages * self.page_nbytes
                      for ns, pages in self._ns_pages.items() if pages}
            inflight = (self.n_pages - len(self._free)) \
                - sum(self._ns_pages.values())
        if inflight > 0:
            per_ns["(unindexed)"] = inflight * self.page_nbytes
        return per_ns

    def _ledger_used_slow(self) -> dict:
        """The DFS ground truth ``_ledger_used`` must agree with — test
        oracle only (holds the lock for a full tree walk)."""
        with self._lock:
            per_ns: dict[str, int] = {}
            for ns, root in self._roots.items():
                pages = 0
                stack = list(root.children.values())
                while stack:
                    n = stack.pop()
                    stack.extend(n.children.values())
                    if n.pages is not None:
                        pages += len(n.pages)
                if pages:
                    per_ns[ns] = pages * self.page_nbytes
        return per_ns

    def _ledger_free(self) -> int:
        with self._lock:
            return len(self._free) * self.page_nbytes

    def _ledger_spill(self) -> int:
        with self._lock:
            return self._spill_used * self.page_nbytes

    def occupancy(self) -> dict:
        """Point-in-time pool occupancy for /health, the
        ``kv_pool_pages_{used,free}`` gauges and /debug/memory's
        fragmentation line (largest run of CONSECUTIVE free page ids vs
        the free count: a fragmented arena can hold many pages but no
        contiguous run — informational here, load-bearing once pages
        stream as the disaggregated-prefill wire format)."""
        with self._lock:
            free_ids = list(self._free)
            pinned = len(self._page_refs)
            spill = self._spill_used
            namespaces = len(self._roots)
        # the O(n log n) run scan happens OUTSIDE the lock (a /metrics
        # scrape must never stall a decode-path allocation on it); the
        # copied snapshot may be an instant stale, which is fine for an
        # occupancy report
        free = len(free_ids)
        run = best = 0
        prev = None
        for pid in sorted(free_ids):
            run = run + 1 if prev is not None and pid == prev + 1 else 1
            best = max(best, run)
            prev = pid
        return {
            "largest_free_run": best,
            "page_tokens": self.page_tokens,
            "page_bytes": self.page_nbytes,
            "pages_total": self.n_pages,
            "pages_used": self.n_pages - free,
            "pages_free": free,
            "pages_pinned": pinned,
            "spill_pages_total": self.spill_pages,
            "spill_pages_used": spill,
            "arena_bytes": self.arena_nbytes,
            "namespaces": namespaces,
        }

    def stats(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def hot_prefixes(self, k: int = 8) -> list[dict]:
        """The ``k`` most recently used cached prefixes across every
        namespace, hottest first — the unit of fleet KV migration
        (serving/fleet/migrate.py): graceful drain pushes these to the
        rendezvous successors, scale-out warm-up pulls peers' lists.
        Each row is ``{"namespace", "ids", "tokens"}`` with ``ids`` the
        full root→leaf token run (whole pages only — exactly what
        ``match_len``/``acquire`` on the far side can use).  Leaf chains
        only: an interior node's run is a prefix of its children's, so
        shipping leaves ships the interiors for free (``import_pages``
        dedups).  Hotness = the leaf's LRU stamp; spilled leaves count
        (their content is intact and exportable after restore)."""
        rows: list[tuple[int, str, list[int]]] = []
        with self._lock:
            for node in self._nodes():
                if node.children:
                    continue
                ids: list[int] = []
                chain: list[_Node] = []
                n: _Node | None = node
                while n is not None and n.parent is not None:
                    chain.append(n)
                    n = n.parent
                for n in reversed(chain):
                    for page in n.edge:
                        ids.extend(int(t) for t in page)
                if ids:
                    rows.append((node.stamp, node.ns, ids))
        rows.sort(key=lambda r: r[0], reverse=True)
        return [{"namespace": ns, "ids": ids, "tokens": len(ids)}
                for _, ns, ids in rows[:max(0, int(k))]]

    # ------------------------------------------------------------------
    # internals (lock held)
    # ------------------------------------------------------------------
    def _pages_of(self, ids: list) -> list[tuple]:
        T = self.page_tokens
        n = len(ids) // T
        return [tuple(ids[i * T:(i + 1) * T]) for i in range(n)]

    def _root_for(self, ns: str) -> _Node:  # lfkt: holds[_lock]
        root = self._roots.get(ns)
        if root is None:
            root = self._roots[ns] = _Node([], [], None, ns)
        return root

    def _match(self, ids: list, ns: str = ""):  # lfkt: holds[_lock]
        """Greedy page-wise walk of ``ns``'s tree.  Returns
        (matched_pages, path) where path is
        [(node, pages_matched_in_node), ...] root-first."""
        want = self._pages_of(ids)
        node = self._roots.get(ns)
        if node is None:
            return 0, []
        i = 0
        path: list[tuple[_Node, int]] = []
        while i < len(want):
            child = node.children.get(want[i])
            if child is None:
                break
            j = 0
            while j < len(child.edge) and i + j < len(want) \
                    and child.edge[j] == want[i + j]:
                j += 1
            path.append((child, j))
            i += j
            if j < len(child.edge):
                break
            node = child
        return i, path

    def _unref(self, pid: int) -> None:  # lfkt: holds[_lock]
        left = self._page_refs.get(pid, 0) - 1
        if left > 0:
            self._page_refs[pid] = left
        else:
            self._page_refs.pop(pid, None)

    def _restore_node(self, node: _Node,
                      span=None) -> bool:  # lfkt: holds[_lock]
        """Bring one spilled node's pages back into the arena (allocating,
        which may evict LRU unpinned nodes).  False when the allocation
        cannot be satisfied — the caller degrades to a miss."""
        n = len(node.edge)
        pids = self._alloc(n)
        if pids is None:
            return False
        t0 = time.time()
        try:
            self.arena = _upload_pages_jit(
                self.arena,
                jax.tree.map(lambda h: jnp.asarray(h), node.host),
                jnp.asarray(pids, jnp.int32))
        except Exception as e:  # noqa: BLE001 — degrade to a miss: the
            # caller takes a full prefill; the just-allocated (unpinned,
            # unindexed) slots must go back on the free list or they leak
            # for the life of the process
            self._free.extend(pids)
            logger.warning("spill restore failed; degrading to a full "
                           "prefill: %s", e)
            return False
        node.pages = pids
        node.host = None
        self._spill_used -= n
        self._ns_pages[node.ns] = self._ns_pages.get(node.ns, 0) + n
        self.counters["restores"] += 1
        self._emit("inc", "prefix_cache_restores_total")
        if span is not None:
            span.event("kv_spill_restore", pages=n,
                       bytes=n * self.page_nbytes,
                       host_s=round(time.time() - t0, 6))
        return True

    def _commit_impl(self, ids: list, ring=None, bcache=None, lane=None,  # lfkt: blocks-under[_lock] -- commit indexes the tail into the radix as it stores: spill-tier evictions on the alloc path are part of the atomic move
                     span=None, namespace: str = "") -> int:
        with self._lock:
            if len(ids) < self.page_tokens:
                return 0
            self.counters["commits"] += 1
            T = self.page_tokens

            def store(pids: list, matched: int, n_tail: int) -> None:
                off = 0
                while off < n_tail:
                    g = jnp.asarray(pids[off:off + _GROUP], jnp.int32)
                    go = jnp.int32((matched + off) * T)
                    if ring is not None:
                        self.arena = _store_pages_jit(self.arena, ring,
                                                      g, go)
                    else:
                        self.arena = _store_lane_pages_jit(
                            self.arena, bcache, jnp.int32(lane), g, go)
                    off += len(g)

            _matched, stored = self._index_tail(ids, namespace, span,
                                                store)
            return stored

    def _index_tail(self, ids: list, namespace: str, span,
                    store) -> tuple:  # lfkt: holds[_lock]
        """THE index-attach skeleton shared by :meth:`commit` /
        :meth:`commit_lane` (device-side ring/lane store) and
        :meth:`import_pages` (host-stack upload, the disagg wire): match
        + LRU-touch, busy-pin the match path, allocate the tail with the
        halving degrade, split/attach, run ``store(pids, matched_pages,
        n_tail)`` (the ONLY varying part — it performs the device
        copies), then insert the node and maintain the counters.
        Returns ``(matched_pages, stored_pages)``.

        Degrade contract: the cache is an optimization — a failed page
        copy must not fail the finished request (or the scheduler loop,
        on the freed-lane path), so a raising ``store`` returns the
        not-yet-indexed pids to the free list (partially copied groups
        are unreachable without a tree node, hence harmless) and reports
        0 stored."""
        want = self._pages_of(ids)
        if not want:
            return 0, 0
        matched, path = self._match(ids, namespace)
        self._clock += 1
        for node, _n in path:
            node.stamp = self._clock
        if matched >= len(want):
            return matched, 0              # fully cached already
        tail = want[matched:]
        # mark the match path busy: the tail's allocation may evict, and
        # evicting (= unlinking) a path node would orphan the subtree
        # this commit is about to attach to
        self._busy.update(id(node) for node, _n in path)
        try:
            n = len(tail)
            pids = self._alloc(n, span=span)
            while pids is None and n > 1:
                # degrade to the leading portion that fits (halving:
                # O(log) alloc attempts, each of which may evict)
                n //= 2
                pids = self._alloc(n, span=span)
        finally:
            self._busy.clear()
        if pids is None:
            self.counters["store_skips"] += 1
            return matched, 0
        tail = tail[:n]
        # attach point: deepest fully-matched node, splitting a
        # partially-matched edge at its page boundary first
        if path and path[-1][1] < len(path[-1][0].edge):
            parent = self._split(path[-1][0], path[-1][1])
        elif path:
            parent = path[-1][0]
        else:
            parent = self._root_for(namespace)
        try:
            store(pids, matched, len(tail))
        except Exception as e:  # noqa: BLE001 — skip the store (see the
            # degrade contract in the docstring)
            self._free.extend(pids)
            self.counters["store_skips"] += 1
            logger.warning("page store failed; commit skipped: %s", e)
            return matched, 0
        child = _Node(tail, pids, parent, namespace)
        child.stamp = self._clock
        parent.children[tail[0]] = child
        self._ns_pages[namespace] = \
            self._ns_pages.get(namespace, 0) + len(tail)
        self.counters["stored_pages"] += len(tail)
        return matched, len(tail)

    def _split(self, node: _Node, at: int) -> _Node:  # lfkt: holds[_lock]
        """Split ``node``'s edge after ``at`` pages; returns the new upper
        node (the attach point for a diverging sibling).  ``at`` >= 1 by
        construction (children are keyed by their first page)."""
        upper = _Node(node.edge[:at],
                      node.pages[:at] if node.pages is not None else None,
                      node.parent, node.ns)
        upper.stamp = node.stamp
        if node.pages is None:
            # spilled: split the host page stacks along the page axis
            upper.host = jax.tree.map(lambda h: h[:at], node.host)
            node.host = jax.tree.map(lambda h: h[at:], node.host)
        else:
            node.pages = node.pages[at:]
        node.edge = node.edge[at:]
        node.parent.children[upper.edge[0]] = upper
        upper.children[node.edge[0]] = node
        node.parent = upper
        return upper

    def _nodes(self) -> list:  # lfkt: holds[_lock]
        """Every tree node across ALL namespaces — eviction/spill/aging
        are pool-wide (one LRU clock), so a hot model's pressure reclaims
        a cold model's pages."""
        out = []
        stack = [c for root in self._roots.values()
                 for c in root.children.values()]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            out.append(n)
        return out

    def _evictable(self) -> list:  # lfkt: holds[_lock]
        """Non-busy device-resident nodes with every page unpinned —
        spill-eligible; drop-eligible additionally requires no children
        (dropping an interior node would orphan its subtree)."""
        return [n for n in self._nodes()
                if n.pages is not None and id(n) not in self._busy
                and not any(p in self._page_refs for p in n.pages)]

    def _spilled_leaves(self) -> list:  # lfkt: holds[_lock]
        """Non-busy spilled leaves — the spill-tier aging set."""
        return [n for n in self._nodes()
                if n.pages is None and not n.children
                and id(n) not in self._busy]

    def _unlink(self, node: _Node) -> None:  # lfkt: holds[_lock]
        node.parent.children.pop(node.edge[0], None)
        node.parent = None

    def _evict_one(self, span=None) -> bool:  # lfkt: holds[_lock]
        """Evict one node, LRU-first: spill its pages to host RAM when the
        spill tier has room (aging out the LRU *spilled* leaf when it
        doesn't), otherwise drop it — interior nodes can only take the
        spill path (dropping one would orphan its subtree), so under a
        full spill tier the LRU droppable *leaf* is taken instead.  False
        when nothing is evictable (every resident page pinned)."""
        cands = sorted(self._evictable(), key=lambda n: n.stamp)
        if not cands:
            return False
        for victim in cands:
            n = len(victim.pages)
            if self.spill_pages:
                # age the spill tier: drop LRU spilled leaves until the
                # victim fits (a spilled conversation colder than the one
                # being evicted is the right one to forget) — but ONLY
                # when aging can actually make it fit: pages held by
                # spilled INTERIOR nodes cannot be aged away (dropping
                # one would orphan its subtree), so a victim that cannot
                # fit past them — or past the tier size itself — skips
                # straight to the drop path instead of destroying every
                # warm leaf for zero benefit.  (Conservative: cascading
                # unlinks could turn an interior node into an ageable
                # leaf mid-loop; we forgo that to keep the guard simple.)
                unageable = self._spill_used - sum(
                    len(s.edge) for s in self._spilled_leaves())
                while n + unageable <= self.spill_pages \
                        and self._spill_used + n > self.spill_pages:
                    spilled = self._spilled_leaves()
                    if not spilled:
                        break
                    aged = min(spilled, key=lambda s: s.stamp)
                    self._spill_used -= len(aged.edge)
                    aged.host = None
                    self._unlink(aged)
            if self.spill_pages and self._spill_used + n <= self.spill_pages:
                t0 = time.time()
                # DMA the victim's pages to host, then free the arena
                # slots; the node stays matchable, restoring on its next
                # hit (works for interior nodes: the tree is untouched)
                victim.host = jax.device_get(jax.tree.map(
                    lambda al: al[jnp.asarray(victim.pages, jnp.int32)],
                    self.arena))
                self._spill_used += n
                self.counters["spills"] += 1
                self._emit("inc", "prefix_cache_spills_total")
                if span is not None:
                    span.event("kv_spill", pages=n,
                               bytes=n * self.page_nbytes,
                               host_s=round(time.time() - t0, 6))
                self._free.extend(victim.pages)
                victim.pages = None
                self._ns_pages[victim.ns] = max(
                    0, self._ns_pages.get(victim.ns, 0) - n)
            elif not victim.children:
                self._free.extend(victim.pages)
                victim.pages = None
                self._ns_pages[victim.ns] = max(
                    0, self._ns_pages.get(victim.ns, 0) - n)
                self._unlink(victim)
            else:
                continue        # interior, no spill room: try the next LRU
            self.counters["evictions"] += 1
            self._emit("inc", "prefix_cache_evictions_total")
            return True
        return False

    def _alloc(self, n: int, span=None):  # lfkt: holds[_lock]
        """``n`` free page ids, evicting LRU unpinned nodes as needed;
        None when the demand cannot be met (pinned solid)."""
        if n > self.n_pages:
            return None
        while len(self._free) < n:
            if not self._evict_one(span=span):
                return None
        out = self._free[:n]
        del self._free[:n]
        return out

"""Device meshes and sharding rules.

The reference has no distributed backend at all — its only parallelism is
k8s-replica data parallelism behind a Service (SURVEY.md §2A "Parallelism
strategies").  On TPU the equivalent *and more* is declarative: build a
``jax.sharding.Mesh`` over the chips, annotate the param/cache pytrees with
``NamedSharding``s, and XLA inserts the collectives (all-gather /
psum / reduce-scatter) over ICI.  There is no NCCL analogue to wrap —
declaring shardings IS the communication backend on TPU (SURVEY.md §5
"Distributed communication backend").

Axes:
- ``dp`` — data parallel over concurrent requests (batch dim).
- ``tp`` — tensor parallel (Megatron-style): attention heads and FFN hidden
  sharded column-wise, output projections row-wise (psum on exit),
  KV cache sharded over kv-heads, LM head sharded over vocab.

The same rules drive the v5e-4 serving config and the virtual 8-device CPU
mesh used by tests and the driver's multi-chip dryrun.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1, devices=None) -> Mesh:
    """dp × tp × sp device mesh.  ``sp`` is the sequence-parallel axis used
    by ring attention (parallel/ring.py); it defaults to 1 so dp/tp-only
    callers see the same layouts as before."""
    if devices is None:
        devices = jax.devices()
    n = dp * tp * sp
    if n > len(devices):
        raise ValueError(f"mesh {dp}x{tp}x{sp} needs {n} devices, have {len(devices)}")
    mesh_devices = mesh_utils.create_device_mesh((dp, tp, sp), devices=devices[:n])
    return Mesh(mesh_devices, axis_names=("dp", "tp", "sp"))


def _ns(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def _linear_sharding(mesh: Mesh, col_parallel: bool) -> dict:
    """Sharding for a stacked linear {'w': (L,out,in)}, {'q','s'} (int8), or
    {'qs','sm'} (fused Q4_K; qs (L,out,in/2), sm (L,in/2048,out,128)).

    Column-parallel (wq/wk/wv/w_gate/w_up): shard the output dim.
    Row-parallel (wo/w_down): shard the input dim; XLA inserts the psum.

    Fused Q4_K shards its OUTPUT dim in both cases: the pallas matmul
    partitions over N (custom_partitioning in ops/pallas/qmatmul.py) but
    never over the contraction dim (K tiles are 2048-wide and e.g. ffn_down's
    7 tiles don't divide tp) — and for row-parallel layers, all-gathering the
    small activations beats all-gathering the quantized weights by ~3 orders
    of magnitude at decode (B=1: KBs of activations vs GBs of weights).
    """
    # fused layouts ({qs,sm} Q4_K / {q5s,q5h,sm5} Q5_K / {q4,q2,sm6} Q6_K)
    # shard their OUTPUT dim in both cases — see the docstring above
    fused_col = {
        "qs": _ns(mesh, None, "tp", None),
        "sm": _ns(mesh, None, None, "tp", None),
        "q5s": _ns(mesh, None, "tp", None),
        "q5h": _ns(mesh, None, "tp", None),
        "q5p": _ns(mesh, None, "tp", None),
        "sm5": _ns(mesh, None, None, "tp", None),
        "q4": _ns(mesh, None, "tp", None),
        "q2": _ns(mesh, None, "tp", None),
        "q6p": _ns(mesh, None, "tp", None),
        "sm6": _ns(mesh, None, None, "tp", None),
        "q8": _ns(mesh, None, "tp", None),
        "sm8": _ns(mesh, None, None, "tp", None),
    }
    if col_parallel:
        return {"w": _ns(mesh, None, "tp", None),
                "q": _ns(mesh, None, "tp", None),
                "s": _ns(mesh, None, "tp"),
                **fused_col}
    return {"w": _ns(mesh, None, None, "tp"),
            "q": _ns(mesh, None, None, "tp"),
            "s": _ns(mesh, None, None),
            **fused_col}


def _match_linear(shardings: dict, linear: dict) -> dict:
    return {k: shardings[k] for k in linear}


def param_shardings(params: dict, mesh: Mesh) -> dict:
    """NamedSharding pytree matching a param pytree from models.params."""
    col = _linear_sharding(mesh, True)
    row = _linear_sharding(mesh, False)
    layers = params["layers"]
    layer_shard = {}
    for name, leaf in layers.items():
        if name in ("attn_norm", "ffn_norm"):
            layer_shard[name] = _ns(mesh, None, None)
        elif name in ("wq", "wk", "wv", "w_gate", "w_up"):
            layer_shard[name] = _match_linear(col, leaf)
        else:  # wo, w_down
            layer_shard[name] = _match_linear(row, leaf)
    out = params["output"]
    head = {"w": _ns(mesh, "tp", None), "q": _ns(mesh, "tp", None),
            "s": _ns(mesh, "tp"), "qs": _ns(mesh, "tp", None),
            "sm": _ns(mesh, None, "tp", None),
            "q5s": _ns(mesh, "tp", None), "q5h": _ns(mesh, "tp", None),
            "q5p": _ns(mesh, "tp", None),
            "sm5": _ns(mesh, None, "tp", None),
            "q4": _ns(mesh, "tp", None), "q2": _ns(mesh, "tp", None),
            "q6p": _ns(mesh, "tp", None),
            "sm6": _ns(mesh, None, "tp", None),
            "q8": _ns(mesh, "tp", None),
            "sm8": _ns(mesh, None, "tp", None)}
    out_shard = {k: head[k] for k in out}
    return {
        "tok_emb": _ns(mesh, None, None),      # replicated (gather-heavy)
        "layers": layer_shard,
        "out_norm": _ns(mesh, None),
        "output": out_shard,
    }


def cache_shardings(cfg: ModelConfig, mesh: Mesh, batched: bool = False):
    """Head-major KV cache (L, n_kv, ctx, hd): kv-heads over tp; batch (if
    any) over dp.  Under ``kv_dtype=int8`` the int8 value rings keep that
    spec and the (L, n_kv, ctx) scale planes get it minus the hd axis."""
    lead = ("dp",) if batched else ()
    s4 = _ns(mesh, *lead, None, "tp", None, None)
    if cfg.kv_dtype == "int8":
        s3 = _ns(mesh, *lead, None, "tp", None)
        return {"k_q": s4, "v_q": s4, "k_s": s3, "v_s": s3}
    return {"k": s4, "v": s4}


def state_shardings(cfg: ModelConfig, mesh: Mesh, batched: bool = False) -> dict:
    """Shardings for the generation-state pytree (models.generate.init_state)."""
    if batched:
        scalar = _ns(mesh, "dp")
        vec = _ns(mesh, "dp", None)
    else:
        scalar = _ns(mesh)
        vec = _ns(mesh, None)
    return {
        "cache": cache_shardings(cfg, mesh, batched),
        "pos": scalar,
        "token": scalar,
        "window": vec,
        "wpos": scalar,
        "key": vec,
    }


def _fit_sharding(arr, ns: NamedSharding) -> NamedSharding:
    """Drop spec axes an array can't honor (dim not divisible by the mesh
    axis) — e.g. tiny test vocabularies vs a tp-sharded LM head.  Real model
    dims divide evenly and keep the full spec."""
    mesh = ns.mesh
    spec = list(ns.spec) + [None] * (arr.ndim - len(ns.spec))
    fixed = []
    for dim, axes in zip(arr.shape, spec):
        if axes is None:
            fixed.append(None)
            continue
        names = (axes,) if isinstance(axes, str) else tuple(axes)
        size = int(np.prod([mesh.shape[a] for a in names]))
        fixed.append(axes if dim % size == 0 else None)
    return NamedSharding(mesh, P(*fixed))


# layout → main leaf (the plane whose N dim decides the whole group's fit);
# "q6p" is the Q6_K `pre` layout's single combined plane
_FUSED_MAIN_KEY = {"qs": "qs", "q4": "q4", "q6p": "q6p",
                   "q5s": "q5s", "q5p": "q5p", "q8": "q8"}


def _fused_key(p: dict) -> str | None:
    for k in _FUSED_MAIN_KEY:
        if k in p:
            return k
    return None


def _fit_q4k(leaf: dict, shard: dict) -> dict:
    """Fused Q4_K/Q6_K leaves: keep the N sharding only if every local shard
    still satisfies the kernel's N tiling (128 sublanes on TPU, 8 in
    interpret mode); otherwise replicate the whole leaf — a half-sharded
    {qs, sm} / {q4, q2, sm6} group would just reshard inside the
    partition rule."""
    from ..ops.pallas import use_interpret

    gran = 8 if use_interpret() else 128
    key = _fused_key(leaf)
    qs = leaf[key]
    ns = shard[key]
    n_dim = qs.ndim - 2                      # (L, N, K/x) or (N, K/x)
    spec = list(ns.spec) + [None] * (qs.ndim - len(ns.spec))
    axes = spec[n_dim]
    keep = True
    if axes is not None:
        names = (axes,) if isinstance(axes, str) else tuple(axes)
        size = int(np.prod([ns.mesh.shape[a] for a in names]))
        N = qs.shape[n_dim]
        keep = N % size == 0 and (N // size) % gran == 0
    if keep:
        return {k: _fit_sharding(leaf[k], shard[k]) for k in leaf}
    return {k: NamedSharding(ns.mesh, P(*([None] * leaf[k].ndim)))
            for k in leaf}


def shard_fused_linear(w: dict, mesh: Mesh, axis: str = "tp") -> dict:
    """Shardings for ONE unstacked fused-layout linear ({qs,sm} /
    {q5s,q5h,sm5} / {q4,q2,sm6} without the layer dim): quantized planes
    (N, K/x) shard their output dim N; scale tables (kt, N, 128) shard N in
    the middle.  The single source for tests/dryruns that shard a bare
    fused dict — the stacked serving path uses :func:`param_shardings`."""
    return {k: (_ns(mesh, axis, None) if w[k].ndim == 2
                else _ns(mesh, None, axis, None)) for k in w}


def fit_shardings(params: dict, shardings: dict) -> dict:
    def fit(p, s):
        if isinstance(p, dict) and _fused_key(p):
            return _fit_q4k(p, s)
        return jax.tree.map(_fit_sharding, p, s)

    return jax.tree.map(
        fit, params, shardings,
        is_leaf=lambda x: isinstance(x, dict) and _fused_key(x) is not None)


def shard_params(params: dict, mesh: Mesh) -> dict:
    return jax.device_put(
        params, fit_shardings(params, param_shardings(params, mesh)))


"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has no long-context story at all: it *suppresses* sequence
length (n_ctx=1024, 400-char clips, oldest-message eviction — reference
api.py:27,37-46; SURVEY.md §5 "Long-context / sequence parallelism").  Here
long context is first-class: the token dimension (and the KV cache's n_ctx
dimension) shard over the ``sp`` mesh axis, and attention runs as a ring —
each device holds one KV chunk, computes a blockwise online-softmax update
against its current chunk, and passes the chunk to its neighbor with
``jax.lax.ppermute`` (ICI neighbor exchange), ``sp`` steps total.  No device
ever materializes more than 1/sp of the KV, so max context scales linearly
with the ring size.

Two ops, both ``shard_map``-ped and composable with ``tp`` (heads stay
sharded over ``tp`` inside the ring):

- :func:`ring_attention` — S queries (seq-sharded) over the full KV ring;
  the prefill path.
- :func:`sharded_decode_attention` — one query (replicated) over the
  seq-sharded KV cache, combined with a global log-sum-exp ``psum``; the
  decode path against an sp-sharded cache.

Model integration: ``attn_impl="ring"`` in ModelConfig routes
``models/llama.py`` attention here; :func:`sp_prefill` / :func:`sp_decode_step`
wrap the jit'd model entry points with the ring context (mesh + axis name,
needed at trace time).  The ``_sp_*_fn`` factories below are the
jit-factory form of lfkt-lint's DON donor registry (a donating jit over
a nested def, returned from an lru_cached builder): the wrapper
functions donate their cache/state transitively, and call sites are
held to the rebind contract (DON001-002, docs/LINT.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading

import jax
import jax.numpy as jnp

try:                                  # newer jax: top-level export
    from jax import shard_map
except ImportError:                   # older jax: the experimental home, with
    # check_vma spelled check_rep — shim the one call-site kwarg we use
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..obs.devtime import timed_jit
from ..ops.pallas.attention import DEFAULT_MASK_VALUE

_local = threading.local()


@contextlib.contextmanager
def ring_context(mesh: Mesh, axis_name: str = "sp"):
    """Makes (mesh, axis) visible to the model's ring-attention branch.
    Must be active while jit *traces* the model (the shard_map is baked into
    the compiled program; cached calls don't need it)."""
    prev = getattr(_local, "ctx", None)
    _local.ctx = (mesh, axis_name)
    try:
        yield
    finally:
        _local.ctx = prev


def current_ring_context():
    return getattr(_local, "ctx", None)


# ---------------------------------------------------------------------------
# shared GQA chunk-scores core (used by both the prefill ring and the decode
# LSE combine — one implementation so mask semantics can never desync)
# ---------------------------------------------------------------------------

def _group_queries(q: jax.Array, n_kv: int) -> jax.Array:
    """(S, H, hd) → (n_kv, group, S, hd) grouped-query layout."""
    S, H, hd = q.shape
    return q.reshape(S, n_kv, H // n_kv, hd).transpose(1, 2, 0, 3)


def _masked_chunk_scores(qg, k_chunk, v_chunk, q_pos, key_offset,
                         sm_scale, sliding_window):
    """Scores of grouped queries against one KV chunk whose global positions
    start at ``key_offset``, causal (+ optional sliding-window) masked.

    Returns ``(scores, vv)`` with scores (n_kv, group, S, C_loc) f32 and
    vv (n_kv, C_loc, hd) ready for the ``ngsc,nch->ngsh`` PV einsum.
    ``k_chunk``/``v_chunk`` are head-major (n_kv, C_loc, hd).
    """
    C_loc = k_chunk.shape[1]
    kk = k_chunk
    vv = v_chunk
    scores = jnp.einsum(
        "ngsh,nch->ngsc", qg, kk, preferred_element_type=jnp.float32
    ) * sm_scale
    key_pos = (key_offset + jnp.arange(C_loc))[None, :]
    mask = key_pos <= q_pos
    if sliding_window:
        mask &= key_pos > q_pos - sliding_window
    return jnp.where(mask[None, None], scores, DEFAULT_MASK_VALUE), vv


# ---------------------------------------------------------------------------
# prefill: seq-sharded queries over the rotating KV ring
# ---------------------------------------------------------------------------

def ring_attention(
    q: jax.Array,           # (S, n_heads, hd), seq-sharded over sp
    k: jax.Array,           # (n_kv, n_ctx, hd) head-major, seq-sharded over sp
    v: jax.Array,
    pos_offset: jax.Array,  # scalar int32: cache position of global q[0]
    sm_scale: float,
    sliding_window: int = 0,
) -> jax.Array:
    ctx = current_ring_context()
    if ctx is None:
        raise RuntimeError("ring_attention requires an active ring_context(mesh)")
    mesh, ax = ctx
    n_ring = mesh.shape[ax]

    def local_fn(q, k, v, pos_offset):
        # local shapes: q (S_loc, H_loc, hd), k/v (n_kv_loc, C_loc, hd)
        s_idx = jax.lax.axis_index(ax)
        S_loc, H, hd = q.shape
        n_kv, C_loc, _ = k.shape
        group = H // n_kv
        qg = _group_queries(q, n_kv)
        q_pos = (pos_offset + s_idx * S_loc + jnp.arange(S_loc))[:, None]

        perm = [(j, (j + 1) % n_ring) for j in range(n_ring)]
        m0 = jnp.full((n_kv, group, S_loc, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((n_kv, group, S_loc, 1), jnp.float32)
        a0 = jnp.zeros((n_kv, group, S_loc, hd), jnp.float32)

        def step(i, carry):
            m, l, acc, k_cur, v_cur = carry
            src = jax.lax.rem(s_idx - i + n_ring, n_ring)  # chunk owner
            scores, vv = _masked_chunk_scores(
                qg, k_cur, v_cur, q_pos, src * C_loc, sm_scale, sliding_window)

            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(scores - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            pv = jnp.einsum("ngsc,nch->ngsh", p.astype(vv.dtype), vv,
                            preferred_element_type=jnp.float32)
            acc_new = acc * alpha + pv
            # rotate the chunk to the next device (one extra hop at the end
            # keeps the loop shape static; the final permute is dead weight
            # XLA can overlap with the epilogue)
            k_nxt = jax.lax.ppermute(k_cur, ax, perm)
            v_nxt = jax.lax.ppermute(v_cur, ax, perm)
            return m_new, l_new, acc_new, k_nxt, v_nxt

        m, l, acc, _, _ = jax.lax.fori_loop(0, n_ring, step, (m0, l0, a0, k, v))
        l = jnp.where(l == 0.0, 1.0, l)
        out = (acc / l).astype(q.dtype)                    # (n_kv, group, S, hd)
        return out.transpose(2, 0, 1, 3).reshape(S_loc, H, hd)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(ax, "tp", None), P("tp", ax, None), P("tp", ax, None), P()),
        out_specs=P(ax, "tp", None),
        check_vma=False,
    )(q, k, v, jnp.asarray(pos_offset, jnp.int32))


# ---------------------------------------------------------------------------
# decode: replicated query over the seq-sharded cache, LSE-combined
# ---------------------------------------------------------------------------

def sharded_decode_attention(
    q: jax.Array,           # (S, n_heads, hd) — S tiny (1), replicated over sp
    k: jax.Array,           # (n_kv, n_ctx, hd) head-major, seq-sharded over sp
    v: jax.Array,
    pos_offset: jax.Array,  # scalar: cache position of q[0]
    sm_scale: float,
    sliding_window: int = 0,
) -> jax.Array:
    ctx = current_ring_context()
    if ctx is None:
        raise RuntimeError("sharded_decode_attention requires ring_context(mesh)")
    mesh, ax = ctx

    def local_fn(q, k, v, pos_offset):
        s_idx = jax.lax.axis_index(ax)
        S, H, hd = q.shape
        n_kv, C_loc, _ = k.shape
        qg = _group_queries(q, n_kv)
        q_pos = (pos_offset + jnp.arange(S))[:, None]
        scores, vv = _masked_chunk_scores(
            qg, k, v, q_pos, s_idx * C_loc, sm_scale, sliding_window)

        m_loc = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m_loc)
        l_loc = jnp.sum(p, axis=-1, keepdims=True)
        acc = jnp.einsum("ngsc,nch->ngsh", p.astype(vv.dtype), vv,
                         preferred_element_type=jnp.float32)
        # combine partial softmaxes across the ring with a global LSE
        m_glb = jax.lax.pmax(m_loc, ax)
        corr = jnp.exp(m_loc - m_glb)
        l_glb = jax.lax.psum(l_loc * corr, ax)
        acc_glb = jax.lax.psum(acc * corr, ax)
        l_glb = jnp.where(l_glb == 0.0, 1.0, l_glb)
        out = (acc_glb / l_glb).astype(q.dtype)
        return out.transpose(2, 0, 1, 3).reshape(S, H, hd)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(None, "tp", None), P("tp", ax, None), P("tp", ax, None), P()),
        out_specs=P(None, "tp", None),
        check_vma=False,
    )(q, k, v, jnp.asarray(pos_offset, jnp.int32))


# ---------------------------------------------------------------------------
# model-level entry points
# ---------------------------------------------------------------------------

def sp_state_shardings(cfg: ModelConfig, mesh: Mesh) -> dict:
    """Head-major cache (L, n_kv, n_ctx, hd): n_ctx sharded over sp,
    kv-heads over tp.  Int8 caches shard the (L, n_kv, n_ctx) scale planes
    the same way minus the hd axis — the per-layer dequant before the ring
    collectives (models/llama.py) is elementwise, so it stays sp-local."""
    s4 = NamedSharding(mesh, P(None, "tp", "sp", None))
    if cfg.kv_dtype == "int8":
        s3 = NamedSharding(mesh, P(None, "tp", "sp"))
        return {"k_q": s4, "v_q": s4, "k_s": s3, "v_s": s3}
    return {"k": s4, "v": s4}



@functools.lru_cache(maxsize=32)
def _sp_prefill_fn(mesh: Mesh, axis_name: str, cfg: ModelConfig):
    """jit'd ring prefill, keyed on (mesh, axis, cfg) so a compiled program
    can never be reused under a different mesh (the ring context is only
    consulted at trace time)."""
    from ..models.llama import prefill as _prefill

    cfg = dataclasses.replace(cfg, attn_impl="ring")

    def fn(params, tokens, length, cache):
        with ring_context(mesh, axis_name):
            return _prefill(params, cfg, tokens, length, cache)

    return timed_jit("sp_prefill", jax.jit(fn, donate_argnames=("cache",)),
                     site="parallel.ring")


@functools.lru_cache(maxsize=32)
def _sp_decode_fn(mesh: Mesh, axis_name: str, cfg: ModelConfig):
    from ..models.llama import decode_step as _decode

    cfg = dataclasses.replace(cfg, attn_impl="ring")

    def fn(params, token, pos, cache):
        with ring_context(mesh, axis_name):
            return _decode(params, cfg, token, pos, cache)

    return timed_jit("sp_decode_step", jax.jit(fn, donate_argnames=("cache",)),
                     site="parallel.ring")


def sp_prefill(params, cfg: ModelConfig, tokens, length, cache, mesh: Mesh,
               axis_name: str = "sp"):
    """Sequence-parallel prompt pass: ``tokens`` (S,) with S % sp == 0,
    cache seq-sharded per :func:`sp_state_shardings` (donated).  Everything
    outside attention is per-token (GSPMD shards it for free); attention
    runs the ring."""
    return _sp_prefill_fn(mesh, axis_name, cfg)(params, tokens, length, cache)


def sp_decode_step(params, cfg: ModelConfig, token, pos, cache, mesh: Mesh,
                   axis_name: str = "sp"):
    """One decode step against a seq-sharded cache (sharded-LSE attention);
    the cache is donated, so steady-state decode is allocation-free."""
    return _sp_decode_fn(mesh, axis_name, cfg)(params, token, pos, cache)


@functools.lru_cache(maxsize=64)
def _sp_chunk_fn(mesh: Mesh, axis_name: str, cfg: ModelConfig,
                 n_steps: int, top_k: int):
    from ..models.generate import generate_chunk

    cfg = dataclasses.replace(cfg, attn_impl="ring")

    def fn(params, state, st):
        with ring_context(mesh, axis_name):
            return generate_chunk(params, cfg, state, st, n_steps, top_k)

    return timed_jit("sp_decode_chunk", jax.jit(fn, donate_argnames=("state",)),
                     site="parallel.ring")


def sp_generate_chunk(params, cfg: ModelConfig, state: dict, st: dict,
                      mesh: Mesh, n_steps: int, top_k: int = 40,
                      axis_name: str = "sp"):
    """``n_steps`` on-device decode+sample steps with sharded-LSE attention
    against the seq-sharded cache — the serving decode loop of the
    sequence-parallel engine (engine/sp.py).  State is donated; the sampled
    tokens (n_steps,) come back replicated."""
    return _sp_chunk_fn(mesh, axis_name, cfg, n_steps, top_k)(params, state, st)

from .sample import SamplingParams, sample_chain, sampling_tensors  # noqa: F401

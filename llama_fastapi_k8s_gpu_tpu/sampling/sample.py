"""llama.cpp-parity sampling chain, fully on-device.

The reference calls ``create_chat_completion(temperature=1.2, top_p=0.9,
frequency_penalty=0.7, presence_penalty=0.8)`` (reference api.py:59-62) and
inherits llama-cpp-python 0.2.77 defaults for everything it omits:
``top_k=40``, ``min_p=0.05``, ``repeat_penalty=1.1`` over the last 64 tokens.
Behavior parity therefore requires the full chain, in llama.cpp's order:

1. repetition + frequency/presence penalties over a 64-token ring buffer
   (prompt tail included, as llama.cpp seeds last_tokens with the prompt);
2. top-k (k=40, static → cheap ``lax.top_k`` instead of a 128k-vocab sort);
3. softmax over the k candidates, top-p on those *untempered* probabilities
   (llama.cpp applies temperature after top-p/min-p);
4. min-p relative to the max candidate probability;
5. temperature, then categorical draw — or argmax when temperature ≤ 0.

Everything is jit-compatible; per-request knobs are traced scalars so
changing them never recompiles.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

PENALTY_WINDOW = 64  # llama.cpp repeat_last_n default


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 1.2
    top_p: float = 0.9
    top_k: int = 40                  # static: participates in compiled shape
    min_p: float = 0.05
    frequency_penalty: float = 0.7
    presence_penalty: float = 0.8
    repeat_penalty: float = 1.1


def sampling_tensors(sp: SamplingParams) -> dict:
    """The traced (non-shape-affecting) knobs as a pytree of scalars.

    ``top_k`` rides along as a *traced* int32 so batched/continuous engines
    can serve per-request k values under one compiled program: the static
    ``top_k`` argument of :func:`sample_chain` becomes a ceiling and the
    traced value masks down to the requested k (llama.cpp semantics:
    k <= 0 disables the truncation)."""
    return {
        "temperature": jnp.float32(sp.temperature),
        "top_p": jnp.float32(sp.top_p),
        "top_k": jnp.int32(sp.top_k if sp.top_k > 0 else 1 << 30),
        "min_p": jnp.float32(sp.min_p),
        "frequency_penalty": jnp.float32(sp.frequency_penalty),
        "presence_penalty": jnp.float32(sp.presence_penalty),
        "repeat_penalty": jnp.float32(sp.repeat_penalty),
    }


def apply_penalties(logits: jax.Array, window: jax.Array, st: dict) -> jax.Array:
    """window: (PENALTY_WINDOW,) int32, -1 = empty slot."""
    vocab = logits.shape[0]
    valid = window >= 0
    idx = jnp.clip(window, 0, vocab - 1)
    counts = jnp.zeros(vocab, jnp.float32).at[idx].add(valid.astype(jnp.float32))
    present = counts > 0
    rp = st["repeat_penalty"]
    logits = jnp.where(
        present,
        jnp.where(logits > 0, logits / rp, logits * rp),
        logits,
    )
    logits = logits - counts * st["frequency_penalty"] - present * st["presence_penalty"]
    return logits


def sample_chain(
    logits: jax.Array,   # (vocab,) f32
    window: jax.Array,   # (PENALTY_WINDOW,) int32 ring buffer, -1 empty
    key: jax.Array,
    st: dict,            # sampling_tensors()
    top_k: int = 40,
) -> jax.Array:
    logits = apply_penalties(logits.astype(jnp.float32), window, st)
    vals, idx = jax.lax.top_k(logits, top_k)          # sorted desc
    if "top_k" in st:                                 # per-request k ≤ static k
        vals = jnp.where(jnp.arange(top_k) < st["top_k"], vals, -jnp.inf)
    probs = jax.nn.softmax(vals)                      # untempered, over candidates
    cum_excl = jnp.cumsum(probs) - probs
    keep = cum_excl < st["top_p"]                     # keeps the crossing token
    keep &= probs >= st["min_p"] * probs[0]
    keep = keep.at[0].set(True)                       # min_keep = 1
    masked = jnp.where(keep, vals, -jnp.inf)
    temp = st["temperature"]
    sampled = jax.random.categorical(key, masked / jnp.maximum(temp, 1e-6))
    choice = jnp.where(temp <= 0, 0, sampled)         # temp<=0 → greedy (idx[0])
    return idx[choice]


def update_window(window: jax.Array, wpos: jax.Array, token: jax.Array):
    """Push token into the ring buffer; returns (window, wpos+1)."""
    window = window.at[wpos % PENALTY_WINDOW].set(token)
    return window, wpos + 1


def seed_window(prompt_ids, vocab_pad_id: int = -1):
    """Ring buffer seeded with the prompt tail, as llama.cpp seeds last_tokens."""
    import numpy as np

    window = np.full(PENALTY_WINDOW, -1, dtype=np.int32)
    tail = list(prompt_ids)[-PENALTY_WINDOW:]
    wpos = len(tail) % PENALTY_WINDOW
    for j, t in enumerate(tail):
        window[j % PENALTY_WINDOW] = t
    if len(tail) == PENALTY_WINDOW:
        wpos = 0
    return jnp.asarray(window), jnp.int32(wpos)

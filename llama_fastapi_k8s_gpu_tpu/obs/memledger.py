"""Live HBM memory ledger — lfkt-mem's accounting half (ISSUE 10).

PR 9 made HBM the contended resource: N models' weights, one shared
paged KV arena, dense rings and the continuous scheduler's scratch now
partition a single chip's memory — but the only accounting was a
load-time weight budget.  At serve time an OOM, a mysteriously shrinking
pool, or a leaked ring was invisible until the process died.  This
module is the process-wide **component registry** (mirroring the devtime
program registry, obs/devtime.py): every device-allocation surface
registers a live byte-count provider with attribution, and the ledger
reconciles the sum against device ground truth so unattributed bytes are
a *visible gauge* (the ``residual`` line), not a silent gap.

Registration (:func:`register_component`): a component name from the
:data:`~.catalog.MEM_COMPONENTS` catalog (enforced at runtime —
``KeyError`` — and statically by lfkt-lint OBS003), an owner (held by
**weakref**: a dead engine's rows vanish with it, so tests and watchdog
re-inits never accumulate ghost attribution), and a provider
``fn(owner) -> int | dict[model, int]`` reading *shape metadata only*
(``.nbytes`` is safe even on donated buffers — the kv_cache_bytes
precedent).  Providers run at snapshot time (scrapes, ``/debug/memory``,
incident capture), never on the decode path.

Ground truth: ``device.memory_stats()['bytes_in_use']`` where the
backend reports it (TPU), else the sum over ``jax.live_arrays()`` (CPU
tests — exact for the single-process case).  The reconciliation is
pinned by tests/test_memledger.py: on a CPU two-model paged registry the
component sum matches live-array ground truth within 5%.

Pressure: :meth:`MemLedger.pressure` is the AdmissionController's memory
signal (engine/continuous.py) — True when device headroom drops under
``LFKT_MEM_PRESSURE_FRACTION`` of the HBM limit, so the scheduler stops
feeding prefill into a chip about to OOM.  It only ever consults
``memory_stats`` (never the O(arrays) live-array walk) and latches off
where the backend has no stats, so a CPU pod pays one failed probe ever.

Zero cost when disarmed (``LFKT_MEM_LEDGER=0``): ``pressure()`` returns
False on a single attribute read — no lock, no allocation — and
``snapshot()`` returns a two-key stub; pinned by the poisoned-ledger
test (the tracer's ``LFKT_TRACE_SAMPLE=0`` analogue).
"""

from __future__ import annotations

import logging
import threading
import weakref

from .catalog import MEM_COMPONENTS

logger = logging.getLogger(__name__)

#: /debug/memory document schema (tools and tests pin it)
SCHEMA = 1


def _physical_nbytes(leaf) -> int:
    """PHYSICAL resident bytes of one array: per-shard size × addressable
    shard count, so a replicated array counts one copy per device and a
    sharded one its pieces — matching what the devices' ``memory_stats``
    count (the reconciliation baseline).  Computed from SHARDING METADATA
    only: materializing ``addressable_shards[i].data`` would cache
    per-device view arrays on the parent, permanently double-counting
    every provider-visited array in the ``jax.live_arrays()`` ground
    truth.  Falls back to the logical ``.nbytes`` for non-array leaves
    and donated buffers whose sharding is no longer readable."""
    try:
        sharding = leaf.sharding
        n = leaf.dtype.itemsize
        for d in sharding.shard_shape(leaf.shape):
            n *= d
        return int(n) * len(sharding.addressable_devices)
    except Exception:  # noqa: BLE001 — scalar leaf / donated buffer
        return int(getattr(leaf, "nbytes", 0) or 0)


def tree_nbytes(tree) -> int:
    """Total physical bytes over a pytree's array leaves (0 for None).
    Shape/placement metadata only — safe on donated buffers, never a
    device sync."""
    if tree is None:
        return 0
    import jax

    return sum(_physical_nbytes(leaf) for leaf in jax.tree.leaves(tree))


class MemLedger:
    """The process-wide memory ledger (module instance: :data:`MEMLEDGER`).

    Producers register at engine/pool construction; consumers are
    ``/debug/memory``, the ``hbm_bytes`` gauges at ``/metrics``, the
    flight recorder's incident bundles, and the admission controller's
    pressure signal."""

    # the entry table is appended at construction time and pruned at
    # snapshot time from scrape threads: one mutex (lfkt-lint LOCK001).
    # _armed / pressure_fraction / the stats latch are single-word
    # hot-path reads by design.
    _GUARDED_BY = {"_entries": "_lock"}
    _SHARED_ATOMIC = ("_armed", "pressure_fraction", "_no_device_stats",
                      "last_headroom", "stats_fn")

    def __init__(self, armed: bool | None = None,
                 pressure_fraction: float | None = None):
        if armed is None or pressure_fraction is None:
            from ..utils.config import knob

            if armed is None:
                armed = bool(knob("LFKT_MEM_LEDGER"))
            if pressure_fraction is None:
                pressure_fraction = float(knob("LFKT_MEM_PRESSURE_FRACTION"))
        self._lock = threading.Lock()
        #: (component, weakref(owner), provider) — owners are engines and
        #: KV pools; a collected owner's rows disappear at the next prune
        self._entries: list[tuple] = []
        self._armed = bool(armed)
        self.pressure_fraction = max(0.0, min(1.0, float(pressure_fraction)))
        #: latched after the first failed memory_stats probe: the pressure
        #: check must never pay a per-wave exception on stat-less backends
        self._no_device_stats = False
        #: (free_bytes, limit_bytes) from the most recent successful
        #: device-stats read — the mem_pressure trace event's byte counts
        self.last_headroom: tuple[int, int] | None = None
        #: test seam: () -> memory_stats-shaped dict (injected fake HBM
        #: limits); None = the real device
        self.stats_fn = None

    # -- configuration (tests + ops) ---------------------------------------
    def configure(self, armed: bool | None = None,
                  pressure_fraction: float | None = None) -> None:
        if armed is not None:
            self._armed = bool(armed)
        if pressure_fraction is not None:
            self.pressure_fraction = max(0.0, min(1.0,
                                                  float(pressure_fraction)))

    @property
    def armed(self) -> bool:
        return self._armed

    def reset(self) -> None:
        """Drop every registration (tests)."""
        with self._lock:
            self._entries = []

    # -- registration ------------------------------------------------------
    def register_component(self, component: str, owner, provider) -> None:
        """Register one allocation surface.  ``provider(owner)`` returns
        live bytes — an int (the row's model label is the owner's
        ``model_name``) or a ``{model: bytes}`` dict (per-namespace
        surfaces).  The owner is weakly held; registration is idempotent
        per (component, owner)."""
        spec = MEM_COMPONENTS.get(component)
        if spec is None or component == "residual":
            raise KeyError(
                f"memory component {component!r} is not in the "
                "MEM_COMPONENTS catalog (obs/catalog.py); register it "
                "before reporting it" if spec is None else
                "the 'residual' component is computed by the ledger, "
                "never registered")
        ref = weakref.ref(owner)
        with self._lock:
            for comp, r, _fn in self._entries:
                if comp == component and r() is owner:
                    return
            self._entries.append((component, ref, provider))

    # -- consumers ---------------------------------------------------------
    def _rows(self) -> list[dict]:
        """Live attribution rows, duplicate (component, model) keys merged
        by summing (two engines serving the same alias must not fight
        over one gauge series).  Dead owners are pruned; a raising
        provider is skipped — telemetry must never fail serving."""
        with self._lock:
            entries = list(self._entries)
        merged: dict[tuple, int] = {}
        dead = False
        for component, ref, provider in entries:
            owner = ref()
            if owner is None:
                dead = True
                continue
            try:
                val = provider(owner)
            except Exception:  # noqa: BLE001 — telemetry must never fail
                logger.exception("memory-ledger provider for %r raised",
                                 component)
                continue
            spec = MEM_COMPONENTS[component]
            if isinstance(val, dict):
                items = val.items()
            else:
                items = ((getattr(owner, "model_name", "") or "", val),)
            for model, b in items:
                b = max(0, int(b or 0))
                if b == 0 and not spec.always:
                    # zero rows drop (an absent scratch ring is not a
                    # row) — EXCEPT always-components, whose zero is the
                    # alert condition (an exhausted free list must read
                    # 0, not "no data")
                    continue
                key = (component, str(model))
                merged[key] = merged.get(key, 0) + b
        if dead:
            with self._lock:
                self._entries = [e for e in self._entries
                                 if e[1]() is not None]
        return [{"component": c, "model": m, "bytes": b,
                 "device": MEM_COMPONENTS[c].device}
                for (c, m), b in sorted(merged.items())]

    def _raw_device_stats(self):
        """The real device probe, summed over the LOCAL mesh (separate so
        tests can pin the latch semantics without faking a backend).
        Providers report physical bytes across every shard, so the
        baseline must be the whole mesh's in-use/limit — one chip's
        stats would make residual go negative by ~(N-1)/N on exactly the
        multi-chip engines this ledger targets."""
        try:
            import jax

            in_use = limit = 0
            seen = False
            for d in jax.local_devices():
                st = d.memory_stats()
                if not st or "bytes_in_use" not in st:
                    continue
                seen = True
                in_use += int(st["bytes_in_use"])
                limit += int(st.get("bytes_limit") or 0)
            if not seen:
                return None
            out = {"bytes_in_use": in_use}
            if limit:
                out["bytes_limit"] = limit
            return out
        except Exception:  # noqa: BLE001 — backend has no stats
            return None

    def _device_stats(self) -> dict:
        if self.stats_fn is not None:
            try:
                return dict(self.stats_fn() or {})
            except Exception:  # noqa: BLE001 — test seam, same contract
                return {}
        if self._no_device_stats:
            return {}
        stats = self._raw_device_stats()
        # a backend WITH memory stats may legitimately report ZERO bytes
        # in use (the registry's pre-load fit check runs before the first
        # allocation) — only the absence of the field marks a stat-less
        # backend; latching on falsy 0 would disable pressure() and
        # fit_check() for the process lifetime on exactly the hardware
        # they target
        if not stats or "bytes_in_use" not in stats:
            self._no_device_stats = True
            return {}
        return dict(stats)

    def ground_truth(self) -> dict:
        """What the device says is resident: ``memory_stats`` where the
        backend reports it, else the exact sum over ``jax.live_arrays()``
        (CPU) — the reconciliation baseline the residual line is computed
        against."""
        stats = self._device_stats()
        if stats:
            limit = stats.get("bytes_limit")
            return {"source": "device.memory_stats",
                    "bytes": int(stats["bytes_in_use"]),
                    "limit": int(limit) if limit else None}
        try:
            import jax

            # same physical (per-shard) measure as the providers, so the
            # two sides of the reconciliation can never disagree about
            # what a replicated array "costs"
            total = sum(_physical_nbytes(a) for a in jax.live_arrays())
        except Exception:  # noqa: BLE001 — jax-less process (tools)
            return {"source": "unavailable", "bytes": None, "limit": None}
        return {"source": "jax.live_arrays", "bytes": int(total),
                "limit": None}

    def snapshot(self) -> dict:
        """The full ``/debug/memory`` core document: attribution rows,
        ground truth, the residual line, and headroom."""
        if not self._armed:
            return {"schema": SCHEMA, "armed": False}
        rows = self._rows()
        truth = self.ground_truth()
        attributed = sum(r["bytes"] for r in rows if r["device"])
        host = sum(r["bytes"] for r in rows if not r["device"])
        residual = (truth["bytes"] - attributed
                    if truth["bytes"] is not None else None)
        headroom = None
        if truth["bytes"] is not None and truth["limit"]:
            headroom = {
                "bytes": truth["limit"] - truth["bytes"],
                "limit": truth["limit"],
                "fraction": round(
                    (truth["limit"] - truth["bytes"]) / truth["limit"], 4),
                "pressure_fraction": self.pressure_fraction,
            }
        return {
            "schema": SCHEMA,
            "armed": True,
            "components": rows,
            "attributed_bytes": attributed,
            "host_bytes": host,
            "ground_truth": truth,
            "residual_bytes": residual,
            "headroom": headroom,
        }

    # -- the admission controller's signal (engine/continuous.py) ----------
    def pressure(self) -> bool:
        """True when device HBM headroom is under ``pressure_fraction``
        of the limit.  Disarmed: one attribute read, no lock, no
        allocation (poisoned-ledger pin).  Stat-less backends (CPU)
        latch False after a single probe."""
        if not self._armed:
            return False
        stats = self._device_stats()
        limit = stats.get("bytes_limit")
        if not limit:
            return False
        free = int(limit) - int(stats.get("bytes_in_use", 0))
        self.last_headroom = (free, int(limit))
        return free < self.pressure_fraction * int(limit)

    def fit_check(self, est_bytes: int, label: str = "") -> str | None:
        """Pre-load fit check (serving/registry.py): would loading
        ``est_bytes`` more clearly overrun the device?  Returns the
        refusal message, or None when it fits / cannot be judged (no
        device stats — the weight *budget* still applies there)."""
        if not self._armed or est_bytes <= 0:
            return None
        stats = self._device_stats()
        limit = stats.get("bytes_limit")
        if not limit:
            return None
        free = int(limit) - int(stats.get("bytes_in_use", 0))
        need = int(est_bytes)
        if need <= free:
            return None
        return (f"pre-load fit check: loading {label or 'model'!s} needs "
                f"~{need / 1e6:.0f}MB but the device reports only "
                f"{free / 1e6:.0f}MB of {limit / 1e6:.0f}MB HBM free — "
                "shrink the manifest, the KV arena, or quantize harder "
                "(docs/RUNBOOK.md 'Diagnosing HBM OOM')")


#: THE process-wide ledger: engines and pools register at construction,
#: /metrics + /debug/memory + incident bundles read it, the continuous
#: scheduler consults pressure() once per wave.
MEMLEDGER = MemLedger()


def register_component(component: str, owner, provider) -> None:
    """Module-level convenience: register on the CURRENT process ledger
    (resolved at call time so tests can swap :data:`MEMLEDGER`)."""
    MEMLEDGER.register_component(component, owner, provider)

"""Fleet-scope observability: cross-process trace assembly + metrics
federation (the router-side half of lfkt-fleetobs).

Since lfkt-obs every pod has carried its own tracer, metrics registry,
SLO engine and flight recorder — all strictly per-process, while the
serving path grew to span up to four processes per request (router →
decode replica → disagg prefill peer, plus KV-migration pulls).  This
module makes the fleet a first-class observability domain with three
pure, HTTP-pull primitives the router (serving/fleet/router.py) and the
operator CLIs (tools/fleet_trace.py) share:

- **trace assembly** — :func:`collect_fragments` pulls each pod's
  ``/debug/traces/{id}`` fragment for one request id and :func:`stitch`
  grafts every fragment's root under the span named by its
  ``parent_span_id`` (the outbound hop stamp from
  :func:`obs.trace.span_traceparent`), yielding ONE multi-process span
  tree.  Fragments whose parent span is missing are kept, attached
  under the primary root and flagged ``orphan`` — an orphan means a hop
  stamped context that nobody opened, which the fleet-trace-continuity
  CI gate pins to zero.

- **metrics federation** — :func:`federate` parses each peer's
  Prometheus exposition text and merges per family: counters SUM across
  peers, histogram families merge BUCKET-WISE (cumulative bucket counts,
  sums and counts add exactly — the merge is pinned against per-pod
  sums by test), gauges re-label by peer (gauges don't sum; a per-peer
  ``peer=`` label keeps them honest).  The merged histogram/counter
  state is also exposed snapshot-shaped (utils/metrics.py
  ``snapshot()`` contract) so the UNMODIFIED SLO engine evaluates the
  existing catalog over fleet-wide distributions via
  :class:`FleetMetricsView` — a breach spread thin across N replicas
  finally confirms at ``slo_burn_rate{scope="fleet"}``.

- **incident correlation** — :func:`incident_pull` fetches recent
  flight-recorder bundle summaries from the ejected peer (best-effort;
  it may be dead) and the surviving fleet, and records ONE local
  ``fleet_peer_ejected`` bundle tying them together.

Everything here is pull-based and bounded: every peer fetch has a hard
timeout, every peer-supplied string is sanitized
(:func:`obs.logctx.sanitize_text`) before it can reach a log line or a
re-rendered exposition, and a peer that answers garbage degrades to
"fragment/family missing from the merge" — never an exception on the
router's serving path.
"""

from __future__ import annotations

import http.client
import json
import re
import threading

from .logctx import sanitize_text
from ..utils.metrics import COUNTER, GAUGE, HISTOGRAM, _fmt, lookup

#: bound on one peer response body (a hostile/byzantine peer must not
#: balloon the router's heap: 8 MiB >> any sane scrape or trace doc)
MAX_BODY = 8 << 20

#: derived-quantile gauge families (utils/metrics.py QUANTILES) are
#: recomputable from the merged buckets and meaningless to sum — skipped
_QUANTILE_SUFFIXES = ("_p50", "_p95", "_p99")

#: one exposition sample line: name{labels} value  (labels optional)
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
#: one label pair inside the braces, honouring \" escapes
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


# ---------------------------------------------------------------------------
# bounded peer HTTP (stdlib only — the router process never imports the
# FastAPI/httpx stack)
# ---------------------------------------------------------------------------

def fetch_text(addr: str, path: str, timeout: float = 2.0) -> str | None:
    """GET ``http://addr path`` → body text, or None on ANY failure
    (connect, timeout, non-200, oversized).  Peer observability fetches
    are best-effort by contract."""
    host, _, port = addr.partition(":")
    try:
        conn = http.client.HTTPConnection(host, int(port or 80),
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            body = resp.read(MAX_BODY + 1)
            if len(body) > MAX_BODY:
                return None
            return body.decode("utf-8", "replace")
        finally:
            conn.close()
    except (OSError, ValueError):
        return None


def fetch_json(addr: str, path: str, timeout: float = 2.0) -> dict | None:
    text = fetch_text(addr, path, timeout=timeout)
    if text is None:
        return None
    try:
        doc = json.loads(text)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


# ---------------------------------------------------------------------------
# layer 1: cross-process trace assembly
# ---------------------------------------------------------------------------

def collect_fragments(trace_id: str, peers: list[str],
                      timeout: float = 2.0,
                      local: dict | None = None,
                      local_name: str = "router") -> list[dict]:
    """Pull ``/debug/traces/{trace_id}`` from every peer; return
    ``[{"peer": name, "doc": trace_doc}]`` for the ones that had the
    fragment.  ``local`` lets the router contribute its own in-process
    fragment without HTTP."""
    out: list[dict] = []
    if local is not None:
        out.append({"peer": local_name, "doc": local})
    for addr in peers:
        doc = fetch_json(addr, f"/debug/traces/{trace_id}",
                         timeout=timeout)
        if doc is not None and doc.get("trace_id") == trace_id:
            out.append({"peer": addr, "doc": doc})
    return out


def _walk_spans(span: dict):
    yield span
    for child in span.get("children", ()):
        yield from _walk_spans(child)


def stitch(fragments: list[dict]) -> dict | None:
    """One multi-process span tree from per-process fragments.

    Each fragment doc is a ``Trace.to_dict()``: its ``parent_span_id``
    names the span (in ANOTHER process) that stamped the hop.  Grafting
    is by span id across all fragments, so chains work unordered: the
    prefiller fragment's parent lives in the replica fragment, whose own
    parent lives in the router fragment.  Fragments with no resolvable
    parent are orphans — attached under the primary root (flagged) so
    evidence is never dropped, and counted so CI can pin zero."""
    if not fragments:
        return None
    frags = [dict(f) for f in fragments]
    index: dict[str, dict] = {}
    for f in frags:
        root = f["doc"].get("root") or {}
        f["root"] = root
        for sp in _walk_spans(root):
            sid = sp.get("span_id")
            if sid:
                index.setdefault(sid, sp)

    def _start(f):
        return f["root"].get("start") or 0.0

    # primary = the rootmost fragment: no parent stamp at all, earliest
    # start breaking ties; with every fragment parented (router fragment
    # missing), fall back to the earliest-started one
    parentless = [f for f in frags if not f["doc"].get("parent_span_id")]
    primary = min(parentless or frags, key=_start)
    primary["root"].setdefault("attrs", {})["process"] = primary["peer"]

    orphans: list[str] = []
    for f in frags:
        if f is primary:
            continue
        attrs = f["root"].setdefault("attrs", {})
        attrs["process"] = f["peer"]
        attrs["hop"] = True
        parent = index.get(f["doc"].get("parent_span_id") or "")
        if parent is None or parent is f["root"]:
            attrs["orphan"] = True
            orphans.append(str(f["peer"]))
            parent = primary["root"]
        parent.setdefault("children", []).append(f["root"])

    return {
        "trace_id": primary["doc"].get("trace_id"),
        "stitched": True,
        "processes": [str(f["peer"]) for f in frags],
        "fragments": len(frags),
        "orphans": orphans,
        "dropped_nodes": sum(int(f["doc"].get("dropped_nodes") or 0)
                             for f in frags),
        "finished": all(bool(f["doc"].get("finished")) for f in frags),
        "root": primary["root"],
    }


# ---------------------------------------------------------------------------
# layer 2: metrics federation
# ---------------------------------------------------------------------------

def parse_exposition(text: str) -> dict:
    """Prometheus exposition text → ``{family: {"type": t, "series":
    {label_key: float}, "hist": {label_key: {"le": {le_str: cum}, "sum",
    "count"}}}}``.  Label keys are tuples of (name, value) pairs in line
    order; values are sanitized (a byzantine peer must not forge merged
    exposition lines through a label value)."""
    fams: dict = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, rawlabels, rawval = m.groups()
        try:
            value = float(rawval)
        except ValueError:
            continue
        labels = [(k, sanitize_text(v.replace('\\"', '"')
                                    .replace("\\\\", "\\")
                                    .replace("\\n", " "), limit=128))
                  for k, v in _LABEL_RE.findall(rawlabels or "")]
        base, kind = name, None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and types.get(name[:-len(suffix)]) \
                    == "histogram":
                base, kind = name[:-len(suffix)], suffix
                break
        fam = fams.setdefault(base, {"type": types.get(base, "untyped"),
                                     "series": {}, "hist": {}})
        fam["type"] = types.get(base, fam["type"])
        if kind is None:
            fam["series"][tuple(labels)] = value
            continue
        le = None
        if kind == "_bucket":
            le = next((v for k, v in labels if k == "le"), None)
            labels = [(k, v) for k, v in labels if k != "le"]
        h = fam["hist"].setdefault(tuple(labels),
                                   {"le": {}, "sum": 0.0, "count": 0.0})
        if kind == "_bucket" and le is not None:
            h["le"][le] = value
        elif kind == "_sum":
            h["sum"] = value
        elif kind == "_count":
            h["count"] = value
    return fams


def _catalog_key(name: str, labels: tuple) -> tuple | None:
    """Reorder parsed (k, v) label pairs into the catalog's label-value
    tuple (the utils/metrics.py snapshot key), or None when the set
    doesn't match the catalog (foreign series never poison the merge)."""
    metric = lookup(name)
    if metric is None:
        return None
    got = dict(labels)
    if set(got) != set(metric.labels):
        return None
    return tuple(got[k] for k in metric.labels)


def federate(texts: dict[str, str]) -> dict:
    """Merge per-peer exposition texts.  Returns::

        {"peers": [...], "exposition": str, "snapshot": {...}}

    ``exposition`` is servable at ``GET /metrics/fleet``: counters
    summed across peers, histograms merged bucket-wise, gauges
    re-labeled ``{...,peer="host:port"}``.  ``snapshot`` holds the
    merged counter/histogram state in the utils/metrics.py
    ``snapshot()`` shape so :class:`FleetMetricsView` can feed the
    unmodified SLO engine."""
    parsed = {peer: parse_exposition(text)
              for peer, text in texts.items() if text}
    names: dict[str, str] = {}
    for fams in parsed.values():
        for name, fam in fams.items():
            if name.endswith(_QUANTILE_SUFFIXES):
                continue
            names.setdefault(name, fam["type"])

    lines: list[str] = []
    snapshot: dict = {}
    for name in sorted(names):
        ftype = names[name]
        metric = lookup(name)
        help_text = metric.help if metric is not None else "federated"
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {ftype}")
        if ftype == "histogram":
            merged: dict = {}
            for fams in parsed.values():
                for key, h in fams.get(name, {}).get("hist", {}).items():
                    agg = merged.setdefault(
                        key, {"le": {}, "sum": 0.0, "count": 0.0})
                    for le, cum in h["le"].items():
                        agg["le"][le] = agg["le"].get(le, 0.0) + cum
                    agg["sum"] += h["sum"]
                    agg["count"] += h["count"]
            snap_per: dict = {}
            for key in sorted(merged):
                agg = merged[key]
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                pre = "{" + lbl + "," if lbl else "{"
                for le, cum in sorted(
                        agg["le"].items(),
                        key=lambda kv: float("inf")
                        if kv[0] == "+Inf" else float(kv[0])):
                    lines.append(f'{name}_bucket{pre}le="{le}"}} '
                                 f'{_fmt(agg["le"][le])}')
                tail = "{" + lbl + "}" if lbl else ""
                lines.append(f'{name}_sum{tail} {_fmt(agg["sum"])}')
                lines.append(f'{name}_count{tail} {_fmt(agg["count"])}')
                skey = _catalog_key(name, key)
                if skey is not None and metric is not None \
                        and metric.mtype == HISTOGRAM:
                    cum_prev, deltas = 0.0, []
                    for bound in metric.buckets:
                        cum = agg["le"].get(_fmt(bound), cum_prev)
                        deltas.append(max(0.0, cum - cum_prev))
                        cum_prev = cum
                    deltas.append(max(0.0, agg["count"] - cum_prev))
                    snap_per[skey] = {"buckets": deltas,
                                      "sum": agg["sum"],
                                      "count": agg["count"]}
            if snap_per:
                snapshot[name] = snap_per
        elif ftype == "counter":
            merged2: dict = {}
            for fams in parsed.values():
                for key, v in fams.get(name, {}).get("series", {}).items():
                    merged2[key] = merged2.get(key, 0.0) + v
            snap_per = {}
            for key in sorted(merged2):
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                tail = "{" + lbl + "}" if lbl else ""
                lines.append(f"{name}{tail} {_fmt(merged2[key])}")
                skey = _catalog_key(name, key)
                if skey is not None and metric is not None \
                        and metric.mtype == COUNTER:
                    snap_per[skey] = merged2[key]
            if snap_per:
                snapshot[name] = snap_per
        else:
            # gauges re-label by peer: summing a utilization or a
            # connected-flag across pods would manufacture nonsense
            for peer in sorted(parsed):
                fam = parsed[peer].get(name)
                if fam is None:
                    continue
                speer = sanitize_text(peer, limit=128)
                for key in sorted(fam["series"]):
                    lbl = ",".join(f'{k}="{v}"' for k, v in key)
                    lbl = (lbl + "," if lbl else "") + f'peer="{speer}"'
                    lines.append(
                        f"{name}{{{lbl}}} {_fmt(fam['series'][key])}")
    return {"peers": sorted(parsed), "exposition": "\n".join(lines) + "\n",
            "snapshot": snapshot}


class FleetMetricsView:
    """The SLO engine's view of the federated fleet: quacks like
    utils/metrics.py ``Metrics`` for exactly the two methods
    obs/slo.py uses — ``snapshot()`` returns the latest merge and
    ``set_gauge`` captures the published burn gauges for re-rendering
    into the ``/metrics/fleet`` body.  The engine itself is unmodified:
    federation happens underneath it, not inside it."""

    # snapshot updates come from whichever thread serves the scrape;
    # reads may race — both sides swap/read whole dicts (lfkt-lint
    # LOCK001: attribute swap is atomic, readers see old or new, never
    # a torn merge)
    _SHARED_ATOMIC = ("_snap", "gauges")

    def __init__(self):
        self._snap: dict = {}
        self.gauges: dict = {}

    def update(self, snapshot: dict) -> None:
        self._snap = snapshot

    def snapshot(self) -> dict:
        return self._snap

    def set_gauge(self, name: str, value: float, **labels) -> None:
        gauges = dict(self.gauges)
        gauges[(name, tuple(sorted(labels.items())))] = float(value)
        self.gauges = gauges

    def render_gauges(self) -> str:
        """Exposition lines for the captured gauges (appended to the
        federated body so ``slo_burn_rate{scope="fleet"}`` rides the
        same scrape that produced it)."""
        items = sorted(self.gauges.items())
        if not items:
            return ""
        lines = []
        seen_help = False
        for (name, labels), value in items:
            if not seen_help:
                metric = lookup(name)
                if metric is not None:
                    lines.append(f"# HELP {name} {metric.help}")
                    lines.append(f"# TYPE {name} {metric.mtype}")
                seen_help = True
            metric = lookup(name)
            order = metric.labels if metric is not None \
                else tuple(k for k, _ in labels)
            got = dict(labels)
            lbl = ",".join(f'{k}="{got[k]}"' for k in order if k in got)
            lines.append(f"{name}{{{lbl}}} {_fmt(value)}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# layer 3: correlated incident capture
# ---------------------------------------------------------------------------

def incident_pull(peer: str, healthy: list[str], reason: str,
                  recorder=None, timeout: float = 2.0,
                  limit: int = 5) -> dict | None:
    """On an ejection/chaos event: fetch recent incident-bundle
    summaries from the ejected peer (best-effort — it may be the corpse)
    and from each surviving peer, and record ONE local
    ``fleet_peer_ejected`` bundle correlating them.  Returns the extra
    dict (for tests), or None when the local recorder is disarmed."""
    from .flightrec import FLIGHTREC

    rec = recorder if recorder is not None else FLIGHTREC
    if not rec.armed:
        return None
    correlated: dict[str, list] = {}
    for addr in [peer] + [a for a in healthy if a != peer]:
        doc = fetch_json(addr, "/debug/incidents", timeout=timeout)
        if doc is None:
            continue
        rows = doc.get("incidents")
        if not isinstance(rows, list):
            continue
        correlated[sanitize_text(addr, limit=128)] = [
            {k: sanitize_text(r.get(k), limit=128)
             for k in ("id", "kind", "reason", "ts") if k in r}
            for r in rows[:limit] if isinstance(r, dict)]
    extra = {"peer": sanitize_text(peer, limit=128),
             "reason": sanitize_text(reason, limit=256),
             "correlated": correlated}
    rec.record("fleet_peer_ejected",
               f"peer {sanitize_text(peer, limit=128)} ejected: "
               f"{sanitize_text(reason, limit=256)}", extra=extra)
    return extra


def incident_pull_async(peer: str, healthy: list[str], reason: str,
                        recorder=None, timeout: float = 2.0) -> None:
    """Fire-and-forget :func:`incident_pull` on a short-lived daemon
    thread — ejections happen on the router's event loop (or the prober
    thread) and must never block on N peer fetches.  The flight
    recorder's per-kind debounce bounds a flapping peer to one bundle
    per window."""
    from .flightrec import FLIGHTREC

    rec = recorder if recorder is not None else FLIGHTREC
    if not rec.armed:
        return
    threading.Thread(
        target=incident_pull, name="lfkt-fleet-incident",
        args=(peer, list(healthy), reason),
        kwargs={"recorder": rec, "timeout": timeout},
        daemon=True).start()

"""Declarative SLOs evaluated as multi-window burn rates (lfkt-perf).

The metric catalog (obs/catalog.py) says what the pod *measures*; this
module says what the deployment *promises* — and turns the promise into a
number a machine can alert on.  Each :class:`SLO` names one cataloged
family, a threshold (helm-tunable through an ``LFKT_SLO_*`` knob), and an
objective (the fraction of events that must be good).  Evaluation follows
the SRE-workbook multi-window burn-rate recipe:

- the engine snapshots the metrics registry's raw cumulative series
  (``Metrics.snapshot``) every time it is consulted (each /metrics scrape
  and each ``/debug/slo`` hit), keeping a bounded history;
- for every window (``LFKT_SLO_WINDOWS``, default 5 m and 1 h) it diffs
  the current snapshot against the one at the window's start — cumulative
  histogram buckets make the delta an exact event count, not a sample;
- ``burn = bad_fraction / error_budget`` where the error budget is
  ``1 - objective`` (latency/floor SLOs) or the error-rate threshold
  itself (ratio SLOs).  1.0 means spending the budget exactly as fast as
  the SLO allows; sustained > 1 on EVERY window is a breach, > 1 on only
  the short window is a warning (a fast burn that has not yet lasted).
  A window truncated to process age (baseline younger than the window —
  fresh pod) can raise a warning but never confirm a breach: until the
  long window has genuinely elapsed it holds the same evidence as the
  short one, and its whole job is to prove the burn *lasted*.

Per-label families (``engine_ttft_seconds{bucket=...}``) are evaluated
per series and report the WORST series' burn — a 32k-bucket TTFT
violation must not hide under a healthy flood of short prompts.  The
verdict document at ``/debug/slo`` carries every per-series number; the
``slo_burn_rate{slo=,window=,scope=}`` gauges carry the worst.

The verdict also folds in the devtime registry's recompile-storm state
(obs/devtime.py): a program minting signatures past
``LFKT_RECOMPILE_BUDGET`` is a perf incident even while latency SLOs
still look green, because the storm spends its budget on compiles that
the TTFT histogram only sees later.

Every SLO must reference a cataloged metric family — machine-checked by
lfkt-lint PERF002 (lint/perf.py).  Catalog + semantics: docs/SLO.md.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from . import flightrec as _flightrec
from .catalog import HISTOGRAM, lookup
from .devtime import DEVTIME

#: snapshot history bound (a 15 s scrape cadence over the default 1 h long
#: window needs 240; headroom for /debug/slo polls in between)
MAX_SNAPSHOTS = 1024

LATENCY = "latency"     # histogram of seconds; good = obs <= threshold
FLOOR = "floor"         # histogram of a rate;  good = obs >= threshold
RATIO = "ratio"         # labeled counter;      bad/total <= threshold


@dataclasses.dataclass(frozen=True)
class SLO:
    """One service-level objective over a cataloged metric family."""

    name: str
    metric: str                 # catalog family (lfkt-lint PERF002)
    kind: str                   # LATENCY | FLOOR | RATIO
    threshold_knob: str         # LFKT_SLO_* knob carrying the threshold
    #                             (single source of truth for the default:
    #                             the Knob table in utils/config.py)
    objective: float = 0.95     # good-event fraction (latency/floor only)
    help: str = ""
    #: RATIO only: name of the label whose value classifies an event as
    #: bad when it starts with ``bad_prefix``
    bad_label: str = ""
    bad_prefix: str = ""
    #: RATIO only: series whose ``route`` label starts with one of these
    #: are self-monitoring traffic (scrapes, probes, debug) — excluded so
    #: a quiet pod's guaranteed-200 probe stream cannot dilute the
    #: user-facing error ratio below its budget
    exclude_routes: tuple = ()


#: THE SLO catalog (docs/SLO.md).  Thresholds are deploy-time knobs
#: (helm ``slo.*`` values); objectives are part of the promise itself.
SLOS: tuple[SLO, ...] = (
    SLO("ttft_p95", metric="engine_ttft_seconds", kind=LATENCY,
        threshold_knob="LFKT_SLO_TTFT_P95_S", objective=0.95,
        help="95% of requests see their first token within the bound, "
             "evaluated per prefill bucket and model (worst "
             "bucket+model series reported)"),
    SLO("decode_floor", metric="engine_decode_tokens_per_sec", kind=FLOOR,
        threshold_knob="LFKT_SLO_DECODE_FLOOR_TPS", objective=0.95,
        help="95% of requests decode at or above the floor, per model "
             "(worst model reported)"),
    SLO("error_rate", metric="http_requests_total", kind=RATIO,
        threshold_knob="LFKT_SLO_ERROR_RATE",
        bad_label="code", bad_prefix="5",
        exclude_routes=("/metrics", "/health", "/debug"),
        help="5xx responses stay under the budget fraction of all "
             "user-facing requests (scrape/probe/debug routes excluded)"),
    SLO("queue_p95", metric="queue_wait_seconds", kind=LATENCY,
        threshold_knob="LFKT_SLO_QUEUE_P95_S", objective=0.95,
        help="95% of admissions leave the queue within the bound"),
)


def _n_at_or_below(bounds, bucket_deltas, count_delta, threshold) -> float:
    """Estimated observations <= ``threshold`` in a windowed histogram
    delta — cumulative up to the containing bucket, linearly interpolated
    inside it (the same convention as the derived quantile gauges in
    utils/metrics.py, so a threshold equal to a bucket bound is exact)."""
    if count_delta <= 0:
        return 0.0
    cum = 0.0
    lo = 0.0
    for i, hi in enumerate(bounds):
        n = bucket_deltas[i]
        if threshold < hi:
            if n <= 0 or hi <= lo:
                return cum
            frac = max(0.0, min(1.0, (threshold - lo) / (hi - lo)))
            return cum + n * frac
        cum += n
        lo = hi
    return float(count_delta)       # threshold >= the largest finite bound


class SLOEngine:
    """Burn-rate evaluator bound to one Metrics registry (per app)."""

    # snapshots are appended by whichever thread scrapes/evaluates;
    # /debug/slo may race a /metrics render (lfkt-lint LOCK001).  The
    # breach-episode latch is a single bool shared with the short-lived
    # incident-record worker; a racing rollback costs at most one extra
    # record attempt, which the recorder's per-kind debounce absorbs.
    _GUARDED_BY = {"_snaps": "_lock"}
    _SHARED_ATOMIC = ("_breach_recorded",)

    def __init__(self, metrics, windows=None, thresholds: dict | None = None,
                 devtime=None, scope: str = "pod"):
        from ..utils.config import knob

        self._metrics = metrics
        #: rides the slo_burn_rate gauge: "pod" for a replica evaluating
        #: its own registry, "fleet" when the router evaluates the
        #: catalog over federated histograms (obs/fleettrace.py)
        self.scope = str(scope)
        self._devtime = devtime if devtime is not None else DEVTIME
        if windows is None:
            raw = str(knob("LFKT_SLO_WINDOWS"))
            windows = [float(w) for w in raw.split(",") if w.strip()]
        self.windows = sorted(float(w) for w in windows) or [300.0, 3600.0]
        self.thresholds: dict[str, float] = {}
        for slo in SLOS:
            if thresholds and slo.name in thresholds:
                self.thresholds[slo.name] = float(thresholds[slo.name])
            else:
                self.thresholds[slo.name] = float(knob(slo.threshold_knob))
        self._lock = threading.Lock()
        self._snaps: deque[tuple[float, dict]] = deque(maxlen=MAX_SNAPSHOTS)
        #: breach-episode edge detector for the flight recorder: True
        #: while the current breach has already been bundled (worst case
        #: under racing evaluators: one extra bundle, caught by the
        #: recorder's own per-kind debounce)
        self._breach_recorded = False
        #: minimum spacing between RETAINED snapshots: without it, a 1 Hz
        #: /debug/slo poller fills the deque in ~17 min and silently
        #: truncates the long window's baseline while the gauge label
        #: still claims the full window.  At this floor the deque always
        #: spans >= 1.5x the longest window.
        self._min_gap = max(1.0, 1.5 * max(self.windows) / MAX_SNAPSHOTS)

    # ------------------------------------------------------------------
    @staticmethod
    def _window_label(w: float) -> str:
        return f"{int(w)}s"

    def _baseline(self, now: float,
                  window: float) -> tuple[float, dict]:  # lfkt: holds[_lock]
        """The snapshot at the window's start: the newest one at least
        ``window`` old, else the oldest available (young process: the
        window truncates to process age), else empty (since-boot)."""
        best = (now, {})
        for t, snap in self._snaps:
            if t <= now - window:
                best = (t, snap)
            else:
                break
        if best[1] or len(self._snaps) <= 1:
            return best
        t, snap = self._snaps[0]
        return (t, snap) if t < now else (now, {})

    def _eval_series(self, slo: SLO, threshold: float, cur: dict,
                     base: dict) -> dict:
        """One (slo, window) evaluation across the family's label series:
        ``{"burn_rate", "bad", "total", "worst_series"}``."""
        metric = lookup(slo.metric)
        fam_cur = cur.get(slo.metric, {})
        fam_base = base.get(slo.metric, {})
        if slo.kind == RATIO:
            bad = total = 0.0
            li = metric.labels.index(slo.bad_label) if slo.bad_label else -1
            ri = (metric.labels.index("route")
                  if slo.exclude_routes and "route" in metric.labels else -1)
            for key, v in fam_cur.items():
                if ri >= 0 and str(key[ri]).startswith(slo.exclude_routes):
                    continue
                d = float(v) - float(fam_base.get(key, 0.0))
                if d <= 0:
                    continue
                total += d
                if li >= 0 and str(key[li]).startswith(slo.bad_prefix):
                    bad += d
            ratio = (bad / total) if total else 0.0
            burn = (ratio / threshold) if threshold > 0 else 0.0
            return {"burn_rate": round(burn, 4), "bad": round(bad, 3),
                    "total": round(total, 3), "worst_series": None}
        # histogram kinds: evaluate each label series, report the worst
        budget = max(1e-9, 1.0 - slo.objective)
        worst = {"burn_rate": 0.0, "bad": 0.0, "total": 0.0,
                 "worst_series": None}
        series_out = {}
        for key, h in fam_cur.items():
            if not isinstance(h, dict):
                continue
            bh = fam_base.get(key)
            dcount = h["count"] - (bh["count"] if bh else 0)
            if dcount <= 0:
                continue
            dbuckets = [n - (bh["buckets"][i] if bh else 0)
                        for i, n in enumerate(h["buckets"])]
            n_le = _n_at_or_below(metric.buckets, dbuckets, dcount,
                                  threshold)
            bad = (dcount - n_le) if slo.kind == LATENCY else n_le
            burn = (bad / dcount) / budget
            label = ",".join(key) if key else ""
            series_out[label] = round(burn, 4)
            if burn >= worst["burn_rate"]:
                worst = {"burn_rate": round(burn, 4),
                         "bad": round(bad, 3), "total": dcount,
                         "worst_series": label or None}
        if series_out:
            worst["series"] = series_out
        return worst

    # ------------------------------------------------------------------
    def evaluate(self, now: float | None = None) -> dict:
        """Take a snapshot, evaluate every (SLO, window), and return the
        full verdict document (the ``/debug/slo`` body).  ``now`` is
        injectable for deterministic tests."""
        if now is None:
            now = time.time()
        snap = self._metrics.snapshot()
        with self._lock:
            if not self._snaps or now - self._snaps[-1][0] >= self._min_gap:
                self._snaps.append((now, snap))
            horizon = now - max(self.windows) * 1.5
            while len(self._snaps) > 2 and self._snaps[1][0] <= horizon:
                self._snaps.popleft()
            baselines = {w: self._baseline(now, w) for w in self.windows}

        slos = []
        worst_rank = 0
        ranks = {"ok": 0, "warn": 1, "breach": 2}
        for slo in SLOS:
            threshold = self.thresholds[slo.name]
            per_window = {}
            burning = []
            confirmed = []
            for w in self.windows:
                t_base, base = baselines[w]
                ev = self._eval_series(slo, threshold, snap, base)
                span = now - t_base
                ev["window_s"] = round(span, 3)
                # a baseline younger than the window means the window is
                # truncated to process age: it holds the SAME evidence as
                # the shorter windows and cannot play its independent
                # confirm-the-burn-lasted role in a breach verdict
                truncated = span < w
                if truncated:
                    ev["truncated"] = True
                per_window[self._window_label(w)] = ev
                hit = ev["burn_rate"] >= 1.0
                burning.append(hit)
                confirmed.append(hit and not truncated)
            if confirmed and all(confirmed):
                verdict = "breach"
            elif any(burning):
                verdict = "warn"
            else:
                verdict = "ok"
            worst_rank = max(worst_rank, ranks[verdict])
            slos.append({
                "name": slo.name, "metric": slo.metric, "kind": slo.kind,
                "threshold": threshold, "objective": slo.objective,
                "help": slo.help, "windows": per_window,
                "verdict": verdict,
            })

        storms = self._devtime.storms()
        recompile = {
            "budget": self._devtime.budget,
            "storms": storms,
            "storms_total": self._devtime.storms_total,
            "verdict": "storm" if storms else "ok",
        }
        overall = ["ok", "warn", "breach"][worst_rank]
        if storms and overall == "ok":
            overall = "warn"        # perf incident with green latency SLOs
        doc = {"now": now,
               "windows": [self._window_label(w) for w in self.windows],
               "slos": slos, "recompile": recompile, "verdict": overall}
        if overall == "breach":
            # flight recorder (obs/flightrec.py): a confirmed breach is an
            # incident — bundle the verdict with the process state while
            # the burn is live.  Recorded on the RISING EDGE only (one
            # bundle per breach episode): a breach persists across every
            # scrape, and re-recording each debounce window would flood
            # the bounded ring and prune the rare trip/OOM bundles the
            # recorder exists to preserve.  The capture+write (ledger
            # snapshot, trace serialization, fsync) runs on a short-lived
            # worker thread: evaluate() is called from the async /metrics
            # and /debug/slo handlers, and a multi-ms disk write must not
            # stall the event loop of an already-degraded pod.  The latch
            # is optimistic and ROLLED BACK by the worker when the record
            # failed (disk full) or was debounced, so a later scrape
            # retries instead of leaving the episode evidence-less.
            if _flightrec.FLIGHTREC.armed and not self._breach_recorded:
                self._breach_recorded = True
                breached = [s["name"] for s in slos
                            if s["verdict"] == "breach"]

                def _record(doc=doc, names=tuple(breached)):
                    if _flightrec.record_incident(
                            "slo_breach",
                            "SLO breach: " + ", ".join(names),
                            extra={"slo": doc}) is None:
                        self._breach_recorded = False
                threading.Thread(target=_record, name="lfkt-slo-incident",
                                 daemon=True).start()
        else:
            self._breach_recorded = False    # episode over: re-arm
        return doc

    def export(self, now: float | None = None) -> dict:
        """Evaluate and publish ``slo_burn_rate{slo,window,scope}``
        gauges into the bound metrics registry (the /metrics scrape
        hook).  Returns the verdict document so callers can reuse it."""
        doc = self.evaluate(now=now)
        for s in doc["slos"]:
            for wl, ev in s["windows"].items():
                self._metrics.set_gauge("slo_burn_rate", ev["burn_rate"],
                                        slo=s["name"], window=wl,
                                        scope=self.scope)
        return doc

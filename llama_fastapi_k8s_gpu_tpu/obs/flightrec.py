"""Incident flight recorder — lfkt-mem's black-box half (ISSUE 10).

A watchdog trip, a DEAD escalation, a device OOM or an SLO breach used
to leave ZERO evidence once the pod restarted: the traces, the scheduler
stats, the memory ledger and the log tail all lived in process memory.
This module snapshots an **incident bundle** — the live memory ledger
(obs/memledger.py), every in-flight trace tree (obs/trace.py),
scheduler_stats, the health-transition history, the devtime
recompile-storm state, and the last-N structured log lines — atomically
into a bounded on-disk ring, so the post-mortem survives the process
that died.

Arming: OFF by default — the recorder does nothing until
``LFKT_INCIDENT_DIR`` names a writable directory (mount it on a pod
volume so bundles survive container restarts; helm/values.yaml
``app.incidentDir``).  Bundles are schema-versioned JSON
(``inc-<seq>-<kind>.json``, written tmp-then-rename so a crash mid-write
never leaves a torn bundle), pruned oldest-first past
``LFKT_INCIDENT_RING``, and served back at ``GET /debug/incidents`` +
``/debug/incidents/{id}`` (server/app.py) and by
``tools/incident_report.py``.  ``tools/ci_gate.py`` validates any
present bundle against the schema.

Trigger points (each passes a ``kind`` from :data:`KINDS`):

- ``watchdog_trip`` / ``dead_escalation`` — engine/watchdog.py, captured
  BEFORE in-flight futures are failed so the tripping request's trace is
  still in the bundle;
- ``resource_exhausted`` — utils/health.py ``Heartbeat.record_error``
  when the error message carries XLA's RESOURCE_EXHAUSTED signature;
- ``slo_breach`` — obs/slo.py when the multi-window verdict confirms a
  breach.

Per-kind debounce (``LFKT_INCIDENT_DEBOUNCE_S``) keeps an error burst or
a breach re-evaluated every scrape from flooding the ring: the FIRST
event of a kind records, repeats inside the window are dropped (the
fault-drill test pins "one trip → exactly one bundle").

Zero cost when disarmed: ``record()`` returns on a single attribute
read — no lock, no allocation, no directory touch — and the log-tail
ring handler is only installed while armed (poisoned-recorder pin,
tests/test_flightrec.py).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

#: bundle schema version (tools/incident_report.py + ci_gate validate it)
SCHEMA = 1

#: the incident kinds the serving stack records (``disagg_peer_dead``:
#: a decode replica's prefill peer died mid-stream — serving/disagg/;
#: ``fleet_peer_ejected``: the router ejected a replica and pulled
#: correlated bundle summaries from the involved peers —
#: obs/fleettrace.py)
KINDS = ("watchdog_trip", "dead_escalation", "resource_exhausted",
         "slo_breach", "disagg_peer_dead", "fleet_peer_ejected")

#: bundle ids are process-minted and filesystem-safe; /debug/incidents/{id}
#: refuses anything else (no path traversal through the id)
_ID_RE = re.compile(r"inc-\d{6}-[a-z_]+")

#: XLA's device-OOM signature (utils/faults.py SimulatedOOM mirrors it)
OOM_SIGNATURE = "RESOURCE_EXHAUSTED"


class _LogRing(logging.Handler):
    """Bounded structured tail of the process log stream — the bundle's
    ``log_tail``.  Installed on the root logger only while the recorder
    is armed; a failing append must never break logging."""

    def __init__(self, ring: deque):
        super().__init__()
        self.ring = ring

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.ring.append({
                "at": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage(),
            })
        except Exception:  # noqa: BLE001 — logging must never fail serving
            pass


class FlightRecorder:
    """The process-wide incident recorder (module instance:
    :data:`FLIGHTREC`)."""

    # record() runs on watchdog / engine / event-loop threads; the seq,
    # debounce table and counters go through one mutex.  ``armed`` is the
    # single hot-path read, by design.
    _GUARDED_BY = {"_seq": "_lock", "_last_at": "_lock",
                   "recorded_total": "_lock", "debounced_total": "_lock"}
    _SHARED_ATOMIC = ("armed", "_dir", "_ring_size", "_debounce_s",
                      "_swept")

    def __init__(self, directory: str | None = None, ring: int | None = None,
                 debounce_s: float | None = None,
                 log_lines: int | None = None):
        if directory is None or ring is None or debounce_s is None \
                or log_lines is None:
            from ..utils.config import knob

            if directory is None:
                directory = str(knob("LFKT_INCIDENT_DIR") or "")
            if ring is None:
                ring = int(knob("LFKT_INCIDENT_RING"))
            if debounce_s is None:
                debounce_s = float(knob("LFKT_INCIDENT_DEBOUNCE_S"))
            if log_lines is None:
                log_lines = int(knob("LFKT_INCIDENT_LOG_LINES"))
        self._lock = threading.Lock()
        self._seq = 0
        self._last_at: dict[str, float] = {}
        self.recorded_total = 0
        self.debounced_total = 0
        self._log_lines = max(1, int(log_lines))
        self._log_ring: deque | None = None
        self._log_handler: _LogRing | None = None
        self._health_ref = None      # weakref: utils/health.HealthMonitor
        self._engine_ref = None      # weakref: the serving engine/registry
        self._fleet_fn = None        # zero-arg fleet-context provider
        self.armed = False
        self._dir = ""
        self._ring_size = 16
        self._debounce_s = 30.0
        self._swept = False
        self.configure(directory=directory, ring=ring,
                       debounce_s=debounce_s)

    # -- configuration (env at import; tests/ops reconfigure) ---------------
    def configure(self, directory: str | None = None, ring: int | None = None,
                  debounce_s: float | None = None) -> None:
        if ring is not None:
            self._ring_size = max(1, int(ring))
        if debounce_s is not None:
            self._debounce_s = max(0.0, float(debounce_s))
        if directory is not None:
            self._dir = str(directory)
            armed = bool(self._dir)
            if armed:
                # continue the on-disk sequence so a restarted process
                # never overwrites the previous crash's evidence; the
                # directory scan runs OFF the lock (lfkt-lint LOCK006:
                # a slow volume must not stall a concurrent record()'s
                # debounce/seq window behind disk I/O).  MERGED, never
                # assigned: a record() that wrote seq N+1 between the
                # scan and the lock must not be rewound to a stale N —
                # the sequence only ever moves forward
                names = self._list_files()
                with self._lock:
                    self._seq = max(
                        [self._seq] + [self._file_seq(n) for n in names])
                    self._last_at.clear()
                # crash-leftover .tmp files are swept lazily at the FIRST
                # write, never here: arming is also what a read-only tool
                # (incident_report / ci_gate) does by importing this
                # module with LFKT_INCIDENT_DIR set, and a reader must
                # not delete a live recorder's in-progress temp file
                self._swept = False
                if self._log_ring is None:
                    self._log_ring = deque(maxlen=self._log_lines)
                    self._log_handler = _LogRing(self._log_ring)
                    logging.getLogger().addHandler(self._log_handler)
                logger.info("incident flight recorder ARMED: dir=%s ring=%d",
                            self._dir, self._ring_size)
            elif self._log_handler is not None:
                logging.getLogger().removeHandler(self._log_handler)
                self._log_handler = None
                self._log_ring = None
            # set LAST: record() keys off this single attribute
            self.armed = armed

    def install(self, health=None, engine=None, fleet=None) -> None:
        """Hand the recorder the process context it cannot import (the
        health monitor and the serving engine/registry) — weakly held, so
        a test's discarded app never pins its engine.  Called by the
        server at startup; in-process tests call it directly.

        ``fleet`` is a zero-arg callable returning this process's fleet
        context (role, peer identity, affinity-key digest, migration
        attribution — whatever the caller can cheaply snapshot); every
        bundle captures it under the ``fleet`` key so a bundle pulled
        off any pod is attributable within the fleet without joining
        logs by hand."""
        import weakref

        if health is not None:
            self._health_ref = weakref.ref(health)
        if fleet is not None:
            self._fleet_fn = fleet
        if engine is not None:
            try:
                self._engine_ref = weakref.ref(engine)
            except TypeError:
                # un-weakref-able fake: bundles go without scheduler
                # stats rather than the process-global recorder pinning a
                # discarded test engine (and its arrays) for life — the
                # weakly-held contract is the point of this method
                self._engine_ref = None

    # -- the one producer entry point ---------------------------------------
    def record(self, kind: str, reason: str, extra: dict | None = None
               ) -> str | None:
        """Snapshot one incident bundle to disk; returns its id, or None
        when disarmed / debounced / the write failed.  Never raises — the
        recorder must not turn an incident into a second incident."""
        if not self.armed:            # disarmed: single attribute read
            return None
        if kind not in KINDS:
            logger.error("unknown incident kind %r dropped", kind)
            return None
        now = time.time()
        with self._lock:
            last = self._last_at.get(kind)
            if last is not None and now - last < self._debounce_s:
                self.debounced_total += 1
                return None
            self._last_at[kind] = now
            self._seq += 1
            seq = self._seq
        incident_id = f"inc-{seq:06d}-{kind}"
        try:
            bundle = self._capture(incident_id, kind, reason, extra, now)
            self._write(incident_id, bundle)
        except Exception:  # noqa: BLE001 — evidence is best-effort
            # roll back the debounce stamp (it was taken optimistically to
            # keep racing producers at one bundle): a failed write — disk
            # full during the very incident being recorded — must not
            # suppress the retry the next trigger would make
            with self._lock:
                if self._last_at.get(kind) == now:
                    del self._last_at[kind]
            logger.exception("incident bundle %s could not be written",
                             incident_id)
            return None
        with self._lock:
            self.recorded_total += 1
        logger.warning("incident bundle recorded: %s (%s) -> %s",
                       incident_id, reason,
                       os.path.join(self._dir, incident_id + ".json"))
        return incident_id

    # -- capture -------------------------------------------------------------
    def _capture(self, incident_id: str, kind: str, reason: str,
                 extra: dict | None, now: float) -> dict:
        from .devtime import DEVTIME
        from .memledger import MEMLEDGER
        from .trace import all_inflight_trees

        health = None
        if self._health_ref is not None:
            h = self._health_ref()
            if h is not None:
                try:
                    health = h.snapshot()
                except Exception:  # noqa: BLE001 — partial bundles beat none
                    pass
        fleet = None
        if self._fleet_fn is not None:
            try:
                fleet = self._fleet_fn()
            except Exception:  # noqa: BLE001 — partial bundles beat none
                pass
        scheduler = None
        if self._engine_ref is not None:
            eng = self._engine_ref()
            stats = getattr(eng, "scheduler_stats", None)
            if callable(stats):
                try:
                    scheduler = stats()
                except Exception:  # noqa: BLE001 — partial bundles beat none
                    pass
        return {
            "schema": SCHEMA,
            "id": incident_id,
            "at": now,
            "kind": kind,
            "reason": str(reason),
            "memory": MEMLEDGER.snapshot(),
            "traces": all_inflight_trees(),
            "scheduler": scheduler,
            "health": health,
            "fleet": fleet,
            "recompile": {"storms": DEVTIME.storms(),
                          "storms_total": DEVTIME.storms_total},
            "log_tail": list(self._log_ring or ()),
            "extra": dict(extra or {}),
        }

    # -- disk ring -----------------------------------------------------------
    @staticmethod
    def _file_seq(name: str) -> int:
        try:
            return int(name.split("-")[1])
        except (IndexError, ValueError):
            return 0

    def _list_files(self) -> list[str]:
        try:
            names = [n for n in os.listdir(self._dir)
                     if _ID_RE.fullmatch(n[:-5]) and n.endswith(".json")]
        except OSError:
            return []
        return sorted(names, key=self._file_seq)

    def _write(self, incident_id: str, bundle: dict) -> None:
        os.makedirs(self._dir, exist_ok=True)
        if not self._swept:
            # first write of this arming: sweep temp files a previous
            # process's crash mid-write left behind (our own write path
            # cleans up after itself below)
            self._swept = True
            try:
                for n in os.listdir(self._dir):
                    if n.startswith(".tmp-"):
                        os.remove(os.path.join(self._dir, n))
            except OSError:
                pass
        final = os.path.join(self._dir, incident_id + ".json")
        tmp = os.path.join(self._dir, f".tmp-{incident_id}.json")
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)   # atomic: never a torn bundle
        except BaseException:
            # a failed write must not LEAVE its torn temp file: the
            # debounce rollback means disk-full retries, and each retry
            # mints a new id — leaked .tmp files would compound the very
            # disk pressure that failed the write
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        files = self._list_files()
        while len(files) > self._ring_size:
            victim = files.pop(0)
            try:
                os.remove(os.path.join(self._dir, victim))
            except OSError:
                pass

    # -- consumers (/debug/incidents, tools/incident_report.py) -------------
    def list(self) -> list[dict]:
        """Newest-first bundle summaries read back from the ring."""
        out = []
        for name in reversed(self._list_files()):
            doc = self.get(name[:-5])
            if doc is None:
                continue
            out.append({k: doc.get(k)
                        for k in ("id", "at", "kind", "reason", "schema")})
        return out

    def get(self, incident_id: str) -> dict | None:
        """One full bundle by id (None for unknown/malformed ids — the id
        grammar is enforced so an id can never escape the ring dir)."""
        if not self._dir or not _ID_RE.fullmatch(incident_id or ""):
            return None
        path = os.path.join(self._dir, incident_id + ".json")
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


def validate_bundle(doc) -> list[str]:
    """Schema violations for one parsed bundle (tools/incident_report.py
    ``--validate`` and ci_gate's incident-schema check run this)."""
    bad: list[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not a JSON object"]
    if doc.get("schema") != SCHEMA:
        bad.append(f"schema {doc.get('schema')!r} != {SCHEMA} (drift)")
    if not isinstance(doc.get("id"), str) \
            or not _ID_RE.fullmatch(doc.get("id") or ""):
        bad.append("missing/malformed 'id'")
    if doc.get("kind") not in KINDS:
        bad.append(f"unknown kind {doc.get('kind')!r}")
    if not isinstance(doc.get("at"), (int, float)):
        bad.append("missing numeric 'at'")
    if not isinstance(doc.get("reason"), str):
        bad.append("missing string 'reason'")
    for field, typ in (("memory", dict), ("traces", list),
                       ("recompile", dict), ("log_tail", list),
                       ("extra", dict)):
        if not isinstance(doc.get(field), typ):
            bad.append(f"missing {typ.__name__} '{field}'")
    for field in ("scheduler", "health", "fleet"):
        if doc.get(field) is not None and not isinstance(doc[field], dict):
            bad.append(f"'{field}' must be an object or null")
    return bad


#: THE process-wide recorder: armed from LFKT_INCIDENT_DIR at import,
#: written by the watchdog/health/SLO trigger points, read by
#: /debug/incidents and tools/incident_report.py.
FLIGHTREC = FlightRecorder()


def record_incident(kind: str, reason: str, extra: dict | None = None
                    ) -> str | None:
    """Module-level convenience: record on the CURRENT process recorder
    (resolved at call time so tests can swap :data:`FLIGHTREC`)."""
    return FLIGHTREC.record(kind, reason, extra=extra)

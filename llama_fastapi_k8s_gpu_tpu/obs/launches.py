"""Deterministic kernel-launch audit of one decode step (lfkt-perf).

The devtime registry (obs/devtime.py) counts HOST dispatches — one per
jit entry call — which is the right grain for compile attribution but
blind to what this repo's round-5 profiling showed actually bounds
decode: the number of *device kernel launches inside* one decode step
(the per-layer fused-matmul / attention / KV-write chain).  This module
makes that number an exact, device-independent integer, the same way the
dispatch pins are: trace the step, walk its jaxpr, and count the
launch-bearing primitives (``pallas_call`` + ``dot_general`` — the MXU /
Mosaic programs XLA cannot fuse away; elementwise ops fuse into their
consumers and are not launches) weighted by the runtime trip count of
every enclosing ``scan`` (``fori_loop`` over layers lowers to one).

That turns the kernel-looping claim (ISSUE 12 / ROADMAP item 2) into a
CPU-pinnable fact: the per-layer path traces L × chain launch primitives
inside its layer loop, the looped path ceil(L/K) ``pallas_call``s — the
launch-count collapse is proven in tier-1 (tests/test_perf_pins.py)
without a chip.

Caveats, stated rather than hidden: a ``while`` body's trip count is not
static — its launches are counted ONCE and the audit marks
``while_loops`` so a reader knows the total is a floor; branch
(``cond``) arms are counted at the maximum over arms.  Neither occurs in
the decode step today.
"""

from __future__ import annotations

import functools

__all__ = ["count_launches", "decode_step_launches"]

#: primitives that survive XLA fusion as their own device kernel launch
#: (a Mosaic program or an MXU dot); everything else fuses into a
#: neighbor's loop nest
LAUNCH_PRIMS = frozenset({
    "pallas_call", "dot_general", "conv_general_dilated",
})

#: primitives whose params carry sub-jaxprs to inline transparently
#: (no runtime multiplier of their own)
_INLINE_PARAMS = ("jaxpr", "call_jaxpr")


def _sub_jaxpr(obj):
    """ClosedJaxpr | Jaxpr -> Jaxpr."""
    return getattr(obj, "jaxpr", obj)


def _walk(jaxpr, audit: dict, mult: int, in_loop: bool) -> None:
    for eq in jaxpr.eqns:
        name = eq.primitive.name
        if name in LAUNCH_PRIMS:
            audit["total"] += mult
            audit["in_loop" if in_loop else "outside"] += mult
            key = name if in_loop else f"{name}(flat)"
            audit["by_prim"][key] = audit["by_prim"].get(key, 0) + mult
            # ONE launch regardless of its body: a pallas_call's params
            # carry the kernel jaxpr (visible in interpret mode) — its
            # inner dots execute inside this launch and must not be
            # double-counted as launches of their own
            continue
        if name == "scan":
            trip = int(eq.params["length"])
            audit["loop_trips"].append(trip)
            _walk(_sub_jaxpr(eq.params["jaxpr"]), audit, mult * trip, True)
        elif name == "while":
            audit["while_loops"] += 1     # trip unknown: counted once (floor)
            _walk(_sub_jaxpr(eq.params["body_jaxpr"]), audit, mult, True)
        elif name == "cond":
            # count the heaviest arm: launches the step MAY pay
            arms = []
            for br in eq.params["branches"]:
                sub = {"total": 0, "in_loop": 0, "outside": 0,
                       "by_prim": {}, "loop_trips": [], "while_loops": 0}
                _walk(_sub_jaxpr(br), sub, mult, in_loop)
                arms.append(sub)
            if arms:
                worst = max(arms, key=lambda a: a["total"])
                for k in ("total", "in_loop", "outside", "while_loops"):
                    audit[k] += worst[k]
                for k, v in worst["by_prim"].items():
                    audit["by_prim"][k] = audit["by_prim"].get(k, 0) + v
                audit["loop_trips"].extend(worst["loop_trips"])
        else:
            for pname in _INLINE_PARAMS:
                sub = eq.params.get(pname) if eq.params else None
                if sub is not None and hasattr(_sub_jaxpr(sub), "eqns"):
                    _walk(_sub_jaxpr(sub), audit, mult, in_loop)


def count_launches(fn, *args) -> dict:
    """Trace ``fn(*args)`` (shape-only: args may be ShapeDtypeStructs) and
    return its launch audit::

        {"total":      launch primitives executed per call (trip-weighted),
         "in_loop":    the subset inside any scan (the layer loop),
         "outside":    flat launches (embedding epilogue, output head),
         "loop_trips": scan trip counts encountered (outermost first),
         "by_prim":    {primitive: weighted count},
         "while_loops": bodies counted once because their trip count is
                        not static (0 for the decode step)}
    """
    import jax

    jx = jax.make_jaxpr(fn)(*args)
    audit = {"total": 0, "in_loop": 0, "outside": 0, "by_prim": {},
             "loop_trips": [], "while_loops": 0}
    _walk(jx.jaxpr, audit, 1, False)
    return audit


def decode_step_launches(params, cfg) -> dict:
    """Launch audit of ONE single-token decode step under ``cfg`` —
    :func:`models.llama.decode_step` traced at shape level (no device
    work, no allocation of a real ring).  The number the kernel-looping
    pins compare: per-layer ``cfg`` vs ``dataclasses.replace(cfg,
    decode_layer_unroll=K)``."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import decode_step, init_cache

    cache = jax.eval_shape(functools.partial(init_cache, cfg))
    tok = jax.ShapeDtypeStruct((), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    shaped = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    return count_launches(
        lambda p, t, po, c: decode_step(p, cfg, t, po, c),
        shaped, tok, pos, cache)

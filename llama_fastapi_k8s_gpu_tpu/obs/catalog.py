"""THE metric catalog — every series /metrics may expose, declared once.

Same single-source-of-truth pattern as the ``LFKT_*`` knob registry
(utils/config.py): every metric name the package passes to
``Metrics.inc/observe/set_gauge`` must be declared here with its type,
help text and (for histograms) buckets.  The registry is enforced at
runtime (an unregistered name raises ``KeyError``, utils/metrics.py) and
statically (lfkt-lint OBS001, lint/obsreg.py); the docs table in
docs/OBSERVABILITY.md is GENERATED from this module (``python -m
llama_fastapi_k8s_gpu_tpu.obs.catalog``) and pinned by OBS002 + a tier-1
test, so a typo'd metric name or an undocumented metric fails the gate.

Engines that synthesize families at runtime (the continuous scheduler's
``scheduler_stats()`` dict) declare a *prefix family* instead of one entry
per key — the ``scheduler_`` entry below — mirroring the bench-only knob
allowlist in lint/configreg.py.
"""

from __future__ import annotations

import dataclasses

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

#: default latency buckets (seconds): tuned for a serving path whose TTFT
#: sits in the 0.05-1 s band and whose tail is the 25 s admission timeout
LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 25.0, 60.0)
#: decode throughput buckets (tokens/sec)
RATE_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0)
#: batch occupancy buckets (lanes filled per cycle)
OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0)
#: token-count buckets (prefix reuse lengths: one page up to a 32k prompt)
TOKEN_BUCKETS = (64.0, 128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0,
                 8192.0, 16384.0, 32768.0)
#: compile-wall buckets (seconds): CPU-tiny test programs compile in tens
#: of ms, real 8B prefill programs in tens of seconds on a cold cache
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0)


@dataclasses.dataclass(frozen=True)
class Metric:
    """One registered metric family.  ``labels`` names the allowed label
    keys (order is the render order); ``prefix=True`` registers a family
    of runtime-synthesized gauges sharing the name as a prefix."""

    name: str
    mtype: str = COUNTER
    help: str = ""
    buckets: tuple = ()
    labels: tuple = ()
    prefix: bool = False


def _register(*metrics: Metric) -> dict[str, Metric]:
    out: dict[str, Metric] = {}
    for m in metrics:
        if m.mtype == HISTOGRAM and not m.buckets:
            raise ValueError(f"histogram {m.name} needs explicit buckets")
        out[m.name] = m
    return out


METRICS: dict[str, Metric] = _register(
    # -- request path (server/app.py) --------------------------------------
    Metric("http_requests_total", COUNTER,
           "requests served, by route and status code",
           labels=("route", "code")),
    Metric("request_seconds", HISTOGRAM,
           "end-to-end request latency, by route",
           buckets=LATENCY_BUCKETS, labels=("route",)),
    Metric("queue_wait_seconds", HISTOGRAM,
           "admission-queue wait (enqueue -> consumer pickup)",
           buckets=LATENCY_BUCKETS),
    Metric("generation_seconds", HISTOGRAM,
           "engine generation wall time (prefill + decode), by model",
           buckets=LATENCY_BUCKETS, labels=("model",)),
    Metric("queue_depth", GAUGE, "admission queue occupancy"),
    Metric("requests_rejected_total", COUNTER,
           "503s from the bounded admission queue"),
    Metric("requests_timed_out_total", COUNTER,
           "408s (admission timeout / stream deadline)"),
    # -- engine phase timings (SURVEY §5 per-phase timers) -----------------
    Metric("engine_ttft_seconds", HISTOGRAM,
           "time to first token (prefill + first sample), by prefill "
           "bucket and model — the SLO engine evaluates each label "
           "series separately, so burn rates report the worst "
           "bucket+model (docs/SLO.md)",
           buckets=LATENCY_BUCKETS, labels=("bucket", "model")),
    Metric("engine_decode_tokens_per_sec", HISTOGRAM,
           "per-request decode throughput, by model",
           buckets=RATE_BUCKETS, labels=("model",)),
    Metric("generated_tokens_total", COUNTER, "completion tokens emitted"),
    Metric("batched_generations_total", COUNTER,
           "mesh-batched generation cycles"),
    Metric("streamed_generations_total", COUNTER, "SSE streams served"),
    Metric("batch_occupancy", HISTOGRAM,
           "requests coalesced per batched cycle",
           buckets=OCCUPANCY_BUCKETS),
    # -- speculative decoding / prefix reuse -------------------------------
    Metric("spec_drafted_tokens_total", COUNTER,
           "speculative tokens drafted"),
    Metric("spec_accepted_tokens_total", COUNTER,
           "speculative tokens accepted"),
    Metric("spec_verify_steps_total", COUNTER, "speculative verify steps"),
    Metric("spec_fallback_steps_total", COUNTER,
           "plain decode steps taken on lookup miss"),
    Metric("prefix_cache_hits_total", COUNTER,
           "requests served with prompt-prefix KV reuse"),
    Metric("prefix_cache_reused_tokens_total", COUNTER,
           "prompt tokens NOT re-prefilled thanks to prefix reuse"),
    # -- block-paged KV pool + radix prefix cache (parallel/kvpool.py) -----
    Metric("prefix_cache_misses_total", COUNTER,
           "requests that consulted the radix prefix index and took a "
           "full prefill (no usable cached prefix)"),
    Metric("prefix_cache_evictions_total", COUNTER,
           "KV pool nodes evicted (LRU, unpinned) to free pages"),
    Metric("prefix_cache_spills_total", COUNTER,
           "evicted KV nodes DMA'd to the host-RAM spill tier"),
    Metric("prefix_cache_restores_total", COUNTER,
           "spilled KV nodes restored to HBM on a prefix hit"),
    Metric("prefix_reuse_tokens", HISTOGRAM,
           "per-hit prompt tokens served from cached KV pages",
           buckets=TOKEN_BUCKETS),
    Metric("kv_pool_pages_used", GAUGE,
           "KV pool pages holding indexed cache content"),
    Metric("kv_pool_pages_free", GAUGE, "KV pool pages on the free list"),
    # -- disaggregated prefill/decode (serving/disagg/) --------------------
    Metric("disagg_prefills_served_total", COUNTER,
           "prefill tier: remote prefill requests answered with pages"),
    Metric("disagg_pages_sent_total", COUNTER,
           "prefill tier: KV pages streamed to decode replicas"),
    Metric("disagg_bytes_sent_total", COUNTER,
           "prefill tier: page payload bytes put on the wire"),
    Metric("disagg_remote_prefills_total", COUNTER,
           "decode replica: admissions whose prefix was imported from "
           "the prefill tier (pages restored instead of local prefill)"),
    Metric("disagg_pages_received_total", COUNTER,
           "decode replica: KV pages received from the prefill tier"),
    Metric("disagg_bytes_received_total", COUNTER,
           "decode replica: page payload bytes received"),
    Metric("disagg_local_fallbacks_total", COUNTER,
           "decode replica: remote prefills degraded to LOCAL prefill, "
           "by reason (peer_dead, peer_unreachable, refused, import, "
           "prefill, ...) — nonzero = the split fleet is not splitting",
           labels=("reason",)),
    Metric("disagg_handshake_refusals_total", COUNTER,
           "page-wire handshakes refused (schema/geometry mismatch — "
           "a mis-deployed tier pair, docs/RUNBOOK.md)"),
    Metric("disagg_transfer_seconds", HISTOGRAM,
           "decode replica: one remote-prefill hop's wall (request -> "
           "pages imported)",
           buckets=LATENCY_BUCKETS),
    Metric("disagg_peer_connected", GAUGE,
           "decode replica: 1 while the prefill peer connection is up"),
    # -- fleet tier: the prefix-affinity router (serving/fleet/) -----------
    Metric("fleet_requests_total", COUNTER,
           "router: requests proxied, by serving replica and affinity-"
           "key source (header | conversation | prefix | opaque)",
           labels=("peer", "source")),
    Metric("fleet_spills_total", COUNTER,
           "router: requests NOT served by their rendezvous owner, by "
           "reason (ejected = retried onto the next peer, spilled = "
           "served off-owner, mid_stream_abort = peer died after bytes "
           "reached the client, no_replica = whole fleet down -> 503); "
           "sustained nonzero = conversations are running cold",
           labels=("reason",)),
    Metric("fleet_peer_ejections_total", COUNTER,
           "router: replica ejections (probe failure or proxied-request "
           "failure), by peer — the /health peers block names the reason",
           labels=("peer",)),
    Metric("fleet_peers_healthy", GAUGE,
           "router: replicas currently accepting traffic"),
    Metric("fleet_proxy_seconds", HISTOGRAM,
           "router: one proxied request's wall (client head in -> "
           "backend response relayed)",
           buckets=LATENCY_BUCKETS),
    Metric("fleet_probe_seconds", HISTOGRAM,
           "router: one health-probe round trip per replica, success or "
           "failure — the ejection-threshold tuning signal (a peer whose "
           "probes crawl toward the timeout is about to be ejected)",
           buckets=LATENCY_BUCKETS, labels=("peer",)),
    # -- fleet KV migration (serving/fleet/migrate.py) ---------------------
    Metric("kv_migration_pulls_total", COUNTER,
           "migration pulls attempted, by trigger (remap = router "
           "prior-owner hint, warmup = scale-out pre-pull, drain = "
           "commanded pull from a DRAINING peer)",
           labels=("reason",)),
    Metric("kv_migration_pushes_total", COUNTER,
           "migration page service: pull requests answered with pages "
           "(this pod was the warm side)"),
    Metric("kv_migration_pages_total", COUNTER,
           "KV pages moved by migration, by direction (pulled | pushed)",
           labels=("reason",)),
    Metric("kv_migration_failures_total", COUNTER,
           "migration attempts degraded, by reason (connect, wire, "
           "refused, deadline, import, drain_push, ...) — every one "
           "fell back to local recompute or plain termination, with "
           "this attribution",
           labels=("reason",)),
    Metric("kv_migration_seconds", HISTOGRAM,
           "one migration hop's wall (request -> pages imported)",
           buckets=LATENCY_BUCKETS),
    # -- live manifest reload (serving/registry.py reload_manifest) --------
    Metric("model_reloads_total", COUNTER,
           "live-reload actions on the model registry (add = model "
           "loaded+warmed in place, remove = namespace drained + weights "
           "released, refused = budget/fit/grammar refusal with the "
           "running set untouched)",
           labels=("action",)),
    # -- prefill pipeline (overlapped chunked prefill + admission control) --
    Metric("prefill_slice_seconds", HISTOGRAM,
           "host wall of one prefill-slice dispatch (prep + enqueue; "
           "long = device-queue pushback)",
           buckets=LATENCY_BUCKETS),
    Metric("admission_budget_tokens", GAUGE,
           "admission controller's live per-wave prefill-token budget"),
    Metric("lane_idle_seconds", GAUGE,
           "cumulative idle lane-seconds while other lanes decoded "
           "(monotonic; the admission controller's raw loss signal)"),
    # -- resilience / error taxonomy (docs/RUNBOOK.md) ---------------------
    Metric("engine_unavailable_total", COUNTER,
           "503s from watchdog trips / recovery in progress"),
    Metric("engine_errors_total", COUNTER, "engine-side request failures"),
    Metric("watchdog_trips_total", COUNTER, "watchdog trip count"),
    Metric("watchdog_recoveries_total", COUNTER,
           "successful watchdog recoveries"),
    Metric("watchdog_escalations_total", COUNTER,
           "recovery budget exhaustions (DEAD)"),
    Metric("health_state", GAUGE,
           "pod health state code (0=STARTING 1=READY 2=DEGRADED "
           "3=DRAINING 4=DEAD)"),
    Metric("engine_inflight", GAUGE, "engine busy count (heartbeat)"),
    Metric("engine_error_count", GAUGE, "heartbeat errors_total"),
    # -- capacity ----------------------------------------------------------
    Metric("kv_cache_bytes", GAUGE, "resident KV-cache HBM bytes"),
    # -- lfkt-mem: live HBM memory ledger (obs/memledger.py) ---------------
    Metric("hbm_bytes", GAUGE,
           "live HBM bytes per memory-ledger component and model "
           "(component=residual carries bytes the ledger cannot "
           "attribute vs device ground truth; docs/OBSERVABILITY.md "
           "memory-ledger section)",
           labels=("component", "model")),
    Metric("hbm_headroom_bytes", GAUGE,
           "free device HBM (bytes_limit - bytes_in_use); only exported "
           "where the backend reports memory_stats"),
    Metric("mem_pressure_events_total", COUNTER,
           "admission-controller budget cuts triggered by low HBM "
           "headroom (rising edges, not waves — docs/RUNBOOK.md "
           "'Diagnosing HBM OOM')"),
    # -- lfkt-mem: incident flight recorder (obs/flightrec.py) -------------
    Metric("incidents_total", GAUGE,
           "incident bundles recorded by the flight recorder this "
           "process (snapshot; bundles live in LFKT_INCIDENT_DIR)"),
    # -- multi-tenant token metering (server/app.py usage counts) ----------
    Metric("tokens_prompt_total", COUNTER,
           "prompt tokens ingested, by model (from the engines' own "
           "usage counts — metering without scraping /v1 responses)",
           labels=("model",)),
    Metric("tokens_generated_total", COUNTER,
           "completion tokens emitted, by model (from the engines' own "
           "usage counts)",
           labels=("model",)),
    # -- multi-model serving (serving/registry.py; docs/MULTIMODEL.md) -----
    Metric("models_loaded", GAUGE,
           "models served by this process (manifest rows, or 1)"),
    Metric("model_weight_bytes", GAUGE,
           "resident weight HBM bytes per served model (the registry's "
           "LFKT_HBM_WEIGHT_BUDGET_MB accounting unit)",
           labels=("model",)),
    # -- tracer self-telemetry (obs/trace.py) ------------------------------
    Metric("trace_ring_used", GAUGE, "completed traces held in the ring"),
    # monotonic tracer counters exported as point-in-time snapshots (the
    # tracer owns the count; /metrics copies it rather than re-counting)
    Metric("traces_started_total", GAUGE, "requests that drew a trace"),
    Metric("traces_sampled_out_total", GAUGE,
           "requests skipped by LFKT_TRACE_SAMPLE"),
    # -- lfkt-perf: compile/dispatch attribution (obs/devtime.py) ----------
    # per-program counters exported as point-in-time snapshots — the
    # devtime registry owns the count; /metrics copies it (same convention
    # as the tracer counters above)
    Metric("xla_compiles_total", GAUGE,
           "jit compile events per program (devtime snapshot)",
           labels=("program",)),
    Metric("jit_dispatches_total", GAUGE,
           "host dispatches per jit program (devtime snapshot)",
           labels=("program",)),
    Metric("xla_recompile_storms_total", GAUGE,
           "signatures minted past LFKT_RECOMPILE_BUDGET "
           "(devtime snapshot; docs/RUNBOOK.md recompile-storm runbook)"),
    Metric("xla_compile_seconds", HISTOGRAM,
           "wall time of jit compile events, by program (first-dispatch "
           "wall; replayed from the devtime event ring at scrape time)",
           buckets=COMPILE_BUCKETS, labels=("program",)),
    Metric("xla_compile_events_dropped_total", GAUGE,
           "compile events evicted from the ring before replay — nonzero "
           "means xla_compile_seconds undercounts vs xla_compiles_total "
           "(a storm outran the scrape cadence)"),
    # -- SLO engine (obs/slo.py; docs/SLO.md) ------------------------------
    Metric("slo_burn_rate", GAUGE,
           "error-budget burn rate per SLO and window (1.0 = burning "
           "exactly the budget; sustained >1 on every window = breach); "
           "scope=pod on replica scrapes, scope=fleet when the router "
           "evaluates the catalog over federated histograms",
           labels=("slo", "window", "scope")),
    # -- runtime-synthesized families --------------------------------------
    Metric("scheduler_", GAUGE,
           "continuous-scheduler occupancy family "
           "(ContinuousEngine.scheduler_stats: lanes_live, pending, "
           "admission_inflight, spec_*, lane_prefix_* / radix_prefix_*)",
           prefix=True),
)


@dataclasses.dataclass(frozen=True)
class MemComponent:
    """One registered memory-ledger component (obs/memledger.py): a
    device-allocation surface that reports live byte counts into the
    ``hbm_bytes{component,model}`` family.  ``device=False`` marks a
    host-RAM tier (listed, but excluded from the HBM reconciliation
    sum).  ``always=True`` keeps the row at ZERO instead of dropping it
    — for gauges whose zero IS the alert condition (a fully exhausted
    free list must read 0, not "no data").  Mirrors :class:`Metric`:
    every ``MemLedger.register_component`` name must appear here —
    enforced at runtime (KeyError) and statically (lfkt-lint OBS003)."""

    name: str
    help: str = ""
    device: bool = True
    always: bool = False


#: THE memory-component catalog: every allocation surface the ledger may
#: attribute.  ``residual`` is computed (ground truth minus the sum of
#: device components), never registered.
MEM_COMPONENTS: dict[str, MemComponent] = {
    c.name: c for c in (
        MemComponent("weights",
                     "per-model resident weight bytes (Engine.weight_bytes"
                     " — the registry's HBM budget unit)"),
        MemComponent("kv_ring",
                     "serial dense KV ring (Engine._cache; allocated on "
                     "every engine, serving or not)"),
        MemComponent("kv_lanes",
                     "batched lane state: the mesh/continuous engines' "
                     "shared decode pytree (parallel/batched.py)"),
        MemComponent("kv_scratch",
                     "the continuous scheduler's persistent prefill "
                     "scratch ring (engine/continuous.py)"),
        MemComponent("kv_arena_used",
                     "KV pool arena pages holding indexed cache content, "
                     "per radix namespace (model); model=(unindexed) is "
                     "allocated-but-uncommitted in-flight pages"),
        MemComponent("kv_arena_free",
                     "KV pool arena pages on the free list (allocated "
                     "HBM, no content); reported even at 0 — exhaustion "
                     "is the alert", always=True),
        MemComponent("host_spill",
                     "host-RAM KV spill tier (LFKT_KV_SPILL_PAGES)",
                     device=False),
        MemComponent("disagg_txbuf",
                     "disagg page-wire send queues: host bytes buffered "
                     "between page export and the socket (bounded by "
                     "LFKT_DISAGG_QUEUE_FRAMES x peers — "
                     "serving/disagg/transport.py)",
                     device=False),
        MemComponent("residual",
                     "ground truth minus every attributed device "
                     "component: bytes the ledger cannot explain "
                     "(computed, never registered)"),
    )
}


def lookup(name: str) -> Metric | None:
    """The catalog entry governing ``name``: exact match first, then the
    longest matching declared prefix family."""
    m = METRICS.get(name)
    if m is not None:
        return m
    best = None
    for entry in METRICS.values():
        if entry.prefix and name.startswith(entry.name):
            if best is None or len(entry.name) > len(best.name):
                best = entry
    return best


def markdown_table() -> str:
    """The docs/OBSERVABILITY.md metrics table — generated, never hand
    edited (tests/test_obs.py pins the docs block to this output)."""
    rows = ["| metric | type | labels | help |",
            "|---|---|---|---|"]
    for m in METRICS.values():
        name = f"{m.name}*" if m.prefix else m.name
        labels = ",".join(m.labels) if m.labels else ""
        rows.append(f"| `{name}` | {m.mtype} | {labels} | {m.help} |")
    return "\n".join(rows)


if __name__ == "__main__":
    print(markdown_table())

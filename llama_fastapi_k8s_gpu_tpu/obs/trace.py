"""Request-scoped span trees: the lfkt-obs tracer.

The reference's only instrument is one request-timing log line (reference
api.py:179-194); nothing answers "where did THIS slow request spend its
time".  This module produces, per sampled request, a span tree covering
the whole serving path — httpd read, admission, queue wait, prefill/TTFT,
per-decode-chunk, SSE write — with watchdog trips, health transitions and
fault injections attached as events, kept in a bounded ring and exported
as JSON at ``GET /debug/traces`` (+ ``/debug/traces/{id}`` and the
in-flight ``/debug/requests`` snapshot, server/app.py).

Design constraints:

- **Zero dependencies** (stdlib only) and **zero cost when sampled out**:
  :meth:`Tracer.start` returns ``None`` for an unsampled request and every
  producer guards with ``if trace is not None`` — the decode hot path then
  pays one ``is None`` test per *chunk*, no allocation, no lock (guarded
  by tests/test_obs.py and the JIT purity lint: nothing here is reachable
  from a jit trace).
- **Thread-safe for sampled requests**: a trace is written by the handler
  coroutine, an engine worker/scheduler thread, and (for events) the
  watchdog thread; each trace carries its own small lock.  Spans are
  appended once per phase or per decode chunk — never per token.
- **W3C trace-context interop**: ``traceparent`` request headers are
  ingested (the incoming trace id becomes this trace's id, the incoming
  span id its remembered parent) and a valid ``traceparent`` for the
  request's root span is exported for response propagation.
"""

from __future__ import annotations

import threading
import time
import uuid
import weakref
from collections import OrderedDict, deque

#: hard ceiling on spans+events per trace: a runaway generation must not
#: grow one trace without bound (past it, drops are counted, not stored)
MAX_NODES_PER_TRACE = 512

_TRACEPARENT_VERSION = "00"


def _new_trace_id() -> str:
    return uuid.uuid4().hex                      # 32 lowercase hex chars


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]                 # 16 lowercase hex chars


def parse_traceparent(value: str | None) -> tuple[str, str] | None:
    """(trace_id, parent_span_id) from a W3C ``traceparent`` header, or
    None when absent/malformed (a bad header must never fail a request —
    it just starts a fresh trace)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4 or parts[0] != _TRACEPARENT_VERSION:
        return None
    trace_id, span_id = parts[1].lower(), parts[2].lower()
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
    except ValueError:
        return None
    return trace_id, span_id


def span_traceparent(span: "Span | Trace | None") -> str | None:
    """The W3C ``traceparent`` naming ``span`` as the parent — the stamp
    an outbound hop (disagg REQ, migration REQ, router proxy attempt)
    carries so the serving side's span tree links under this exact node.
    None-tolerant: sampled-out callers pass their ``span=None`` straight
    through and the wire field rides as null (zero-cost contract).
    Accepts a :class:`Trace` too (some producers hand the whole trace
    around rather than a span — app.py's migrate hook does)."""
    if span is None:
        return None
    if isinstance(span, Trace):
        return span.traceparent()
    return (f"{_TRACEPARENT_VERSION}-{span._trace.trace_id}"
            f"-{span.span_id}-01")


class Span:
    """One timed phase of a request.  Built by :meth:`Trace.span` /
    :meth:`Span.child`; closed with :meth:`end` (idempotent)."""

    __slots__ = ("name", "span_id", "t0", "t1", "attrs", "events",
                 "children", "_trace")

    def __init__(self, name: str, trace: "Trace", t0: float | None = None):
        self.name = name
        self.span_id = _new_span_id()
        self.t0 = time.time() if t0 is None else t0
        self.t1: float | None = None
        self.attrs: dict = {}
        self.events: list[dict] = []
        self.children: list[Span] = []
        self._trace = trace

    # -- producer API -------------------------------------------------------
    def child(self, name: str, t0: float | None = None) -> "Span":
        sp = Span(name, self._trace, t0=t0)
        tr = self._trace
        with tr._lock:
            if tr._nodes < MAX_NODES_PER_TRACE:
                tr._nodes += 1
                self.children.append(sp)
            else:
                tr._dropped += 1
        return sp

    def set(self, **attrs) -> "Span":
        tr = self._trace
        with tr._lock:
            self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> None:
        tr = self._trace
        with tr._lock:
            if tr._nodes < MAX_NODES_PER_TRACE:
                tr._nodes += 1
                self.events.append(
                    {"name": name, "at": time.time(), **attrs})
            else:
                tr._dropped += 1

    def end(self, t1: float | None = None) -> None:
        if self.t1 is None:
            self.t1 = time.time() if t1 is None else t1

    # -- export -------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "start": self.t0,
            "end": self.t1,
            "duration_s": (self.t1 - self.t0) if self.t1 is not None else None,
            "attrs": dict(self.attrs),
            "events": list(self.events),
            "children": [c.to_dict() for c in self.children],
        }


class Trace:
    """One request's span tree plus the live metadata ``/debug/requests``
    snapshots (engine, lane, deadline, tokens so far)."""

    __slots__ = ("trace_id", "parent_span_id", "root", "meta",
                 "_lock", "_nodes", "_dropped", "finished")

    def __init__(self, name: str = "request",
                 traceparent: str | None = None,
                 t0: float | None = None):
        ingested = parse_traceparent(traceparent)
        if ingested is not None:
            self.trace_id, self.parent_span_id = ingested
        else:
            self.trace_id, self.parent_span_id = _new_trace_id(), None
        self._lock = threading.Lock()
        self._nodes = 1
        self._dropped = 0
        self.finished = False
        self.root = Span(name, self, t0=t0)
        #: live request metadata, overwritten in place (cheap single-key
        #: stores) — NOT part of the span tree
        self.meta: dict = {}

    # -- producer API -------------------------------------------------------
    def span(self, name: str, t0: float | None = None) -> Span:
        return self.root.child(name, t0=t0)

    def event(self, name: str, **attrs) -> None:
        self.root.event(name, **attrs)

    def note(self, **meta) -> None:
        """Update the live ``/debug/requests`` metadata (engine, lane,
        deadline, tokens...).  Single dict stores; no span allocation."""
        with self._lock:
            self.meta.update(meta)

    def traceparent(self) -> str:
        """A W3C traceparent naming this trace's root span (propagation)."""
        return (f"{_TRACEPARENT_VERSION}-{self.trace_id}"
                f"-{self.root.span_id}-01")

    # -- export -------------------------------------------------------------
    def to_dict(self) -> dict:
        with self._lock:
            d = {
                "trace_id": self.trace_id,
                "parent_span_id": self.parent_span_id,
                "finished": self.finished,
                "dropped_nodes": self._dropped,
                "meta": dict(self.meta),
                "root": self.root.to_dict(),
            }
        return d

    def summary(self) -> dict:
        r = self.root
        with self._lock:
            return {
                "trace_id": self.trace_id,
                "name": r.name,
                "start": r.t0,
                "duration_s": (r.t1 - r.t0) if r.t1 is not None else None,
                "finished": self.finished,
                "spans": self._nodes,
                "meta": dict(self.meta),
            }

    def _close_open_spans(self) -> None:
        """End every still-open span at the root's end time, stamped
        ``auto_closed`` — error paths (a prefill that raised, a scheduler
        that died mid-admission) must not export half-open spans that
        waterfall tools render as still-running phases."""
        t1 = self.root.t1
        with self._lock:
            stack = [self.root]
            while stack:
                s = stack.pop()
                if s.t1 is None:
                    s.t1 = t1
                    s.attrs.setdefault("auto_closed", True)
                stack.extend(s.children)


class Tracer:
    """Sampling decision + in-flight registry + bounded completed-trace ring.

    ``sample`` (LFKT_TRACE_SAMPLE): fraction of requests traced — 1.0
    traces everything, 0 disarms the tracer entirely (``start`` returns
    None before taking any lock).  Sampling is deterministic-by-counter so
    a 0.25 sample traces exactly every 4th request (testable, no RNG).
    ``ring`` (LFKT_TRACE_RING): completed traces kept for /debug/traces.
    """

    # start/finish run on the event loop; annotate_inflight on watchdog/
    # health threads; /debug reads on the loop — all table access is
    # lock-guarded (lfkt-lint LOCK001).  _armed is a single bool read on
    # the hot path (GIL-atomic by design).
    _GUARDED_BY = {"_ring": "_lock", "_inflight": "_lock",
                   "_count": "_lock", "started_total": "_lock",
                   "sampled_out_total": "_lock"}
    _SHARED_ATOMIC = ("_armed",)

    def __init__(self, sample: float | None = None, ring: int | None = None):
        if sample is None or ring is None:
            from ..utils.config import knob

            if sample is None:
                sample = knob("LFKT_TRACE_SAMPLE")
            if ring is None:
                ring = knob("LFKT_TRACE_RING")
        self.sample = max(0.0, min(1.0, float(sample)))
        self.ring = max(1, int(ring))
        self._lock = threading.Lock()
        self._ring: deque[Trace] = deque(maxlen=self.ring)
        self._inflight: OrderedDict[str, Trace] = OrderedDict()
        self._count = 0
        self.started_total = 0
        self.sampled_out_total = 0
        #: the hot-path guard: False means start() returns None without
        #: touching the lock and annotate_inflight is a no-op
        self._armed = self.sample > 0.0
        with _REGISTRY_LOCK:
            # process-wide event fan-in (annotate_all_inflight): watchdog/
            # health/fault events reach EVERY live tracer's in-flight
            # traces, including private instances tests hand to create_app
            _TRACERS.add(self)

    # -- lifecycle ----------------------------------------------------------
    def start(self, name: str = "request",
              traceparent: str | None = None,
              t0: float | None = None) -> Trace | None:
        """Begin a trace for one request, or None when sampled out."""
        if not self._armed:
            return None
        with self._lock:
            self._count += 1
            if self.sample < 1.0:
                # deterministic counter sampling: trace request i iff the
                # integral of the rate crosses an integer at i
                if int(self._count * self.sample) == int(
                        (self._count - 1) * self.sample):
                    self.sampled_out_total += 1
                    return None
            tr = Trace(name, traceparent=traceparent, t0=t0)
            self.started_total += 1
            self._inflight[tr.trace_id] = tr
        return tr

    def start_linked(self, name: str,
                     traceparent: str | None,
                     t0: float | None = None) -> Trace | None:
        """Begin a SERVER-SIDE trace fragment under a remote parent, or
        None.  Unlike :meth:`start` this is parent-based sampling: the
        client's decision propagates — we trace iff armed AND the wire
        actually carried valid trace context.  Running the counter
        sampler here would randomly orphan hops of requests the client
        sampled in, which is worse than either extreme."""
        if not self._armed or parse_traceparent(traceparent) is None:
            return None
        with self._lock:
            tr = Trace(name, traceparent=traceparent, t0=t0)
            self.started_total += 1
            self._inflight[tr.trace_id] = tr
        return tr

    def finish(self, trace: Trace | None) -> None:
        """Close a trace's root span and move it to the ring (idempotent;
        None-tolerant so callers never need their own sampled-out guard).
        Any span a producer's error path left open is swept closed at the
        root's end time (``auto_closed``)."""
        if trace is None:
            return
        trace.root.end()
        with self._lock:
            if trace.finished:
                return
            trace.finished = True
            self._inflight.pop(trace.trace_id, None)
            self._ring.append(trace)
        trace._close_open_spans()

    # -- global event fan-in (watchdog / health / faults) --------------------
    def annotate_inflight(self, name: str, **attrs) -> None:
        """Attach an event to every in-flight trace: watchdog trips,
        health transitions and fault injections are process-level facts
        that explain whatever requests they overlapped."""
        if not self._armed:
            return
        with self._lock:
            traces = list(self._inflight.values())
        for tr in traces:
            tr.event(name, **attrs)

    # -- /debug reads -------------------------------------------------------
    def get(self, trace_id: str) -> Trace | None:
        with self._lock:
            tr = self._inflight.get(trace_id)
            if tr is not None:
                return tr
            for t in self._ring:
                if t.trace_id == trace_id:
                    return t
        return None

    def traces(self) -> list[dict]:
        """Newest-first summaries of the completed ring."""
        with self._lock:
            ring = list(self._ring)
        return [t.summary() for t in reversed(ring)]

    def inflight(self) -> list[dict]:
        """Live-request snapshot for /debug/requests."""
        now = time.time()
        with self._lock:
            traces = list(self._inflight.values())
        out = []
        for t in traces:
            meta = dict(t.meta)
            deadline = meta.pop("deadline", None)
            out.append({
                "trace_id": t.trace_id,
                "name": t.root.name,
                "age_s": now - t.root.t0,
                "deadline_remaining_s":
                    (deadline - now) if deadline is not None else None,
                **meta,
            })
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "sample": self.sample,
                "ring": self.ring,
                "ring_used": len(self._ring),
                "inflight": len(self._inflight),
                "started_total": self.started_total,
                "sampled_out_total": self.sampled_out_total,
            }


#: every live Tracer, for the process-level event fan-in; weak so a
#: test's discarded private tracer does not outlive its test
_REGISTRY_LOCK = threading.Lock()
_TRACERS: "weakref.WeakSet[Tracer]" = weakref.WeakSet()


def annotate_all_inflight(name: str, **attrs) -> None:
    """Attach an event to every in-flight trace of EVERY live tracer —
    the watchdog/health/fault fan-in.  Process-level facts must reach
    private tracers too (create_app(tracer=...)), not just the module
    default; each tracer's own ``_armed`` guard keeps this free when
    tracing is off."""
    with _REGISTRY_LOCK:
        tracers = list(_TRACERS)
    for t in tracers:
        t.annotate_inflight(name, **attrs)


def all_inflight_trees(limit: int = 32) -> list[dict]:
    """Full span trees of every in-flight trace across EVERY live tracer
    — the incident flight recorder's trace capture (obs/flightrec.py):
    the request a watchdog trip interrupted must ride the bundle even
    when the server was handed a private tracer.  Bounded: a bundle is a
    post-mortem, not a dump."""
    with _REGISTRY_LOCK:
        tracers = list(_TRACERS)
    out: list[dict] = []
    for t in tracers:
        with t._lock:
            traces = list(t._inflight.values())
        for tr in traces:
            out.append(tr.to_dict())
            if len(out) >= limit:
                return out
    return out


#: process-wide default tracer the serving stack shares: the server starts
#: traces on it (unless create_app was handed a private instance), engines
#: attach spans to the handed-down Trace objects, and the watchdog/health/
#: fault layers annotate whatever is in flight across all live tracers.
#: Built from the env knobs at import.
TRACER = Tracer()

"""Request-id log stamping + the structured JSON access-log formatter.

The reference logs free-text lines with no request identity (reference
api.py:188-193), so correlating a 500 with its access line under
concurrent traffic is guesswork.  Here the active request id (the trace
id when sampled, a fresh id otherwise) rides a :mod:`contextvars` context
variable — it follows the request through ``await`` points AND into
``asyncio.to_thread`` workers (to_thread copies the context) — and a
:class:`RequestIdFilter` stamps it onto every log record, so ANY log line
emitted while serving a request carries ``request_id=...`` without the
call sites changing.

:class:`JsonFormatter` renders records as one JSON object per line (ts,
level, logger, message, request_id, plus exception text when present) —
the machine-parseable access log the k8s log pipeline ingests.  Install
both with :func:`setup_json_logging` (server/__main__.py does for
production; tests attach them to private handlers).

:func:`sanitize_text` is THE log-injection declassifier: any
request-derived string (a model name off the admin manifest, an explicit
affinity header, a peer-supplied ejection/health reason, a wire-frame
error detail) must pass through it before interpolation into a log
record or an outbound header.  lfkt-lint's taint analyzer (lint/taint.py
TAINT003) enforces that statically — ``sanitize_text`` is the registered
sanitizer for the ``log`` and ``header`` sink classes.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import re

#: C0 control bytes (including CR/LF — the log-forging pair) + DEL; the
#: text log format is line-framed and the raw HTTP header format is
#: CRLF-framed, so any of these inside an attacker-controlled string can
#: forge a record boundary
_CONTROL_BYTES = re.compile(r"[\x00-\x1f\x7f]+")


def sanitize_text(value, limit: int = 512) -> str:
    """``value`` as a single-line, bounded, printable string.  Control
    bytes (CR/LF included) collapse to one space and the result is
    truncated to ``limit`` chars — enough to neutralize log-record
    forging and header-splitting while keeping the payload legible for
    attribution.  Accepts any type (peer JSON fields arrive as whatever
    the peer sent); never raises."""
    text = value if isinstance(value, str) else str(value)
    text = _CONTROL_BYTES.sub(" ", text)
    if len(text) > limit:
        text = text[:limit] + "..."
    return text

#: the active request id ("-" outside any request scope)
_REQUEST_ID: contextvars.ContextVar[str] = contextvars.ContextVar(
    "lfkt_request_id", default="-")


def current_request_id() -> str:
    return _REQUEST_ID.get()


@contextlib.contextmanager
def bind_request_id(rid: str):
    """Scope ``rid`` as the active request id for log stamping."""
    token = _REQUEST_ID.set(rid)
    try:
        yield
    finally:
        _REQUEST_ID.reset(token)


class RequestIdFilter(logging.Filter):
    """Stamps ``record.request_id`` from the context variable.  A filter
    (not a formatter concern) so EVERY formatter downstream — JSON or the
    default text one — can reference ``%(request_id)s``."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = _REQUEST_ID.get()
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line; always includes the request id."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "request_id": getattr(record, "request_id", None)
            or _REQUEST_ID.get(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        # structured extras attached via logger.*(..., extra={...});
        # peer/spills/attempt are the fleet router's access fields — one
        # record per proxy attempt, joinable with replica access lines
        # through the shared request id
        for key in ("route", "method", "status", "duration_s",
                    "peer", "spills", "attempt"):
            v = record.__dict__.get(key)
            if v is not None:
                out[key] = v
        return json.dumps(out)


#: the access logger server/app.py's timing middleware writes to — one
#: structured record per served request
access_logger = logging.getLogger("lfkt.access")


def setup_json_logging(logger: logging.Logger | None = None,
                       stream=None) -> logging.Handler:
    """Attach a JSON handler (+ request-id filter) to ``logger`` (root by
    default).  Returns the handler so callers/tests can detach it."""
    target = logger if logger is not None else logging.getLogger()
    handler = logging.StreamHandler(stream)
    handler.addFilter(RequestIdFilter())
    handler.setFormatter(JsonFormatter())
    target.addHandler(handler)
    return handler

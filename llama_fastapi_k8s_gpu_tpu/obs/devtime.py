"""Compile & dispatch attribution — THE jit program registry (lfkt-perf).

The serving stack's hot path is a handful of jitted programs (prefill /
decode-chunk programs, the continuous scheduler's lane ops, the KV pool's
page-copy programs) plus trace-inner dispatch sites (fused quantized
matmuls, flash attention, KV write-quantize) that compile *as part of*
whichever host program traces them.  Before this module nothing could
answer "what did this pod compile, when, and how often is it
recompiling" — the exact failure mode (silent recompile storms, extra
per-chunk dispatches) that erases kernel-level wins without failing a
single test.

Two registration forms, one registry:

- :func:`timed_jit` wraps a HOST jit entry point.  Every call increments
  the program's dispatch count; a call that grew the underlying jit cache
  (``fn._cache_size()``, with a signature-set fallback on jax versions
  without it) is a compile event: the program records the static-shape
  signature and the call's wall time (first-dispatch wall ≈ compile wall,
  the standard attribution), and the event is exported to the
  ``xla_compiles_total`` / ``xla_compile_seconds`` /
  ``jit_dispatches_total`` catalog families by the server's /metrics
  render.
- :func:`register_program` declares a TRACE-INNER dispatch site (a
  ``jax.jit``/``pallas_call`` that only ever runs inside another traced
  program — fused matmul builders, flash attention, write-quantize).
  Inner programs compile as part of their enclosing entry's compile wall;
  registration makes them inventory-visible at ``/debug/compiles`` and
  satisfies lfkt-lint PERF001 (every jit/pallas entry point must be
  registered — lint/perf.py).

Recompile storms: a program whose distinct-signature set grows past
``LFKT_RECOMPILE_BUDGET`` is flagged on the spot — a counter, a
structured-log warning, and a ``recompile_storm`` event annotated onto
every in-flight trace (obs/trace.py fan-in), so the requests a storm
stalled carry the explanation in their own span trees.

Zero cost when disarmed (``LFKT_DEVTIME=0``): the wrapper's first check
is a plain attribute read and the call forwards untouched — no signature,
no lock, no allocation (pinned by the poisoned-registry test in
tests/test_devtime.py, the tracer's ``LFKT_TRACE_SAMPLE=0`` analogue).

Determinism dividend: because compile/dispatch counts are exact and
device-independent, tier-1 pins them on CPU (tests/test_perf_pins.py) —
a silent recompile or a stray extra dispatch per decode chunk fails a
CPU test long before it burns a chip session.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque

logger = logging.getLogger(__name__)

#: bounded compile-event ring: /metrics replays events it has not seen yet
#: into the xla_compile_seconds histogram via a per-consumer cursor
MAX_EVENTS = 1024
#: full signature STRINGS retained per program (newest first out) — the
#: /debug/compiles display and the per-signature compile walls.  Distinct
#: counts and storm detection stay exact past this via a per-program set
#: of signature hashes (8 bytes each): a sustained storm costs the ledger
#: ~a word per mint, not a multi-KB string — negligible next to the
#: compiled executable jax itself retains for every one of them.
MAX_SIGNATURES_SHOWN = 64

ENTRY = "entry"    # host-dispatched jit program (wrapped by timed_jit)
INNER = "inner"    # trace-inner dispatch site (compiles inside its caller)


def _describe_leaf(leaf) -> str:
    """One signature atom: ``dtype[shape]`` for arrays, ``repr`` for plain
    scalars/strings, ``TypeName#hash`` for hashable statics (ModelConfig),
    ``TypeName`` otherwise.  Metadata only — never forces a device sync."""
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return f"{leaf.dtype}[{','.join(str(d) for d in leaf.shape)}]"
    if isinstance(leaf, (bool, int, float, str)) or leaf is None:
        return repr(leaf)
    try:
        h = hash(leaf)
    except TypeError:
        return type(leaf).__name__
    return f"{type(leaf).__name__}#{h & 0xFFFFFFFF:08x}"


#: Fastest plausible jit compile wall.  The no-cache-probe fallback only
#: computes a dispatch signature when the call's wall reaches this floor:
#: trace+lower+LLVM is milliseconds even for `lambda x: x`, while a
#: steady-state cache-hit dispatch stays well under it.
_FALLBACK_COMPILE_FLOOR_S = 1e-3


def _signature(args: tuple, kwargs: dict) -> str:
    """Static-shape signature of one dispatch — the (shapes, dtypes,
    statics) key a jit cache distinguishes programs by, rendered as a
    stable string.  Computed only on compile-scale calls: with a cache
    probe that means actual compile events (rare); without one, only
    calls whose wall clears _FALLBACK_COMPILE_FLOOR_S — so the lazy jax
    import and the O(leaves) tree walk never ride a steady-state
    (sub-millisecond) dispatch."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    return ";".join(_describe_leaf(leaf) for leaf in leaves)


class _Program:
    """One registered program's ledger."""

    __slots__ = ("name", "kind", "site", "signatures", "sig_seen",
                 "compiles", "dispatches", "compile_s", "storms")

    def __init__(self, name: str, kind: str, site: str | None):
        self.name = name
        self.kind = kind
        self.site = site
        #: signature -> {"wall_s": first-compile wall, "count": compiles};
        #: bounded to MAX_SIGNATURES_SHOWN full strings (oldest evicted)
        self.signatures: OrderedDict[str, dict] = OrderedDict()
        #: hashes of every distinct signature ever seen — exact
        #: distinct/storm accounting without retaining the strings
        self.sig_seen: set[int] = set()
        self.compiles = 0
        self.dispatches = 0
        self.compile_s = 0.0
        self.storms = 0


class DevtimeRegistry:
    """The process-wide compile/dispatch ledger (module instance:
    :data:`DEVTIME`).  Producers are engine worker threads, the continuous
    scheduler thread, and load-time code; consumers are /metrics,
    /debug/compiles, /debug/slo and the tier-1 perf pins."""

    # every mutable table goes through one mutex (lfkt-lint LOCK001);
    # _armed is the single hot-path bool, read without the lock by design
    _GUARDED_BY = {"_programs": "_lock", "_events": "_lock",
                   "_seq": "_lock", "storms_total": "_lock",
                   "events_dropped": "_lock", "_floor": "_lock",
                   "_degrades": "_lock"}
    _SHARED_ATOMIC = ("_armed", "budget")

    def __init__(self, armed: bool | None = None, budget: int | None = None):
        if armed is None or budget is None:
            from ..utils.config import knob

            if armed is None:
                armed = bool(knob("LFKT_DEVTIME"))
            if budget is None:
                budget = int(knob("LFKT_RECOMPILE_BUDGET"))
        self._lock = threading.Lock()
        self._programs: dict[str, _Program] = {}
        self._events: deque[dict] = deque(maxlen=MAX_EVENTS)
        self._seq = 0                  # monotonic event id (survives reset)
        self.storms_total = 0
        #: events a consumer found already evicted from the ring (cursor
        #: gap) — nonzero means xla_compile_seconds undercounts vs the
        #: exact xla_compiles_total ledger: a storm minted >MAX_EVENTS
        #: compiles inside one scrape interval and the tail was lost
        self.events_dropped = 0
        self._floor = 0        # events at or below this were reset, not dropped
        #: degrade ledger: {(program, reason) -> count} decisions where a
        #: registered program was NOT served (probe failure, ineligible
        #: config) and a slower path took over — the /debug/compiles
        #: attribution the kernel-degrade contract (KER002) promises.
        #: Bounded: distinct (program, reason) pairs are capped; repeats
        #: only bump counts (trace-time producers, never the hot path).
        self._degrades: OrderedDict[tuple, dict] = OrderedDict()
        self.budget = max(1, int(budget))
        self._armed = bool(armed)

    # -- configuration (tests + ops) ---------------------------------------
    def configure(self, armed: bool | None = None,
                  budget: int | None = None) -> None:
        if armed is not None:
            self._armed = bool(armed)
        if budget is not None:
            self.budget = max(1, int(budget))

    @property
    def armed(self) -> bool:
        return self._armed

    def reset(self) -> None:
        """Zero every ledger (tests).  The event sequence stays monotonic
        so /metrics cursors held by live apps never replay old events."""
        with self._lock:
            for p in self._programs.values():
                p.signatures.clear()
                p.sig_seen.clear()
                p.compiles = p.dispatches = p.storms = 0
                p.compile_s = 0.0
            self._events.clear()
            self._degrades.clear()
            self.storms_total = 0
            self.events_dropped = 0
            self._floor = self._seq    # cleared events are not "dropped"

    # -- registration ------------------------------------------------------
    def _program(self, name: str, kind: str,
                 site: str | None) -> _Program:  # lfkt: holds[_lock]
        p = self._programs.get(name)
        if p is None:
            p = self._programs[name] = _Program(name, kind, site)
        elif site is not None and p.site is None:
            p.site = site
        return p

    def register_program(self, name: str, kind: str = INNER,
                         site: str | None = None) -> str:
        """Declare a program without wrapping it (trace-inner dispatch
        sites).  Idempotent; returns the name so call sites can use it as
        an expression."""
        with self._lock:
            self._program(name, kind, site)
        return name

    def timed_jit(self, name: str, fn, site: str | None = None):
        """Wrap a host jit entry point.  Re-wrapping under the same name
        (lru-cached factories minting one jit per mesh/config key) merges
        into one program ledger — exactly what storm detection wants."""
        with self._lock:
            self._program(name, ENTRY, site)
        return _TimedJit(self, name, fn)

    #: distinct (program, reason) degrade pairs retained; repeats past the
    #: bound still count into the OLDEST entry's overflow marker
    MAX_DEGRADES = 32

    def record_degrade(self, program: str, reason: str) -> None:
        """Attribute one degrade decision: ``program`` exists in the
        inventory but a slower path is serving in its place (Mosaic probe
        failure, ineligible weights/config).  Trace/probe-time producer —
        a retrace of the same decision bumps the count, it never grows
        the ledger.  Surfaced in :meth:`snapshot` (``/debug/compiles``)
        so "why is this pod not running kernel X" is answerable from the
        pod itself."""
        key = (program, str(reason)[:400])
        with self._lock:
            self._program(program, INNER, None)   # inventory-visible
            entry = self._degrades.get(key)
            if entry is not None:
                entry["count"] += 1
                return
            if len(self._degrades) >= self.MAX_DEGRADES:
                # keep the ledger bounded; fold the tail into a marker
                key = (program, "(degrade ledger full — older distinct "
                                "reasons folded)")
                entry = self._degrades.get(key)
                if entry is not None:
                    entry["count"] += 1
                    return
            self._degrades[key] = {"program": key[0], "reason": key[1],
                                   "count": 1, "at": time.time()}

    def degrades(self) -> list[dict]:
        """The degrade ledger (insertion order) — /debug/compiles and the
        decode-loop tests read it."""
        with self._lock:
            return [dict(v) for v in self._degrades.values()]

    # -- producer API ------------------------------------------------------
    def record_dispatch(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._program(name, ENTRY, None).dispatches += n

    def record_compile(self, name: str, signature: str, wall_s: float,
                       new_only: bool = False) -> None:
        """Record one compile event.  ``new_only`` is the fallback path for
        jit callables without a cache-size probe: only an unseen signature
        counts as a compile.  Storm side effects (log + trace fan-in) fire
        outside the lock."""
        storm = None
        with self._lock:
            p = self._program(name, ENTRY, None)
            sig_h = hash(signature)
            known = sig_h in p.sig_seen
            if new_only and known:
                return
            if known:
                entry = p.signatures.get(signature)
                if entry is not None:     # display entry may be evicted
                    entry["count"] += 1
            else:
                p.sig_seen.add(sig_h)
                p.signatures[signature] = {"wall_s": round(wall_s, 6),
                                           "count": 1}
                while len(p.signatures) > MAX_SIGNATURES_SHOWN:
                    p.signatures.popitem(last=False)
            p.compiles += 1
            p.compile_s += wall_s
            self._seq += 1
            self._events.append({"seq": self._seq, "program": name,
                                 "wall_s": wall_s, "signature": signature,
                                 "at": time.time()})
            if not known and len(p.sig_seen) > self.budget:
                p.storms += 1
                self.storms_total += 1
                storm = {"program": name, "signatures": len(p.sig_seen),
                         "budget": self.budget}
        if storm is not None:
            logger.warning(
                "recompile storm: program %s minted signature #%d "
                "(budget %d) — static shapes are churning "
                "(docs/RUNBOOK.md 'Diagnosing a recompile storm')",
                storm["program"], storm["signatures"], storm["budget"],
                extra=storm)
            from .trace import annotate_all_inflight

            annotate_all_inflight("recompile_storm", **storm)

    # -- consumers ---------------------------------------------------------
    def counters(self) -> dict[str, dict]:
        """{program: {"compiles", "dispatches", "signatures", "storms"}} —
        the cheap ledger for /metrics gauges and the tier-1 perf pins."""
        with self._lock:
            return {name: {"compiles": p.compiles,
                           "dispatches": p.dispatches,
                           "signatures": len(p.sig_seen),
                           "storms": p.storms}
                    for name, p in self._programs.items()}

    def events_since(self, cursor: int) -> tuple[int, list[dict]]:
        """Compile events newer than ``cursor`` (bounded ring) + the new
        cursor — /metrics replays them into the xla_compile_seconds
        histogram exactly once per consumer.  A cursor gap (the oldest
        retained event is not the consumer's next) means the ring
        overflowed between replays — a storm minting >MAX_EVENTS compiles
        inside one scrape interval — and is surfaced rather than silently
        skipped: ``events_dropped`` grows by the gap and a warning names
        the undercounting series.  A negative cursor marks a NEVER-read
        consumer (a freshly built app in a process whose ring already
        overflowed): it replays the retained events and charges no gap —
        those events were not lost between ITS scrapes."""
        fresh = cursor < 0
        lost = 0
        with self._lock:
            if cursor > self._seq:          # stale cursor across a reset
                cursor = 0
            if self._events and not fresh:
                oldest = self._events[0]["seq"]
                lost = max(0, (oldest - 1) - max(cursor, self._floor))
                if lost:
                    self.events_dropped += lost
            events = [dict(e) for e in self._events if e["seq"] > cursor]
            new_cursor = self._seq
        if lost:
            logger.warning(
                "compile-event ring overflowed: %d event(s) evicted before "
                "replay — xla_compile_seconds undercounts this interval "
                "(xla_compiles_total stays exact)", lost,
                extra={"events_dropped": lost})
        return new_cursor, events

    def storms(self) -> list[dict]:
        """Programs currently past the signature budget (the /debug/slo
        recompile verdict input)."""
        with self._lock:
            return [{"program": p.name, "signatures": len(p.sig_seen),
                     "budget": self.budget, "storms": p.storms}
                    for p in self._programs.values()
                    if len(p.sig_seen) > self.budget]

    def snapshot(self) -> dict:
        """The full /debug/compiles document: program inventory with
        per-signature compile walls (display-bounded)."""
        # copy-then-release (lfkt-lint LOCK006): O(programs) field copies
        # under the lock; the sort and document assembly run OFF it so a
        # /debug/compiles read never stalls a compile-event record
        with self._lock:
            rows = [(p.name, p.kind, p.site, p.compiles, p.dispatches,
                     p.compile_s, len(p.sig_seen), p.storms,
                     dict(p.signatures))
                    for p in self._programs.values()]
            degrades = [dict(v) for v in self._degrades.values()]
            armed = self._armed
            storms_total = self.storms_total
            dropped = self.events_dropped
        programs = []
        for name, kind, site, compiles, dispatches, compile_s, n_sigs, \
                storms, signatures in sorted(rows):
            sigs = [{"signature": s, **meta}
                    for s, meta in signatures.items()]
            programs.append({
                "name": name, "kind": kind, "site": site,
                "compiles": compiles, "dispatches": dispatches,
                "compile_seconds_total": round(compile_s, 6),
                "signatures": n_sigs,
                "storms": storms,
                "signature_list": sigs,   # ledger bounds retention
            })
        return {"armed": armed, "budget": self.budget,
                "storms_total": storms_total,
                "events_dropped": dropped,
                "degrades": degrades,
                "programs": programs}


class _TimedJit:
    """The per-entry-point wrapper ``timed_jit`` returns.  Call-compatible
    with the wrapped jit function; donation, static args and sharding all
    pass through untouched (the wrapper never copies or inspects buffers
    beyond shape/dtype metadata, and only on compile events)."""

    __slots__ = ("_reg", "_name", "_fn", "_probe", "__wrapped__")

    def __init__(self, reg: DevtimeRegistry, name: str, fn):
        self._reg = reg
        self._name = name
        self._fn = fn
        self.__wrapped__ = fn
        # jax's PjitFunction exposes its compiled-variant count; older
        # versions fall back to registry signature-set membership
        self._probe = getattr(fn, "_cache_size", None)

    def __call__(self, *args, **kwargs):
        reg = self._reg
        if not reg._armed:          # disarmed: forward untouched, allocate
            return self._fn(*args, **kwargs)   # nothing (poisoned-reg test)
        probe = self._probe
        before = probe() if probe is not None else -1
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if probe is not None:
            if probe() > before:
                reg.record_compile(self._name, _signature(args, kwargs), dt)
        elif dt >= _FALLBACK_COMPILE_FLOOR_S:
            # No cache probe (old jax): signature-set membership detects
            # compiles, but walking a ~300-leaf params tree per decode
            # chunk is exactly the overhead this tool attributes.  A jit
            # compile is never sub-millisecond, so a call that returns
            # under the floor cannot have compiled and skips the walk;
            # the first dispatch of any new signature pays compile wall
            # and always clears it.  Membership lives in the REGISTRY
            # ledger (new_only), not wrapper-private state, so reset()
            # zeroes it with everything else; the lock it costs is one
            # record_dispatch already pays on every call.
            reg.record_compile(self._name, _signature(args, kwargs), dt,
                               new_only=True)
        reg.record_dispatch(self._name)
        return out


#: THE process-wide registry: entry points wrap themselves through it at
#: import, /metrics + /debug/compiles read it, tier-1 pins its counters.
DEVTIME = DevtimeRegistry()


def timed_jit(name: str, fn, site: str | None = None):
    """Module-level convenience: wrap ``fn`` as program ``name`` on the
    process registry (the form every entry-point module uses)."""
    return DEVTIME.timed_jit(name, fn, site=site)


def register_program(name: str, kind: str = INNER,
                     site: str | None = None) -> str:
    """Module-level convenience: declare a trace-inner dispatch site on
    the process registry (lfkt-lint PERF001's registration form)."""
    return DEVTIME.register_program(name, kind=kind, site=site)

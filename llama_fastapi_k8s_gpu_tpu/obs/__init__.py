"""lfkt-obs — zero-dependency tracing + metrics for the serving stack.

The observability layer the ROADMAP's production-scale north star needs
on top of PR 2's watchdog/deadline machinery: per-request span trees
(:mod:`.trace` → ``/debug/traces``, ``/debug/requests``), the declarative
metric catalog behind the labeled/histogram ``/metrics`` registry
(:mod:`.catalog` + utils/metrics.py), and request-id-stamped structured
logging (:mod:`.logctx`).  Stdlib only; nothing here is importable from a
jit trace, and everything is strictly zero-cost for sampled-out requests
(LFKT_TRACE_SAMPLE=0 → ``Tracer.start`` returns None before any lock).

Span taxonomy, metric catalog, sampling and the debug endpoints:
docs/OBSERVABILITY.md.  Slow-request triage flow (tools/trace_report.py
waterfalls): docs/RUNBOOK.md "Triaging a slow request".
"""

from .catalog import METRICS, Metric, lookup, markdown_table  # noqa: F401
from .logctx import (  # noqa: F401
    JsonFormatter,
    RequestIdFilter,
    access_logger,
    bind_request_id,
    current_request_id,
    setup_json_logging,
)
from .trace import TRACER, Span, Trace, Tracer, parse_traceparent  # noqa: F401

__all__ = [
    "METRICS", "Metric", "lookup", "markdown_table",
    "JsonFormatter", "RequestIdFilter", "access_logger", "bind_request_id",
    "current_request_id", "setup_json_logging",
    "TRACER", "Span", "Trace", "Tracer", "parse_traceparent",
]

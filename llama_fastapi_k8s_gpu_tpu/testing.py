"""Test/bench fixtures: tiny synthetic models and GGUF files.

No network egress exists in any deployment of this framework's CI or bench
(BASELINE.md), so every test artifact is synthesized: byte-level vocabularies
and random weights written through the real GGUF writer, then loaded through
the real reader/dequant/tokenizer/model path.
"""

from __future__ import annotations

import numpy as np

from .gguf import GGMLType, GGUFWriter
from .models.config import ModelConfig
from .tokenizer.base import TokenType
from .tokenizer.bpe import bytes_to_unicode

TINY_CFG = ModelConfig(
    vocab_size=256 + 7, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, n_ctx=128, rope_theta=10000.0,
)

LLAMA3_SPECIALS = [
    "<|begin_of_text|>", "<|end_of_text|>", "<|start_header_id|>",
    "<|end_header_id|>", "<|eot_id|>", "<|python_tag|>", "<|eom_id|>",
]


def byte_vocab_with_specials() -> tuple[list[str], list[int]]:
    """256 byte tokens + llama-3 control tokens; ids stable and dense."""
    tokens = [bytes_to_unicode()[b] for b in range(256)] + list(LLAMA3_SPECIALS)
    types = [int(TokenType.NORMAL)] * 256 + [int(TokenType.CONTROL)] * len(LLAMA3_SPECIALS)
    return tokens, types


LLAMA3_CHAT_TEMPLATE = (
    "{{bos_token}}{% for m in messages %}<|start_header_id|>{{m['role']}}"
    "<|end_header_id|>\n\n{{m['content']}}<|eot_id|>{% endfor %}"
)


def write_llama_gguf_meta(
    w: GGUFWriter,
    cfg: ModelConfig,
    tokens: list[str],
    types: list[int],
    merges: list[str] | None = None,
    name: str = "tiny-llama-test",
    n_ctx: int | None = None,
    chat_template: str | None = LLAMA3_CHAT_TEMPLATE,
) -> None:
    """The llama-architecture GGUF metadata block (hparams + BPE tokenizer)
    shared by the tiny test fixture and the full-size cold-start bench."""
    w.add_metadata("general.architecture", "llama")
    w.add_metadata("general.name", name)
    w.add_metadata("llama.block_count", cfg.n_layers)
    w.add_metadata("llama.context_length", n_ctx or cfg.n_ctx)
    w.add_metadata("llama.embedding_length", cfg.dim)
    w.add_metadata("llama.feed_forward_length", cfg.ffn_dim)
    w.add_metadata("llama.attention.head_count", cfg.n_heads)
    w.add_metadata("llama.attention.head_count_kv", cfg.n_kv_heads)
    w.add_metadata("llama.attention.layer_norm_rms_epsilon", cfg.rms_eps)
    w.add_metadata("llama.rope.freq_base", cfg.rope_theta)
    w.add_metadata("llama.vocab_size", cfg.vocab_size)
    if cfg.sliding_window:
        w.add_metadata("llama.attention.sliding_window", cfg.sliding_window)
    w.add_metadata("tokenizer.ggml.model", "gpt2")
    w.add_metadata("tokenizer.ggml.pre", "llama-bpe")
    w.add_metadata("tokenizer.ggml.tokens", tokens)
    w.add_metadata("tokenizer.ggml.token_type", types)
    w.add_metadata("tokenizer.ggml.merges", list(merges or []))
    w.add_metadata("tokenizer.ggml.bos_token_id",
                   tokens.index("<|begin_of_text|>"))
    w.add_metadata("tokenizer.ggml.eos_token_id", tokens.index("<|eot_id|>"))
    if chat_template:
        w.add_metadata("tokenizer.chat_template", chat_template)


def write_tiny_llama_gguf(
    path: str,
    cfg: ModelConfig = TINY_CFG,
    seed: int = 0,
    quant: GGMLType = GGMLType.Q8_0,
    ffn_quant: GGMLType | None = None,
) -> ModelConfig:
    """Write a random-weight llama GGUF with a byte-level BPE tokenizer.

    vocab_size is forced to 256+len(specials) so every byte is encodable.
    """
    tokens, types = byte_vocab_with_specials()
    cfg = ModelConfig(**{**cfg.__dict__, "vocab_size": len(tokens)})
    rng = np.random.default_rng(seed)
    scale = cfg.dim ** -0.5

    w = GGUFWriter(path)
    write_llama_gguf_meta(w, cfg, tokens, types)

    if ffn_quant is None:
        ffn_quant = quant
    kv_dim = cfg.n_kv_heads * cfg.head_dim

    def t(name, shape, gtype):
        w.add_tensor(name, rng.standard_normal(shape).astype(np.float32) * scale, gtype)

    t("token_embd.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    for i in range(cfg.n_layers):
        p = f"blk.{i}."
        t(p + "attn_norm.weight", (cfg.dim,), GGMLType.F32)
        t(p + "attn_q.weight", (cfg.dim, cfg.dim), quant)
        t(p + "attn_k.weight", (kv_dim, cfg.dim), quant)
        t(p + "attn_v.weight", (kv_dim, cfg.dim), quant)
        t(p + "attn_output.weight", (cfg.dim, cfg.dim), quant)
        t(p + "ffn_norm.weight", (cfg.dim,), GGMLType.F32)
        t(p + "ffn_gate.weight", (cfg.ffn_dim, cfg.dim), ffn_quant)
        t(p + "ffn_up.weight", (cfg.ffn_dim, cfg.dim), ffn_quant)
        t(p + "ffn_down.weight", (cfg.dim, cfg.ffn_dim), ffn_quant)
    t("output_norm.weight", (cfg.dim,), GGMLType.F32)
    t("output.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    w.write()
    return cfg


def synth_bpe_vocab(n_merges: int = 280_000, seed: int = 0,
                    ) -> tuple[list[str], list[str], list[int]]:
    """Deterministic Llama-3-*scale* BPE vocab: 256 byte tokens + specials +
    ``n_merges`` merge rules (~the real 128k-token / 280k-merge table's order
    of magnitude, which the reference's tokenizer runs through llama.cpp —
    reference api.py:56-57).  Returns (tokens, merges, token_types).

    Construction (all seeded, no I/O):
    - a *doubling chain* over "ab" (ab, abab, ...·2) so a long unbroken
      letter run exercises ~log-depth cascading merges — the shape that made
      the round-2 O(n²)-per-merge loop a latency cliff;
    - all 26² lowercase pairs, then seeded random concatenations of existing
      tokens (capped length) until ``n_merges`` rules exist.
    """
    rng = np.random.default_rng(seed)
    b2u = bytes_to_unicode()
    base = [b2u[b] for b in range(256)]
    tokens: list[str] = list(base)
    token_set = set(tokens)
    pair_set: set[tuple[str, str]] = set()
    merges: list[str] = []

    def add_merge(left: str, right: str) -> None:
        if (left, right) in pair_set:
            return
        pair_set.add((left, right))
        merges.append(f"{left} {right}")
        merged = left + right
        if merged not in token_set:
            token_set.add(merged)
            tokens.append(merged)

    cur = "ab"
    add_merge("a", "b")
    while len(cur) < 8192:
        add_merge(cur, cur)
        cur += cur
    for a in "abcdefghijklmnopqrstuvwxyz":
        for b in "abcdefghijklmnopqrstuvwxyz":
            add_merge(a, b)
    # bulk: seeded random concatenations of existing tokens (drawn from the
    # earlier/shorter end so chains stay plausible), capped length
    while len(merges) < n_merges:
        n_tok = len(tokens)
        li = rng.integers(0, min(n_tok, 60_000), size=4096)
        ri = rng.integers(0, min(n_tok, 60_000), size=4096)
        for i, j in zip(li, ri):
            left, right = tokens[int(i)], tokens[int(j)]
            if len(left) + len(right) > 24:
                continue
            add_merge(left, right)
            if len(merges) >= n_merges:
                break
    tokens.extend(LLAMA3_SPECIALS)
    types = [int(TokenType.NORMAL)] * (len(tokens) - len(LLAMA3_SPECIALS)) \
        + [int(TokenType.CONTROL)] * len(LLAMA3_SPECIALS)
    return tokens, merges, types


def spm_byte_vocab() -> tuple[list[str], list[int], list[float]]:
    """Minimal SentencePiece-style vocab: specials + full byte fallback."""
    tokens = ["<unk>", "<s>", "</s>", "▁"]
    types = [int(TokenType.UNKNOWN)] + [int(TokenType.CONTROL)] * 2 + [
        int(TokenType.NORMAL)]
    scores = [0.0, 0.0, 0.0, -1.0]
    for b in range(256):
        tokens.append(f"<0x{b:02X}>")
        types.append(int(TokenType.BYTE))
        scores.append(0.0)
    return tokens, types, scores


def write_tiny_mistral_gguf(
    path: str,
    cfg: ModelConfig | None = None,
    seed: int = 0,
    quant: GGMLType = GGMLType.Q8_0,
) -> ModelConfig:
    """Random-weight **mistral**-architecture GGUF: SPM tokenizer with byte
    fallback, sliding-window attention, [INST] chat template — the
    reference-baseline "Mistral-7B sliding-window" config (BASELINE.json)
    at test scale."""
    tokens, types, scores = spm_byte_vocab()
    base = cfg or TINY_CFG
    cfg = ModelConfig(**{**base.__dict__, "vocab_size": len(tokens),
                         "sliding_window": base.sliding_window or 16})
    rng = np.random.default_rng(seed)
    scale = cfg.dim ** -0.5

    w = GGUFWriter(path)
    w.add_metadata("general.architecture", "mistral")
    w.add_metadata("general.name", "tiny-mistral-test")
    w.add_metadata("mistral.block_count", cfg.n_layers)
    w.add_metadata("mistral.context_length", cfg.n_ctx)
    w.add_metadata("mistral.embedding_length", cfg.dim)
    w.add_metadata("mistral.feed_forward_length", cfg.ffn_dim)
    w.add_metadata("mistral.attention.head_count", cfg.n_heads)
    w.add_metadata("mistral.attention.head_count_kv", cfg.n_kv_heads)
    w.add_metadata("mistral.attention.layer_norm_rms_epsilon", cfg.rms_eps)
    w.add_metadata("mistral.attention.sliding_window", cfg.sliding_window)
    w.add_metadata("mistral.rope.freq_base", cfg.rope_theta)
    w.add_metadata("mistral.vocab_size", cfg.vocab_size)
    w.add_metadata("tokenizer.ggml.model", "llama")
    w.add_metadata("tokenizer.ggml.tokens", tokens)
    w.add_metadata("tokenizer.ggml.token_type", types)
    w.add_metadata("tokenizer.ggml.scores", scores)
    w.add_metadata("tokenizer.ggml.bos_token_id", 1)
    w.add_metadata("tokenizer.ggml.eos_token_id", 2)
    w.add_metadata(
        "tokenizer.chat_template",
        "{{bos_token}}{% for m in messages %}{% if m['role'] == 'user' %}"
        "[INST] {{m['content']}} [/INST]{% else %}{{m['content']}}</s>"
        "{% endif %}{% endfor %}",
    )

    kv_dim = cfg.n_kv_heads * cfg.head_dim

    def t(name, shape, gtype):
        w.add_tensor(name, rng.standard_normal(shape).astype(np.float32) * scale, gtype)

    t("token_embd.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    for i in range(cfg.n_layers):
        p = f"blk.{i}."
        t(p + "attn_norm.weight", (cfg.dim,), GGMLType.F32)
        t(p + "attn_q.weight", (cfg.dim, cfg.dim), quant)
        t(p + "attn_k.weight", (kv_dim, cfg.dim), quant)
        t(p + "attn_v.weight", (kv_dim, cfg.dim), quant)
        t(p + "attn_output.weight", (cfg.dim, cfg.dim), quant)
        t(p + "ffn_norm.weight", (cfg.dim,), GGMLType.F32)
        t(p + "ffn_gate.weight", (cfg.ffn_dim, cfg.dim), quant)
        t(p + "ffn_up.weight", (cfg.ffn_dim, cfg.dim), quant)
        t(p + "ffn_down.weight", (cfg.dim, cfg.ffn_dim), quant)
    t("output_norm.weight", (cfg.dim,), GGMLType.F32)
    t("output.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    w.write()
    return cfg

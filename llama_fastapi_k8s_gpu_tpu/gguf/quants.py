"""Numpy reference codecs for GGML quantization formats.

These are the load-time dequantization reference (and the bit-exactness oracle
for the Pallas kernels in ``ops/pallas``).  The reference repo gets this
behavior from llama.cpp's C kernels inside ``llama-cpp-python==0.2.77``
(reference docker/Dockerfile.base:30-32); here the block layouts are
re-implemented from the public GGML format definitions, vectorized over numpy.

Dequant functions take a flat ``uint8`` buffer and the element count and
return ``float32``.  Quantizers exist so tests and model synthesis can build
valid GGUF files; they use straightforward affine fits per sub-block (not
llama.cpp's iterative search), which is irrelevant for decode-side parity —
only the *decode* layout is contractual.

Layout notes (all little-endian):

- ``Q8_0``  block=32:   f16 d | 32×i8 q;            y = d*q
- ``Q4_0``  block=32:   f16 d | 16B nibbles;        y = d*(q-8)
- ``Q4_1``  block=32:   f16 d | f16 m | 16B nibbles; y = d*q + m
- ``Q5_0``  block=32:   f16 d | u32 qh | 16B nibbles; y = d*(q-16),
                        q = nibble | (qh-bit << 4); element j gets qh bit j,
                        element j+16 gets qh bit j+16
- ``Q5_1``  block=32:   f16 d | f16 m | u32 qh | 16B nibbles; y = d*q + m
- ``Q2_K``  block=256:  16B 4-bit scale/min pairs | 64B 2-bit qs | f16 d | f16 dmin
                        y = d*sc[j]*q - dmin*m[j], 16 sub-blocks of 16
- ``Q3_K``  block=256:  32B hmask | 64B 2-bit qs | 12B 6-bit signed scales | f16 d
                        q = 2 low bits + hmask high bit (clear ⇒ −4)
- ``Q4_K``  block=256:  f16 d | f16 dmin | 12B 6-bit scales/mins | 128B nibbles
                        y = d*sc[j]*q - dmin*m[j], 8 sub-blocks of 32
- ``Q5_K``  block=256:  f16 d | f16 dmin | 12B scales | 32B qh | 128B qs
                        q = low-nibble + 16*high-bit
- ``Q6_K``  block=256:  128B ql | 64B qh | 16×i8 scales | f16 d
                        y = d*sc[j]*(q-32), 16 sub-blocks of 16, q 6-bit
"""

from __future__ import annotations

import functools

import numpy as np

from .constants import GGML_BLOCK_SIZES, GGMLType, QK_K


def _garbage_tolerant(fn):
    """Silence numpy's invalid/overflow RuntimeWarnings inside a dequant
    codec or kernel-prep function: random-byte (fuzz) inputs decode f16
    scale fields to inf/NaN, and the resulting 0·inf → NaN arithmetic is
    the *correct* value for garbage — warning about it only spams every
    fuzz test.  Numeric correctness of the decorated bodies is NOT
    guarded by warnings (they are suppressed wholesale here) but by the
    bit-exact oracles: dequant round-trips in tests/test_gguf_quants.py
    and the native-packer parity suite in tests/test_native.py fail on
    any real value change.  pytest.ini's error::RuntimeWarning filter
    covers the rest of the package, where a new warning means a real
    regression."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with np.errstate(invalid="ignore", over="ignore"):
            return fn(*args, **kwargs)
    return wrapped


def _f16(buf: np.ndarray) -> np.ndarray:
    return buf.view(np.float16).astype(np.float32)


# ---------------------------------------------------------------------------
# simple / float formats
# ---------------------------------------------------------------------------

def dequant_f32(buf: np.ndarray, n: int) -> np.ndarray:
    return buf[: n * 4].view(np.float32).copy()


def dequant_f16(buf: np.ndarray, n: int) -> np.ndarray:
    return buf[: n * 2].view(np.float16).astype(np.float32)


def dequant_bf16(buf: np.ndarray, n: int) -> np.ndarray:
    u16 = buf[: n * 2].view(np.uint16).astype(np.uint32)
    return (u16 << 16).view(np.float32).copy()


def quant_bf16(x: np.ndarray) -> np.ndarray:
    # round-to-nearest-even on the mantissa boundary
    u32 = np.ascontiguousarray(x, dtype=np.float32).view(np.uint32)
    rounded = (u32 + 0x7FFF + ((u32 >> 16) & 1)) >> 16
    return rounded.astype(np.uint16).view(np.uint8)


# ---------------------------------------------------------------------------
# Q8_0
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q8_0(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // 32
    blocks = buf[: nb * 34].reshape(nb, 34)
    d = _f16(blocks[:, :2].reshape(-1))  # (nb,)
    q = blocks[:, 2:].view(np.int8).astype(np.float32)  # (nb, 32)
    return (d[:, None] * q).reshape(-1)


def quant_q8_0(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 32)
    amax = np.abs(x).max(axis=1)
    d = (amax / 127.0).astype(np.float16)
    inv = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    q = np.clip(np.round(x * inv[:, None]), -128, 127).astype(np.int8)
    out = np.empty((x.shape[0], 34), dtype=np.uint8)
    out[:, :2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q.view(np.uint8)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q4_0
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q4_0(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // 32
    blocks = buf[: nb * 18].reshape(nb, 18)
    d = _f16(blocks[:, :2].reshape(-1))
    qs = blocks[:, 2:]
    lo = (qs & 0x0F).astype(np.float32) - 8.0  # elements 0..15
    hi = (qs >> 4).astype(np.float32) - 8.0    # elements 16..31
    q = np.concatenate([lo, hi], axis=1)       # (nb, 32)
    return (d[:, None] * q).reshape(-1)


def quant_q4_0(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 32)
    # llama.cpp picks d from the max-|x| element so that it maps to -8
    idx = np.abs(x).argmax(axis=1)
    maxv = x[np.arange(x.shape[0]), idx]
    d = (maxv / -8.0).astype(np.float16)
    inv = np.where(d != 0, 1.0 / d.astype(np.float32), 0.0)
    q = np.clip(np.round(x * inv[:, None]) + 8, 0, 15).astype(np.uint8)
    out = np.empty((x.shape[0], 18), dtype=np.uint8)
    out[:, :2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q[:, :16] | (q[:, 16:] << 4)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q4_1 / Q5_0 / Q5_1 (legacy affine/5-bit formats, still common in the wild)
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q4_1(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // 32
    blocks = buf[: nb * 20].reshape(nb, 20)
    d = _f16(blocks[:, 0:2].reshape(-1))
    m = _f16(blocks[:, 2:4].reshape(-1))
    qs = blocks[:, 4:]
    lo = (qs & 0x0F).astype(np.float32)   # elements 0..15
    hi = (qs >> 4).astype(np.float32)     # elements 16..31
    q = np.concatenate([lo, hi], axis=1)
    return (d[:, None] * q + m[:, None]).reshape(-1)


def quant_q4_1(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 32)
    mn = x.min(axis=1)
    mx = x.max(axis=1)
    d = ((mx - mn) / 15.0).astype(np.float16)
    m = mn.astype(np.float16)
    inv = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    q = np.clip(np.round((x - mn[:, None]) * inv[:, None]), 0, 15).astype(np.uint8)
    out = np.empty((x.shape[0], 20), dtype=np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:4] = m.view(np.uint8).reshape(-1, 2)
    out[:, 4:] = q[:, :16] | (q[:, 16:] << 4)
    return out.reshape(-1)


def _q5_high_bits(qh_bytes: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(nb, 4) uint8 → ((nb, 16), (nb, 16)) fifth-bit planes already shifted
    to bit 4: element j takes qh bit j, element j+16 takes qh bit j+16."""
    qh = qh_bytes.copy().view(np.uint32).reshape(-1)  # (nb,)
    j = np.arange(16, dtype=np.uint32)
    lo = (((qh[:, None] >> j) & 1) << 4).astype(np.uint8)
    hi = (((qh[:, None] >> (j + 16)) & 1) << 4).astype(np.uint8)
    return lo, hi


@_garbage_tolerant
def dequant_q5_0(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // 32
    blocks = buf[: nb * 22].reshape(nb, 22)
    d = _f16(blocks[:, 0:2].reshape(-1))
    xh0, xh1 = _q5_high_bits(blocks[:, 2:6])
    qs = blocks[:, 6:]
    lo = ((qs & 0x0F) | xh0).astype(np.float32) - 16.0
    hi = ((qs >> 4) | xh1).astype(np.float32) - 16.0
    return (d[:, None] * np.concatenate([lo, hi], axis=1)).reshape(-1)


def quant_q5_0(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 32)
    # like Q4_0: d from the max-|x| element so it maps to -16
    idx = np.abs(x).argmax(axis=1)
    maxv = x[np.arange(x.shape[0]), idx]
    d = (maxv / -16.0).astype(np.float16)
    inv = np.where(d != 0, 1.0 / d.astype(np.float32), 0.0)
    q = np.clip(np.round(x * inv[:, None]) + 16, 0, 31).astype(np.uint8)
    return _pack_q5(q, d.view(np.uint8).reshape(-1, 2), None)


@_garbage_tolerant
def dequant_q5_1(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // 32
    blocks = buf[: nb * 24].reshape(nb, 24)
    d = _f16(blocks[:, 0:2].reshape(-1))
    m = _f16(blocks[:, 2:4].reshape(-1))
    xh0, xh1 = _q5_high_bits(blocks[:, 4:8])
    qs = blocks[:, 8:]
    lo = ((qs & 0x0F) | xh0).astype(np.float32)
    hi = ((qs >> 4) | xh1).astype(np.float32)
    q = np.concatenate([lo, hi], axis=1)
    return (d[:, None] * q + m[:, None]).reshape(-1)


def quant_q5_1(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 32)
    mn = x.min(axis=1)
    mx = x.max(axis=1)
    d = ((mx - mn) / 31.0).astype(np.float16)
    m = mn.astype(np.float16)
    inv = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    q = np.clip(np.round((x - mn[:, None]) * inv[:, None]), 0, 31).astype(np.uint8)
    return _pack_q5(q, d.view(np.uint8).reshape(-1, 2),
                    m.view(np.uint8).reshape(-1, 2))


def _pack_q5(q: np.ndarray, d_bytes: np.ndarray,
             m_bytes: np.ndarray | None) -> np.ndarray:
    """(nb, 32) 5-bit values + scale (+min) bytes → Q5_0/Q5_1 raw blocks."""
    nb = q.shape[0]
    j = np.arange(16, dtype=np.uint32)
    qh = (((q[:, :16] >> 4).astype(np.uint32) << j).sum(axis=1)
          | ((q[:, 16:] >> 4).astype(np.uint32) << (j + 16)).sum(axis=1))
    qs = (q[:, :16] & 0x0F) | ((q[:, 16:] & 0x0F) << 4)
    head = 2 if m_bytes is None else 4
    out = np.empty((nb, head + 4 + 16), dtype=np.uint8)
    out[:, 0:2] = d_bytes
    if m_bytes is not None:
        out[:, 2:4] = m_bytes
    out[:, head:head + 4] = qh.astype(np.uint32).view(np.uint8).reshape(nb, 4)
    out[:, head + 4:] = qs
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# K-quants: shared 6-bit scale/min unpacking (get_scale_min_k4)
# ---------------------------------------------------------------------------

def unpack_scale_min_k4(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(nb, 12) uint8 → ((nb, 8) scales, (nb, 8) mins), both uint8 6-bit."""
    s = scales.astype(np.uint8)
    sc = np.empty(s.shape[:-1] + (8,), dtype=np.uint8)
    mn = np.empty_like(sc)
    for j in range(4):
        sc[..., j] = s[..., j] & 63
        mn[..., j] = s[..., j + 4] & 63
    for j in range(4, 8):
        sc[..., j] = (s[..., j + 4] & 0x0F) | ((s[..., j - 4] >> 6) << 4)
        mn[..., j] = (s[..., j + 4] >> 4) | ((s[..., j] >> 6) << 4)
    return sc, mn


def pack_scale_min_k4(sc: np.ndarray, mn: np.ndarray) -> np.ndarray:
    """Inverse of :func:`unpack_scale_min_k4`; inputs 6-bit (nb, 8)."""
    sc = sc.astype(np.uint8)
    mn = mn.astype(np.uint8)
    out = np.zeros(sc.shape[:-1] + (12,), dtype=np.uint8)
    for j in range(4):
        out[..., j] = (sc[..., j] & 63) | ((sc[..., j + 4] >> 4) << 6)
        out[..., j + 4] = (mn[..., j] & 63) | ((mn[..., j + 4] >> 4) << 6)
        out[..., j + 8] = (sc[..., j + 4] & 0x0F) | ((mn[..., j + 4] & 0x0F) << 4)
    return out


# ---------------------------------------------------------------------------
# IQ4_NL / IQ4_XS — non-linear 4-bit: indices into a fixed 16-value LUT
# (llama.cpp kvalues_iq4nl).  IQ4_NL: block 32 = f16 d | 16B nibble
# indices.  IQ4_XS: super-block 256 = f16 d | u16 scales_h | 4B scales_l |
# 128B qs; 8 sub-blocks of 32 with 6-bit scales (ls − 32), low nibbles →
# elements 0..15 of the sub-block, high → 16..31.
# ---------------------------------------------------------------------------

KVALUES_IQ4NL = np.array(
    [-127, -104, -83, -65, -49, -35, -22, -10,
     1, 13, 25, 38, 53, 69, 89, 113], dtype=np.float32)


@_garbage_tolerant
def dequant_iq4_nl(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // 32
    blocks = buf[: nb * 18].reshape(nb, 18)
    d = _f16(blocks[:, 0:2].reshape(-1))
    qs = blocks[:, 2:]
    lo = KVALUES_IQ4NL[qs & 0x0F]
    hi = KVALUES_IQ4NL[qs >> 4]
    return (d[:, None] * np.concatenate([lo, hi], axis=1)).reshape(-1)


def _nearest_iq4nl(x: np.ndarray) -> np.ndarray:
    """Values → nearest-LUT 4-bit indices (any shape)."""
    return np.abs(x[..., None] - KVALUES_IQ4NL).argmin(axis=-1).astype(np.uint8)


def quant_iq4_nl(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 32)
    amax = np.abs(x).max(axis=1)
    d = (amax / 113.0).astype(np.float16)    # map the peak onto ±113
    inv = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    q = _nearest_iq4nl(x * inv[:, None])
    out = np.empty((x.shape[0], 18), dtype=np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q[:, :16] | (q[:, 16:] << 4)
    return out.reshape(-1)


@_garbage_tolerant
def dequant_iq4_xs(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.IQ4_XS][1]  # 136
    blocks = buf[: nb * bs].reshape(nb, bs)
    d = _f16(blocks[:, 0:2].reshape(-1))
    scales_h = blocks[:, 2:4].copy().view(np.uint16).reshape(-1)  # (nb,)
    scales_l = blocks[:, 4:8]
    qs = blocks[:, 8:].reshape(nb, 8, 16)
    ib = np.arange(8)
    ls = (((scales_l[:, ib // 2] >> (4 * (ib % 2))) & 0x0F)
          | (((scales_h[:, None] >> (2 * ib)) & 3) << 4)).astype(np.float32)
    dl = d[:, None] * (ls - 32.0)                               # (nb, 8)
    lo = KVALUES_IQ4NL[qs & 0x0F]                               # (nb, 8, 16)
    hi = KVALUES_IQ4NL[qs >> 4]
    y = dl[:, :, None] * np.concatenate([lo, hi], axis=2)       # (nb, 8, 32)
    return y.reshape(-1)


def quant_iq4_xs(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, QK_K)
    nb = x.shape[0]
    sub = x.reshape(nb, 8, 32)
    # signed fit against the max-magnitude element (as quant_q3_k does):
    # map it onto the kvalue table's wider −127 end, so sub-block scales
    # carry its sign and use the full −32..31 range instead of only 32..63
    idx = np.abs(sub).argmax(axis=2)
    maxv = np.take_along_axis(sub, idx[:, :, None], axis=2)[:, :, 0]
    dl_sub = maxv / -127.0                                      # signed
    amax = np.abs(dl_sub).max(axis=1)
    d = np.where(amax > 0, amax / 31.0, 0.0).astype(np.float16)  # |ls−32| ≤ 31
    invd = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    ls = np.clip(np.round(dl_sub * invd[:, None]) + 32, 0, 63).astype(np.uint8)
    dl_q = d.astype(np.float32)[:, None] * (ls.astype(np.float32) - 32.0)
    inv_dl = np.where(dl_q != 0, 1.0 / dl_q, 0.0)
    q = _nearest_iq4nl(sub * inv_dl[:, :, None])                # (nb, 8, 32)
    out = np.empty((nb, 136), dtype=np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    ib = np.arange(8)
    sh = ((ls >> 4).astype(np.uint32) << (2 * ib)).sum(axis=1).astype(np.uint16)
    out[:, 2:4] = sh.view(np.uint8).reshape(nb, 2)
    low = ls & 0x0F
    out[:, 4:8] = low[:, 0::2] | (low[:, 1::2] << 4)
    out[:, 8:] = (q[:, :, :16] | (q[:, :, 16:] << 4)).reshape(nb, 128)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q2_K — 16 sub-blocks of 16; 4-bit scale + 4-bit min per sub-block,
# superblock f16 d/dmin; 2-bit quants.  Layout per llama.cpp block_q2_K:
# scales[16] | qs[64] | d | dmin (84 B).  Element order: two 128-halves;
# within a half, shift ∈ {0,2,4,6} over qs bytes [0:16] then [16:32].
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q2_k(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q2_K][1]  # 84
    blocks = buf[: nb * bs].reshape(nb, bs)
    scales = blocks[:, :16]
    qs = blocks[:, 16:80].reshape(nb, 2, 32)
    d = _f16(blocks[:, 80:82].reshape(-1))
    dmin = _f16(blocks[:, 82:84].reshape(-1))
    dl = d[:, None] * (scales & 0x0F).astype(np.float32)    # (nb, 16)
    ml = dmin[:, None] * (scales >> 4).astype(np.float32)   # (nb, 16)
    parts = []
    for h in range(2):
        for s in range(0, 8, 2):
            parts.append((qs[:, h, :16] >> s) & 3)
            parts.append((qs[:, h, 16:] >> s) & 3)
    qv = np.stack(parts, axis=1).astype(np.float32)          # (nb, 16, 16)
    return (dl[:, :, None] * qv - ml[:, :, None]).reshape(-1)


def quant_q2_k(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, QK_K)
    nb = x.shape[0]
    sub = x.reshape(nb, 16, 16)
    mn = sub.min(axis=2)
    mx = sub.max(axis=2)
    ml_sub = np.maximum(-mn, 0.0)                 # y = dl*q - ml, q ∈ 0..3
    dl_sub = np.maximum(mx + ml_sub, 0.0) / 3.0
    d = (dl_sub.max(axis=1) / 15.0).astype(np.float16)
    dmin = (ml_sub.max(axis=1) / 15.0).astype(np.float16)
    invd = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    invm = np.where(dmin > 0, 1.0 / dmin.astype(np.float32), 0.0)
    sc4 = np.clip(np.round(dl_sub * invd[:, None]), 0, 15).astype(np.uint8)
    mn4 = np.clip(np.round(ml_sub * invm[:, None]), 0, 15).astype(np.uint8)
    dl_q = d.astype(np.float32)[:, None] * sc4
    ml_q = dmin.astype(np.float32)[:, None] * mn4
    inv_dl = np.where(dl_q > 0, 1.0 / dl_q, 0.0)
    q = np.clip(np.round((sub + ml_q[:, :, None]) * inv_dl[:, :, None]),
                0, 3).astype(np.uint8)            # (nb, 16, 16)
    out = np.empty((nb, 84), dtype=np.uint8)
    out[:, :16] = sc4 | (mn4 << 4)
    # invert the element order: sub-block k = (half, shift, lo/hi 16)
    qs = np.zeros((nb, 2, 32), dtype=np.uint8)
    k = 0
    for h in range(2):
        for s in range(0, 8, 2):
            qs[:, h, :16] |= q[:, k] << s
            qs[:, h, 16:] |= q[:, k + 1] << s
            k += 2
    out[:, 16:80] = qs.reshape(nb, 64)
    out[:, 80:82] = d.view(np.uint8).reshape(-1, 2)
    out[:, 82:84] = dmin.view(np.uint8).reshape(-1, 2)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q3_K — 16 sub-blocks of 16; 6-bit signed scales (−32..31) packed in 12 B,
# superblock f16 d; 3-bit quants = 2 low bits in qs + 1 high bit in hmask
# (bit clear ⇒ −4 offset).  Layout per llama.cpp block_q3_K:
# hmask[32] | qs[64] | scales[12] | d (110 B).  Same two-half/shift element
# order as Q2_K; the hmask bit index runs 0..7 ACROSS both halves.
# ---------------------------------------------------------------------------

def _q3k_unpack_scales(sb: np.ndarray) -> np.ndarray:
    """(nb, 12) uint8 → (nb, 16) float32 scales in −32..31 (bias removed),
    mirroring llama.cpp's kmask aux munging bytewise."""
    k = np.arange(4)
    a0 = (sb[:, k] & 0x0F) | ((sb[:, 8 + k] & 3) << 4)
    a1 = (sb[:, 4 + k] & 0x0F) | (((sb[:, 8 + k] >> 2) & 3) << 4)
    a2 = (sb[:, k] >> 4) | (((sb[:, 8 + k] >> 4) & 3) << 4)
    a3 = (sb[:, 4 + k] >> 4) | (((sb[:, 8 + k] >> 6) & 3) << 4)
    return np.concatenate([a0, a1, a2, a3], axis=1).astype(np.float32) - 32.0


def _q3k_pack_scales(sc6: np.ndarray) -> np.ndarray:
    """(nb, 16) uint8 6-bit (bias-32 applied by caller) → (nb, 12) bytes."""
    sc6 = sc6.astype(np.uint8)
    nb = sc6.shape[0]
    out = np.zeros((nb, 12), dtype=np.uint8)
    k = np.arange(4)
    out[:, k] = (sc6[:, k] & 0x0F) | ((sc6[:, 8 + k] & 0x0F) << 4)
    out[:, 4 + k] = (sc6[:, 4 + k] & 0x0F) | ((sc6[:, 12 + k] & 0x0F) << 4)
    out[:, 8 + k] = (((sc6[:, k] >> 4) & 3)
                     | (((sc6[:, 4 + k] >> 4) & 3) << 2)
                     | (((sc6[:, 8 + k] >> 4) & 3) << 4)
                     | (((sc6[:, 12 + k] >> 4) & 3) << 6))
    return out


@_garbage_tolerant
def dequant_q3_k(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q3_K][1]  # 110
    blocks = buf[: nb * bs].reshape(nb, bs)
    hm = blocks[:, :32]
    qs = blocks[:, 32:96].reshape(nb, 2, 32)
    d = _f16(blocks[:, 108:110].reshape(-1))
    dl = d[:, None] * _q3k_unpack_scales(blocks[:, 96:108])  # (nb, 16)
    parts = []
    for h in range(2):
        for j in range(4):
            m = 1 << (4 * h + j)
            s = 2 * j
            lo = ((qs[:, h, :16] >> s) & 3).astype(np.float32) \
                - np.where(hm[:, :16] & m, 0.0, 4.0)
            hi = ((qs[:, h, 16:] >> s) & 3).astype(np.float32) \
                - np.where(hm[:, 16:] & m, 0.0, 4.0)
            parts.append(lo)
            parts.append(hi)
    qv = np.stack(parts, axis=1)                             # (nb, 16, 16)
    return (dl[:, :, None] * qv).reshape(-1)


def quant_q3_k(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, QK_K)
    nb = x.shape[0]
    sub = x.reshape(nb, 16, 16)
    # symmetric per-sub-block fit onto −4..3 (like Q4_0's max-|x|→−end)
    idx = np.abs(sub).argmax(axis=2)
    maxv = np.take_along_axis(sub, idx[:, :, None], axis=2)[:, :, 0]
    dl_sub = maxv / -4.0
    amax = np.abs(dl_sub).max(axis=1)
    d = np.where(amax > 0, amax / 31.0, 0.0).astype(np.float16)
    invd = np.where(d > 0, 1.0 / d.astype(np.float32), 0.0)
    sc = np.clip(np.round(dl_sub * invd[:, None]), -32, 31)  # (nb, 16)
    dl_q = d.astype(np.float32)[:, None] * sc
    inv_dl = np.where(dl_q != 0, 1.0 / dl_q, 0.0)
    q = np.clip(np.round(sub * inv_dl[:, :, None]), -4, 3).astype(np.int8)
    qplus = (q + 4).astype(np.uint8)            # 0..7: 2 low bits + hm bit
    out = np.empty((nb, 110), dtype=np.uint8)
    hm = np.zeros((nb, 32), dtype=np.uint8)
    qs = np.zeros((nb, 2, 32), dtype=np.uint8)
    k = 0
    for h in range(2):
        for j in range(4):
            m = 1 << (4 * h + j)
            s = 2 * j
            qs[:, h, :16] |= (qplus[:, k] & 3) << s
            qs[:, h, 16:] |= (qplus[:, k + 1] & 3) << s
            hm[:, :16] |= np.where(qplus[:, k] & 4, m, 0).astype(np.uint8)
            hm[:, 16:] |= np.where(qplus[:, k + 1] & 4, m, 0).astype(np.uint8)
            k += 2
    out[:, :32] = hm
    out[:, 32:96] = qs.reshape(nb, 64)
    out[:, 96:108] = _q3k_pack_scales((sc + 32).astype(np.uint8))
    out[:, 108:110] = d.view(np.uint8).reshape(-1, 2)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q4_K
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q4_k(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q4_K][1]  # 144
    blocks = buf[: nb * bs].reshape(nb, bs)
    d = _f16(blocks[:, 0:2].reshape(-1))
    dmin = _f16(blocks[:, 2:4].reshape(-1))
    sc, mn = unpack_scale_min_k4(blocks[:, 4:16])  # (nb, 8)
    qs = blocks[:, 16:].reshape(nb, 4, 32)
    lo = (qs & 0x0F).astype(np.float32)  # sub-blocks 0,2,4,6
    hi = (qs >> 4).astype(np.float32)    # sub-blocks 1,3,5,7
    q = np.stack([lo, hi], axis=2).reshape(nb, 8, 32)
    scale = d[:, None] * sc.astype(np.float32)       # (nb, 8)
    minv = dmin[:, None] * mn.astype(np.float32)     # (nb, 8)
    y = scale[:, :, None] * q - minv[:, :, None]
    return y.reshape(-1)


def quant_q4_k(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 8, 32)
    nb = x.shape[0]
    vmin = np.minimum(x.min(axis=2), 0.0)           # (nb, 8) — mins are ≥0 offsets
    vmax = x.max(axis=2)
    sub_scale = np.maximum((vmax - vmin) / 15.0, 0.0)
    d = (sub_scale.max(axis=1) / 63.0).astype(np.float16)
    dmin = ((-vmin).max(axis=1) / 63.0).astype(np.float16)
    df = d.astype(np.float32)
    dminf = dmin.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        sc = np.where(df[:, None] > 0, np.round(sub_scale / df[:, None]), 0)
        mn = np.where(dminf[:, None] > 0, np.round(-vmin / dminf[:, None]), 0)
    sc = np.clip(sc, 0, 63).astype(np.uint8)
    mn = np.clip(mn, 0, 63).astype(np.uint8)
    eff_scale = df[:, None] * sc                      # (nb, 8)
    eff_min = dminf[:, None] * mn
    with np.errstate(divide="ignore", invalid="ignore"):
        q = np.where(
            eff_scale[:, :, None] > 0,
            np.round((x + eff_min[:, :, None]) / eff_scale[:, :, None]),
            0,
        )
    q = np.clip(q, 0, 15).astype(np.uint8)            # (nb, 8, 32)
    pairs = q.reshape(nb, 4, 2, 32)
    packed = pairs[:, :, 0, :] | (pairs[:, :, 1, :] << 4)  # (nb, 4, 32)
    out = np.empty((nb, 144), dtype=np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:4] = dmin.view(np.uint8).reshape(-1, 2)
    out[:, 4:16] = pack_scale_min_k4(sc, mn)
    out[:, 16:] = packed.reshape(nb, 128)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q5_K
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q5_k(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q5_K][1]  # 176
    blocks = buf[: nb * bs].reshape(nb, bs)
    d = _f16(blocks[:, 0:2].reshape(-1))
    dmin = _f16(blocks[:, 2:4].reshape(-1))
    sc, mn = unpack_scale_min_k4(blocks[:, 4:16])
    qh = blocks[:, 16:48]                      # (nb, 32)
    qs = blocks[:, 48:].reshape(nb, 4, 32)
    lo = (qs & 0x0F).astype(np.uint8)
    hi = (qs >> 4).astype(np.uint8)
    # sub-block j (0..7) gets high bit (qh >> j) & 1; even j from low nibble,
    # odd j from high nibble (u1=1,u2=2 doubling per 64-group in llama.cpp).
    shifts = np.arange(8, dtype=np.uint8)
    hibits = ((qh[:, None, :] >> shifts[None, :, None]) & 1).astype(np.uint8)  # (nb, 8, 32)
    q = np.empty((nb, 8, 32), dtype=np.float32)
    q[:, 0::2, :] = lo
    q[:, 1::2, :] = hi
    q += hibits.astype(np.float32) * 16.0
    scale = d[:, None] * sc.astype(np.float32)
    minv = dmin[:, None] * mn.astype(np.float32)
    y = scale[:, :, None] * q - minv[:, :, None]
    return y.reshape(-1)


def quant_q5_k(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 8, 32)
    nb = x.shape[0]
    vmin = np.minimum(x.min(axis=2), 0.0)
    vmax = x.max(axis=2)
    sub_scale = np.maximum((vmax - vmin) / 31.0, 0.0)
    d = (sub_scale.max(axis=1) / 63.0).astype(np.float16)
    dmin = ((-vmin).max(axis=1) / 63.0).astype(np.float16)
    df = d.astype(np.float32)
    dminf = dmin.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        sc = np.where(df[:, None] > 0, np.round(sub_scale / df[:, None]), 0)
        mn = np.where(dminf[:, None] > 0, np.round(-vmin / dminf[:, None]), 0)
    sc = np.clip(sc, 0, 63).astype(np.uint8)
    mn = np.clip(mn, 0, 63).astype(np.uint8)
    eff_scale = df[:, None] * sc
    eff_min = dminf[:, None] * mn
    with np.errstate(divide="ignore", invalid="ignore"):
        q = np.where(
            eff_scale[:, :, None] > 0,
            np.round((x + eff_min[:, :, None]) / eff_scale[:, :, None]),
            0,
        )
    q = np.clip(q, 0, 31).astype(np.uint8)            # (nb, 8, 32), 5-bit
    lo = q & 0x0F
    hb = (q >> 4) & 1
    shifts = np.arange(8, dtype=np.uint8)
    qh = np.zeros((nb, 32), dtype=np.uint8)
    for j in range(8):
        qh |= (hb[:, j, :] << shifts[j])
    packed = lo[:, 0::2, :] | (lo[:, 1::2, :] << 4)   # (nb, 4, 32)
    out = np.empty((nb, 176), dtype=np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:4] = dmin.view(np.uint8).reshape(-1, 2)
    out[:, 4:16] = pack_scale_min_k4(sc, mn)
    out[:, 16:48] = qh
    out[:, 48:] = packed.reshape(nb, 128)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Q6_K
# ---------------------------------------------------------------------------

@_garbage_tolerant
def dequant_q6_k(buf: np.ndarray, n: int) -> np.ndarray:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q6_K][1]  # 210
    blocks = buf[: nb * bs].reshape(nb, bs)
    ql = blocks[:, 0:128].reshape(nb, 2, 64)       # two 128-element halves
    qh = blocks[:, 128:192].reshape(nb, 2, 32)
    sc = blocks[:, 192:208].view(np.int8).astype(np.float32)  # (nb, 16)
    d = _f16(blocks[:, 208:210].reshape(-1))
    low = np.empty((nb, 2, 128), dtype=np.uint8)
    low[:, :, 0:64] = ql[:, :, :] & 0x0F           # l, l+32 from ql[0:64] & 0xF
    low[:, :, 64:128] = ql[:, :, :] >> 4           # l+64, l+96 from ql >> 4
    hi = np.empty((nb, 2, 128), dtype=np.uint8)
    hi[:, :, 0:32] = (qh >> 0) & 3
    hi[:, :, 32:64] = (qh >> 2) & 3
    hi[:, :, 64:96] = (qh >> 4) & 3
    hi[:, :, 96:128] = (qh >> 6) & 3
    q = (low | (hi << 4)).astype(np.float32) - 32.0  # (nb, 2, 128)
    q = q.reshape(nb, 16, 16)                        # 16 sub-blocks of 16
    y = d[:, None, None] * sc[:, :, None] * q
    return y.reshape(-1)


def quant_q6_k(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1, 16, 16)
    nb = x.shape[0]
    amax = np.abs(x).max(axis=2)                    # (nb, 16)
    sub_scale = amax / 31.0                         # q-32 ∈ [-32, 31]
    d = (sub_scale.max(axis=1) / 127.0).astype(np.float16)
    df = d.astype(np.float32)
    with np.errstate(divide="ignore", invalid="ignore"):
        sc = np.where(df[:, None] > 0, np.round(sub_scale / df[:, None]), 0)
    sc = np.clip(sc, -128, 127).astype(np.int8)
    eff = df[:, None] * sc.astype(np.float32)       # (nb, 16)
    with np.errstate(divide="ignore", invalid="ignore"):
        q = np.where(np.abs(eff[:, :, None]) > 0, np.round(x / eff[:, :, None]), 0)
    q = (np.clip(q, -32, 31) + 32).astype(np.uint8)  # (nb, 16, 16) 6-bit
    q = q.reshape(nb, 2, 128)
    low = q & 0x0F
    hi = q >> 4                                      # 2 bits
    ql = np.empty((nb, 2, 64), dtype=np.uint8)
    ql[:, :, :] = low[:, :, 0:64] | (low[:, :, 64:128] << 4)
    qh = (
        hi[:, :, 0:32]
        | (hi[:, :, 32:64] << 2)
        | (hi[:, :, 64:96] << 4)
        | (hi[:, :, 96:128] << 6)
    )
    out = np.empty((nb, 210), dtype=np.uint8)
    out[:, 0:128] = ql.reshape(nb, 128)
    out[:, 128:192] = qh.reshape(nb, 64)
    out[:, 192:208] = sc.view(np.uint8)
    out[:, 208:210] = d.view(np.uint8).reshape(-1, 2)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

DEQUANT = {
    GGMLType.F32: dequant_f32,
    GGMLType.F16: dequant_f16,
    GGMLType.BF16: dequant_bf16,
    GGMLType.Q4_0: dequant_q4_0,
    GGMLType.Q4_1: dequant_q4_1,
    GGMLType.Q5_0: dequant_q5_0,
    GGMLType.Q5_1: dequant_q5_1,
    GGMLType.Q8_0: dequant_q8_0,
    GGMLType.Q2_K: dequant_q2_k,
    GGMLType.Q3_K: dequant_q3_k,
    GGMLType.Q4_K: dequant_q4_k,
    GGMLType.Q5_K: dequant_q5_k,
    GGMLType.Q6_K: dequant_q6_k,
    GGMLType.IQ4_NL: dequant_iq4_nl,
    GGMLType.IQ4_XS: dequant_iq4_xs,
}

QUANT = {
    GGMLType.F32: lambda x: np.ascontiguousarray(x, dtype=np.float32).view(np.uint8),
    GGMLType.F16: lambda x: np.ascontiguousarray(x, dtype=np.float32).astype(np.float16).view(np.uint8),
    GGMLType.BF16: quant_bf16,
    GGMLType.Q4_0: quant_q4_0,
    GGMLType.Q4_1: quant_q4_1,
    GGMLType.Q5_0: quant_q5_0,
    GGMLType.Q5_1: quant_q5_1,
    GGMLType.Q8_0: quant_q8_0,
    GGMLType.Q2_K: quant_q2_k,
    GGMLType.Q3_K: quant_q3_k,
    GGMLType.Q4_K: quant_q4_k,
    GGMLType.Q5_K: quant_q5_k,
    GGMLType.Q6_K: quant_q6_k,
    GGMLType.IQ4_NL: quant_iq4_nl,
    GGMLType.IQ4_XS: quant_iq4_xs,
}


def _type_name(ggml_type) -> str:
    try:
        return GGMLType(ggml_type).name
    except ValueError:
        return f"ggml type code {int(ggml_type)}"


def dequantize(buf: np.ndarray, ggml_type: GGMLType, n_elements: int) -> np.ndarray:
    """Flat uint8 buffer → float32 array of ``n_elements``.

    Routes through the in-tree C++ library (``native/``, multithreaded,
    bit-exact with the codecs above) when available; numpy otherwise.
    Disable with ``LFKT_NATIVE=0``.
    """
    try:
        fn = DEQUANT[GGMLType(ggml_type)]
    except (KeyError, ValueError):
        raise NotImplementedError(f"dequant for {_type_name(ggml_type)}") from None
    from ..native import native_dequantize

    out = native_dequantize(buf, int(ggml_type), n_elements)
    if out is not None:
        return out
    return fn(np.ascontiguousarray(buf, dtype=np.uint8).reshape(-1), n_elements)


def quantize(x: np.ndarray, ggml_type: GGMLType) -> np.ndarray:
    """float array → flat uint8 buffer in ``ggml_type`` layout."""
    try:
        fn = QUANT[GGMLType(ggml_type)]
    except (KeyError, ValueError):
        raise NotImplementedError(f"quant for {_type_name(ggml_type)}") from None
    x = np.asarray(x).reshape(-1)
    block = GGML_BLOCK_SIZES[GGMLType(ggml_type)][0]
    if x.size % block != 0:
        raise ValueError(
            f"{_type_name(ggml_type)}: element count {x.size} not divisible by block {block}"
        )
    return fn(x)

"""GGUF v2/v3 container reader: mmap'd, zero-copy tensor views.

Replaces the file-loading half of the native engine the reference constructs
at import time (``Llama(model_path=...)``, reference api.py:24-28): header,
metadata KV store (architecture, hparams, tokenizer vocab/merges, chat
template), tensor index, and aligned data section exposed as ``np.memmap``
slices so multi-GB weights are paged in lazily.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any

import numpy as np

from .constants import (
    GGUF_DEFAULT_ALIGNMENT,
    GGUF_MAGIC,
    GGUF_SCALAR_FMT as _SCALAR_FMT,
    GGMLType,
    GGUFValueType,
    align_up,
    tensor_nbytes,
)

_SCALAR_NP = {
    GGUFValueType.UINT8: np.uint8,
    GGUFValueType.INT8: np.int8,
    GGUFValueType.UINT16: np.uint16,
    GGUFValueType.INT16: np.int16,
    GGUFValueType.UINT32: np.uint32,
    GGUFValueType.INT32: np.int32,
    GGUFValueType.FLOAT32: np.float32,
    GGUFValueType.UINT64: np.uint64,
    GGUFValueType.INT64: np.int64,
    GGUFValueType.FLOAT64: np.float64,
}


class _Cursor:
    """Sequential little-endian decoder over a buffer."""

    def __init__(self, buf: memoryview, offset: int = 0):
        self.buf = buf
        self.off = offset

    def scalar(self, vtype: GGUFValueType):
        fmt = _SCALAR_FMT[vtype]
        size = struct.calcsize(fmt)
        (val,) = struct.unpack_from(fmt, self.buf, self.off)
        self.off += size
        return val

    def u32(self) -> int:
        return self.scalar(GGUFValueType.UINT32)

    def u64(self) -> int:
        return self.scalar(GGUFValueType.UINT64)

    def string(self, len_type: GGUFValueType = GGUFValueType.UINT64) -> str:
        n = self.scalar(len_type)
        raw = bytes(self.buf[self.off : self.off + n])
        self.off += n
        return raw.decode("utf-8", errors="replace")

    def value(self, vtype: GGUFValueType, len_type: GGUFValueType):
        vtype = GGUFValueType(vtype)
        if vtype == GGUFValueType.STRING:
            return self.string(len_type)
        if vtype == GGUFValueType.BOOL:
            return bool(self.scalar(GGUFValueType.INT8))
        if vtype == GGUFValueType.ARRAY:
            elem_type = GGUFValueType(self.u32())
            count = self.scalar(len_type)
            if elem_type in _SCALAR_NP and elem_type != GGUFValueType.BOOL:
                dt = np.dtype(_SCALAR_NP[elem_type]).newbyteorder("<")
                arr = np.frombuffer(self.buf, dtype=dt, count=count, offset=self.off)
                self.off += arr.nbytes
                return arr.tolist()
            return [self.value(elem_type, len_type) for _ in range(count)]
        return self.scalar(vtype)


@dataclasses.dataclass
class GGUFTensor:
    name: str
    shape: tuple[int, ...]  # ggml order: shape[0] is fastest-varying (row length)
    ggml_type: GGMLType
    offset: int             # relative to data-section start
    _file: "GGUFFile" = dataclasses.field(repr=False, default=None)

    @property
    def n_elements(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return tensor_nbytes(self.ggml_type, self.n_elements)

    def raw(self) -> np.ndarray:
        """Zero-copy uint8 view of the on-disk block data."""
        start = self._file.data_offset + self.offset
        return self._file.mmap[start : start + self.nbytes]

    def astype_f32(self) -> np.ndarray:
        """Dequantize to float32, shaped (shape[-1], ..., shape[0]).

        GGUF stores dims innermost-first; numpy is outermost-first, so a 2-D
        weight with ggml shape (n_in, n_out) comes back as (n_out, n_in) —
        i.e. rows are output features, matching `x @ w.T` usage.
        """
        from . import quants

        flat = quants.dequantize(self.raw(), self.ggml_type, self.n_elements)
        return flat.reshape(tuple(reversed(self.shape)))


class GGUFFile:
    """Parsed GGUF container. ``metadata`` dict + named tensor index."""

    def __init__(self, path: str):
        self.path = path
        self.mmap = np.memmap(path, dtype=np.uint8, mode="r")
        cur = _Cursor(memoryview(self.mmap))
        try:
            self._parse(path, cur)
        except (struct.error, IndexError) as e:
            raise ValueError(f"{path}: truncated or corrupt GGUF file ({e})") from e

    def _parse(self, path: str, cur: "_Cursor"):
        magic = cur.u32()
        if magic != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file (magic {magic:#x})")
        self.version = cur.u32()
        if self.version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {self.version}")
        len_type = GGUFValueType.UINT64 if self.version >= 2 else GGUFValueType.UINT32
        n_tensors = cur.scalar(len_type)
        n_kv = cur.scalar(len_type)

        self.metadata: dict[str, Any] = {}
        for _ in range(n_kv):
            key = cur.string(len_type)
            vtype = GGUFValueType(cur.u32())
            self.metadata[key] = cur.value(vtype, len_type)

        self.tensors: dict[str, GGUFTensor] = {}
        for _ in range(n_tensors):
            name = cur.string(len_type)
            n_dims = cur.u32()
            shape = tuple(cur.u64() for _ in range(n_dims))
            ggml_type = GGMLType(cur.u32())
            offset = cur.u64()
            self.tensors[name] = GGUFTensor(name, shape, ggml_type, offset, self)

        self.alignment = int(self.metadata.get("general.alignment", GGUF_DEFAULT_ALIGNMENT))
        self.data_offset = align_up(cur.off, self.alignment)

    @property
    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "llama")

    def hparam(self, key: str, default=None):
        """Look up ``<arch>.<key>`` with a plain-key fallback."""
        arch = self.architecture
        if f"{arch}.{key}" in self.metadata:
            return self.metadata[f"{arch}.{key}"]
        return self.metadata.get(key, default)

    def __contains__(self, name: str) -> bool:
        return name in self.tensors

    def __getitem__(self, name: str) -> GGUFTensor:
        return self.tensors[name]

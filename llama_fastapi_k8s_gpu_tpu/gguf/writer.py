"""GGUF v3 writer.

The reference never writes GGUF (artifacts come from S3,
helm/templates/deployment.yaml:26-49); this writer exists so the framework can
(a) build tiny hand-made GGUF files for golden tests (SURVEY.md §4) and
(b) synthesize full-size quantized models for benchmarking without network
egress.
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

from .constants import (
    GGUF_DEFAULT_ALIGNMENT,
    GGUF_MAGIC,
    GGUF_SCALAR_FMT,
    GGUF_VERSION,
    GGMLType,
    GGUFValueType,
    align_up,
    tensor_nbytes,
)
from . import quants


def _pack_string(s: str) -> bytes:
    raw = s.encode("utf-8")
    return struct.pack("<Q", len(raw)) + raw


def _normalize(v: Any) -> Any:
    """numpy scalars/arrays → plain Python so type inference and struct.pack work."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (list, tuple)):
        return [_normalize(it) for it in v]
    return v


def _infer_type(v: Any) -> GGUFValueType:
    if isinstance(v, bool):
        return GGUFValueType.BOOL
    if isinstance(v, int):
        if v < 0:
            return GGUFValueType.INT32 if v >= -(2**31) else GGUFValueType.INT64
        return GGUFValueType.UINT32 if v < 2**32 else GGUFValueType.UINT64
    if isinstance(v, float):
        return GGUFValueType.FLOAT32
    if isinstance(v, str):
        return GGUFValueType.STRING
    if isinstance(v, (list, tuple, np.ndarray)):
        return GGUFValueType.ARRAY
    raise TypeError(f"cannot infer GGUF type for {type(v)}")


def _infer_array_elem_type(items: list) -> GGUFValueType:
    """Widest element type across the whole array, not just items[0]."""
    if not items:
        return GGUFValueType.STRING
    types = {_infer_type(it) for it in items}
    if types == {GGUFValueType.UINT32}:
        return GGUFValueType.UINT32
    int_types = {GGUFValueType.UINT32, GGUFValueType.INT32,
                 GGUFValueType.UINT64, GGUFValueType.INT64}
    if types <= int_types:
        if GGUFValueType.UINT64 in types and GGUFValueType.INT32 not in types \
                and GGUFValueType.INT64 not in types:
            return GGUFValueType.UINT64
        return GGUFValueType.INT64 if (
            GGUFValueType.UINT64 in types or GGUFValueType.INT64 in types
        ) else GGUFValueType.INT32
    if len(types) == 1:
        return next(iter(types))
    raise TypeError(f"mixed array element types {types}")


def _pack_value(v: Any, vtype: GGUFValueType) -> bytes:
    if vtype == GGUFValueType.STRING:
        return _pack_string(v)
    if vtype == GGUFValueType.BOOL:
        return struct.pack("<b", 1 if v else 0)
    if vtype == GGUFValueType.ARRAY:
        items = list(v)
        etype = _infer_array_elem_type(items)
        out = struct.pack("<IQ", int(etype), len(items))
        return out + b"".join(_pack_value(it, etype) for it in items)
    return struct.pack(GGUF_SCALAR_FMT[vtype], v)


class GGUFWriter:
    def __init__(self, path: str, alignment: int = GGUF_DEFAULT_ALIGNMENT):
        self.path = path
        self.alignment = alignment
        self.metadata: list[tuple[str, Any, GGUFValueType]] = []
        # (name, ggml shape innermost-first, type, raw bytes)
        self._tensors: list[tuple[str, tuple[int, ...], GGMLType, np.ndarray]] = []

    def add_metadata(self, key: str, value: Any, vtype: GGUFValueType | None = None):
        value = _normalize(value)
        self.metadata.append((key, value, vtype or _infer_type(value)))

    def add_tensor(self, name: str, array: np.ndarray, ggml_type: GGMLType):
        """``array`` in numpy orientation (outermost-first); quantized here."""
        array = np.asarray(array)
        ggml_shape = tuple(reversed(array.shape))
        raw = quants.quantize(array.astype(np.float32), ggml_type)
        expect = tensor_nbytes(ggml_type, array.size)
        if raw.nbytes != expect:
            raise AssertionError(f"{name}: {raw.nbytes} != {expect}")
        self._tensors.append((name, ggml_shape, ggml_type, raw))

    def add_raw_tensor(self, name: str, ggml_shape: tuple[int, ...],
                       ggml_type: GGMLType, raw: np.ndarray):
        self._tensors.append((name, tuple(ggml_shape), ggml_type, np.ascontiguousarray(raw, dtype=np.uint8)))

    def write(self):
        if self.alignment != GGUF_DEFAULT_ALIGNMENT and not any(
            k == "general.alignment" for k, _, _ in self.metadata
        ):
            # the reader derives data_offset from this key; omitting it would
            # silently corrupt every tensor view
            self.add_metadata("general.alignment", self.alignment)
        with open(self.path, "wb") as f:
            f.write(struct.pack("<IIQQ", GGUF_MAGIC, GGUF_VERSION,
                                len(self._tensors), len(self.metadata)))
            for key, value, vtype in self.metadata:
                f.write(_pack_string(key))
                f.write(struct.pack("<I", int(vtype)))
                f.write(_pack_value(value, vtype))
            offset = 0
            for name, shape, ggml_type, raw in self._tensors:
                f.write(_pack_string(name))
                f.write(struct.pack("<I", len(shape)))
                for d in shape:
                    f.write(struct.pack("<Q", d))
                f.write(struct.pack("<IQ", int(ggml_type), offset))
                offset += align_up(raw.nbytes, self.alignment)
            pos = f.tell()
            f.write(b"\x00" * (align_up(pos, self.alignment) - pos))
            for _, _, _, raw in self._tensors:
                f.write(raw.tobytes())
                f.write(b"\x00" * (align_up(raw.nbytes, self.alignment) - raw.nbytes))

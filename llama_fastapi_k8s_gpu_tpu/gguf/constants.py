"""GGUF container + GGML quant-type constants.

The reference consumes GGUF files through the opaque native engine
(``llama_cpp.Llama(model_path=...)``, reference api.py:24-28, pulling
``*Q4_K_M.gguf`` artifacts — reference api.py:14,
helm/templates/deployment.yaml:32).  This module pins the file-format contract
that the in-tree TPU engine implements instead.

Layouts follow the public GGUF spec (ggml-org/ggml docs/gguf.md) and the GGML
quantization block formats; values are the on-disk wire constants.
"""

from __future__ import annotations

import enum

GGUF_MAGIC = 0x46554747  # b"GGUF" little-endian
GGUF_VERSION = 3
GGUF_DEFAULT_ALIGNMENT = 32

QK_K = 256  # K-quant super-block size
QK8_0 = 32
QK4_0 = 32
QK5_0 = 32


class GGUFValueType(enum.IntEnum):
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    UINT32 = 4
    INT32 = 5
    FLOAT32 = 6
    BOOL = 7
    STRING = 8
    ARRAY = 9
    UINT64 = 10
    INT64 = 11
    FLOAT64 = 12


# struct format for each scalar metadata value type (shared by reader/writer)
GGUF_SCALAR_FMT = {
    GGUFValueType.UINT8: "<B",
    GGUFValueType.INT8: "<b",
    GGUFValueType.UINT16: "<H",
    GGUFValueType.INT16: "<h",
    GGUFValueType.UINT32: "<I",
    GGUFValueType.INT32: "<i",
    GGUFValueType.FLOAT32: "<f",
    GGUFValueType.UINT64: "<Q",
    GGUFValueType.INT64: "<q",
    GGUFValueType.FLOAT64: "<d",
}


class GGMLType(enum.IntEnum):
    F32 = 0
    F16 = 1
    Q4_0 = 2
    Q4_1 = 3
    Q5_0 = 6
    Q5_1 = 7
    Q8_0 = 8
    Q8_1 = 9
    Q2_K = 10
    Q3_K = 11
    Q4_K = 12
    Q5_K = 13
    Q6_K = 14
    Q8_K = 15
    IQ4_NL = 20
    IQ4_XS = 23
    I8 = 24
    I16 = 25
    I32 = 26
    I64 = 27
    F64 = 28
    BF16 = 30


# (elements per block, bytes per block)
GGML_BLOCK_SIZES: dict[GGMLType, tuple[int, int]] = {
    GGMLType.F32: (1, 4),
    GGMLType.F16: (1, 2),
    GGMLType.BF16: (1, 2),
    GGMLType.I8: (1, 1),
    GGMLType.I16: (1, 2),
    GGMLType.I32: (1, 4),
    GGMLType.I64: (1, 8),
    GGMLType.F64: (1, 8),
    GGMLType.Q4_0: (QK4_0, 2 + 16),
    GGMLType.Q4_1: (QK4_0, 2 + 2 + 16),
    GGMLType.Q5_0: (QK5_0, 2 + 4 + 16),
    GGMLType.Q5_1: (QK5_0, 2 + 2 + 4 + 16),
    GGMLType.Q8_0: (QK8_0, 2 + 32),
    GGMLType.Q2_K: (QK_K, QK_K // 16 + QK_K // 4 + 2 + 2),
    GGMLType.Q3_K: (QK_K, QK_K // 8 + QK_K // 4 + 12 + 2),
    GGMLType.Q4_K: (QK_K, 2 + 2 + 12 + QK_K // 2),
    GGMLType.Q5_K: (QK_K, 2 + 2 + 12 + QK_K // 8 + QK_K // 2),
    GGMLType.Q6_K: (QK_K, QK_K // 2 + QK_K // 4 + QK_K // 16 + 2),
    GGMLType.IQ4_NL: (32, 2 + 16),
    GGMLType.IQ4_XS: (QK_K, 2 + 2 + QK_K // 64 + QK_K // 2),
}


def align_up(n: int, alignment: int) -> int:
    return (n + alignment - 1) // alignment * alignment


def tensor_nbytes(ggml_type: GGMLType, n_elements: int) -> int:
    block, nbytes = GGML_BLOCK_SIZES[ggml_type]
    if n_elements % block != 0:
        raise ValueError(
            f"{ggml_type.name}: element count {n_elements} not divisible by block {block}"
        )
    return (n_elements // block) * nbytes

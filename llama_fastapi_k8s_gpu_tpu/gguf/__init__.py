from .constants import GGMLType, GGUFValueType  # noqa: F401
from .reader import GGUFFile, GGUFTensor  # noqa: F401
from .writer import GGUFWriter  # noqa: F401
from . import quants  # noqa: F401

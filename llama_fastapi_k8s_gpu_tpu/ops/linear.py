"""Linear-layer formats and the matmul dispatch.

The reference's weights live inside llama.cpp's ggml tensors and are consumed
by cuBLAS kernels (reference docker/Dockerfile.base:30-32).  Here a linear is
a small pytree whose keys select the compute path — the structure is static
under jit, so dispatch costs nothing:

- ``{"w": bf16 (out, in)}``               — plain MXU matmul.
- ``{"q": int8 (out, in), "s": f32 (out,)}`` — weight-only int8 with dynamic
  per-row activation quantization; both operands int8 so the MXU runs its
  int8 path and HBM traffic per decoded token is halved vs bf16.  This is
  what lets Llama-3-8B (16 GB at bf16) fit a single v5e chip (16 GB HBM).

A Pallas fused dequant-matmul over raw Q4_K blocks (ops/pallas) is the next
step down the memory-footprint ladder.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.devtime import timed_jit


def make_linear_bf16(w: np.ndarray) -> dict:
    """w: (out, in) float."""
    return {"w": jnp.asarray(w, dtype=jnp.bfloat16)}


def make_linear_int8(w: np.ndarray) -> dict:
    """Symmetric per-output-channel int8 quantization of (out, in) weights."""
    w = np.asarray(w, dtype=np.float32)
    amax = np.abs(w).max(axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale[:, None]), -127, 127).astype(np.int8)
    return {"q": jnp.asarray(q), "s": jnp.asarray(scale)}


@jax.jit
def make_linear_int8_device(w: jax.Array) -> dict:
    """:func:`make_linear_int8` on device — used by the Pallas load path so
    requantization never round-trips through the host."""
    w = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale[:, None]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


make_linear_int8_device = timed_jit("load_linear_int8",
                                    make_linear_int8_device,
                                    site="ops.linear")


def make_linear_q4k(w: np.ndarray) -> dict:
    """(out, in) float weights → fused-kernel Q4_K layout (quantize with the
    in-tree codec, then pack for ops/pallas/qmatmul.py).  ~5 bit/weight in
    HBM; the decode-bandwidth format."""
    from ..gguf.quants import quant_q4_k
    from .pallas.qmatmul import prep_q4k

    w = np.ascontiguousarray(w, dtype=np.float32)
    n_out, k_in = w.shape
    return prep_q4k(quant_q4_k(w.reshape(-1)), n_out, k_in)


def make_linear_q8(w: np.ndarray) -> dict:
    """(out, in) float weights → fused-kernel Q8_0 layout (quantize with the
    in-tree codec, then pack for ops/pallas/q8matmul.py).  ~9 bit/weight on
    the file's own per-32-block quantization grid (scales folded to bf16)."""
    from ..gguf.quants import quant_q8_0
    from .pallas.q8matmul import prep_q8_0

    w = np.ascontiguousarray(w, dtype=np.float32)
    n_out, k_in = w.shape
    return prep_q8_0(quant_q8_0(w.reshape(-1)), n_out, k_in)


def make_linear_q6k(w: np.ndarray) -> dict:
    """(out, in) float weights → fused-kernel Q6_K layout (quantize with the
    in-tree codec, then pack for ops/pallas/q6matmul.py).  ~7 bit/weight in
    HBM; the format Q4_K_M files use for ffn_down / attn_v / output."""
    from ..gguf.quants import quant_q6_k
    from .pallas.q6matmul import prep_q6k

    w = np.ascontiguousarray(w, dtype=np.float32)
    n_out, k_in = w.shape
    return prep_q6k(quant_q6_k(w.reshape(-1)), n_out, k_in)


def make_linear_q5k(w: np.ndarray) -> dict:
    """(out, in) float weights → fused-kernel Q5_K layout (quantize with the
    in-tree codec, then pack for ops/pallas/q5matmul.py).  ~6 bit/weight."""
    from ..gguf.quants import quant_q5_k
    from .pallas.q5matmul import prep_q5k

    w = np.ascontiguousarray(w, dtype=np.float32)
    n_out, k_in = w.shape
    return prep_q5k(quant_q5_k(w.reshape(-1)), n_out, k_in)


def _fused_fns(w: dict):
    """(matmul, matmul_stacked) for a fused-layout weight dict, or None.
    The single dispatch point shared by :func:`linear` and
    :func:`linear_at` — one place to extend when a format is added."""
    if "qs" in w:
        from .pallas import qmatmul as m

        return m.q4k_matmul, m.q4k_matmul_stacked
    if "q4" in w or "q6p" in w:   # split or `pre` Q6_K layout
        from .pallas import q6matmul as m

        return m.q6k_matmul, m.q6k_matmul_stacked
    if "q5s" in w or "q5p" in w:  # split or `pre` Q5_K layout
        from .pallas import q5matmul as m

        return m.q5k_matmul, m.q5k_matmul_stacked
    if "q8" in w:
        from .pallas import q8matmul as m

        return m.q8_matmul, m.q8_matmul_stacked
    return None


def linear(x: jax.Array, w: dict) -> jax.Array:
    """x: (..., in) bf16 → (..., out) bf16."""
    fns = _fused_fns(w)
    if fns is not None:
        return fns[0](x, w)
    if "w" in w:
        return jax.lax.dot_general(
            x, w["w"],
            dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)
    # int8 weight-only: dynamically quantize activations per row
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    xs = jnp.where(amax > 0, amax / 127.0, 1.0)
    xq = jnp.round(xf / xs).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, w["q"],
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * xs * w["s"]
    return y.astype(x.dtype)


def linear_at(x: jax.Array, w: dict, idx) -> jax.Array:
    """:func:`linear` against layer ``idx`` of weights stacked as (L, ...)
    arrays — the form the model scans over (models/llama.py).

    Fused Pallas layouts stream their blocks straight from the stacked HBM
    array via scalar prefetch: slicing them per layer (what ``lax.scan``
    over weight xs does) materializes a copy of every layer's quantized
    planes before each pallas_call, measured at +6.3 ms/token for 8B Q4_K
    decode on v5e (tools/decode_breakdown.py).  Non-fused formats slice at
    ``idx`` — XLA fuses that dynamic-slice into the dot_general read, so
    it was never the bottleneck."""
    fns = _fused_fns(w)
    if fns is not None:
        return fns[1](x, w, idx)
    return linear(x, jax.tree_util.tree_map(lambda a: a[idx], w))

"""Startup compile probes for the Pallas kernels.

A Mosaic lowering failure (new libtpu, unexpected geometry) must degrade a
pod to a slower path — not crash-loop it behind a misleading traceback.
These probes compile each risky kernel once on a tiny shape at engine
construction time, so the *caller* can pick the fallback (int8 weights /
XLA attention) with correct attribution, for every engine variant (serial,
mesh-batched, continuous, sequence-parallel — they all construct through
``Engine.__init__``) and for the benches.

Each probe returns ``None`` on success or a short error string; results are
cached per process (the real warmup then reuses the compiled programs'
cache lineage at different shapes, so the probe cost is one small Mosaic
compile each, TPU only — interpret mode always passes cheaply)."""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["probe_fused_q4k", "probe_fused_q5k", "probe_fused_q6k",
           "probe_fused_q8", "probe_flash_attention", "probe_kv_quant",
           "probe_decode_loop"]


def _err(e: BaseException) -> str:
    return f"{type(e).__name__}: {e}"[:400]


def _probe_n() -> int:
    """N for the matmul probes: 512 on TPU so the kernel compiles with the
    TN=512 tile every 8B serving shape uses (qmatmul._pick_tn); 8 in
    interpret mode to keep CPU tests fast.  A probe at a toy tile size
    would miss tile-dependent Mosaic regressions."""
    from . import use_interpret

    return 8 if use_interpret() else 512


@functools.lru_cache(maxsize=1)
def probe_fused_q4k() -> str | None:
    """Compile + run the fused Q4_K matmul at the serving tile geometry."""
    try:
        import jax.numpy as jnp

        from .qmatmul import prep_q4k, q4k_matmul, q4k_matmul_stacked

        rng = np.random.default_rng(0)
        from ...gguf.quants import quant_q4_k

        n = _probe_n()
        w = prep_q4k(quant_q4_k(
            rng.standard_normal(n * 2048).astype(np.float32) * 0.02),
            n, 2048)
        x = jnp.ones((1, 2048), jnp.bfloat16)
        y = q4k_matmul(x, w)          # unstacked: the output head's path
        float(y.sum())   # host fetch: the only reliable sync on the tunnel
        # stacked scalar-prefetch variant: the per-layer serving path
        ws = {k: jnp.stack([v, v]) for k, v in w.items()}
        float(q4k_matmul_stacked(x, ws, 1).sum())
        return None
    except Exception as e:  # noqa: BLE001 — any failure means "don't use it"
        return _err(e)


@functools.lru_cache(maxsize=1)
def probe_fused_q5k() -> str | None:
    """Compile + run the fused Q5_K matmul at the serving tile geometry."""
    try:
        import jax.numpy as jnp

        from ...gguf.quants import quant_q5_k
        from .q5matmul import prep_q5k, q5k_matmul, q5k_matmul_stacked

        rng = np.random.default_rng(0)
        n = _probe_n()
        w = prep_q5k(quant_q5_k(
            rng.standard_normal(n * 2048).astype(np.float32) * 0.02),
            n, 2048)
        x = jnp.ones((1, 2048), jnp.bfloat16)
        float(q5k_matmul(x, w).sum())
        ws = {k: jnp.stack([v, v]) for k, v in w.items()}
        float(q5k_matmul_stacked(x, ws, 1).sum())
        return None
    except Exception as e:  # noqa: BLE001
        return _err(e)


@functools.lru_cache(maxsize=1)
def probe_fused_q6k() -> str | None:
    """Compile + run the fused Q6_K matmul at the serving tile geometry."""
    try:
        import jax.numpy as jnp

        from ...gguf.quants import quant_q6_k
        from .q6matmul import prep_q6k, q6k_matmul, q6k_matmul_stacked

        rng = np.random.default_rng(0)
        n = _probe_n()
        w = prep_q6k(quant_q6_k(
            rng.standard_normal(n * 2048).astype(np.float32) * 0.02),
            n, 2048)
        x = jnp.ones((1, 2048), jnp.bfloat16)
        float(q6k_matmul(x, w).sum())
        ws = {k: jnp.stack([v, v]) for k, v in w.items()}
        float(q6k_matmul_stacked(x, ws, 1).sum())
        return None
    except Exception as e:  # noqa: BLE001
        return _err(e)


@functools.lru_cache(maxsize=1)
def probe_fused_q8() -> str | None:
    """Compile + run the fused Q8_0 matmul at the serving tile geometry."""
    try:
        import jax.numpy as jnp

        from ...gguf.quants import quant_q8_0
        from .q8matmul import prep_q8_0, q8_matmul, q8_matmul_stacked

        rng = np.random.default_rng(0)
        n = _probe_n()
        w = prep_q8_0(quant_q8_0(
            rng.standard_normal(n * 2048).astype(np.float32) * 0.02),
            n, 2048)
        x = jnp.ones((1, 2048), jnp.bfloat16)
        float(q8_matmul(x, w).sum())
        ws = {k: jnp.stack([v, v]) for k, v in w.items()}
        float(q8_matmul_stacked(x, ws, 1).sum())
        return None
    except Exception as e:  # noqa: BLE001
        return _err(e)


@functools.lru_cache(maxsize=2)
def probe_flash_attention(quantized: bool = False) -> str | None:
    """Compile + run the flash prefill kernel at the Llama-3-8B head
    layout (32 q heads / 8 kv heads / head_dim 128) on a short sequence.
    ``quantized=True`` probes the int8-cache fused-dequant variant
    (kv_dtype=int8 engines call both: the two lower to different Mosaic
    programs and must degrade independently)."""
    try:
        import jax.numpy as jnp

        from . import use_interpret
        from .attention import _env_kv_unroll, flash_attention

        itp = use_interpret()
        S, H, KV, HD, CTX = (8, 2, 2, 128, 32) if itp else (128, 32, 8, 128, 256)
        q = jnp.ones((S, H, HD), jnp.bfloat16)
        if quantized:
            k = jnp.ones((KV, CTX, HD), jnp.int8)
            v = jnp.ones((KV, CTX, HD), jnp.int8)
            ks = jnp.full((KV, CTX), 1 / 127.0, jnp.float32)
            y = flash_attention(q, k, v, jnp.int32(0), sm_scale=HD ** -0.5,
                                k_scale=ks, v_scale=ks, interpret=itp)
        else:
            k = jnp.ones((KV, CTX, HD), jnp.bfloat16)  # head-major ring layout
            v = jnp.ones((KV, CTX, HD), jnp.bfloat16)
            y = flash_attention(q, k, v, jnp.int32(0), sm_scale=HD ** -0.5,
                                interpret=itp)
        float(y.astype(jnp.float32).sum())
        if _env_kv_unroll() > 1:
            # the multi-KV-block inner loop (LFKT_FLASH_KV_UNROLL > 1) is a
            # structurally different Mosaic program (fused K/V fetch +
            # in-kernel sub-block loop); probe it at small explicit blocks
            # so a lowering failure degrades attn_impl instead of crashing
            # the first long-context prefill.  The probe shapes above clamp
            # the unroll to 1 (ring == one block), so they cannot cover it.
            ctx2 = 4 * 128
            if quantized:
                k2 = jnp.ones((KV, ctx2, HD), jnp.int8)
                ks2 = jnp.full((KV, ctx2), 1 / 127.0, jnp.float32)
                y = flash_attention(q, k2, k2, jnp.int32(0),
                                    sm_scale=HD ** -0.5, block_q=128,
                                    block_k=128, k_scale=ks2, v_scale=ks2,
                                    interpret=itp)
            else:
                k2 = jnp.ones((KV, ctx2, HD), jnp.bfloat16)
                y = flash_attention(q, k2, k2, jnp.int32(0),
                                    sm_scale=HD ** -0.5, block_q=128,
                                    block_k=128, interpret=itp)
            float(y.astype(jnp.float32).sum())
        return None
    except Exception as e:  # noqa: BLE001
        return _err(e)


@functools.lru_cache(maxsize=8)
def probe_decode_loop(quantized: bool = False, int8_weights: bool = False,
                      n_kv: int = 2, head_dim: int = 64,
                      n_ctx: int = 128, sliding_window: int = 0,
                      n_heads: int | None = None,
                      ffn_dim: int | None = None) -> str | None:
    """Compile + run the layer-looped decode kernel
    (ops/pallas/decode_loop.py) at the ENGINE'S full geometry.

    Unlike the matmul probes (tiny shapes: only tile-dependent Mosaic
    regressions vary with size), the looped kernel's VMEM residency
    scales with the serving shape — one layer's WHOLE weight set
    (``dim``/``ffn_dim`` planes) plus its full ``(n_kv, n_ctx, hd)``
    ring block live in VMEM per grid step — so a smaller-than-serving
    probe would pass while warmup's real program fails.  The engine
    therefore threads every residency-bearing dimension
    (``decode_loop.loop_geometry``); only ``n_layers`` is synthetic
    (2: the layer count changes the grid, never the per-step shape).

    Beyond compiling, the probe verifies the partial-grid aliasing
    contract the kernel leans on: with a 2-layer stack launched one
    layer at a time (``unroll=1, layer0=1``), layer 0's ring bytes must
    ride the input/output alias untouched.  A backend where unwritten
    aliased blocks do not retain input bytes corrupts every layer
    outside the launch window — that must degrade the pod, not corrupt
    decode."""
    try:
        import dataclasses

        import jax
        import jax.numpy as jnp

        from . import use_interpret
        from ...models.config import ModelConfig
        from ...models.llama import init_cache
        from ...models.params import decode_loop_plan, synth_params
        from .decode_loop import decode_loop_step

        if n_heads is None:
            n_heads = 2 * n_kv
        dim = n_heads * head_dim
        cfg = ModelConfig(
            vocab_size=64, dim=dim, n_layers=2, n_heads=n_heads,
            n_kv_heads=n_kv, ffn_dim=ffn_dim or 2 * dim, n_ctx=n_ctx,
            sliding_window=sliding_window,
            kv_dtype="int8" if quantized else "bf16",
            decode_layer_unroll=1)
        params = synth_params(cfg, fmt="int8" if int8_weights else "bf16")
        fmts, reason = decode_loop_plan(params, cfg)
        if reason is not None:
            return reason
        cache = init_cache(cfg)
        # plant a sentinel in layer 0's ring so retention is checkable
        leaf = "k_q" if quantized else "k"
        sentinel = jnp.ones_like(cache[leaf][0, :, :1])
        cache[leaf] = cache[leaf].at[0, :, :1].set(sentinel)
        h = jnp.ones((1, cfg.dim), jnp.bfloat16)
        itp = use_interpret()
        # eager pallas_call (no enclosing jit): the kernel is trace-inner
        # in serving, and the probe wants exactly its Mosaic lowering
        h2, cache2 = decode_loop_step(
            params["layers"], cache, h, jnp.int32(3), jnp.int32(1),
            cfg, fmts, unroll=1, interpret=itp)
        float(h2.astype(jnp.float32).sum())   # host fetch: the only
        #                                       reliable sync on the tunnel
        kept = jax.device_get(cache2[leaf][0, :, :1])
        if not (kept == jax.device_get(sentinel)).all():
            return ("aliased cache layers outside the launch window did "
                    "not retain their bytes — in-place layer-loop update "
                    "unsupported on this backend")
        # the grouped launch (unroll=2) is a different grid/program shape
        h3, _ = decode_loop_step(
            params["layers"], cache2, h, jnp.int32(4), jnp.int32(0),
            dataclasses.replace(cfg, decode_layer_unroll=2), fmts,
            unroll=2, interpret=itp)
        float(h3.astype(jnp.float32).sum())
        return None
    except Exception as e:  # noqa: BLE001 — any failure means "don't use it"
        return _err(e)


@functools.lru_cache(maxsize=1)
def probe_kv_quant() -> str | None:
    """Compile + run the int8 KV-cache write-quantize kernel
    (ops/pallas/kvquant.py) at a decode-like shape.  A failure degrades
    writes to the identical XLA formulation (force_xla_quant) instead of
    crash-looping the pod at its first prefill."""
    try:
        import jax.numpy as jnp

        from . import use_interpret
        from .kvquant import quantize_kv_pallas

        q, s = quantize_kv_pallas(jnp.ones((8, 8, 128), jnp.bfloat16),
                                  interpret=use_interpret())
        float(s.sum()) + float(q.astype(jnp.float32).sum())
        return None
    except Exception as e:  # noqa: BLE001
        return _err(e)

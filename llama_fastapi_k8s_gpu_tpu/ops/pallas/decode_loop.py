"""Layer-looped decode step: K transformer layers per Pallas launch.

ROADMAP item 2 ("Kernel Looping: Eliminating Synchronization Boundaries",
PAPERS.md).  Round-5 profiling showed the decode ceiling is launch/DMA
overhead, not compute: an all-skip 8k flash probe still cost 14.3 of
15.4 ms/layer, and the per-layer path dispatches a separate fused-matmul /
attention / KV-write chain for every one of the L layers on every decode
step.  This module extends the ``kv_unroll`` idea ("U KV blocks per
launch", ops/pallas/attention.py) across the LAYER axis: one
``pallas_call`` whose grid iterates K layers in-kernel — rms-norm → QKV
matmuls → RoPE → KV write(-quantize) → decode attention (int8
fused-dequant reads included, "BitDecoding" PAPERS.md) → output proj →
MLP — so a decode step goes from O(L × ops) launches to O(L/K)
(``LFKT_DECODE_LAYER_UNROLL``; ``-1`` = all layers in ONE launch).

Bit-exactness contract: the kernel body executes the SAME source the
per-layer path executes — :func:`models.llama.rms_norm` /
:func:`~models.llama.rope_interleaved` / :func:`~models.llama.
xla_attention`, :func:`ops.linear.linear` on the per-layer weight dicts,
:func:`~.kvquant.quantize_kv_xla`, and the same ``dynamic_update_slice``
ring write — traced per layer in the same order, on the same dtypes.  On
the CPU dev-gate (interpret mode) the looped greedy decode is therefore
bit-identical to the per-layer reference (tests/test_decode_loop.py, the
resplit/vbf32 adjudication pattern); on chip the Mosaic program is
adjudicated by ``bench.py --decode-unroll-sweep`` + the perf gate.

Residency: each grid step holds one layer's weights + its full KV ring
block in VMEM.  That bounds the serving shapes Mosaic will accept —
the startup probe (ops/pallas/probe.py: ``probe_decode_loop``) compiles
the engine's REAL ring geometry, so an over-budget shape degrades the
pod to the per-layer path at construction time with attribution
(``/debug/compiles`` degrade ledger), never at first traffic.  The probe
also verifies the partial-grid aliasing contract this kernel leans on:
cache layers outside the launched [layer0, layer0+K) window must retain
their input bytes through the aliased output.

The residual stream ``h`` rides VMEM scratch across grid steps (TPU
grids execute sequentially — the flash kernel's accumulator idiom); the
KV ring leaves are input/output-aliased so the update is in place, and
each layer's ring block is written exactly once by its own grid step.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...models.params import LOOP_LINEARS as _LINEARS
from ...obs.devtime import register_program

logger = logging.getLogger(__name__)

__all__ = [
    "decode_loop_disabled",
    "decode_loop_step",
    "disable_decode_loop",
    "effective_unroll",
    "forward_layers_looped",
    "note_degrade",
]

#: probe-degrade pins (the ``force_xla_quant`` idiom, but PER GEOMETRY):
#: a Mosaic failure at engine construction pins the per-layer path for
#: that kernel geometry — keyed exactly like the probe's lru_cache, so a
#: co-resident model whose own geometry probes clean keeps looping
#: (serving/manifest.py's per-model ``decode_layer_unroll`` override)
_DISABLED: dict[tuple, str] = {}

#: reasons already attributed this process (note_degrade logs once per
#: distinct reason; the devtime degrade ledger keeps the counts)
_NOTED: set[str] = set()


def loop_geometry(cfg, fmts: dict) -> tuple:
    """The kernel-geometry key a compile verdict is valid for — the
    probe's argument tuple (ops/pallas/probe.py: ``probe_decode_loop``)
    derived from a config + weight plan.  Everything that changes the
    Mosaic program's residency or structure is in here; ``n_layers`` is
    not (the layer count only changes the grid, never the per-step
    shape)."""
    return (cfg.kv_dtype == "int8", fmts["wq"] == "int8",
            cfg.n_kv_heads, cfg.head_dim, cfg.n_ctx, cfg.sliding_window,
            cfg.n_heads, cfg.ffn_dim)


def disable_decode_loop(reason: str | None, key: tuple = ()) -> None:
    """Pin the per-layer decode path for one kernel geometry (set by the
    engine when the looped kernel fails its startup compile probe on
    TPU); ``None`` re-arms everything (tests)."""
    if reason is None:
        _DISABLED.clear()
    else:
        _DISABLED[key] = reason


def decode_loop_disabled(key: tuple = ()) -> str | None:
    return _DISABLED.get(key)


def note_degrade(program: str, reason: str) -> None:
    """Attribute one degrade decision: a structured log line (once per
    distinct reason per process) + the /debug/compiles degrade ledger
    (obs/devtime.py).  Called at trace/probe time only — never on the
    steady-state dispatch path."""
    from ...obs.devtime import DEVTIME

    DEVTIME.record_degrade(program, reason)
    if reason not in _NOTED:
        _NOTED.add(reason)
        logger.warning("%s degraded: %s", program, reason)


def effective_unroll(cfg) -> int:
    """Clamp ``cfg.decode_layer_unroll`` to a divisor of ``n_layers``:
    ``-1`` (or K ≥ L) fuses all layers into one launch; any other K walks
    down to the nearest divisor so the group scan covers every layer
    exactly once (the flash ``kv_unroll`` clamp idiom).  0 stays 0."""
    K = int(cfg.decode_layer_unroll)
    L = int(cfg.n_layers)
    if K == 0:
        return 0
    if K < -1:
        raise ValueError(
            f"decode_layer_unroll must be >= -1, got {K} "
            "(0 = off, -1 = all layers per launch)")
    if K < 0 or K >= L:
        return L
    while K > 1 and L % K:
        K -= 1
    return K


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------

def _loop_kernel(s_ref, h_ref, *rest, cfg, fmts, out_count: int):
    """One grid step = one transformer layer against the residual stream
    held in VMEM scratch.

    ``s_ref``: prefetched scalars ``[pos, layer0]`` — the ring slot of the
    new token and the first layer of this launch's window (index maps
    address layer ``layer0 + program_id``).  ``rest`` is the flat operand
    list built by :func:`decode_loop_step` — per-linear weight planes,
    norms, cache leaves — then the outputs (``h_out`` + new cache leaves)
    and the ``h`` scratch.  All math below is the per-layer path's own
    source (models/llama.py, ops/linear.py, kvquant.py), which is the
    whole bit-exactness argument."""
    from ...models.llama import rms_norm, rope_interleaved, xla_attention
    from ...ops.linear import linear
    from .kvquant import quantize_kv_xla

    quant = cfg.kv_dtype == "int8"
    refs = list(rest)
    hscr = refs.pop()
    outs = refs[len(refs) - out_count:]
    ins = refs[:len(refs) - out_count]

    it = iter(ins)
    wrefs: dict[str, tuple] = {}
    for name in _LINEARS:
        if fmts[name] == "int8":
            wrefs[name] = (next(it), next(it))
        else:
            wrefs[name] = (next(it),)
    attn_norm = next(it)
    ffn_norm = next(it)
    cache_ins = list(it)
    h_out, *cache_outs = outs

    l = pl.program_id(0)

    @pl.when(l == 0)
    def _seed():
        hscr[...] = h_ref[...]

    h = hscr[...]                                    # (1, D)
    pos = s_ref[0]
    # the reference's ``positions = pos_offset + jnp.arange(S)`` at S=1
    positions = pos + jnp.arange(1, dtype=jnp.int32)

    def lin(x, name):
        r = wrefs[name]
        if fmts[name] == "int8":
            w = {"q": r[0][0], "s": r[1][0]}
        else:
            w = {"w": r[0][0]}
        return linear(x, w)

    hd, n_kv = cfg.head_dim, cfg.n_kv_heads
    hn = rms_norm(h, attn_norm[0], cfg.rms_eps)
    q = lin(hn, "wq").reshape(1, cfg.n_heads, hd)
    k = lin(hn, "wk").reshape(1, n_kv, hd)
    v = lin(hn, "wv").reshape(1, n_kv, hd)
    q = rope_interleaved(q, positions, cfg.rope_theta)
    k = rope_interleaved(k, positions, cfg.rope_theta)

    if quant:
        # the XLA quantize formulation, not quantize_kv_pallas: a
        # pallas_call cannot nest inside a kernel.  On the CPU dev-gate
        # the per-layer reference quantizes through the same XLA source,
        # so the gate compares identical math (kvquant.py docstring).
        kq, ks = quantize_kv_xla(k.transpose(1, 0, 2))   # (n_kv, 1, hd)
        vq, vs = quantize_kv_xla(v.transpose(1, 0, 2))
        kq_in, vq_in, ks_in, vs_in = cache_ins
        ck = jax.lax.dynamic_update_slice(kq_in[0], kq, (0, pos, 0))
        cv = jax.lax.dynamic_update_slice(vq_in[0], vq, (0, pos, 0))
        cks = jax.lax.dynamic_update_slice(ks_in[0], ks, (0, pos))
        cvs = jax.lax.dynamic_update_slice(vs_in[0], vs, (0, pos))
        for ref, val in zip(cache_outs, (ck, cv, cks, cvs)):
            ref[...] = val[None]
    else:
        k_in, v_in = cache_ins
        kh = k.astype(k_in.dtype).transpose(1, 0, 2)     # (n_kv, 1, hd)
        vh = v.astype(v_in.dtype).transpose(1, 0, 2)
        ck = jax.lax.dynamic_update_slice(k_in[0], kh, (0, pos, 0))
        cv = jax.lax.dynamic_update_slice(v_in[0], vh, (0, pos, 0))
        cache_outs[0][...] = ck[None]
        cache_outs[1][...] = cv[None]
        cks = cvs = None

    ctx = xla_attention(q, ck, cv, cks, cvs, positions, cfg, h.dtype)
    h = h + lin(ctx, "wo")

    hn = rms_norm(h, ffn_norm[0], cfg.rms_eps)
    gated = jax.nn.silu(lin(hn, "w_gate").astype(jnp.float32)).astype(h.dtype)
    h = h + lin(gated * lin(hn, "w_up"), "w_down")
    hscr[...] = h

    @pl.when(l == pl.num_programs(0) - 1)
    def _finish():
        h_out[...] = h


def _layer_spec(shape: tuple) -> pl.BlockSpec:
    """Per-layer block of a layer-major stacked array: block (1, *rest)
    addressed at layer ``layer0 + l`` (``s_ref[1]`` is the prefetched
    window start)."""
    rest = shape[1:]
    zeros = (0,) * len(rest)
    return pl.BlockSpec(
        (1, *rest), lambda l, s, _z=zeros: (s[1] + l, *_z))


def _whole_spec(shape: tuple) -> pl.BlockSpec:
    """A block covering the whole (small) array, same for every grid step
    — the residual stream in/out."""
    zeros = (0,) * len(shape)
    return pl.BlockSpec(shape, lambda l, s, _z=zeros: _z)


def decode_loop_step(layers: dict, cache: dict, h: jax.Array, pos,
                     layer0, cfg, fmts: dict, unroll: int,
                     interpret: bool = False):
    """Run layers [layer0, layer0 + unroll) of a single-token decode step
    as ONE ``pallas_call`` (grid = the K layers; the residual stream rides
    VMEM scratch between them).

    ``layers``: the stacked param tree (models/params.py); ``cache``: the
    full stacked KV ring pytree — its leaves are input/output-aliased, so
    layers outside this launch's window keep their bytes and the K
    launched layers are updated in place.  ``fmts``: the
    :func:`~models.params.decode_loop_plan` tags.  Returns ``(h, cache)``
    with the same pytree structure the per-layer path carries.
    """
    quant = cfg.kv_dtype == "int8"
    cache_keys = ("k_q", "v_q", "k_s", "v_s") if quant else ("k", "v")

    operands: list = [h]
    in_specs: list = [_whole_spec(h.shape)]
    for name in _LINEARS:
        w = layers[name]
        if fmts[name] == "int8":
            planes = (w["q"], w["s"])
        else:
            planes = (w["w"],)
        for p in planes:
            operands.append(p)
            in_specs.append(_layer_spec(p.shape))
    for nm in ("attn_norm", "ffn_norm"):
        operands.append(layers[nm])
        in_specs.append(_layer_spec(layers[nm].shape))
    alias_base = len(operands) + 1      # +1: the scalar-prefetch operand
    for key in cache_keys:
        operands.append(cache[key])
        in_specs.append(_layer_spec(cache[key].shape))

    out_specs = [_whole_spec(h.shape)]
    out_shape = [jax.ShapeDtypeStruct(h.shape, h.dtype)]
    aliases = {}
    for i, key in enumerate(cache_keys):
        leaf = cache[key]
        out_specs.append(_layer_spec(leaf.shape))
        out_shape.append(jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
        aliases[alias_base + i] = 1 + i

    kernel = functools.partial(
        _loop_kernel, cfg=cfg, fmts=fmts, out_count=1 + len(cache_keys))
    scalars = jnp.stack([jnp.asarray(pos, jnp.int32).reshape(()),
                         jnp.asarray(layer0, jnp.int32).reshape(())])
    res = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(unroll,),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[pltpu.VMEM(tuple(h.shape), h.dtype)],
        ),
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(scalars, *operands)
    h_new = res[0]
    new_cache = dict(zip(cache_keys, res[1:]))
    return h_new, new_cache


def forward_layers_looped(layers: dict, cfg, h: jax.Array, pos_offset,
                          cache: dict, unroll: int, fmts: dict):
    """The layer stack of one decode step as O(L / unroll) launches: a
    ``lax.scan`` over layer groups, each group one
    :func:`decode_loop_step` launch.  ``unroll`` divides ``n_layers``
    and ``fmts`` is the validated weight plan — both come from the
    caller's :func:`models.llama._loop_unroll` eligibility pass (clamp +
    plan walk happen once per trace, there).  With ``unroll ==
    n_layers`` the scan disappears and the whole step is ONE launch."""
    from . import use_interpret

    interpret = use_interpret()
    n_groups = cfg.n_layers // unroll
    if n_groups == 1:
        return decode_loop_step(layers, cache, h, pos_offset,
                                jnp.int32(0), cfg, fmts, unroll,
                                interpret=interpret)

    def body(carry, g):
        hh, cc = carry
        hh, cc = decode_loop_step(layers, cc, hh, pos_offset, g * unroll,
                                  cfg, fmts, unroll, interpret=interpret)
        return (hh, cc), None

    (h, cache), _ = jax.lax.scan(
        body, (h, cache), jnp.arange(n_groups, dtype=jnp.int32))
    return h, cache


# devtime inventory (lfkt-lint PERF001): the looped decode kernel
# (decode_loop_step's pallas_call) is a TRACE-INNER dispatch site — it
# compiles as part of the decode-chunk entry programs that select it
# (obs/devtime.py; /debug/compiles shows it under kind="inner", and the
# "decode_loop" degrade-ledger entries carry the reason whenever an armed
# pod serves per-layer instead)
register_program("decode_loop_step", site="ops.pallas.decode_loop")

"""Fused Q6_K dequant-matmul (Pallas): the Q4_K_M file's *other* format.

llama.cpp's Q4_K_M quantization (the reference's served artifact,
reference api.py:14, docker/Dockerfile.base:30-32) is mixed: most linears
are Q4_K but ``ffn_down``, some ``attn_v`` layers and ``output.weight`` are
**Q6_K** (~27% of the weights).  Round 2 served those from an int8 requant
(1 B/weight); this kernel keeps them at their file precision and
~0.88 B/weight in HBM — less decode traffic AND less of the 16 GB chip —
so a Q4_K_M file serves fully fused with no requantization anywhere.

Same design as the v2 Q4_K kernel (ops/pallas/qmatmul.py — float nibble
split, lane-tiled scales, corrections folded into extra K columns), adapted
to Q6_K's layout (gguf/quants.py: ``y = d·sc[j]·(q6−32)``, 16 sub-blocks of
16, int8 sub-scales, ``q6 = ql_nibble | qh_crumb<<4`` ∈ [0,64)):

- the 4 low bits of each weight ride a re-biased packed byte
  ``v4 = (hi−8)·16 + lo`` (two weights/byte), split by ``floor``;
- the 2 high bits ride a crumb byte ``v2 = ((c3·4+c2)·4+c1)·4+c0 − 128``
  (four weights/byte), split by a 3-step ``floor`` chain;
- a K-tile of 2048 = 8 super-blocks × 16 sub-blocks = exactly **128
  sub-scales**, so with element-major columns (column ``c`` → sub-block
  ``c % 128``) the effective scale ``d·sc`` lane-tiles with period 128 —
  one vreg-tiling ``pltpu.repeat``, no arithmetic;
- per weight the kernel computes ``nib·eff + crumb·(16·eff)`` (2 muls, 1
  add, 1 cast); the −32 offset and the hi-half's +8 nibble bias become 256
  correction columns dotted against per-sub-block activation sums.

Layout contract (:func:`prep_q6k`):

- ``q4`` (N, K/2) int8 — tile-local byte ``b`` ∈ [0,1024) holds the low
  nibbles of columns ``b`` and ``b+1024``; column ``c = e·128 + s``,
  sub-block ``s = c % 128`` (block-major), element ``e = c//128`` ∈ [0,16).
- ``q2`` (N, K/4) int8 — byte ``b`` ∈ [0,512) holds the crumbs of columns
  ``b``, ``b+512``, ``b+1024``, ``b+1536`` (c0..c3 low-to-high).
- ``sm6`` (K/2048, N, 128) bf16 — the 128 effective sub-scales ``d·sc`` of
  the tile, block-major.

Shape requirements: ``K % 2048 == 0``, ``N % 128`` == 0 — same classes as
the Q4_K kernel; ineligible tensors fall back to int8 (models/params.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...gguf.constants import GGML_BLOCK_SIZES, GGMLType, QK_K
from ...obs.devtime import register_program
from ...gguf.quants import _garbage_tolerant
from .qmatmul import (
    batched_rows,
    def_partition_compat,
    _env_variant,
    _interpret,
    _lane_repeat,
    _pick_tn,
    plain_pallas_call,
    _q4k_accum,
    q4k_compatible,
    rows_vmappable,
    _spec_axis,
    stacked_pallas_call,
    stacked_partitioned,
    TK,
    _tn_prefs_for,
)

# first entry = the env-knob default (ops/pallas/qmatmul.py::_env_variant).
# `cur` and `parfloor` are bit-identical planes (independent exact f32
# floors vs the serial remainder chain) and trade places inside noise
# across sessions: the 07-31 engine A/B had parfloor +0.75%, the 08-01
# microbench has cur -0.1% per-op.  `cur` leads because the 08-01 banked
# headline A/B (bench_q4km_variant_ab: 72.32 tok/s, the shipped-defaults
# claim) ran LFKT_Q4K_KERNEL=resplit + LFKT_Q6K_KERNEL=cur — the default
# tuple ships exactly the measured configuration (and the warm compile
# cache the driver bench inherits).
#
# `pre` is a LAYOUT variant (the others only re-order the kernel body):
# prep stores one pre-combined int8 plane ``q6p = q6 ∈ [0,64)`` (N, K) at
# 1 B/weight instead of the packed q4+q2 split at 0.75 B/weight.  The
# kernel then pays ~3 VPU ops/weight (convert, ·eff, bf16 cast) instead
# of ~7 (nibble+crumb extraction and recombination) — attacking the
# measured 200 vs 147 µs gap to the Q4_K kernel at equal MXU tile count
# (kernel_microbench_2026-08-01; the q4km mix carries ~32% of its
# weights in Q6_K).  Numerics: ``q6·eff`` is an exact f32 product (6-bit
# int × bf16 ≤ 14 mantissa bits), so the bf16-cast plane equals the
# split path's plane; only the +8 hi-nibble bias moves from a separately
# bf16-rounded corr column into the exact plane — deviation vs `cur` is
# corr-rounding scale (~1e-3), same class as `onedot`, gated on chip.
Q6K_VARIANTS = ("cur", "parfloor", "vbf32", "pre")

_SUBS6 = TK // 16    # 128 sub-blocks of 16 per k-tile
TKA6 = TK + 256      # + [xsum_all(128) | xsum_hi(128)] correction columns


q6k_compatible = q4k_compatible  # same divisibility classes


# ---------------------------------------------------------------------------
# host-side weight prep
# ---------------------------------------------------------------------------

def _combine_q6p(q4: np.ndarray, q2: np.ndarray, n_out: int,
                 k_in: int) -> np.ndarray:
    """Split planes → the `pre` layout's combined plane ``q6p`` (N, K) int8,
    true ``q6 = nib | crumb<<4`` ∈ [0, 64) in element-major tile-column
    order.  Tile-local column ``c``: nibble from q4 byte ``c % 1024``
    (lo if c < 1024 else hi), crumb from q2 byte ``c % 512`` (digit
    ``c // 512``).  Pure integer numpy over the native packers' output —
    the C++ layout contract is untouched."""
    kt = k_in // TK
    v4 = q4.reshape(n_out, kt, TK // 2)
    lo = (v4 & 0x0F).astype(np.int8)                  # low nibble
    hi = ((v4 >> 4) + 8).astype(np.int8)              # true high nibble
    nib = np.concatenate([lo, hi], axis=2)            # (N, kt, TK)
    u = q2.reshape(n_out, kt, TK // 4).astype(np.int16) + 128  # ∈ [0,255]
    crumb = np.concatenate(
        [u & 3, (u >> 2) & 3, (u >> 4) & 3, (u >> 6) & 3], axis=2)
    return (nib + (crumb << 4).astype(np.int8)).reshape(n_out, k_in)


@_garbage_tolerant
def prep_q6k(raw: np.ndarray, n_out: int, k_in: int) -> dict:
    """Raw Q6_K block bytes (row-major, ``n_out`` rows of ``k_in`` elements)
    → the kernel layout dict: {"q4", "q2", "sm6"} (split layout) or
    {"q6p", "sm6"} under ``LFKT_Q6K_KERNEL=pre`` (see Q6K_VARIANTS).

    Dispatches to the threaded C++ packer (native/src/gguf_dequant.cpp,
    bit-identical planes — tests/test_native.py) when available; the numpy
    chain below is the reference implementation and the fallback."""
    if not q6k_compatible(n_out, k_in):
        raise ValueError(f"({n_out}, {k_in}) not fused-Q6_K compatible "
                         f"(need K%{TK}==0, N%128==0)")
    from ...native import native_prep_q6k

    pre = _env_variant("LFKT_Q6K_KERNEL", Q6K_VARIANTS) == "pre"
    nat = native_prep_q6k(raw, n_out, k_in)
    if nat is not None:
        if pre:
            return {"q6p": jnp.asarray(_combine_q6p(
                        np.asarray(nat["q4"]), np.asarray(nat["q2"]),
                        n_out, k_in)),
                    "sm6": jnp.asarray(nat["sm6"])}
        return {"q4": jnp.asarray(nat["q4"]), "q2": jnp.asarray(nat["q2"]),
                "sm6": jnp.asarray(nat["sm6"])}
    bs = GGML_BLOCK_SIZES[GGMLType.Q6_K][1]           # 210
    nb = k_in // QK_K
    kt = k_in // TK
    blocks = np.ascontiguousarray(raw, dtype=np.uint8)[: n_out * nb * bs]
    blocks = blocks.reshape(n_out, nb, bs)
    ql = blocks[..., 0:128].reshape(n_out, nb, 2, 64)
    qh = blocks[..., 128:192].reshape(n_out, nb, 2, 32)
    sc = blocks[..., 192:208].view(np.int8).astype(np.float32)  # (N, nb, 16)
    d = blocks[..., 208:210].copy().view(np.float16).astype(np.float32)[..., 0]

    low = np.empty((n_out, nb, 2, 128), dtype=np.uint8)
    low[..., 0:64] = ql & 0x0F
    low[..., 64:128] = ql >> 4
    hi = np.empty((n_out, nb, 2, 128), dtype=np.uint8)
    hi[..., 0:32] = qh & 3
    hi[..., 32:64] = (qh >> 2) & 3
    hi[..., 64:96] = (qh >> 4) & 3
    hi[..., 96:128] = (qh >> 6) & 3
    q6 = (low | (hi << 4)).reshape(n_out, nb, 256)    # elem idx = sub*16 + e

    # element-major tile columns: Q[..., e, s], s = blk*16 + sub
    Q = q6.reshape(n_out, kt, 8, 16, 16).transpose(0, 1, 4, 2, 3)
    Q = np.ascontiguousarray(Q).reshape(n_out, kt, 16, _SUBS6)
    nib = Q & 0x0F
    crumb = Q >> 4                                    # ∈ [0, 4)

    lo4 = nib[:, :, :8, :].reshape(n_out, kt, TK // 2)
    hi4 = nib[:, :, 8:, :].reshape(n_out, kt, TK // 2)
    v4 = ((hi4.astype(np.int16) - 8) << 4) + lo4
    q4 = v4.astype(np.int8).reshape(n_out, k_in // 2)

    cr = crumb.reshape(n_out, kt, 4, TK // 4).astype(np.int16)
    v2 = (((cr[:, :, 3] * 4 + cr[:, :, 2]) * 4 + cr[:, :, 1]) * 4
          + cr[:, :, 0]) - 128
    q2 = v2.astype(np.int8).reshape(n_out, k_in // 4)

    eff = d[..., None] * sc                           # (N, nb, 16)
    sm6 = eff.reshape(n_out, kt, _SUBS6).transpose(1, 0, 2)
    sm6 = jnp.asarray(np.ascontiguousarray(sm6), dtype=jnp.bfloat16)
    if pre:
        return {"q6p": jnp.asarray(_combine_q6p(q4, q2, n_out, k_in)),
                "sm6": sm6}
    return {"q4": jnp.asarray(q4), "q2": jnp.asarray(q2), "sm6": sm6}


def permute_x6(x: jax.Array) -> jax.Array:
    """(..., K) → (..., K): element-major column order (column ``e·128+s`` ←
    original element ``(s//16)·256 + (s%16)·16 + e``)."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    nl = len(lead)
    xb = x.reshape(*lead, K // TK, 8, 16, 16)         # [blk, sub, e]
    xe = jnp.transpose(xb, (*range(nl), nl, nl + 3, nl + 1, nl + 2))
    return xe.reshape(*lead, K)


def augment_x6(xp: jax.Array) -> jax.Array:
    """Permuted activations (B, K) → (B, K/TK·TKA6): each tile gains 256
    correction columns [sum per sub-block | sum over the hi-nibble half]
    dotted against [−32·eff | 8·eff]."""
    B, K = xp.shape
    kt = K // TK
    xt = xp.reshape(B, kt, 16, _SUBS6)
    xsum = jnp.sum(xt, axis=2)                        # (B, kt, 128)
    xsum_hi = jnp.sum(xt[:, :, 8:, :], axis=2)
    xpa = jnp.concatenate([xt.reshape(B, kt, TK), xsum, xsum_hi], axis=-1)
    return xpa.reshape(B, kt * TKA6)


def dequant_ref6(w: dict) -> jax.Array:
    """(N, K) f32 dequantized weights in **permuted** column order."""
    N, half = w["q4"].shape
    kt = half // (TK // 2)
    v4 = w["q4"].astype(jnp.float32).reshape(N, kt, TK // 2)
    h = jnp.floor(v4 / 16.0)
    nib = jnp.concatenate([v4 - 16.0 * h, h + 8.0], axis=2)   # (N, kt, TK)
    u = w["q2"].astype(jnp.float32).reshape(N, kt, TK // 4) + 128.0
    c3 = jnp.floor(u / 64.0)
    r = u - 64.0 * c3
    c2 = jnp.floor(r / 16.0)
    r = r - 16.0 * c2
    c1 = jnp.floor(r / 4.0)
    c0 = r - 4.0 * c1
    crumb = jnp.concatenate([c0, c1, c2, c3], axis=2)         # (N, kt, TK)
    q6 = nib + 16.0 * crumb
    eff = jnp.transpose(w["sm6"], (1, 0, 2)).astype(jnp.float32)
    eff = jnp.tile(eff, (1, 1, TK // _SUBS6))
    return (eff * (q6 - 32.0)).reshape(N, kt * TK)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _q6k_matmul_kernel(xpa_ref, q4_ref, q2_ref, sm_ref, o_ref, *, interpret,
                       variant="cur"):
    TN = q4_ref.shape[0]
    v4 = q4_ref[...].astype(jnp.float32)              # (TN, TK/2)
    h = jnp.floor(v4 * 0.0625)

    u = q2_ref[...].astype(jnp.float32) + 128.0       # (TN, TK/4)

    sm = sm_ref[...].reshape(TN, 128)                 # eff = d·sc
    corr = jnp.concatenate([sm * -32.0, sm * 8.0], axis=1).astype(jnp.bfloat16)

    if variant == "vbf32":
        _q6k_vbf32_body(xpa_ref, v4, h, u, sm, corr, o_ref, interpret)
        return

    l = v4 - h * 16.0
    nib = jnp.concatenate([l, h], axis=1)             # (TN, TK); hi bias → corr
    if variant == "parfloor":
        # all floors depend only on u (u ≤ 255 integer; /4,/16,/64 are
        # exact power-of-two scalings, so every quantity is an exact f32
        # integer and the crumbs come out bit-identical to the chain)
        c3 = jnp.floor(u * (1.0 / 64.0))
        f2 = jnp.floor(u * 0.0625)
        f1 = jnp.floor(u * 0.25)
        c2 = f2 - 4.0 * c3
        c1 = f1 - 4.0 * f2
        c0 = u - 4.0 * f1
    else:
        c3 = jnp.floor(u * (1.0 / 64.0))
        r = u - 64.0 * c3
        c2 = jnp.floor(r * 0.0625)
        r = r - 16.0 * c2
        c1 = jnp.floor(r * 0.25)
        c0 = r - 4.0 * c1
    crumb = jnp.concatenate([c0, c1, c2, c3], axis=1)  # (TN, TK)

    eff = _lane_repeat(sm, TK // 128, interpret)
    eff16 = _lane_repeat(sm * 16.0, TK // 128, interpret)

    a = (nib * eff + crumb * eff16).astype(jnp.bfloat16)

    xpa = xpa_ref[...]
    part = jax.lax.dot_general(
        xpa[:, :TK], a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    _q4k_accum(o_ref, part)


def _q6k_vbf32_body(xpa_ref, v4, h, u, sm, corr, o_ref, interpret):
    """Activation-side recombination with f32 planes (Q6_K analogue of the
    Q4_K ``vbf32`` variant, ops/pallas/qmatmul.py).

    Nibbles: ``x_lo·(l·eff) + x_hi·(h·eff)`` rewritten through ``l = v4 −
    16h`` as ``x_lo·(v4·eff) + (x_hi − 16·x_lo)·(h·eff)`` — no per-weight
    reconstruction.  Crumbs: with partial floors ``f1 = ⌊u/4⌋``,
    ``f2 = ⌊u/16⌋``, ``c3 = ⌊u/64⌋`` the base-4 digit sum telescopes,
    ``Σⱼ xⱼ·cⱼ = x₀·u + (x₁−4x₀)·f1 + (x₂−4x₁)·f2 + (x₃−4x₂)·c3``, so no
    digit is ever isolated.  Per packed byte: 1 floor + 2 muls (nibbles),
    3 floors + 4 muls (4 crumbs) — vs the default's per-WEIGHT multiply,
    add and bf16 cast.  All planes are exact f32 products (≤8-bit int ×
    bf16 scale needs ≤16 mantissa bits); the dots take f32 operands so the
    amplified-magnitude cancellations stay at f32 accuracy IF the backend's
    f32 dot is multi-pass (residual ~64·2⁻²² per term — below the shared
    bf16 corr path); see the chip-gate note at the dot call below.

    Scale alignment: a crumb byte's four columns ``b+512j`` and a nibble
    byte's pair ``b, b+1024`` all share sub-block ``b % 128`` (512 and
    1024 are multiples of 128), so one repeated ``sm`` plane serves every
    term."""
    eff_h = _lane_repeat(sm, (TK // 2) // 128, interpret)
    eff_q = _lane_repeat(sm * 16.0, (TK // 4) // 128, interpret)

    f1 = jnp.floor(u * 0.25)
    f2 = jnp.floor(u * 0.0625)
    c3 = jnp.floor(u * (1.0 / 64.0))

    xpa = xpa_ref[...]
    Q = TK // 4
    x0 = xpa[:, 0 * Q: 1 * Q].astype(jnp.float32)
    x1 = xpa[:, 1 * Q: 2 * Q].astype(jnp.float32)
    x2 = xpa[:, 2 * Q: 3 * Q].astype(jnp.float32)
    x3 = xpa[:, 3 * Q: 4 * Q].astype(jnp.float32)
    x_lo = jnp.concatenate([x0, x1], axis=1)          # columns [0, TK/2)
    x_hi = jnp.concatenate([x2, x3], axis=1)          # columns [TK/2, TK)

    # f32-operand dots; Mosaic rejects an explicit precision attr — see the
    # Q4_K vbf32 note (qmatmul.py): the chip microbench's numerics
    # cross-check gates whether its f32 lowering preserves the cancellation
    dot = functools.partial(
        jax.lax.dot_general, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part = dot(x_lo, v4 * eff_h)
    part += dot(x_hi - 16.0 * x_lo, h * eff_h)
    part += dot(x0, u * eff_q)
    part += dot(x1 - 4.0 * x0, f1 * eff_q)
    part += dot(x2 - 4.0 * x1, f2 * eff_q)
    part += dot(x3 - 4.0 * x2, c3 * eff_q)
    part += dot(xpa[:, TK:], corr)
    _q4k_accum(o_ref, part)


def _q6k_pre_kernel(xpa_ref, q6p_ref, sm_ref, o_ref, *, interpret):
    """`pre` layout body: one combined int8 plane, ~3 VPU ops/weight.

    ``y = Σ x·(q6−32)·eff = dot(x, q6·eff) − 32·Σ_s eff_s·xsum_s`` — the
    hi-nibble bias lives inside the exact plane, so only the −32 offset
    rides the correction dot; the xsum_hi half of the shared augment_x6
    columns is dotted against zeros (keeping one activation layout for
    both Q6_K layouts costs 128 dead columns ≈ 6% of the corr dot, which
    is itself ~6% of the MXU work)."""
    TN = q6p_ref.shape[0]
    sm = sm_ref[...].reshape(TN, 128)
    eff = _lane_repeat(sm, TK // 128, interpret)
    a = (q6p_ref[...].astype(jnp.float32) * eff).astype(jnp.bfloat16)
    corr = jnp.concatenate(
        [sm * -32.0, jnp.zeros_like(sm)], axis=1).astype(jnp.bfloat16)
    xpa = xpa_ref[...]
    part = jax.lax.dot_general(
        xpa[:, :TK], a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    _q4k_accum(o_ref, part)


def _q6k_pre_specs(B: int, TN: int):
    """(in_specs, out_spec) for the `pre` layout: one (TN, TK) int8 plane
    plus the shared sm6 scale plane."""
    return (
        [
            ((B, TKA6), lambda n, k: (0, k)),
            ((TN, TK), lambda n, k: (n, k)),
            ((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        ((B, TN), lambda n, k: (0, n)),
    )


_TN_PREFS_Q6K = (256, 128)  # wider f32 intermediates than Q4_K: smaller TN


def _q6k_specs(B: int, TN: int):
    """Single tiling definition for both the unstacked and stacked calls
    (see qmatmul._q4k_specs)."""
    return (
        [
            ((B, TKA6), lambda n, k: (0, k)),
            ((TN, TK // 2), lambda n, k: (n, k)),
            ((TN, TK // 4), lambda n, k: (n, k)),
            ((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        ((B, TN), lambda n, k: (0, n)),
    )


def _q6k_2d_raw(xpa: jax.Array, q4: jax.Array, q2: jax.Array, sm: jax.Array,
                interpret: bool, variant: str = "cur") -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA6) * TK
    N = q4.shape[0]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q6K))
    in_specs, out_spec = _q6k_specs(B, TN)
    return plain_pallas_call(
        functools.partial(_q6k_matmul_kernel, interpret=interpret,
                          variant=variant),
        (N // TN, K // TK), in_specs, out_spec,
        jax.ShapeDtypeStruct((B, N), jnp.float32), interpret,
    )(xpa, q4, q2, sm)


def _q6k_pre_2d_raw(xpa: jax.Array, q6p: jax.Array, sm: jax.Array,
                    interpret: bool) -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA6) * TK
    N = q6p.shape[0]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q6K))
    in_specs, out_spec = _q6k_pre_specs(B, TN)
    return plain_pallas_call(
        functools.partial(_q6k_pre_kernel, interpret=interpret),
        (N // TN, K // TK), in_specs, out_spec,
        jax.ShapeDtypeStruct((B, N), jnp.float32), interpret,
    )(xpa, q6p, sm)


@functools.lru_cache(maxsize=4)
def _q6k_pre_2d_partitioned(interpret: bool):
    """GSPMD rule for the `pre` layout (same contract: partition N/rows,
    never K)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xpa, q6p, sm):
        return _q6k_pre_2d_raw(xpa, q6p, sm, interpret)

    def partition(mesh, arg_shapes, result_shape):
        rows = _spec_axis(arg_shapes[0].sharding, 0)
        n_ax = _spec_axis(arg_shapes[1].sharding, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )

        def lower(xpa, q6p, sm):
            return _q6k_pre_2d_raw(xpa, q6p, sm, interpret)

        return (mesh, lower, NamedSharding(mesh, P(rows, n_ax)),
                arg_shardings)

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule="b k, n j, t n l -> b n",
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=0))


def _q6k_pre_2d_stacked_raw(idx: jax.Array, xpa: jax.Array, q6p: jax.Array,
                            sm: jax.Array, interpret: bool) -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA6) * TK
    N = q6p.shape[1]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q6K))
    in_specs, out_spec = _q6k_pre_specs(B, TN)
    call = stacked_pallas_call(
        functools.partial(_q6k_pre_kernel, interpret=interpret),
        grid=(N // TN, K // TK),
        in_specs=in_specs,
        out_spec=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )
    return call(idx, xpa, q6p, sm)


@functools.lru_cache(maxsize=4)
def _q6k_pre_2d_stacked_partitioned(interpret: bool):
    return stacked_partitioned(
        _q6k_pre_2d_stacked_raw, "i, b k, l n j, l t n m -> b n", interpret)


@functools.lru_cache(maxsize=8)
def _q6k_2d_partitioned(interpret: bool, variant: str = "cur"):
    """GSPMD rule mirroring the Q4_K kernel's: partition over N (and rows),
    never over K; tp-sharded weights compute locally."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xpa, q4, q2, sm):
        return _q6k_2d_raw(xpa, q4, q2, sm, interpret, variant)

    def partition(mesh, arg_shapes, result_shape):
        xp_s, q4_s, q2_s, sm_s = (a.sharding for a in arg_shapes)
        rows = _spec_axis(xp_s, 0)
        n_ax = _spec_axis(q4_s, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )
        result_sharding = NamedSharding(mesh, P(rows, n_ax))

        def lower(xpa, q4, q2, sm):
            return _q6k_2d_raw(xpa, q4, q2, sm, interpret, variant)

        return mesh, lower, result_sharding, arg_shardings

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule="b k, n j, n p, t n l -> b n",
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=0))


def _q6k_2d_stacked_raw(idx: jax.Array, xpa: jax.Array, q4: jax.Array,
                        q2: jax.Array, sm: jax.Array,
                        interpret: bool, variant: str = "cur") -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA6) * TK
    N = q4.shape[1]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q6K))
    in_specs, out_spec = _q6k_specs(B, TN)
    call = stacked_pallas_call(
        functools.partial(_q6k_matmul_kernel, interpret=interpret,
                          variant=variant),
        grid=(N // TN, K // TK),
        in_specs=in_specs,
        out_spec=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )
    return call(idx, xpa, q4, q2, sm)


@functools.lru_cache(maxsize=8)
def _q6k_2d_stacked_partitioned(interpret: bool, variant: str = "cur"):
    return stacked_partitioned(
        functools.partial(_q6k_2d_stacked_raw, variant=variant),
        "i, b k, l n j, l n p, l t n m -> b n", interpret)


def q6k_matmul_stacked(x: jax.Array, w: dict, idx,
                       interpret: bool | None = None) -> jax.Array:
    """x (..., K) → (..., N) against layer ``idx`` of stacked Q6_K weights
    (``q4`` (L, N, K/2), ``q2`` (L, N, K/4), ``sm6`` (L, K/2048, N, 128);
    or ``q6p`` (L, N, K) + ``sm6`` for the `pre` layout).  The program is
    dispatched on the LAYOUT (plane presence), not the env knob, so
    weights prepped under one variant can never meet the other family's
    kernel."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xpa = augment_x6(permute_x6(x).reshape(-1, K).astype(jnp.bfloat16))
    i1 = jnp.asarray(idx, jnp.int32).reshape(1)
    if "q6p" in w:
        fn = _q6k_pre_2d_stacked_partitioned(_interpret(interpret))
        y = batched_rows(lambda xp, *ws: fn(i1, xp, *ws),
                         xpa, w["q6p"], w["sm6"])
    else:
        var = _env_variant("LFKT_Q6K_KERNEL", Q6K_VARIANTS)
        fn = _q6k_2d_stacked_partitioned(
            _interpret(interpret), "cur" if var == "pre" else var)
        y = batched_rows(lambda xp, *ws: fn(i1, xp, *ws),
                         xpa, w["q4"], w["q2"], w["sm6"])
    return y.reshape(*lead, -1).astype(x.dtype)


def q6k_matmul(x: jax.Array, w: dict, interpret: bool | None = None) -> jax.Array:
    """x (..., K) bf16/f32 → (..., N) in x.dtype, weights in Q6_K kernel
    layout.  The fused path of ``ops.linear.linear`` for Q6_K tensors.
    Layout-dispatched like :func:`q6k_matmul_stacked`."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xpa = augment_x6(permute_x6(x).reshape(-1, K).astype(jnp.bfloat16))
    if "q6p" in w:
        fn = _q6k_pre_2d_partitioned(_interpret(interpret))
        y = batched_rows(fn, xpa, w["q6p"], w["sm6"])
    else:
        # `pre` is a layout variant: split-layout weights (e.g. prepped
        # before the env flip) run the split default, never a silent
        # mislabel
        var = _env_variant("LFKT_Q6K_KERNEL", Q6K_VARIANTS)
        fn = _q6k_2d_partitioned(
            _interpret(interpret), "cur" if var == "pre" else var)
        y = batched_rows(fn, xpa, w["q4"], w["q2"], w["sm6"])
    return y.reshape(*lead, -1).astype(x.dtype)


# devtime inventory (lfkt-lint PERF001): trace-inner fused-matmul builders
# (see ops/pallas/qmatmul.py for the attribution contract)
register_program("_q6k_2d_partitioned", site="ops.pallas.q6matmul")
register_program("_q6k_pre_2d_partitioned", site="ops.pallas.q6matmul")

"""Pallas TPU kernels — the in-tree analogue of the CUDA kernels the
reference consumes through llama.cpp's cuBLAS build (reference
docker/Dockerfile.base:30-32).

Two kernel families:

- :mod:`.attention` — blockwise flash attention (online softmax) for the
  prefill hot path, causal + optional sliding window, GQA-aware.
- :mod:`.dequant` — K-quant dequantization (Q4_K / Q5_K / Q6_K / Q8_0)
  executed *on device*: the host uploads the raw quantized block bytes
  (≈4.5 bit/weight) and the TPU expands them to bf16/f32 in HBM, so the
  host→device transfer is the quantized size, not the dequantized size.

Every kernel runs in interpret mode off-TPU so the whole suite is testable
on the CPU backend (SURVEY.md §4 "Device tests").
"""

from __future__ import annotations

import jax


def use_interpret() -> bool:
    """Pallas kernels compile natively only on TPU; interpret elsewhere.
    (Callers that need to force a mode pass ``interpret=`` explicitly —
    every kernel entry point takes it; the old module-global override
    hook was never used and was removed by the dead-code lint.)"""
    return jax.default_backend() != "tpu"


from .attention import flash_attention  # noqa: E402
from .dequant import (  # noqa: E402
    device_dequant,
    dequant_q4_k_device,
    dequant_q5_k_device,
    dequant_q6_k_device,
    dequant_q8_0_device,
)
from .q5matmul import prep_q5k, q5k_matmul, q5k_matmul_stacked  # noqa: E402
from .q6matmul import prep_q6k, q6k_matmul, q6k_matmul_stacked  # noqa: E402
from .q8matmul import prep_q8_0, q8_matmul, q8_matmul_stacked  # noqa: E402
from .qmatmul import prep_q4k, q4k_matmul, q4k_matmul_stacked  # noqa: E402

__all__ = [
    "flash_attention",
    "device_dequant",
    "dequant_q4_k_device",
    "dequant_q5_k_device",
    "dequant_q6_k_device",
    "dequant_q8_0_device",
    "prep_q4k",
    "prep_q5k",
    "prep_q6k",
    "prep_q8_0",
    "q4k_matmul",
    "q4k_matmul_stacked",
    "q5k_matmul",
    "q5k_matmul_stacked",
    "q6k_matmul",
    "q6k_matmul_stacked",
    "q8_matmul",
    "q8_matmul_stacked",
    "use_interpret",
]
